"""Fleet telemetry aggregation: stitch per-process exports into ONE
trace tree and ONE registry view (docs/OBSERVABILITY.md "Trace
propagation and aggregation").

A fleet run leaves one export tree under the topology's ``base_dir``:
the router's flight dumps (its span ring — every ``fleet_request`` root
span and ``fleet_dispatch`` event — plus the clock-handshake offsets in
the ``router_drain`` dump's context), each replica's
``replica_<i>_flight/`` dumps (the replica-side rings: ``fleet_wire_hop``
adoption spans, ``serve_queue_wait``/``serve_dispatch``/``serve_drain``
and their stream twins, all carrying the router-minted ``trace_id``),
and optionally each replica's ``replica_<i>_telemetry.jsonl`` periodic
snapshots. This module merges them OFFLINE:

- :func:`collect_fleet_records` reads the latest parsable dump per
  process and the handshake's clock offsets;
- :func:`fleet_traces` groups every record by ``trace_id`` (the
  ``match_records`` semantics: a batch span's plural ``trace_ids``
  matches too), translates replica-side timestamps onto the router's
  monotonic clock through the offsets, and orders each trace's records
  into one cross-process timeline;
- :func:`hop_attribution` derives the per-hop latency breakdown —
  router queue / wire / replica queue / device / return — from that
  timeline, clamped at zero (the offset estimate carries up to rtt/2 of
  error; a hop must never read negative);
- :func:`aggregate_registry` merges the replicas' registry snapshots
  into one fleet view (counters summed, gauges maxed), explicitly
  marking replicas whose exports are missing or unreadable (``gaps``)
  instead of silently shrinking the denominator.

Everything is **tolerant by construction**: a replica that died
mid-write leaves a truncated JSONL line or a torn dump, and a
postmortem tool that raises on the evidence of the very fault it is
investigating is useless — unparsable lines/dumps are skipped and
COUNTED, never raised.

Host-only stdlib (JGL010 covers this package): the aggregator runs on a
laptop from the export directory, no jax, no backend.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

from raft_ncup_tpu.observability.flight import match_records

ROUTER_ORIGIN = "router"

_REPLICA_FLIGHT_RE = re.compile(r"^replica_(\d+)_flight$")
_REPLICA_ANY_RE = re.compile(r"^replica_(\d+)[._]")

# Replica-side span/event names that belong to a request's journey, in
# rough pipeline order (used only for display ordering fallbacks).
QUEUE_WAIT_NAMES = ("serve_queue_wait", "stream_queue_wait")
DRAIN_NAMES = ("serve_drain", "stream_drain")
DISPATCH_NAMES = ("serve_dispatch", "stream_dispatch")


# --------------------------------------------------------------- readers


def read_jsonl_tolerant(path: str) -> Tuple[List[dict], int]:
    """Read a JSONL export, skipping (and counting) unparsable lines —
    the truncated-mid-write tail a killed replica leaves behind.
    Returns ``(records, skipped)``; a missing file is ``([], 0)``."""
    records: List[dict] = []
    skipped = 0
    try:
        fh = open(path, encoding="utf-8")
    except OSError:
        return records, skipped
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                skipped += 1
    return records, skipped


def dump_sort_key(path: str):
    """Deterministic recency order for ``flight_<trigger>_<ts>_<seq>``
    names (the scripts/postmortem.py rule: embedded (timestamp, seq),
    never mtime). Unparsable names sort oldest."""
    stem = os.path.basename(path)
    if stem.endswith(".json"):
        stem = stem[: -len(".json")]
    parts = stem.split("_")
    if len(parts) >= 3 and parts[-1].isdigit():
        return (1, parts[-2], int(parts[-1]), stem)
    return (0, "", 0, stem)


def load_dump_tolerant(path: str) -> Optional[dict]:
    """One flight dump, or ``None`` when torn/foreign (counted by the
    caller) — the aggregator must survive the evidence of a crash."""
    try:
        with open(path, encoding="utf-8") as fh:
            dump = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(dump, dict) or "spans" not in dump:
        return None
    return dump


def _latest_parsable_dump(paths: List[str]) -> Tuple[Optional[dict], int]:
    """The newest dump that parses, walking backwards through older
    ones when the newest is torn. Returns ``(dump, skipped)``."""
    skipped = 0
    for p in sorted(paths, key=dump_sort_key, reverse=True):
        dump = load_dump_tolerant(p)
        if dump is not None:
            return dump, skipped
        skipped += 1
    return None, skipped


def _dumps_under(root: str) -> List[str]:
    out = []
    for dirpath, _, files in os.walk(root):
        out.extend(
            os.path.join(dirpath, f)
            for f in files
            if f.startswith("flight_") and f.endswith(".json")
        )
    return out


# ------------------------------------------------------------ collection


def collect_fleet_records(base_dir: str) -> dict:
    """Read a fleet export tree into one host-side structure::

        {"origins":       {"router": [records...], "replica_0": [...]},
         "clock_offsets": {0: replica0_mono - router_mono, ...},
         "replicas":      [0, 1, ...],   # replicas with records
         "expected":      [0, 1, 2],     # replicas the tree names at all
         "gaps":          [2],           # expected but no parsable dump
         "skipped_dumps": 1}

    Per process the LATEST parsable dump wins (a drain dump holds the
    fullest ring; older dumps of the same process overlap it). Router
    records are every ``flight_*.json`` outside the
    ``replica_<i>_flight/`` subtrees; clock offsets come from router
    dump contexts (``router_drain``) plus any ``fleet_clock_handshake``
    events in the router's ring.
    """
    origins: Dict[str, List[dict]] = {}
    offsets: Dict[int, float] = {}
    expected: set = set()
    gaps: List[int] = []
    skipped = 0

    replica_dirs: Dict[int, str] = {}
    router_dump_paths: List[str] = []
    try:
        entries = sorted(os.listdir(base_dir))
    except OSError:
        entries = []
    for name in entries:
        full = os.path.join(base_dir, name)
        m = _REPLICA_FLIGHT_RE.match(name)
        if m and os.path.isdir(full):
            idx = int(m.group(1))
            replica_dirs[idx] = full
            expected.add(idx)
            continue
        m = _REPLICA_ANY_RE.match(name)
        if m:
            # Sockets/healthz/telemetry files name the replica even when
            # it never dumped — that is how a dead replica becomes a
            # GAP instead of silently absent.
            expected.add(int(m.group(1)))
        if os.path.isdir(full):
            router_dump_paths.extend(_dumps_under(full))
        elif name.startswith("flight_") and name.endswith(".json"):
            router_dump_paths.append(full)

    router_dump, s = _latest_parsable_dump(router_dump_paths)
    skipped += s
    if router_dump is not None:
        origins[ROUTER_ORIGIN] = list(router_dump.get("spans") or [])
        ctx_offsets = (router_dump.get("context") or {}).get(
            "clock_offsets"
        ) or {}
        for k, v in ctx_offsets.items():
            try:
                offsets[int(k)] = float(v)
            except (TypeError, ValueError):
                continue
        for rec in origins[ROUTER_ORIGIN]:
            if rec.get("name") == "fleet_clock_handshake":
                attrs = rec.get("attrs") or {}
                try:
                    offsets[int(attrs["replica"])] = float(
                        attrs["offset_s"]
                    )
                except (KeyError, TypeError, ValueError):
                    continue

    for idx in sorted(expected):
        paths = (
            _dumps_under(replica_dirs[idx]) if idx in replica_dirs else []
        )
        dump, s = _latest_parsable_dump(paths)
        skipped += s
        if dump is None:
            gaps.append(idx)
            continue
        origins[f"replica_{idx}"] = list(dump.get("spans") or [])

    return {
        "origins": origins,
        "clock_offsets": offsets,
        "replicas": sorted(
            int(o.split("_", 1)[1]) for o in origins
            if o != ROUTER_ORIGIN
        ),
        "expected": sorted(expected),
        "gaps": gaps,
        "skipped_dumps": skipped,
    }


# ----------------------------------------------------------- trace trees


def _record_trace_ids(record: dict) -> List[str]:
    attrs = record.get("attrs") or {}
    out = []
    tid = attrs.get("trace_id")
    if isinstance(tid, str):
        out.append(tid)
    tids = attrs.get("trace_ids")
    if isinstance(tids, list):
        out.extend(t for t in tids if isinstance(t, str))
    return out


def _origin_offset(origin: str, offsets: Dict[int, float]) -> float:
    if origin == ROUTER_ORIGIN:
        return 0.0
    try:
        return float(offsets.get(int(origin.split("_", 1)[1]), 0.0))
    except (ValueError, IndexError):
        return 0.0


def fleet_traces(
    collected: dict,
    trace_id: Optional[str] = None,
    request_id: Optional[int] = None,
) -> List[dict]:
    """Group the collected records into per-trace timelines.

    Each trace is::

        {"trace_id": ..., "request_id": ..., "origins": ["router",
         "replica_1"], "records": [tagged records, time-ordered],
         "hops": hop_attribution(...), "total_ms": float | None}

    A tagged record is the ring record plus ``origin`` and ``t`` — its
    start translated onto the ROUTER's monotonic clock (``t_s -
    offset``), which is what makes one cross-process timeline orderable
    at all. Filters narrow to one ``trace_id`` or ``request_id``.
    Traces sort slowest-first by ``total_ms`` (unknown durations last).
    """
    offsets = collected.get("clock_offsets") or {}
    by_trace: Dict[str, List[dict]] = {}
    for origin, records in (collected.get("origins") or {}).items():
        off = _origin_offset(origin, offsets)
        for rec in records:
            tids = _record_trace_ids(rec)
            if not tids:
                continue
            t = rec.get("t_s")
            tagged = dict(rec)
            tagged["origin"] = origin
            tagged["t"] = None if t is None else round(float(t) - off, 6)
            for tid in tids:
                by_trace.setdefault(tid, []).append(tagged)
    traces = []
    for tid, records in by_trace.items():
        if trace_id is not None and tid != trace_id:
            continue
        if request_id is not None and not match_records(
            records, request_id=request_id
        ):
            continue
        records.sort(
            key=lambda r: (r["t"] is None, r["t"] or 0.0)
        )
        root = next(
            (r for r in records if r.get("name") == "fleet_request"),
            None,
        )
        rid = None
        for r in records:
            attrs = r.get("attrs") or {}
            if isinstance(attrs.get("request_id"), int):
                rid = attrs["request_id"]
                break
        traces.append({
            "trace_id": tid,
            "request_id": rid,
            "origins": sorted({r["origin"] for r in records}),
            "records": records,
            "hops": hop_attribution(records),
            "total_ms": None if root is None else root.get("duration_ms"),
        })
    traces.sort(
        key=lambda tr: (
            tr["total_ms"] is None, -(tr["total_ms"] or 0.0)
        )
    )
    return traces


def _first(records: List[dict], *names: str) -> Optional[dict]:
    for r in records:
        if r.get("name") in names:
            return r
    return None


def hop_attribution(records: List[dict]) -> dict:
    """Per-hop latency breakdown of one trace's tagged records:
    ``router_queue_ms`` (submit → wire send), ``wire_ms`` (send →
    replica receive, the replica-measured ``fleet_wire_hop`` when
    present), ``replica_queue_ms`` (replica admission → batch
    assembly), ``device_ms`` (dispatch → delivered, compute + the
    sanctioned pull), ``return_ms`` (the residual: response wire +
    router completion). Every value is clamped at 0 — the clock-offset
    estimate carries up to rtt/2 of error and a hop must never read
    negative. Keys are absent when the evidence for them is (a dead
    replica's ring never exported)."""
    hops: Dict[str, float] = {}
    root = _first(records, "fleet_request")
    dispatch_ev = _first(records, "fleet_dispatch")
    wire = _first(records, "fleet_wire_hop")
    queue = _first(records, *QUEUE_WAIT_NAMES)
    drain = _first(records, *DRAIN_NAMES)
    if root is not None and root.get("t") is not None \
            and dispatch_ev is not None and dispatch_ev.get("t") is not None:
        hops["router_queue_ms"] = round(
            max(0.0, (dispatch_ev["t"] - root["t"]) * 1e3), 3
        )
    if wire is not None and wire.get("duration_ms") is not None:
        hops["wire_ms"] = max(0.0, wire["duration_ms"])
    elif (
        dispatch_ev is not None and dispatch_ev.get("t") is not None
        and queue is not None and queue.get("t") is not None
    ):
        hops["wire_ms"] = round(
            max(0.0, (queue["t"] - dispatch_ev["t"]) * 1e3), 3
        )
    if queue is not None and queue.get("duration_ms") is not None:
        hops["replica_queue_ms"] = max(0.0, queue["duration_ms"])
    if drain is not None and drain.get("duration_ms") is not None:
        hops["device_ms"] = max(0.0, drain["duration_ms"])
    total = None if root is None else root.get("duration_ms")
    if total is not None and hops:
        hops["return_ms"] = round(
            max(0.0, total - sum(hops.values())), 3
        )
    return hops


def render_trace(trace: dict) -> List[str]:
    """Human-readable lines for one stitched trace (the postmortem /
    trace_report view): the cross-process timeline indented under the
    root, then the per-hop breakdown."""
    head = (
        f"trace {trace['trace_id']}  request_id="
        f"{trace['request_id']}  total "
        + (
            f"{trace['total_ms']:.1f} ms"
            if trace["total_ms"] is not None else "?"
        )
        + f"  [{', '.join(trace['origins'])}]"
    )
    lines = [head]
    t0 = next(
        (r["t"] for r in trace["records"] if r["t"] is not None), None
    )
    for r in trace["records"]:
        dt = (
            "      --"
            if r["t"] is None or t0 is None
            else f"{(r['t'] - t0) * 1e3:+8.1f}"
        )
        dur = r.get("duration_ms")
        dur_s = f"{dur:9.3f} ms" if dur is not None else "         --"
        kind = "event" if r.get("event") else "span "
        lines.append(
            f"  {dt}  {r['origin']:<10} {kind} {dur_s}  {r['name']}"
        )
    hops = trace.get("hops") or {}
    if hops:
        lines.append(
            "  hops: " + " | ".join(
                f"{k[:-3]} {v:.1f} ms" for k, v in hops.items()
            )
        )
    return lines


# ------------------------------------------------------- registry merge


def latest_snapshot_report(path: str) -> Tuple[Optional[dict], int]:
    """The newest ``telemetry_snapshot`` report in a replica's periodic
    JSONL export, skipping truncated lines. ``(report, skipped)``."""
    records, skipped = read_jsonl_tolerant(path)
    for rec in reversed(records):
        if rec.get("name") == "telemetry_snapshot" and isinstance(
            rec.get("report"), dict
        ):
            return rec["report"], skipped
    return None, skipped


def aggregate_registry(
    base_dir: str, n_replicas: Optional[int] = None
) -> dict:
    """One fleet-wide registry view from the per-replica exports:
    counters SUMMED (fleet totals), gauges MAXED on value and peak (the
    worst replica is the capacity question), with the per-replica
    sources kept alongside. A replica with no readable export lands in
    ``gaps`` — the merge SKIPS it and says so, never averages around it
    silently. Prefers the periodic ``replica_<i>_telemetry.jsonl``
    snapshot (fresher than a fault dump); falls back to the latest
    flight dump's embedded report."""
    collected_idx: set = set()
    try:
        for name in os.listdir(base_dir):
            m = _REPLICA_ANY_RE.match(name)
            if m:
                collected_idx.add(int(m.group(1)))
    except OSError:
        pass
    if n_replicas is not None:
        collected_idx |= set(range(int(n_replicas)))
    counters: Dict[str, float] = {}
    gauges: Dict[str, dict] = {}
    per_replica: Dict[int, Optional[dict]] = {}
    gaps: List[int] = []
    skipped_lines = 0
    for idx in sorted(collected_idx):
        report, skipped = latest_snapshot_report(
            os.path.join(base_dir, f"replica_{idx}_telemetry.jsonl")
        )
        skipped_lines += skipped
        if report is None:
            dump, _ = _latest_parsable_dump(_dumps_under(
                os.path.join(base_dir, f"replica_{idx}_flight")
            ))
            if dump is not None and isinstance(dump.get("report"), dict):
                report = dump["report"]
        if report is None:
            gaps.append(idx)
            per_replica[idx] = None
            continue
        metrics = report.get("metrics") or {}
        per_replica[idx] = metrics
        for name, v in (metrics.get("counters") or {}).items():
            try:
                counters[name] = counters.get(name, 0) + float(v)
            except (TypeError, ValueError):
                continue
        for name, g in (metrics.get("gauges") or {}).items():
            if not isinstance(g, dict):
                continue
            cur = gauges.setdefault(
                name, {"value": float("-inf"), "peak": float("-inf")}
            )
            for k in ("value", "peak"):
                try:
                    cur[k] = max(cur[k], float(g.get(k)))
                except (TypeError, ValueError):
                    continue
    gauges = {
        k: {
            kk: (None if vv == float("-inf") else vv)
            for kk, vv in g.items()
        }
        for k, g in gauges.items()
    }
    counters = {
        k: int(v) if v == int(v) else v for k, v in counters.items()
    }
    return {
        "counters": counters,
        "gauges": gauges,
        "per_replica": per_replica,
        "replicas": sorted(i for i in per_replica if per_replica[i]),
        "gaps": gaps,
        "skipped_lines": skipped_lines,
    }
