"""Fault flight recorder: one atomic JSON dump of the recent past on
every fault trigger (docs/OBSERVABILITY.md "Flight recorder").

PR 11's producers keep a bounded ring of spans and events in memory —
exactly the evidence a postmortem needs, and exactly the evidence that
evaporates when the process exits 75/76 or an operator restarts it. The
flight recorder closes that gap: on a fault trigger it snapshots

- the span/event ring (the recent timeline, correlation attrs intact),
- the full registry snapshot (counters/gauges/histograms),
- health states and SLO verdicts (the consumer half's view),
- the mesh + precision-policy fingerprints (harvested from the most
  recent dispatch span — the compiled-program identity the fault ran
  under),

into one ``flight_<trigger>_<ts>.json`` written atomically (tmp +
``os.replace``: a poller or a second trigger never sees a torn file).

Trigger matrix (the producers call ``Telemetry.flight_dump``):

| trigger | site |
|---|---|
| ``poison_quarantine``   | FlowServer dispatch-time NaN isolation |
| ``stream_anomaly_reset``| StreamEngine in-graph reset delivered |
| ``sentinel_halt``       | train.py divergence halt (exit 76) |
| ``preemption_drain``    | serve.py / train.py SIGTERM drain (exit 75) |
| ``guard_violation``     | analysis/guards.py intercepted implicit pull |
| ``slo_page``            | SloEngine page edge |

Bounded by construction, like every telemetry structure: per-trigger
rate limiting (``min_interval_s`` — a poison storm leaves the first
dump and a suppression count, not a full disk) and a dump-file cap
(``max_dumps`` — oldest dumps are deleted). A dump failure is counted
(``flight_dump_failed_total``), never raised: the recorder reports on
faults, it must never cause one.

``scripts/postmortem.py`` reassembles a request/stream journey from a
dump (+ optionally a ``--telemetry_jsonl`` snapshot file) using the
same correlation matching as ``SpanTracer.for_attr`` —
:func:`match_records` is that matcher, shared so the offline tool and
the in-memory tracer can never drift.

Like the rest of ``observability/``: pure stdlib, host-only (JGL010) —
everything dumped is already host data.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

DEFAULT_MAX_DUMPS = 16
DEFAULT_MIN_INTERVAL_S = 5.0

FLIGHT_ENV = "RAFT_NCUP_FLIGHT_DIR"


def match_records(records: List[dict], **match) -> List[dict]:
    """Correlation query over dumped (or live) ring records — the
    ``SpanTracer.for_attr`` semantics, shared with scripts/postmortem.py:
    a record matches when every given key equals the record's attr, is
    contained in a list-valued attr, or is contained in the PLURAL form
    of the attr (``request_id=12`` matches a batch span's
    ``request_ids`` list)."""
    out = []
    for r in records:
        attrs = r.get("attrs", {})
        ok = True
        for k, v in match.items():
            got = attrs.get(k)
            if got == v:
                continue
            if isinstance(got, list) and v in got:
                continue
            plural = attrs.get(k + "s")
            if isinstance(plural, list) and v in plural:
                continue
            ok = False
            break
        if ok:
            out.append(r)
    return out


def harvest_fingerprints(records: List[dict]) -> Dict[str, object]:
    """The mesh/policy fingerprints of the most recent dispatch: scan
    the ring backwards for the last record carrying both attrs (the
    serve/stream dispatch spans always do)."""
    for r in reversed(records):
        attrs = r.get("attrs", {})
        if "mesh" in attrs and "policy" in attrs:
            return {"mesh": attrs["mesh"], "policy": attrs["policy"]}
    return {}


class FlightRecorder:
    """Bounded, rate-limited fault dump writer for one telemetry hub."""

    def __init__(
        self,
        directory: str,
        max_dumps: int = DEFAULT_MAX_DUMPS,
        min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
        clock: Callable[[], float] = time.monotonic,
        walltime: Callable[[], float] = time.time,
    ):
        self.directory = directory
        self.max_dumps = max(1, int(max_dumps))
        self.min_interval_s = float(min_interval_s)
        self._clock = clock
        self._walltime = walltime
        self._last_by_trigger: Dict[str, float] = {}
        self._seq = 0
        self.dumps = 0
        self.suppressed = 0
        self.failed = 0
        self._lock = threading.Lock()

    def record(self, trigger: str, tel, **context) -> Optional[str]:
        """Write one dump for ``trigger``; returns the path, or None
        when rate-limited or the write failed (both counted, both also
        visible as registry counters through the hub)."""
        trigger = str(trigger)
        now = self._clock()
        with self._lock:
            last = self._last_by_trigger.get(trigger)
            if last is not None and now - last < self.min_interval_s:
                self.suppressed += 1
                if tel is not None:
                    tel.inc("flight_dump_suppressed_total")
                return None
            self._last_by_trigger[trigger] = now
            self._seq += 1
            seq = self._seq
        path = None
        try:
            path = self._write(trigger, seq, tel, context)
        except OSError as e:
            with self._lock:
                self.failed += 1
                # Re-open the rate-limit window: a transient write
                # failure must not suppress the NEXT fault's dump for
                # min_interval_s — writing dumps is the recorder's one
                # job, the limiter only throttles successes.
                if self._last_by_trigger.get(trigger) == now:
                    if last is None:
                        del self._last_by_trigger[trigger]
                    else:
                        self._last_by_trigger[trigger] = last
            if tel is not None:
                # The point event auto-feeds flight_dump_failed_total.
                tel.event("flight_dump_failed", trigger=trigger,
                          error=repr(e))
            return None
        with self._lock:
            self.dumps += 1
        if tel is not None:
            # The point event auto-feeds flight_dump_total.
            tel.event("flight_dump", trigger=trigger, path=path)
        return path

    def _write(self, trigger: str, seq: int, tel, context: dict) -> str:
        os.makedirs(self.directory, exist_ok=True)
        wall = self._walltime()
        ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime(wall))
        safe = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in trigger
        )
        fname = f"flight_{safe}_{ts}_{seq:04d}.json"
        path = os.path.join(self.directory, fname)
        records = tel.tracer.records() if tel is not None else []
        # Import here, not at module top: export.py imports this module
        # (hub construction), and telemetry_report lives there.
        from raft_ncup_tpu.observability.export import telemetry_report

        dump = {
            "flight_recorder_version": 1,
            "trigger": trigger,
            "time_unix_s": round(wall, 3),
            "context": {k: context[k] for k in sorted(context)},
            "fingerprints": harvest_fingerprints(records),
            "report": (
                telemetry_report(tel) if tel is not None else None
            ),
            "spans": records,
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(dump, fh)
            fh.write("\n")
        os.replace(tmp, path)  # atomic: a poller never sees a torn dump
        self._enforce_cap()
        return path

    def _enforce_cap(self) -> None:
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith("flight_") and n.endswith(".json")
            )
        except OSError:
            return
        # Names sort by (trigger, timestamp, seq); age order needs mtime.
        if len(names) <= self.max_dumps:
            return
        paths = [os.path.join(self.directory, n) for n in names]
        paths.sort(key=lambda p: (os.path.getmtime(p), p))
        for p in paths[: len(paths) - self.max_dumps]:
            try:
                os.remove(p)
            except OSError:
                pass  # racing pollers/cleaners; the cap is best-effort

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "directory": self.directory,
                "dumps": self.dumps,
                "suppressed": self.suppressed,
                "failed": self.failed,
            }


def load_dump(path: str) -> dict:
    """Read one flight dump (postmortem entry point; validates the
    version field so a truncated/foreign file fails loudly)."""
    with open(path, encoding="utf-8") as fh:
        dump = json.load(fh)
    if dump.get("flight_recorder_version") != 1:
        raise ValueError(
            f"{path}: not a flight-recorder dump (version "
            f"{dump.get('flight_recorder_version')!r})"
        )
    return dump
