"""Declarative SLOs evaluated host-side with classic multi-window
burn-rate alerting (docs/OBSERVABILITY.md "SLO burn rate").

The multi-accelerator-abstraction argument (PAPERS.md, arXiv:2606.11390
— one declarative object everything reads) applied to service
objectives: an :class:`SloSpec` is declared ONCE (frozen, pure data) and
the server's degrade decisions, the bench rows, the healthz file, and
``flip_recommendations`` all read the SAME verdicts instead of each
re-deriving "is this window healthy" from raw counters.

Burn-rate math (the SRE-workbook discipline, scaled): an SLO with
*objective* ``o`` (good fraction, e.g. 0.99) has an error budget
``1 - o``; the **burn rate** over a window is

    burn(w) = bad_fraction(w) / (1 - o)

— 1.0 means the budget is being consumed exactly at the sustainable
rate, 14.4 means a 30-day budget gone in 2 days. A spec **pages** only
when BOTH its fast window (default 5 m) and its slow window (default
1 h) burn at or above ``page_burn``: the fast window makes the page
responsive, the slow window keeps a single bad batch from paging and a
page from clearing the instant one good batch lands. Windows scale
(``SloSpec.scaled`` / the engine's ``window_scale``) so CPU tests and
bench windows exercise the same code path in seconds, driven by an
injectable fake clock.

Three SLI shapes cover the declared objectives (p99 latency, shed rate,
error rate, slot occupancy):

- ``ratio``  — bad-event counter over total-event counter (shed rate,
  error rate): windowed via cumulative-counter deltas;
- ``latency`` — fraction of a ``*_ms`` histogram's observations above
  ``threshold_ms`` (the p99-latency objective re-expressed as a ratio:
  "≤ 1% of requests over the threshold" IS "p99 ≤ threshold"), windowed
  via bucket-cumulative deltas — no raw samples re-read;
- ``gauge``  — fraction of evaluation samples where a gauge exceeds
  ``max_value`` (slot occupancy): the engine's own sampling cadence is
  the time base.

Verdicts drive the loop closed: a page edge flips the subsystem's
:mod:`health` tracker READY → DEGRADED, feeds
``IterationBudgetController`` as the second degrade input (telemetry
drives the anytime knob instead of just watching it), triggers a flight
recorder dump, and lands as an ``slo_page`` ring event; a clean
re-evaluation clears the page and restores READY.

Like the rest of ``observability/``: pure stdlib, host-only (JGL010) —
everything here reads host counters the producers already maintain.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from raft_ncup_tpu.observability.health import READY

DEFAULT_FAST_WINDOW_S = 300.0  # the classic 5m fast window
DEFAULT_SLOW_WINDOW_S = 3600.0  # the classic 1h slow window
DEFAULT_PAGE_BURN = 14.4  # 30-day budget in ~2 days

_SLI_KINDS = ("ratio", "latency", "gauge")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One frozen service-level objective. Pure data: the engine does
    all the reading; specs can be declared at import time and shared by
    server, bench, and flip_recommendations."""

    name: str
    subsystem: str  # health-tracker key: "serve" | "stream" | "train"
    sli: str  # "ratio" | "latency" | "gauge"
    objective: float  # good fraction target in [0, 1)
    # sli == "ratio": bad/total cumulative counters.
    bad: str = ""
    total: str = ""
    # sli == "latency": histogram ({stage}_ms) + threshold.
    histogram: str = ""
    threshold_ms: float = 0.0
    # sli == "gauge": gauge name + max healthy value.
    gauge: str = ""
    max_value: float = 0.0
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    page_burn: float = DEFAULT_PAGE_BURN
    # Minimum events (ratio/latency: total-counter delta; gauge: samples)
    # in the FAST window before a verdict can page: a single bad request
    # in an otherwise idle window is noise, not an outage.
    min_events: int = 4

    def __post_init__(self) -> None:
        if self.sli not in _SLI_KINDS:
            raise ValueError(
                f"slo {self.name}: sli must be one of {_SLI_KINDS}, "
                f"got {self.sli!r}"
            )
        if not 0.0 <= self.objective < 1.0:
            raise ValueError(
                f"slo {self.name}: objective must be in [0, 1), got "
                f"{self.objective} (1.0 leaves a zero error budget — "
                "burn rate would be undefined)"
            )
        if not 0.0 < self.fast_window_s < self.slow_window_s:
            raise ValueError(
                f"slo {self.name}: want 0 < fast_window_s < "
                f"slow_window_s, got {self.fast_window_s}/"
                f"{self.slow_window_s}"
            )
        needed = {
            "ratio": (self.bad, self.total),
            "latency": (self.histogram, self.threshold_ms),
            "gauge": (self.gauge,),
        }[self.sli]
        if not all(needed):
            raise ValueError(
                f"slo {self.name}: sli {self.sli!r} requires "
                "its metric fields to be set"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def scaled(self, window_scale: float) -> "SloSpec":
        """The same objective over proportionally shrunk windows (test /
        bench determinism; 1.0 returns self)."""
        if window_scale == 1.0:
            return self
        return dataclasses.replace(
            self,
            fast_window_s=self.fast_window_s * window_scale,
            slow_window_s=self.slow_window_s * window_scale,
        )


def serve_slos(
    window_scale: float = 1.0,
    p99_ms: float = 2000.0,
) -> Tuple[SloSpec, ...]:
    """The serving tier's declared objectives: 99% of requests neither
    shed nor over the latency threshold, 99.9% not errored server-side.
    Declared once; FlowServer, bench, serve.py, and flip all read the
    verdicts."""
    specs = (
        SloSpec(
            name="serve_shed_rate", subsystem="serve", sli="ratio",
            objective=0.99,
            bad="serve_requests_shed_total",
            total="serve_requests_submitted_total",
        ),
        SloSpec(
            name="serve_error_rate", subsystem="serve", sli="ratio",
            objective=0.999,
            bad="serve_requests_error_total",
            total="serve_requests_submitted_total",
        ),
        SloSpec(
            name="serve_p99_latency", subsystem="serve", sli="latency",
            objective=0.99,
            histogram="serve_e2e_ms", threshold_ms=p99_ms,
        ),
    )
    return tuple(s.scaled(window_scale) for s in specs)


def stream_slos(
    capacity: int,
    window_scale: float = 1.0,
    p99_ms: float = 2000.0,
) -> Tuple[SloSpec, ...]:
    """The streaming tier's declared objectives; ``capacity`` sizes the
    slot-occupancy bound (sustained ≥ 90% occupancy means stream
    admission is about to shed — the router should spread load)."""
    specs = (
        SloSpec(
            name="stream_shed_rate", subsystem="stream", sli="ratio",
            objective=0.99,
            bad="stream_frames_shed_total",
            total="stream_frames_submitted_total",
        ),
        SloSpec(
            name="stream_error_rate", subsystem="stream", sli="ratio",
            objective=0.999,
            bad="stream_frames_error_total",
            total="stream_frames_submitted_total",
        ),
        SloSpec(
            name="stream_p99_latency", subsystem="stream", sli="latency",
            objective=0.99,
            histogram="stream_e2e_ms", threshold_ms=p99_ms,
        ),
        SloSpec(
            name="stream_slot_occupancy", subsystem="stream", sli="gauge",
            # Gauge SLIs saturate at bad_fraction 1.0, so the page must
            # be reachable: objective 0.95 caps burn at 1.0/0.05 = 20
            # (> page_burn 14.4 — a table pinned near-full for both
            # windows pages; objective 0.9 would cap at 10 and could
            # NEVER page, silently).
            objective=0.95,
            gauge="stream_slot_occupancy",
            max_value=max(1.0, 0.9 * capacity),
        ),
    )
    return tuple(s.scaled(window_scale) for s in specs)


class SloVerdict:
    """One spec's evaluation result (immutable snapshot)."""

    __slots__ = (
        "name", "subsystem", "page", "burn_fast", "burn_slow",
        "bad_fraction_fast", "events_fast", "objective",
    )

    def __init__(self, name, subsystem, page, burn_fast, burn_slow,
                 bad_fraction_fast, events_fast, objective):
        self.name = name
        self.subsystem = subsystem
        self.page = page
        self.burn_fast = burn_fast
        self.burn_slow = burn_slow
        self.bad_fraction_fast = bad_fraction_fast
        self.events_fast = events_fast
        self.objective = objective

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "subsystem": self.subsystem,
            "page": self.page,
            "burn_fast": round(self.burn_fast, 3),
            "burn_slow": round(self.burn_slow, 3),
            "bad_fraction_fast": round(self.bad_fraction_fast, 5),
            "events_fast": self.events_fast,
            "objective": self.objective,
        }


# Sample-ring size at which resolution halves (see SloEngine.__init__).
_RING_CAP = 4096


class SloEngine:
    """Evaluate a fixed spec set against a hub's registry on a cadence.

    ``evaluate()`` is the ONLY mutation: it samples the registry (host
    counters — never a device value), appends to bounded per-spec sample
    rings, computes fast/slow burn rates, publishes
    ``slo_{name}_burn_fast``/``_burn_slow`` gauges, and on page EDGES
    emits ``slo_page``/``slo_clear`` events, flips the subsystem's
    health tracker, and triggers a flight dump. It is called by
    ``PeriodicSnapshot`` on its cadence in production and directly (with
    a fake clock) in tests — same code path, deterministic.
    """

    def __init__(
        self,
        specs: Sequence[SloSpec],
        telemetry,
        clock: Callable[[], float] = time.monotonic,
    ):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo names: {names}")
        self.specs = tuple(specs)
        self._tel = telemetry
        self._clock = clock
        # Per-spec ring of (t, bad_cumulative, total_cumulative) — for
        # gauges, (t, bad01, 1). Pruned to the slow window each
        # evaluate(); beyond _RING_CAP samples the ring HALVES its
        # resolution instead of evicting its oldest entry — a blind cap
        # at a sub-second cadence (fleet replicas tick at 0.25 s) would
        # silently shrink the declared 1 h slow window to
        # cap x cadence seconds, and burn_slow would page on a horizon
        # the declared window damps.
        self._samples: Dict[str, deque] = {
            s.name: deque() for s in self.specs
        }
        self._paging: Dict[str, bool] = {s.name: False for s in self.specs}
        self._verdicts: Dict[str, SloVerdict] = {}
        self._pages_total = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ sampling

    def _sample(self, spec: SloSpec) -> Tuple[float, float]:
        """Current (bad_cumulative, total_cumulative) for one spec."""
        reg = self._tel.registry
        if spec.sli == "ratio":
            bad = reg.get(spec.bad)
            total = reg.get(spec.total)
            return (
                float(bad.value) if bad is not None else 0.0,
                float(total.value) if total is not None else 0.0,
            )
        if spec.sli == "latency":
            hist = reg.get(spec.histogram)
            if hist is None or not hasattr(hist, "buckets_ms"):
                return 0.0, 0.0
            snap = hist.snapshot()
            total = float(snap["count"])
            # Observations at or under the smallest bucket bound >= the
            # threshold count as good (bucket resolution is the
            # measurement resolution; DEFAULT_BUCKETS_MS straddles the
            # serving latencies).
            good = 0.0
            for upper, c in zip(hist.buckets_ms, snap["buckets"].values()):
                if upper <= spec.threshold_ms:
                    good += c
            return total - good, total
        # gauge: one 0/1 sample per evaluation tick.
        g = reg.get(spec.gauge)
        value = float(g.value) if g is not None else 0.0
        return (1.0 if value > spec.max_value else 0.0), 1.0

    @staticmethod
    def _window_burn(
        samples: List[Tuple[float, float, float]],
        now: float,
        window_s: float,
        spec: SloSpec,
        is_gauge: bool,
    ) -> Tuple[float, float, float]:
        """(burn, bad_fraction, events) over [now - window_s, now]."""
        in_window = [s for s in samples if s[0] >= now - window_s]
        if not in_window:
            return 0.0, 0.0, 0.0
        if is_gauge:
            # Each evaluation contributed one 0/1 observation.
            events = float(len(in_window))
            bad = float(sum(s[1] for s in in_window))
        else:
            # Cumulative counters: delta from the window's oldest sample
            # to its newest (the current one).
            base, cur = in_window[0], in_window[-1]
            bad = cur[1] - base[1]
            events = cur[2] - base[2]
        if events <= 0:
            return 0.0, 0.0, 0.0
        frac = max(0.0, bad) / events
        return frac / spec.budget, frac, events

    # ---------------------------------------------------------- evaluation

    def evaluate(self, now: Optional[float] = None) -> Dict[str, SloVerdict]:
        """One evaluation pass; returns the fresh verdicts by name."""
        now = self._clock() if now is None else float(now)
        edges: List[Tuple[SloSpec, bool, SloVerdict]] = []
        with self._lock:
            for spec in self.specs:
                bad_cum, total_cum = self._sample(spec)
                ring = self._samples[spec.name]
                ring.append((now, bad_cum, total_cum))
                # Prune beyond the slow window (keep the ring tight; the
                # oldest in-window sample is the delta base).
                while ring and ring[0][0] < now - spec.slow_window_s:
                    ring.popleft()
                if len(ring) > _RING_CAP:
                    # Memory bound WITHOUT shrinking the window: drop
                    # every other sample, keeping the oldest (the slow
                    # delta base) and the newest. Counter SLIs are
                    # cumulative so deltas are exact at any resolution;
                    # gauge SLIs keep a representative 0/1 sample mix.
                    kept = list(ring)[::2]
                    if kept[-1] != ring[-1]:
                        kept.append(ring[-1])
                    ring.clear()
                    ring.extend(kept)
                samples = list(ring)
                is_gauge = spec.sli == "gauge"
                burn_f, frac_f, events_f = self._window_burn(
                    samples, now, spec.fast_window_s, spec, is_gauge
                )
                burn_s, _, _ = self._window_burn(
                    samples, now, spec.slow_window_s, spec, is_gauge
                )
                page = (
                    events_f >= spec.min_events
                    and burn_f >= spec.page_burn
                    and burn_s >= spec.page_burn
                )
                verdict = SloVerdict(
                    spec.name, spec.subsystem, page, burn_f, burn_s,
                    frac_f, int(events_f), spec.objective,
                )
                self._verdicts[spec.name] = verdict
                was = self._paging[spec.name]
                self._paging[spec.name] = page
                if page != was:
                    edges.append((spec, page, verdict))
                    if page:
                        self._pages_total += 1
            paging_subsystems = {
                s.subsystem for s in self.specs if self._paging[s.name]
            }
            verdicts_now = dict(self._verdicts)
        # Publish outside the lock (the hub takes its own locks).
        for spec in self.specs:
            v = verdicts_now[spec.name]
            self._tel.gauge_set(f"slo_{spec.name}_burn_fast",
                                round(v.burn_fast, 3))
            self._tel.gauge_set(f"slo_{spec.name}_burn_slow",
                                round(v.burn_slow, 3))
        for spec, page, v in edges:
            if page:
                self._tel.event(
                    "slo_page", slo=spec.name, subsystem=spec.subsystem,
                    burn_fast=round(v.burn_fast, 3),
                    burn_slow=round(v.burn_slow, 3),
                )
                self._tel.flight_dump(
                    "slo_page", slo=spec.name,
                    subsystem=spec.subsystem,
                    burn_fast=round(v.burn_fast, 3),
                )
            else:
                self._tel.event(
                    "slo_clear", slo=spec.name, subsystem=spec.subsystem,
                )
                if spec.subsystem not in paging_subsystems:
                    self._tel.health(spec.subsystem).ready(
                        f"slo {spec.name} recovered"
                    )
        # Health degrade is RE-ASSERTED every evaluation, not only on
        # page edges: a page that fires while the tracker is still
        # STARTING/WARMING (or while a fresh tracker replaced the old
        # one — re-entrant drivers) is an illegal-edge no-op then, and
        # an edge-only degrade would leave health READY for the whole
        # ongoing page. Idempotent when already DEGRADED.
        for sub in paging_subsystems:
            tr = self._tel.health(sub)
            if tr.state == READY:
                worst = max(
                    (
                        verdicts_now[s.name]
                        for s in self.specs
                        if s.subsystem == sub
                        and verdicts_now[s.name].page
                    ),
                    key=lambda v: v.burn_fast,
                    default=None,
                )
                if worst is not None:
                    tr.degrade(
                        f"slo {worst.name} burning "
                        f"{worst.burn_fast:.1f}x fast / "
                        f"{worst.burn_slow:.1f}x slow"
                    )
        return verdicts_now

    # ------------------------------------------------------------ queries

    def paging(self, subsystem: Optional[str] = None) -> bool:
        """Is any spec (of ``subsystem``, or at all) currently paging?
        The budget controller's second degrade input — one lock, one
        dict scan, no device work."""
        with self._lock:
            for spec in self.specs:
                if subsystem is not None and spec.subsystem != subsystem:
                    continue
                if self._paging[spec.name]:
                    return True
            return False

    @property
    def pages_total(self) -> int:
        with self._lock:
            return self._pages_total

    def verdicts(self) -> Dict[str, SloVerdict]:
        with self._lock:
            return dict(self._verdicts)

    def snapshot(self) -> dict:
        """JSON-able view for telemetry_report()/healthz/bench rows."""
        with self._lock:
            return {
                "specs": [s.name for s in self.specs],
                "verdicts": {
                    k: v.to_dict() for k, v in sorted(
                        self._verdicts.items()
                    )
                },
                "paging": sorted({
                    s.subsystem for s in self.specs
                    if self._paging[s.name]
                }),
                "pages_total": self._pages_total,
            }
