"""Telemetry export layer: the process-wide hub, a bounded JSONL event
sink, periodic snapshots, a Prometheus text dump, and the one
``telemetry_report()`` dict that ``serve.py --report`` and ``bench.py``
both read.

The :class:`Telemetry` hub bundles one :class:`MetricsRegistry` and one
:class:`SpanTracer` behind no-op-when-disabled facade methods — every
producer call site does ``tel.inc(...)`` / ``with tel.span(...)``
unconditionally, and a disabled hub reduces each to a bool check. That
is also how the bench measures telemetry's own overhead honestly: the
serve row runs the SAME warm window with the hub enabled and disabled
and records the p50 delta (docs/PERF.md; the acceptance bar is <= 3% of
p50 on CPU).

One process-wide default hub (:func:`get_telemetry`) is what the serving
and streaming constructors bind when not handed an explicit hub; tests
and bench windows pass their own for isolation. ``RAFT_NCUP_TELEMETRY=0``
disables the default hub at creation.

Like the rest of ``observability/``: pure stdlib, no jax (JGL010) — the
sink writes host dicts, the snapshot thread reads host counters, and
nothing here can ever touch a device array or add a sync.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from raft_ncup_tpu.observability.spans import (
    NOOP_SPAN,
    SpanTracer,
)
from raft_ncup_tpu.observability.telemetry import MetricsRegistry

TELEMETRY_ENV = "RAFT_NCUP_TELEMETRY"


class Telemetry:
    """Registry + tracer behind one enable flag. The facade methods are
    the ONLY producer API the rest of the codebase uses, so flipping
    ``enabled`` turns the entire telemetry surface on/off at once."""

    def __init__(
        self,
        enabled: bool = True,
        span_capacity: int = 2048,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = MetricsRegistry()
        self.tracer = SpanTracer(
            self.registry, capacity=span_capacity, clock=clock
        )
        self.enabled = bool(enabled)

    # ---------------------------------------------------------- producers

    def inc(self, name: str, n=1) -> None:
        if self.enabled:
            self.registry.counter(name).inc(n)

    def gauge_set(self, name: str, value) -> None:
        if self.enabled:
            self.registry.gauge(name).set(value)

    def observe_ms(self, name: str, ms, **attrs) -> None:
        if self.enabled:
            self.tracer.observe_ms(name, ms, **attrs)

    def event(self, name: str, **attrs) -> None:
        if self.enabled:
            self.tracer.event(name, **attrs)

    def span(self, name: str, **attrs):
        if self.enabled:
            return self.tracer.span(name, **attrs)
        return NOOP_SPAN

    # ---------------------------------------------------------- consumers

    def counter_value(self, name: str) -> float:
        m = self.registry.get(name)
        return 0.0 if m is None else m.value

    def report(self) -> dict:
        return telemetry_report(self)

    def reset(self) -> None:
        self.registry.reset()
        self.tracer.reset()


_default_lock = threading.Lock()
_default: Optional[Telemetry] = None


def get_telemetry() -> Telemetry:
    """The process-wide default hub (created on first use; honors
    ``RAFT_NCUP_TELEMETRY=0``)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Telemetry(
                enabled=os.environ.get(TELEMETRY_ENV, "1") != "0"
            )
        return _default


def set_telemetry(tel: Optional[Telemetry]) -> Optional[Telemetry]:
    """Swap the process default (tests/bench isolation); returns the
    previous hub so callers can restore it."""
    global _default
    with _default_lock:
        prev, _default = _default, tel
        return prev


def telemetry_report(tel: Optional[Telemetry] = None) -> dict:
    """The one snapshot dict every consumer reads: full registry
    snapshot, per-stage latency breakdown, and ring accounting."""
    tel = tel or get_telemetry()
    return {
        "enabled": tel.enabled,
        "metrics": tel.registry.snapshot(),
        "stages": tel.tracer.stage_summary(),
        "spans_recorded": len(tel.tracer.records()),
        "spans_dropped": tel.tracer.dropped,
    }


def prometheus_text(tel: Optional[Telemetry] = None) -> str:
    """Prometheus text exposition of the hub's registry."""
    return (tel or get_telemetry()).registry.prometheus_text()


class JsonlSink:
    """Bounded JSONL event sink: one JSON object per line, hard-capped
    at ``max_events`` lines — beyond the cap events are DROPPED and
    counted (``dropped``), never buffered or grown: an event sink that
    can fill a disk is an outage amplifier, and the span ring upstream
    already keeps the recent past. Thread-safe; ``close()`` appends a
    final ``jsonl_sink_closed`` record carrying the drop count."""

    def __init__(self, path: str, max_events: int = 100_000):
        self._path = path
        self._max = max(1, int(max_events))
        self._written = 0
        self.dropped = 0
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8")

    def write(self, record: dict) -> bool:
        """Append one event; False (and counted) once the cap is hit."""
        with self._lock:
            if self._fh.closed:
                return False
            if self._written >= self._max:
                self.dropped += 1
                return False
            self._fh.write(json.dumps(record) + "\n")
            self._written += 1
            return True

    def flush(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh.closed:
                return
            if self.dropped:
                self._fh.write(json.dumps({
                    "name": "jsonl_sink_closed",
                    "dropped": self.dropped,
                }) + "\n")
            self._fh.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PeriodicSnapshot:
    """Background thread writing ``telemetry_report`` snapshots to a
    :class:`JsonlSink` every ``interval_s`` (plus one final snapshot at
    ``stop()``), stamped with wall time — the long-running-server export
    path (serve.py ``--telemetry_jsonl``)."""

    def __init__(
        self,
        tel: Telemetry,
        sink: JsonlSink,
        interval_s: float = 10.0,
    ):
        self._tel = tel
        self._sink = sink
        self._interval = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-snapshot", daemon=True
        )

    def start(self) -> "PeriodicSnapshot":
        self._thread.start()
        return self

    def _write_one(self) -> None:
        self._sink.write({
            "name": "telemetry_snapshot",
            "time_unix_s": round(time.time(), 3),
            "report": telemetry_report(self._tel),
        })
        self._sink.flush()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._write_one()

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join()
        self._write_one()

    def __enter__(self) -> "PeriodicSnapshot":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
