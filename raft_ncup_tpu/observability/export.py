"""Telemetry export layer: the process-wide hub, a bounded JSONL event
sink, periodic snapshots, a Prometheus text dump, and the one
``telemetry_report()`` dict that ``serve.py --report`` and ``bench.py``
both read.

The :class:`Telemetry` hub bundles one :class:`MetricsRegistry` and one
:class:`SpanTracer` behind no-op-when-disabled facade methods — every
producer call site does ``tel.inc(...)`` / ``with tel.span(...)``
unconditionally, and a disabled hub reduces each to a bool check. That
is also how the bench measures telemetry's own overhead honestly: the
serve row runs the SAME warm window with the hub enabled and disabled
and records the p50 delta (docs/PERF.md; the acceptance bar is <= 3% of
p50 on CPU).

One process-wide default hub (:func:`get_telemetry`) is what the serving
and streaming constructors bind when not handed an explicit hub; tests
and bench windows pass their own for isolation. ``RAFT_NCUP_TELEMETRY=0``
disables the default hub at creation.

Like the rest of ``observability/``: pure stdlib, no jax (JGL010) — the
sink writes host dicts, the snapshot thread reads host counters, and
nothing here can ever touch a device array or add a sync.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional

from raft_ncup_tpu.observability.flight import FLIGHT_ENV, FlightRecorder
from raft_ncup_tpu.utils.knobs import knob_enabled, knob_raw
from raft_ncup_tpu.observability.health import HealthTracker, overall_state
from raft_ncup_tpu.observability.spans import (
    NOOP_SPAN,
    SpanTracer,
)
from raft_ncup_tpu.observability.telemetry import MetricsRegistry

TELEMETRY_ENV = "RAFT_NCUP_TELEMETRY"

# Process start (unix wall clock), for the healthz replica-identity
# block: a router distinguishing "same replica, later" from "restarted
# replica reusing the pid" needs the start time, not just the pid.
_PROCESS_START_UNIX_S = round(time.time(), 3)


class Telemetry:
    """Registry + tracer behind one enable flag, plus the consumer half
    (docs/OBSERVABILITY.md): per-subsystem :class:`HealthTracker`s, an
    optional attached :class:`~raft_ncup_tpu.observability.slo.SloEngine`
    (``slo``), and an optional :class:`FlightRecorder` (``flight``). The
    facade methods are the ONLY producer API the rest of the codebase
    uses, so flipping ``enabled`` turns the entire telemetry surface
    on/off at once — health STATE keeps tracking even when disabled (it
    gates the budget controller and the healthz file: product logic,
    not just an exported number), but its gauges/events are suppressed
    like every other producer call."""

    def __init__(
        self,
        enabled: bool = True,
        span_capacity: int = 2048,
        clock: Callable[[], float] = time.monotonic,
        flight_dir: Optional[str] = None,
    ):
        self.registry = MetricsRegistry()
        self.tracer = SpanTracer(
            self.registry, capacity=span_capacity, clock=clock
        )
        self.enabled = bool(enabled)
        self.clock = clock
        # Consumer half: health trackers are get-or-create per
        # subsystem; the SLO engine and flight recorder are attached by
        # the driver (serve.py/train.py/bench) that knows the specs/dir.
        self._health: dict = {}
        self._health_lock = threading.Lock()
        self.slo = None
        # Replica identity the healthz file advertises to a fleet router
        # (docs/FLEET.md): producers deposit host facts here — serve.py
        # threads the warmed (shape, batch, iters) executable set and
        # the mesh fingerprint through after warmup. Host values only
        # (JGL010); merged verbatim into every write_healthz payload.
        self.identity: dict = {}
        self.flight = (
            FlightRecorder(flight_dir) if flight_dir else None
        )

    # ---------------------------------------------------------- producers

    def inc(self, name: str, n=1) -> None:
        if self.enabled:
            self.registry.counter(name).inc(n)

    def gauge_set(self, name: str, value) -> None:
        if self.enabled:
            self.registry.gauge(name).set(value)

    def observe_ms(self, name: str, ms, **attrs) -> None:
        if self.enabled:
            self.tracer.observe_ms(name, ms, **attrs)

    def hist_observe(self, name: str, ms) -> None:
        """Registry-histogram-only observation (no ring record): the
        per-request end-to-end latency feed — one histogram append per
        request would be fine, one ring record per request would crowd
        the batch-level spans out of the flight recorder's window."""
        if self.enabled:
            self.registry.histogram(name).observe_ms(ms)

    def event(self, name: str, **attrs) -> None:
        if self.enabled:
            self.tracer.event(name, **attrs)

    def span(self, name: str, **attrs):
        if self.enabled:
            return self.tracer.span(name, **attrs)
        return NOOP_SPAN

    # ------------------------------------------------------ consumer half

    def health(self, subsystem: str, fresh: bool = False) -> HealthTracker:
        """The subsystem's health tracker (created STARTING on first
        use). One tracker per subsystem per hub — the process's answer
        to "is this replica healthy". ``fresh=True`` replaces any
        existing tracker (a re-entrant driver run must start STARTING,
        not inherit a previous run's terminal HALTED)."""
        with self._health_lock:
            tr = self._health.get(subsystem)
            if tr is None or fresh:
                tr = HealthTracker(subsystem, telemetry=self,
                                   clock=self.clock)
                self._health[subsystem] = tr
            return tr

    def health_snapshot(self) -> dict:
        with self._health_lock:
            trackers = dict(self._health)
        return {name: tr.snapshot() for name, tr in sorted(
            trackers.items()
        )}

    def slo_paging(self, subsystem: Optional[str] = None) -> bool:
        """Is an attached SLO engine currently paging (for
        ``subsystem``)? False with no engine — the budget controller's
        second degrade input degrades to pure queue-depth behavior."""
        eng = self.slo
        return False if eng is None else eng.paging(subsystem)

    def flight_dump(self, trigger: str, **context) -> Optional[str]:
        """Trigger a flight-recorder dump (no-op without a recorder or
        when the hub is disabled); returns the dump path or None."""
        rec = self.flight
        if rec is None or not self.enabled:
            return None
        return rec.record(trigger, self, **context)

    def counter_value(self, name: str) -> float:
        m = self.registry.get(name)
        return 0.0 if m is None else m.value

    def report(self) -> dict:
        return telemetry_report(self)

    def reset(self) -> None:
        self.registry.reset()
        self.tracer.reset()


_default_lock = threading.Lock()
_default: Optional[Telemetry] = None


def get_telemetry() -> Telemetry:
    """The process-wide default hub (created on first use; honors
    ``RAFT_NCUP_TELEMETRY=0`` and arms the flight recorder when
    ``RAFT_NCUP_FLIGHT_DIR`` names a directory — the drivers attach one
    explicitly either way)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Telemetry(
                enabled=knob_enabled(TELEMETRY_ENV),
                flight_dir=knob_raw(FLIGHT_ENV) or None,
            )
        return _default


def set_telemetry(tel: Optional[Telemetry]) -> Optional[Telemetry]:
    """Swap the process default (tests/bench isolation); returns the
    previous hub so callers can restore it."""
    global _default
    with _default_lock:
        prev, _default = _default, tel
        return prev


def telemetry_report(tel: Optional[Telemetry] = None) -> dict:
    """The one snapshot dict every consumer reads: full registry
    snapshot, per-stage latency breakdown, ring accounting — and the
    consumer half's verdicts: per-subsystem health states and (when an
    engine is attached) the SLO verdict block."""
    tel = tel or get_telemetry()
    report = {
        "enabled": tel.enabled,
        "metrics": tel.registry.snapshot(),
        "stages": tel.tracer.stage_summary(),
        "spans_recorded": len(tel.tracer.records()),
        "spans_dropped": tel.tracer.dropped,
        "health": tel.health_snapshot(),
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
    }
    if tel.flight is not None:
        report["flight"] = tel.flight.snapshot()
    return report


def write_healthz(
    path: str,
    tel: Optional[Telemetry] = None,
    interval_s: Optional[float] = None,
) -> None:
    """Atomically rewrite the machine-readable health file a fleet
    router polls (serve.py ``--healthz_file``): per-subsystem health
    snapshots, the worst-state headline, the SLO verdict block, the
    drain/halt exit contract (DRAINING rides the existing SIGTERM →
    exit-75 path; HALTED the sentinel → exit-76 one), and the replica
    identity a router routes on — ``pid``, process start time, plus
    whatever the producers deposited in ``Telemetry.identity`` (serve.py
    threads the mesh fingerprint and the warmed ``(shape, batch,
    iters)`` executable set through after warmup; docs/FLEET.md).

    **Staleness contract**: ``interval_s`` is the rewrite cadence the
    writer promises; consumers MUST treat a payload whose
    ``time_unix_s`` is older than ``stale_after_s`` (2x the cadence) as
    a dead replica even if the process lingers — a wedged or SIGSTOPped
    replica keeps its pid but stops heartbeating
    (``fleet/replica.healthz_fresh`` is the reference consumer; schema
    pinned in tests/test_observability.py).

    tmp + ``os.replace`` — a poller never reads a torn file."""
    tel = tel or get_telemetry()
    health = tel.health_snapshot()
    payload = {
        "time_unix_s": round(time.time(), 3),
        "overall": overall_state(health),
        "health": health,
        "slo": tel.slo.snapshot() if tel.slo is not None else None,
        "draining": any(
            s["state"] == "draining" for s in health.values()
        ),
        "exit_contract": {"draining": 75, "halted": 76},
        "pid": os.getpid(),
        "start_time_unix_s": _PROCESS_START_UNIX_S,
        **dict(tel.identity),
    }
    if interval_s is not None:
        payload["interval_s"] = round(float(interval_s), 3)
        payload["stale_after_s"] = round(2.0 * float(interval_s), 3)
    parent = os.path.dirname(path)
    if parent:
        # Same courtesy as the flight recorder: a healthz path in a
        # not-yet-created run dir must not crash the server at startup.
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
        fh.write("\n")
    os.replace(tmp, path)


def prometheus_text(tel: Optional[Telemetry] = None) -> str:
    """Prometheus text exposition of the hub's registry."""
    return (tel or get_telemetry()).registry.prometheus_text()


class JsonlSink:
    """Bounded JSONL event sink: one JSON object per line, hard-capped
    at ``max_events`` lines — beyond the cap events are DROPPED and
    counted (``dropped``), never buffered or grown: an event sink that
    can fill a disk is an outage amplifier, and the span ring upstream
    already keeps the recent past. Thread-safe; ``close()`` appends a
    final ``jsonl_sink_closed`` record carrying the drop count."""

    def __init__(self, path: str, max_events: int = 100_000):
        self._path = path
        self._max = max(1, int(max_events))
        self._written = 0
        self.dropped = 0
        self._lock = threading.Lock()
        self._fh = open(path, "a", encoding="utf-8")

    def write(self, record: dict) -> bool:
        """Append one event; False (and counted) once the cap is hit."""
        with self._lock:
            if self._fh.closed:
                return False
            if self._written >= self._max:
                self.dropped += 1
                return False
            self._fh.write(json.dumps(record) + "\n")
            self._written += 1
            return True

    def flush(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh.closed:
                return
            if self.dropped:
                self._fh.write(json.dumps({
                    "name": "jsonl_sink_closed",
                    "dropped": self.dropped,
                }) + "\n")
            self._fh.close()

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class PeriodicSnapshot:
    """Background thread driving the telemetry cadence every
    ``interval_s``: evaluate the hub's attached SLO engine (so burn
    rates stay fresh without a second timer), write a
    ``telemetry_report`` snapshot to the :class:`JsonlSink`, and rewrite
    the ``healthz_path`` file when configured — plus one final tick at
    ``stop()``. The long-running-server export path (serve.py
    ``--telemetry_jsonl`` / ``--healthz_file``).

    ``sink`` may be None (healthz-only cadence). ``stop()`` before
    ``start()`` is a no-op: a monitor that never ran has nothing final
    to report, and writing a "final" snapshot from it would stamp a
    phantom observation into the sink (regression-pinned in
    tests/test_observability.py).
    """

    def __init__(
        self,
        tel: Telemetry,
        sink: Optional[JsonlSink],
        interval_s: float = 10.0,
        healthz_path: Optional[str] = None,
    ):
        self._tel = tel
        self._sink = sink
        self._interval = max(0.05, float(interval_s))
        self._healthz = healthz_path
        self._started = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-snapshot", daemon=True
        )

    def start(self) -> "PeriodicSnapshot":
        self._started = True
        # First tick immediately: the healthz file must exist before the
        # first interval elapses (a router polling a just-started
        # replica reads STARTING/WARMING, not ENOENT).
        self._write_one()
        self._thread.start()
        return self

    def _write_one(self) -> None:
        if self._tel.slo is not None:
            self._tel.slo.evaluate()
        if self._sink is not None:
            self._sink.write({
                "name": "telemetry_snapshot",
                "time_unix_s": round(time.time(), 3),
                "report": telemetry_report(self._tel),
            })
            self._sink.flush()
        if self._healthz:
            write_healthz(self._healthz, self._tel,
                          interval_s=self._interval)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._write_one()

    def stop(self) -> None:
        """Final tick + teardown. No-op before ``start()`` or after a
        previous ``stop()``. Callers owning a sink must close it AFTER
        this returns (final-snapshot → sink-close ordering): the final
        report of a drained run is the one the postmortem reads."""
        if not self._started or self._stop.is_set():
            return
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join()
        self._write_one()

    def __enter__(self) -> "PeriodicSnapshot":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
