"""Per-subsystem health state machine: the consumer half of the health
story (docs/OBSERVABILITY.md "Health states").

PR 11 gave every subsystem producers — counters, gauges, spans — but a
fleet router asking "is this replica healthy / draining / degraded"
needs one machine-readable answer, not a registry dump to interpret.
:class:`HealthTracker` is that answer: a small validated state machine

    STARTING → WARMING → READY ⇄ DEGRADED → DRAINING → HALTED

whose transitions are driven by exactly two kinds of input:

- **lifecycle calls** from the subsystem that owns the tracker
  (``FlowServer``/``StreamEngine`` construction → STARTING, warmup →
  WARMING → READY, ``drain()`` → DRAINING, a sentinel halt → HALTED);
- **SLO verdicts** computed from the PR 11 registry (``slo.SloEngine``):
  a paging burn rate flips READY → DEGRADED, a clean re-evaluation
  flips it back. No transition ever reads a device array — the state
  derives purely from registry counters and host lifecycle facts.

The READY ⇄ DEGRADED pair is deliberately the only cycle: DEGRADED is a
*serving* state (the anytime iteration budget is coarser, the replica
still answers), DRAINING and HALTED are terminal for the process
(DRAINING is the SIGTERM/exit-75 contract — the fleet router must stop
routing new work here; HALTED is the sentinel/exit-76 contract — do not
requeue without investigation).

Robustness rule: an *illegal* transition is a counted no-op, never an
exception — the health tracker reports on the server; it must never be
able to take the server down. Same-state calls are silent no-ops (drain
is idempotent, SLO evaluations repeat).

Like the rest of ``observability/``: pure stdlib, host-only (JGL010).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

# Canonical state names (lowercase: they travel through JSON reports and
# healthz files the fleet router string-matches on).
STARTING = "starting"
WARMING = "warming"
READY = "ready"
DEGRADED = "degraded"
DRAINING = "draining"
HALTED = "halted"

# Numeric codes for the `{subsystem}_health_state` gauge (a Prometheus
# scraper can alert on `>= DEGRADED` without string labels). Order is
# severity-ish: the healthz "overall" field is the max across subsystems.
STATE_CODES: Dict[str, int] = {
    STARTING: 0,
    WARMING: 1,
    READY: 2,
    DEGRADED: 3,
    DRAINING: 4,
    HALTED: 5,
}

# The legal edges. STARTING → READY exists for subsystems that serve
# without an explicit warmup (the first completed batch marks readiness);
# every state may drain or halt except the two terminals themselves.
ALLOWED_TRANSITIONS: Dict[str, frozenset] = {
    STARTING: frozenset({WARMING, READY, DRAINING, HALTED}),
    WARMING: frozenset({READY, DRAINING, HALTED}),
    READY: frozenset({DEGRADED, DRAINING, HALTED}),
    DEGRADED: frozenset({READY, DRAINING, HALTED}),
    DRAINING: frozenset({HALTED}),
    HALTED: frozenset(),
}

_HISTORY_CAP = 64  # bounded like every other telemetry structure


class HealthTracker:
    """One subsystem's health state, thread-safe, telemetry-publishing.

    ``telemetry`` is the hub the tracker publishes through (gauge
    ``{name}_health_state`` + event ``{name}_health_transition``); the
    STATE itself is tracked even when the hub is disabled — health is
    product logic (it gates the budget controller and the healthz file),
    not just an exported number.
    """

    def __init__(
        self,
        name: str,
        telemetry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.name = name
        self._tel = telemetry
        self._clock = clock
        self._state = STARTING
        self._reason = "created"
        self._since = clock()
        self._history: deque = deque(maxlen=_HISTORY_CAP)
        self._transitions = 0
        self._invalid = 0
        self._lock = threading.Lock()
        self._publish(STARTING)

    # ------------------------------------------------------------ queries

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def reason(self) -> str:
        with self._lock:
            return self._reason

    def history(self) -> List[dict]:
        with self._lock:
            return list(self._history)

    def snapshot(self) -> dict:
        """JSON-able view for report()/healthz/flight dumps."""
        with self._lock:
            return {
                "state": self._state,
                "code": STATE_CODES[self._state],
                "reason": self._reason,
                "since_s": round(self._clock() - self._since, 3),
                "transitions": self._transitions,
                "invalid_transitions": self._invalid,
            }

    # -------------------------------------------------------- transitions

    def to(self, state: str, reason: str = "") -> bool:
        """Attempt a transition; True when the state actually changed.

        Same-state is a silent no-op (False). An illegal edge is a
        COUNTED no-op (False; ``{name}_health_invalid_transition_total``)
        — the tracker must never raise into the serving hot path.
        """
        if state not in STATE_CODES:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            prev = self._state
            if state == prev:
                return False
            if state not in ALLOWED_TRANSITIONS[prev]:
                self._invalid += 1
                if self._tel is not None:
                    self._tel.inc(
                        f"{self.name}_health_invalid_transition_total"
                    )
                return False
            self._state = state
            self._reason = reason
            self._since = self._clock()
            self._transitions += 1
            self._history.append(
                {"from": prev, "to": state, "reason": reason}
            )
        self._publish(state, prev, reason)
        return True

    def _publish(self, state: str, prev: Optional[str] = None,
                 reason: str = "") -> None:
        if self._tel is None:
            return
        self._tel.gauge_set(
            f"{self.name}_health_state", STATE_CODES[state]
        )
        if prev is not None:
            self._tel.event(
                f"{self.name}_health_transition",
                from_state=prev, to_state=state, reason=reason,
            )

    # ------------------------------------------------ convenience helpers

    def warming(self, reason: str = "warmup") -> bool:
        return self.to(WARMING, reason)

    def ready(self, reason: str = "") -> bool:
        """Mark READY from STARTING/WARMING/DEGRADED (the SLO-recovery
        edge shares this helper)."""
        return self.to(READY, reason)

    def degrade(self, reason: str) -> bool:
        return self.to(DEGRADED, reason)

    def draining(self, reason: str = "drain") -> bool:
        return self.to(DRAINING, reason)

    def halted(self, reason: str) -> bool:
        return self.to(HALTED, reason)


def overall_state(snapshots: Dict[str, dict]) -> str:
    """The fleet-router headline across subsystems: the worst (highest-
    code) state among them, READY when nothing is tracked yet."""
    states = [
        s.get("state") for s in snapshots.values()
        if s.get("state") in STATE_CODES
    ]
    if not states:
        return READY
    return max(states, key=lambda s: STATE_CODES[s])
