"""Monotonic-clock span tracer: per-stage latency spans and point
events, carrying correlation IDs through the serving/streaming/inference
machinery.

A **span** is one timed stage (``serve_dispatch``, ``stream_drain``…);
a **point event** is an instant lifecycle fact (``stream_slot_evicted``,
``io_retry``…). Both carry free-form *correlation attributes* — request
id, stream id, batch id, mesh fingerprint, precision-policy name — so a
request's journey through admission → batching → dispatch → drain can be
reassembled from the record ring afterwards (``for_attr``), which is the
debugging primitive the multi-replica/multi-segment ROADMAP items need.

Everything here is host-only stdlib (JGL010): the clock is
``time.monotonic`` (injectable — tests and the serving stack drive it
deterministically), span records live in a bounded ring (old spans fall
off; telemetry must never grow without bound), and attribute values are
validated host scalars/strings — handing a device array to a span is a
``TypeError`` *before* anything could sync (``telemetry.host_number``).

Finishing a span also feeds ``{name}_ms`` in the metrics registry, so
per-stage p50/p99 fall out of the same fixed-bucket histograms the rest
of telemetry uses; a point event feeds ``{name}_total``. xprof-side
stage labels are NOT this module's job — the ``jax.profiler`` named
annotations live with the jitted code they label (``models/raft.py``,
``parallel/step.py``, ``utils/profiling.py``).

**Cross-process traces** (docs/OBSERVABILITY.md "Trace propagation"):
a request whose life spans the fleet's router → replica hop carries a
:class:`TraceContext` — ``trace_id`` (minted once at the fleet edge),
the parent ``span_id``, and the sender→receiver monotonic-clock offset
estimated by the wire handshake (``fleet/router.py``). The context is a
plain JSON-able dict on the wire (an OPTIONAL header field: old peers
ignore it, new peers parse old frames without it), and on each side it
degrades to ordinary correlation attrs (``trace_id=...``) on the spans
that already exist — ``for_attr``/``match_records`` then reassemble one
trace across processes, and ``observability/aggregate.py`` stitches the
exported rings into one tree. Every ring record also stamps ``t_s``
(its start on the producer's monotonic clock) so per-hop deltas are
computable once the clock offsets are known.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from raft_ncup_tpu.observability.telemetry import (
    MetricsRegistry,
    host_number,
)

DEFAULT_SPAN_CAPACITY = 2048

_ATTR_OK_TYPES = (str, bool, type(None))


def new_trace_id() -> str:
    """A fresh 16-hex trace id (host entropy; one per fleet request)."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """A fresh 8-hex span id (parenting label for cross-process spans)."""
    return os.urandom(4).hex()


@dataclass(frozen=True)
class TraceContext:
    """Serializable trace context carried across a process boundary.

    ``trace_id`` names the whole request journey; ``span_id`` is the
    sender-side parent span the receiver's spans nest under;
    ``clock_offset_s`` is the handshake's estimate of ``receiver_mono -
    sender_mono`` (so ``sent_s + clock_offset_s`` is the send instant on
    the RECEIVER's clock and per-hop deltas are meaningful across
    processes); ``sent_s`` is the sender's monotonic clock at send time.

    The wire form is a plain dict and deliberately OPTIONAL in every
    frame schema: ``from_wire`` returns ``None`` for an absent or
    malformed value, so an old peer's frames (no context) and a new
    peer's frames (context present) both parse everywhere (JGL010's
    wire-compat check pins the consumer side to ``.get``).
    """

    trace_id: str
    span_id: str
    clock_offset_s: float = 0.0
    sent_s: Optional[float] = None

    def to_wire(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "clock_offset_s": round(float(self.clock_offset_s), 9),
        }
        if self.sent_s is not None:
            out["sent_s"] = round(float(self.sent_s), 9)
        return out

    @classmethod
    def from_wire(cls, value) -> Optional["TraceContext"]:
        if not isinstance(value, dict):
            return None
        tid = value.get("trace_id")
        if not isinstance(tid, str) or not tid:
            return None
        try:
            sent = value.get("sent_s")
            return cls(
                trace_id=tid,
                span_id=str(value.get("span_id") or ""),
                clock_offset_s=float(value.get("clock_offset_s") or 0.0),
                sent_s=None if sent is None else float(sent),
            )
        except (TypeError, ValueError):
            return None

    def child(self, span_id: str, *, clock_offset_s: Optional[float] = None,
              sent_s: Optional[float] = None) -> "TraceContext":
        """The same trace, re-parented under ``span_id`` (the next hop's
        inbound context)."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=span_id,
            clock_offset_s=(
                self.clock_offset_s if clock_offset_s is None
                else clock_offset_s
            ),
            sent_s=sent_s,
        )


def _host_attr(name: str, key: str, value):
    """Validate one span attribute as host data (scalar, string, or a
    small tuple/list of those) — never a device array."""
    if isinstance(value, _ATTR_OK_TYPES):
        return value
    if isinstance(value, (tuple, list)):
        return [_host_attr(name, key, v) for v in value]
    if isinstance(value, int):
        # bool handled above; plain ints (request ids) pass untouched.
        return value
    return host_number(value, f"span {name} attr {key!r}")


class Span:
    """One in-progress or finished stage. Created by
    :meth:`SpanTracer.span`; ``duration_ms`` is valid after exit."""

    __slots__ = ("name", "attrs", "start_s", "end_s")

    def __init__(self, name: str, attrs: dict, start_s: float):
        self.name = name
        self.attrs = attrs
        self.start_s = start_s
        self.end_s: Optional[float] = None

    @property
    def duration_ms(self) -> Optional[float]:
        if self.end_s is None:
            return None
        return (self.end_s - self.start_s) * 1000.0

    def set(self, **attrs) -> None:
        """Attach correlation attributes mid-span (e.g. the batch id is
        only known after assembly)."""
        for k, v in attrs.items():
            self.attrs[k] = _host_attr(self.name, k, v)

    def record(self) -> dict:
        # ``t_s`` is the span's start on the tracer's monotonic clock:
        # the absolute anchor aggregate.py needs to order records and
        # compute per-hop deltas across processes (after translating
        # through the handshake's clock offsets).
        rec = {
            "name": self.name,
            "attrs": dict(self.attrs),
            "t_s": round(self.start_s, 6),
        }
        if self.end_s is not None:
            rec["duration_ms"] = round(self.duration_ms, 3)
        return rec


class _SpanContext:
    """Context manager yielded by :meth:`SpanTracer.span`."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "SpanTracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, *exc) -> None:
        self._tracer._finish(self.span)


class _NoopSpan:
    """Shared do-nothing span for disabled tracers: the hot path pays
    one attribute lookup and a with-statement, nothing else."""

    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class SpanTracer:
    """Bounded ring of finished spans + point events, with registry
    feeding. Thread-safe: clients, the dispatcher, and drain workers all
    produce concurrently."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        capacity: int = DEFAULT_SPAN_CAPACITY,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = registry
        self.clock = clock
        self._records: deque = deque(maxlen=max(1, int(capacity)))
        self._dropped = 0
        self._lock = threading.Lock()

    # -------------------------------------------------------- producers

    def span(self, name: str, **attrs) -> _SpanContext:
        """``with tracer.span("serve_dispatch", batch_id=7) as sp: ...``
        — measures wall time on the tracer's monotonic clock, records
        the span, and observes ``{name}_ms`` in the registry."""
        checked = {
            k: _host_attr(name, k, v) for k, v in attrs.items()
        }
        return _SpanContext(self, Span(name, checked, self.clock()))

    def _finish(self, span: Span) -> None:
        span.end_s = self.clock()
        self._append(span.record())
        if self.registry is not None:
            self.registry.histogram(
                f"{span.name}_ms"
            ).observe_ms(span.duration_ms)

    def event(self, name: str, **attrs) -> None:
        """Point event: recorded in the ring and counted as
        ``{name}_total`` in the registry."""
        checked = {
            k: _host_attr(name, k, v) for k, v in attrs.items()
        }
        self._append({
            "name": name, "attrs": checked, "event": True,
            "t_s": round(self.clock(), 6),
        })
        if self.registry is not None:
            self.registry.counter(f"{name}_total").inc()

    def observe_ms(self, name: str, ms, **attrs) -> None:
        """Record an externally-timed duration as if it were a span —
        the per-request queue-wait case, where the interval's endpoints
        live in different threads and a context manager cannot wrap it."""
        ms = host_number(ms, f"span {name} duration")
        checked = {
            k: _host_attr(name, k, v) for k, v in attrs.items()
        }
        self._append({
            "name": name, "attrs": checked, "duration_ms": round(ms, 3),
            # Start estimate: the interval ended "now" on this clock.
            "t_s": round(self.clock() - ms / 1e3, 6),
        })
        if self.registry is not None:
            self.registry.histogram(f"{name}_ms").observe_ms(ms)

    def _append(self, record: dict) -> None:
        with self._lock:
            if len(self._records) == self._records.maxlen:
                self._dropped += 1
            self._records.append(record)

    # --------------------------------------------------------- consumers

    def records(self, name: Optional[str] = None) -> List[dict]:
        with self._lock:
            recs = list(self._records)
        if name is None:
            return recs
        return [r for r in recs if r["name"] == name]

    def for_attr(self, **match) -> List[dict]:
        """Correlation query: records whose attrs contain every given
        key with an equal value — or whose list-valued attr CONTAINS
        the value. A singular key also matches its plural list attr
        (``request_id=12`` matches a batch span's ``request_ids``
        containing 12), so ``tracer.for_attr(request_id=12)``
        reassembles request 12's whole journey: its own queue-wait plus
        every batch-level stage that carried it.

        The matching itself is ``flight.match_records`` — ONE
        implementation shared with the offline postmortem tool, so the
        live tracer and a dumped ring can never drift semantically.
        """
        from raft_ncup_tpu.observability.flight import match_records

        return match_records(self.records(), **match)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def stage_summary(self) -> Dict[str, dict]:
        """Per-stage latency breakdown from the registry's ``*_ms``
        histograms: {stage: {count, p50_ms, p99_ms}} — what ``report()``
        embeds alongside the legacy keys."""
        if self.registry is None:
            return {}
        out: Dict[str, dict] = {}
        for name in self.registry.names():
            if not name.endswith("_ms"):
                continue
            m = self.registry.get(name)
            snap_fn = getattr(m, "percentile_ms", None)
            if snap_fn is None:
                continue  # a gauge that happens to end in _ms
            out[name[: -len("_ms")]] = {
                "count": m.count,
                "p50_ms": m.percentile_ms(0.50),
                "p99_ms": m.percentile_ms(0.99),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._dropped = 0
