"""Unified telemetry subsystem: metrics registry, span tracer, export
layer — and the consumer half that closes the loop: health state
machine, SLO burn-rate engine, fault flight recorder
(docs/OBSERVABILITY.md).

One registry, one event stream, every subsystem a producer — serving,
streaming, inference, and the resilience layer all mirror their
accounting here without changing a single legacy ``report()`` key
(``telemetry.LEGACY_KEY_ALIASES`` is the pinned map). The consumers
read it back at runtime: declared SLOs burn against the registry,
paging verdicts flip per-subsystem health READY ⇄ DEGRADED and degrade
the serving tier's anytime iteration budget, and every fault trigger
banks one bounded atomic flight-recorder dump.

Host-only by construction: nothing in this package may import jax,
touch a device array, or add a sync — lint rule JGL010 enforces it
statically, ``telemetry.host_number`` at runtime, and the bench's
telemetry-on-vs-off overhead row measures it.
"""

from raft_ncup_tpu.observability.aggregate import (
    aggregate_registry,
    collect_fleet_records,
    fleet_traces,
    hop_attribution,
    read_jsonl_tolerant,
    render_trace,
)
from raft_ncup_tpu.observability.export import (
    JsonlSink,
    PeriodicSnapshot,
    Telemetry,
    get_telemetry,
    prometheus_text,
    set_telemetry,
    telemetry_report,
    write_healthz,
)
from raft_ncup_tpu.observability.flight import (
    FlightRecorder,
    load_dump,
    match_records,
)
from raft_ncup_tpu.observability.health import (
    DEGRADED,
    DRAINING,
    HALTED,
    READY,
    STARTING,
    STATE_CODES,
    WARMING,
    HealthTracker,
    overall_state,
)
from raft_ncup_tpu.observability.slo import (
    SloEngine,
    SloSpec,
    serve_slos,
    stream_slos,
)
from raft_ncup_tpu.observability.spans import (
    NOOP_SPAN,
    Span,
    SpanTracer,
    TraceContext,
    new_span_id,
    new_trace_id,
)
from raft_ncup_tpu.observability.telemetry import (
    DEFAULT_BUCKETS_MS,
    LEGACY_KEY_ALIASES,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    host_number,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS_MS",
    "DEGRADED",
    "DRAINING",
    "FlightRecorder",
    "Gauge",
    "HALTED",
    "HealthTracker",
    "Histogram",
    "JsonlSink",
    "LEGACY_KEY_ALIASES",
    "MetricsRegistry",
    "NOOP_SPAN",
    "PeriodicSnapshot",
    "READY",
    "STARTING",
    "STATE_CODES",
    "SloEngine",
    "SloSpec",
    "Span",
    "SpanTracer",
    "Telemetry",
    "TraceContext",
    "WARMING",
    "aggregate_registry",
    "collect_fleet_records",
    "fleet_traces",
    "get_telemetry",
    "hop_attribution",
    "host_number",
    "load_dump",
    "match_records",
    "new_span_id",
    "new_trace_id",
    "overall_state",
    "prometheus_text",
    "read_jsonl_tolerant",
    "render_trace",
    "serve_slos",
    "set_telemetry",
    "stream_slos",
    "telemetry_report",
    "write_healthz",
]
