"""Unified telemetry subsystem: metrics registry, span tracer, export
layer (docs/OBSERVABILITY.md).

One registry, one event stream, every subsystem a producer — serving,
streaming, inference, and the resilience layer all mirror their
accounting here without changing a single legacy ``report()`` key
(``telemetry.LEGACY_KEY_ALIASES`` is the pinned map).

Host-only by construction: nothing in this package may import jax,
touch a device array, or add a sync — lint rule JGL010 enforces it
statically, ``telemetry.host_number`` at runtime, and the bench's
telemetry-on-vs-off overhead row measures it.
"""

from raft_ncup_tpu.observability.export import (
    JsonlSink,
    PeriodicSnapshot,
    Telemetry,
    get_telemetry,
    prometheus_text,
    set_telemetry,
    telemetry_report,
)
from raft_ncup_tpu.observability.spans import (
    NOOP_SPAN,
    Span,
    SpanTracer,
)
from raft_ncup_tpu.observability.telemetry import (
    DEFAULT_BUCKETS_MS,
    LEGACY_KEY_ALIASES,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    host_number,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS_MS",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "LEGACY_KEY_ALIASES",
    "MetricsRegistry",
    "NOOP_SPAN",
    "PeriodicSnapshot",
    "Span",
    "SpanTracer",
    "Telemetry",
    "get_telemetry",
    "host_number",
    "prometheus_text",
    "set_telemetry",
    "telemetry_report",
]
