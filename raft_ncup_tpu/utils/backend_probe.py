"""Bounded, hang-proof JAX backend liveness probe.

The inherited axon TPU backend can HANG inside ``jax.devices()`` rather
than fail fast (round-2 postmortem, VERDICT.md), so any code that needs
to know "is there a live accelerator?" must ask in a watchdogged child
process, never in-process. Shared by bench.py and tests_tpu/conftest.py
so the postmortem-driven details (config-vs-env forcing, timeout
semantics, PLATFORM= parsing) live in exactly one place.
"""

from __future__ import annotations

import subprocess
import sys
from typing import NamedTuple, Optional


class ChildResult(NamedTuple):
    returncode: Optional[int]  # None when killed by the watchdog
    stdout: str
    stderr: str
    timed_out: bool

    def tail(self, n: int = 12) -> str:
        """Last ``n`` lines of the child's combined output (stdout then
        stderr) for diagnostics — neither stream is dropped."""
        combined = "\n".join(s for s in (self.stdout, self.stderr) if s)
        return "\n".join(combined.strip().splitlines()[-n:])


def run_watchdogged(
    cmd: list[str],
    timeout_s: float,
    env: Optional[dict] = None,
    cwd: Optional[str] = None,
) -> ChildResult:
    """``subprocess.run(capture_output=True, timeout=...)`` loses the
    child's partial output on timeout (POSIX ``TimeoutExpired.stdout`` is
    None — verified on this interpreter), which defeats harvest-on-kill
    designs. This Popen-based variant kills the child on expiry and then
    drains the pipes, so whatever the child printed before the watchdog
    fired is preserved."""
    proc = subprocess.Popen(
        cmd,
        env=env,
        cwd=cwd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
        return ChildResult(proc.returncode, out or "", err or "", False)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:  # pragma: no cover - wedged pipes
            out, err = "", ""
        return ChildResult(None, out or "", err or "", True)


class ProbeResult(NamedTuple):
    platform: Optional[str]  # e.g. 'tpu', 'axon', 'cpu'; None when dead
    reason: str  # 'ok' | 'hung' | 'failed'
    detail: str = ""


_PROBE_CODE = (
    "import os, jax\n"
    "p = os.environ.get('_BENCH_FORCE_PLATFORM')\n"
    "if p is not None: jax.config.update('jax_platforms', p)\n"
    "print('PLATFORM=' + jax.devices()[0].platform)\n"
)


def probe_backend(
    timeout_s: float,
    env: Optional[dict] = None,
    retries_on_fast_failure: int = 1,
) -> ProbeResult:
    """Import jax + list devices in a child process, bounded by
    ``timeout_s``. A hang (timeout) is terminal — the backend is wedged
    and retrying would just burn the budget. A FAST failure (nonzero rc
    in seconds, e.g. a transient backend-init crash — the round-1 mode)
    is retried up to ``retries_on_fast_failure`` times.
    """
    import os
    import time

    if timeout_s <= 5:
        return ProbeResult(None, "failed", "no probe budget")
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    last = ProbeResult(None, "failed")
    for attempt in range(retries_on_fast_failure + 1):
        res = run_watchdogged(
            [sys.executable, "-c", _PROBE_CODE], timeout_s, env=full_env
        )
        if res.timed_out:
            return ProbeResult(None, "hung", f"probe exceeded {timeout_s:.0f}s")
        for line in res.stdout.splitlines():
            if line.startswith("PLATFORM="):
                return ProbeResult(line.split("=", 1)[1].strip(), "ok")
        tail = "\n".join(res.stderr.strip().splitlines()[-4:])
        last = ProbeResult(None, "failed", tail)
        if attempt < retries_on_fast_failure:
            time.sleep(5)
    return last
