"""Import PyTorch reference checkpoints into our parameter trees.

Supports the reference's three checkpoint-loading semantics (SURVEY.md §5):

- ``--restore_ckpt`` on a DataParallel-wrapped model (keys prefixed
  ``module.``, non-strict in train / strict in eval — reference:
  train.py:179-180, evaluate.py:257);
- ``--load_pretrained`` warm-starting the RAFT trunk before NCUP is
  attached (prefix-stripping load — reference: core/raft_nc_dbl.py:57-66);
- plain state dicts.

Layout translation: torch convs are OIHW, ours are HWIO; torch norm
``weight``/``bias``/``running_mean``/``running_var`` become flax
``scale``/``bias`` params and ``mean``/``var`` batch_stats. Module-path
translation is table-driven and validated against the destination tree, so
unknown/missing keys are reported instead of silently dropped.

This module deliberately has no torch dependency: checkpoints are loaded
with ``torch.load`` by the caller (or any pickle reader) and passed in as a
``{key: numpy array}`` mapping.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import numpy as np
from flax import traverse_util

_SEGMENT_RULES: list[tuple[re.Pattern, Any]] = [
    (re.compile(r"^layer(\d+)\.(\d+)$"), lambda m: [f"layer{m.group(1)}_{m.group(2)}"]),
    (re.compile(r"^downsample\.0$"), lambda m: ["downsample_conv"]),
    (re.compile(r"^downsample\.1$"), lambda m: ["downsample_norm"]),
    (re.compile(r"^mask\.0$"), lambda m: ["mask_conv1"]),
    (re.compile(r"^mask\.2$"), lambda m: ["mask_conv2"]),
    (re.compile(r"^nconv_x2\.(\d+)$"), lambda m: [f"nconv_x2_{m.group(1)}"]),
    (re.compile(r"^decoder\.(\d+)$"), lambda m: [f"decoder_{m.group(1)}"]),
    (re.compile(r"^encoder\.(\d+)$"), lambda m: [f"encoder_{m.group(1)}"]),
    (re.compile(r"^conv\.(\d+)\.0$"), lambda m: [f"conv{m.group(1)}"]),
    (re.compile(r"^conv\.(\d+)\.1$"), lambda m: [f"bn{m.group(1)}"]),
]


def _translate_module_path(parts: list[str]) -> list[str]:
    """Translate a dotted torch module path into flax path segments."""
    out: list[str] = []
    i = 0
    while i < len(parts):
        matched = False
        # Try two-segment and three-segment composite rules first.
        for span in (3, 2, 1):
            if i + span > len(parts):
                continue
            seg = ".".join(parts[i : i + span])
            for pat, repl in _SEGMENT_RULES:
                m = pat.match(seg)
                if m:
                    out.extend(repl(m))
                    i += span
                    matched = True
                    break
            if matched:
                break
        if not matched:
            out.append(parts[i])
            i += 1
    return out


def strip_module_prefix(state: Mapping[str, Any]) -> dict[str, Any]:
    """Remove DataParallel's ``module.`` prefix (reference:
    core/raft_nc_dbl.py:62-64)."""
    return {
        (k[len("module.") :] if k.startswith("module.") else k): v
        for k, v in state.items()
    }


def import_torch_state(
    state: Mapping[str, Any],
    variables: dict,
    strict: bool = True,
    allow_unmatched: tuple[str, ...] = (),
) -> dict:
    """Merge a torch state dict into ``variables`` (from ``RAFT.init``).

    Args:
      state: torch parameter name -> array-like (numpy or torch tensors).
      variables: destination {'params': ..., 'batch_stats': ...} tree.
      strict: raise if a checkpoint key has no destination (missing
        destinations — e.g. loading a plain RAFT trunk into raft_nc_dbl —
        are always allowed, mirroring the reference's strict=False resume).
      allow_unmatched: regex patterns (matched against the ``module.``-
        stripped torch key) for source keys that are *expected* to have no
        destination even under strict loading — e.g. the convex-mask head
        when warm-starting a model that deleted it (reference loads the
        state dict before deleting the head, core/raft_nc_dbl.py:57-68).
    Returns:
      A new variables dict with imported values (float32 numpy).
    """
    allow_res = [re.compile(p) for p in allow_unmatched]
    state = strip_module_prefix(state)
    params = dict(traverse_util.flatten_dict(variables.get("params", {})))
    stats = dict(traverse_util.flatten_dict(variables.get("batch_stats", {})))

    unmatched: list[str] = []
    for tkey, tval in state.items():
        leaf = tkey.rsplit(".", 1)[-1]
        if leaf == "num_batches_tracked":
            continue
        val = np.asarray(getattr(tval, "numpy", lambda: tval)(), dtype=np.float32)
        mod_parts = tkey.split(".")[:-1]
        base = tuple(_translate_module_path(mod_parts))

        placed = False
        if leaf in ("weight", "weight_p"):
            name = "kernel" if leaf == "weight" else "weight_p"
            key = base + (name,)
            if key in params:
                if val.ndim == 4:
                    val = val.transpose(2, 3, 1, 0)  # OIHW -> HWIO
                if params[key].shape != val.shape and val.ndim == 4:
                    # ConvTranspose torch weight is (in, out, kh, kw); ours
                    # is (kh, kw, out, in) — same transpose, so a mismatch
                    # here is a real error.
                    raise ValueError(
                        f"shape mismatch for {tkey}: {val.shape} vs "
                        f"{params[key].shape}"
                    )
                params[key] = val
                placed = True
            else:
                # Norm weight -> scale on the wrapped norm module.
                for inner in ("BatchNorm_0", "GroupNorm_0"):
                    key = base + (inner, "scale")
                    if key in params:
                        params[key] = val
                        placed = True
                        break
        elif leaf == "bias":
            key = base + ("bias",)
            if key in params:
                params[key] = val
                placed = True
            else:
                for inner in ("BatchNorm_0", "GroupNorm_0"):
                    key = base + (inner, "bias")
                    if key in params:
                        params[key] = val
                        placed = True
                        break
        elif leaf in ("running_mean", "running_var"):
            name = "mean" if leaf == "running_mean" else "var"
            key = base + ("BatchNorm_0", name)
            if key in stats:
                stats[key] = val
                placed = True

        if not placed:
            # Shared-encoder aliases (interpolation_net.encoder.*) duplicate
            # nconv_in / nconv_x2 tensors; silently skip those.
            if ".encoder." in tkey and "interpolation_net" in tkey:
                continue
            # Residual/bottleneck blocks register the downsample norm both
            # as normN and inside the downsample Sequential (reference:
            # core/extractor.py:44-45,103-104); downsample.1 carries it.
            if base and re.fullmatch(r"norm[34]", base[-1]):
                alias = base[:-1] + ("downsample_norm",)
                if any(k[: len(alias)] == alias for k in (*params, *stats)):
                    continue
            if any(p.search(tkey) for p in allow_res):
                continue
            unmatched.append(tkey)

    if unmatched and strict:
        raise KeyError(
            f"{len(unmatched)} torch keys had no destination, e.g. "
            f"{unmatched[:5]}"
        )

    out = {"params": traverse_util.unflatten_dict(params)}
    if stats:
        out["batch_stats"] = traverse_util.unflatten_dict(stats)
    return out


def load_torch_checkpoint(
    path: str,
    variables: dict,
    strict: bool = True,
    allow_unmatched: tuple[str, ...] = (),
) -> dict:
    """Load a ``.pth`` file (requires torch, CPU) and import it."""
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    state = {k: v.numpy() for k, v in state.items()}
    return import_torch_state(
        state, variables, strict=strict, allow_unmatched=allow_unmatched
    )
