"""Profiling and throughput measurement.

The reference ships no profiler hooks or timers (SURVEY.md §5 "tracing").
Here: a ``jax.profiler`` trace context for capturing device traces viewable
in TensorBoard/Perfetto, and a wall-clock throughput meter for the
north-star metric (frame-pairs/sec/chip).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a device trace into ``log_dir`` (no-op when None).

    View with TensorBoard's profile plugin or Perfetto.
    """
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def measure_throughput(
    fn: Callable[[], object],
    warmup: int = 2,
    reps: int = 5,
    sync: Optional[Callable[[object], None]] = None,
) -> float:
    """Time ``fn`` (one unit of work) and return calls/sec.

    ``sync`` receives the output and must force completion (e.g. pull one
    scalar to host); defaults to ``jax.block_until_ready``.
    """
    sync = sync or (lambda out: jax.block_until_ready(out))
    for _ in range(warmup):
        sync(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    sync(out)
    return reps / (time.perf_counter() - t0)
