"""Profiling and throughput measurement.

The reference ships no profiler hooks or timers (SURVEY.md §5 "tracing").
Here: a ``jax.profiler`` trace context for capturing device traces viewable
in TensorBoard/Perfetto, and a wall-clock throughput meter for the
north-star metric (frame-pairs/sec/chip).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a device trace into ``log_dir`` (no-op when None).

    View with TensorBoard's profile plugin or Perfetto.
    """
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Rolling step-time/throughput meter.

    ``items_per_step`` is the unit count per step (e.g. frame pairs in the
    global batch); rates are reported per chip.
    """

    def __init__(self, items_per_step: float, window: int = 50):
        self.items_per_step = items_per_step
        self.window = window
        self._times: list[float] = []
        self._last: Optional[float] = None
        self._chips = max(1, len(jax.devices()))

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
            if len(self._times) > self.window:
                self._times.pop(0)
        self._last = now

    @property
    def step_time(self) -> float:
        return float(np.median(self._times)) if self._times else float("nan")

    @property
    def items_per_sec_per_chip(self) -> float:
        st = self.step_time
        if not np.isfinite(st) or st <= 0:
            return float("nan")
        return self.items_per_step / st / self._chips

    def summary(self) -> dict:
        return {
            "step_time_s": self.step_time,
            "items_per_sec_per_chip": self.items_per_sec_per_chip,
        }


def measure_throughput(
    fn: Callable[[], object],
    warmup: int = 2,
    reps: int = 5,
    sync: Optional[Callable[[object], None]] = None,
) -> float:
    """Time ``fn`` (one unit of work) and return calls/sec.

    ``sync`` receives the output and must force completion (e.g. pull one
    scalar to host); defaults to ``jax.block_until_ready``.
    """
    sync = sync or (lambda out: jax.block_until_ready(out))
    for _ in range(warmup):
        sync(fn())
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    sync(out)
    return reps / (time.perf_counter() - t0)
