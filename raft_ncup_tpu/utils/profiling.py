"""Profiling and throughput measurement.

The reference ships no profiler hooks or timers (SURVEY.md §5 "tracing").
Here: a ``jax.profiler`` trace context for capturing device traces viewable
in TensorBoard/Perfetto, and a wall-clock throughput meter for the
north-star metric (frame-pairs/sec/chip).
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Iterator, Optional

import jax


def stage_annotation(name: str):
    """Host-side xprof stage label: a ``jax.profiler.TraceAnnotation``
    that shows up on the host-thread timeline of a profiler capture
    (``trace``/``bench.py --trace_dir``), labeling serve/stream dispatch
    stages next to the device ops the jitted code's ``jax.named_scope``
    labels. Constructing it outside an active capture is a few ns — the
    serving hot path wears it permanently (docs/OBSERVABILITY.md). The
    host-only telemetry spans (observability/spans.py) deliberately do
    NOT use this: they must work without jax."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a device trace into ``log_dir`` (no-op when None).

    View with TensorBoard's profile plugin or Perfetto.
    """
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def measure_throughput(
    fn: Callable[[], object],
    warmup: int = 2,
    reps: int = 5,
    sync: Optional[Callable[[object], None]] = None,
) -> float:
    """Time ``fn`` (one unit of work) and return calls/sec."""
    return measure_throughput_detailed(fn, warmup, reps, sync)[0]


def measure_throughput_detailed(
    fn: Callable[[], object],
    warmup: int = 2,
    reps: int = 5,
    sync: Optional[Callable[[object], None]] = None,
) -> tuple[float, list[float]]:
    """Time ``fn`` per-rep and return ``(calls/sec, [rep_seconds...])``.

    ``sync`` receives the output and must force completion (e.g. pull one
    scalar to host); defaults to ``jax.block_until_ready``. Each rep is
    synced individually so the record can carry dispersion — single-shot
    CPU numbers on a shared host wobble ±5-10% (VERDICT r4 weak #1) and a
    mean alone cannot distinguish noise from regression. The per-rep sync
    costs one host round-trip per rep, negligible against the >100 ms
    step times this harness measures.
    """
    sync = sync or (lambda out: jax.block_until_ready(out))
    for _ in range(warmup):
        sync(fn())
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn())
        times.append(time.perf_counter() - t0)
    return reps / sum(times), times
