"""Export our parameter trees as reference-keyed PyTorch state dicts.

The inverse of :mod:`raft_ncup_tpu.utils.torch_import`: given model
variables from ``RAFT.init`` / a trained checkpoint, produce the exact
``{torch key: numpy array}`` mapping the PyTorch reference's STRICT
``load_state_dict`` expects (reference: evaluate.py:257 loads a
DataParallel-wrapped model — keys prefixed ``module.`` — with
``strict=True``), so checkpoints trained here drop into the reference
the day real hardware/data exist (VERDICT r4 #5).

Strictness is the hard part: beyond inverting the module-path
translation and the HWIO→OIHW layout, the export must *regenerate* every
key the import deliberately skips:

- ``num_batches_tracked`` for each BatchNorm (zeros — the reference
  never consults it with ``track_running_stats`` defaults at eval);
- the residual-block duplicate norm (the downsample norm is registered
  both as ``normN`` and ``downsample.1`` — reference:
  core/extractor.py:44-45,103-104);
- the NConvUNet shared-encoder aliases (``encoder.0.0`` = ``nconv_in``,
  ``encoder.0.1.K`` = ``nconv_x2.K``, ``encoder.J`` = ``nconv_x2.0`` for
  J>=1 under ``shared_encoder`` — reference: core/nconv_modules.py:76-83).

Like the import, this module has no torch dependency; the caller saves
with ``torch.save`` (or :func:`save_torch_checkpoint` which does it for
you when torch is available).
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np
from flax import traverse_util

_NORM_WRAPPERS = ("BatchNorm_0", "GroupNorm_0")


def _untranslate_segment(seg: str, in_weights_est: bool) -> list[str]:
    """Inverse of torch_import._translate_module_path, one flax segment
    to torch dotted segments."""
    m = re.fullmatch(r"layer(\d+)_(\d+)", seg)
    if m:
        return [f"layer{m.group(1)}", m.group(2)]
    if seg == "downsample_conv":
        return ["downsample", "0"]
    if seg == "downsample_norm":
        return ["downsample", "1"]
    if seg == "mask_conv1":
        return ["mask", "0"]
    if seg == "mask_conv2":
        return ["mask", "2"]
    for name in ("nconv_x2", "decoder", "encoder"):
        m = re.fullmatch(rf"{name}_(\d+)", seg)
        if m:
            return [name, m.group(1)]
    if in_weights_est:
        # The Simple weights-est net is a Sequential of (conv, bn) pairs
        # (torch conv.N.0 / conv.N.1). Context-gated: plain residual-block
        # convN must stay convN.
        m = re.fullmatch(r"conv(\d+)", seg)
        if m:
            return ["conv", m.group(1), "0"]
        m = re.fullmatch(r"bn(\d+)", seg)
        if m:
            return ["conv", m.group(1), "1"]
    return [seg]


def _torch_module_path(flax_path: tuple[str, ...]) -> str:
    in_we = "weights_est_net" in flax_path
    out: list[str] = []
    for seg in flax_path:
        out.extend(_untranslate_segment(seg, in_we))
    return ".".join(out)


def _export_kernel(val: np.ndarray) -> np.ndarray:
    v = np.asarray(val, np.float32)
    if v.ndim == 4:
        return v.transpose(3, 2, 0, 1)  # HWIO -> OIHW (inverse of import)
    return v


def export_torch_state(variables: dict) -> dict[str, Any]:
    """Build the reference-keyed state dict (no ``module.`` prefix; see
    :func:`save_torch_checkpoint` for the DataParallel form)."""
    params = traverse_util.flatten_dict(variables.get("params", {}))
    stats = traverse_util.flatten_dict(variables.get("batch_stats", {}))
    out: dict[str, Any] = {}

    for key, val in params.items():
        *mod, leaf = key
        mod = tuple(mod)
        if mod and mod[-1] in _NORM_WRAPPERS:
            base = _torch_module_path(mod[:-1])
            name = {"scale": "weight", "bias": "bias"}[leaf]
            out[f"{base}.{name}"] = np.asarray(val, np.float32)
            continue
        base = _torch_module_path(mod)
        if leaf == "kernel":
            out[f"{base}.weight"] = _export_kernel(val)
        elif leaf == "weight_p":
            # NConv2d's positive conv weight: conv-shaped, so the same
            # HWIO->OIHW transpose as 'kernel' (the import transposes any
            # 4-d weight/weight_p).
            out[f"{base}.weight_p"] = _export_kernel(val)
        else:  # bias and any future verbatim leaf
            out[f"{base}.{leaf}"] = np.asarray(val, np.float32)

    norm_paths = set()
    for key, val in stats.items():
        *mod, leaf = key
        mod = tuple(mod)
        if mod and mod[-1] in _NORM_WRAPPERS:
            mod = mod[:-1]
        base = _torch_module_path(mod)
        name = {"mean": "running_mean", "var": "running_var"}[leaf]
        out[f"{base}.{name}"] = np.asarray(val, np.float32)
        norm_paths.add(base)
    for base in norm_paths:
        # torch BatchNorm2d registers the step counter as a buffer; the
        # strict load requires the key, eval never reads the value.
        out[f"{base}.num_batches_tracked"] = np.asarray(0, np.int64)

    _add_resblock_norm_duplicates(params, out)
    _add_shared_encoder_aliases(params, out)
    return out


def _add_resblock_norm_duplicates(params: dict, out: dict) -> None:
    """Residual blocks register the downsample norm twice: ``normN`` and
    ``downsample.1`` (reference: core/extractor.py:44-45,103-104). N is
    one past the block's conv count (BasicBlock: norm3, Bottleneck:
    norm4)."""
    blocks = {
        key[:-3]
        for key in params
        if len(key) >= 3 and key[-3] == "downsample_norm"
    }
    for block in blocks:
        convs = [
            int(re.fullmatch(r"conv(\d+)", k[len(block)]).group(1))
            for k in params
            if len(k) > len(block)
            and k[: len(block)] == block
            and re.fullmatch(r"conv(\d+)", k[len(block)])
        ]
        if not convs:
            continue
        dup = f"norm{max(convs) + 1}"
        src = _torch_module_path(block + ("downsample_norm",))
        dst = _torch_module_path(block + (dup,))
        for key in list(out):
            if key.startswith(src + "."):
                out[dst + key[len(src):]] = out[key]


def _add_shared_encoder_aliases(params: dict, out: dict) -> None:
    """NConvUNet registers its encoder stages as aliases of nconv_in /
    nconv_x2 (reference: core/nconv_modules.py:76-83); a strict torch
    load expects those duplicate keys."""
    nets = {
        key[: key.index("interpolation_net") + 1]
        for key in params
        if "interpolation_net" in key
    }
    for net in nets:
        sub = {k[len(net):]: k for k in params if k[: len(net)] == net}
        x2_idx = sorted(
            {
                int(re.fullmatch(r"nconv_x2_(\d+)", k[0]).group(1))
                for k in sub
                if re.fullmatch(r"nconv_x2_(\d+)", k[0])
            }
        )
        n_down = len(
            {k[0] for k in sub if re.fullmatch(r"decoder_\d+", k[0])}
        )
        base = _torch_module_path(net)

        def copy(src_seg: str, dst_dotted: str) -> None:
            # nconv_x2_K untranslates to dotted 'nconv_x2.K'
            src = f"{base}." + ".".join(_untranslate_segment(src_seg, False))
            dst = f"{base}.{dst_dotted}"
            for key in list(out):
                if key.startswith(src + "."):
                    out[dst + key[len(src):]] = out[key]

        copy("nconv_in", "encoder.0.0")
        for j in x2_idx:
            copy(f"nconv_x2_{j}", f"encoder.0.1.{j}")
        for stage in range(1, n_down + 1):
            if any(k[0] == f"encoder_{stage}" for k in sub):
                continue  # non-shared encoder: real params, already emitted
            copy("nconv_x2_0", f"encoder.{stage}")


def save_torch_checkpoint(
    path: str, variables: dict, data_parallel: bool = True
) -> None:
    """``torch.save`` the exported state dict; ``data_parallel`` adds the
    ``module.`` prefix the reference's eval-time strict load expects
    (reference: evaluate.py:246-257)."""
    import torch

    state = {
        (f"module.{k}" if data_parallel else k): torch.from_numpy(
            np.ascontiguousarray(v)
        )
        for k, v in export_torch_state(variables).items()
    }
    torch.save(state, path)
