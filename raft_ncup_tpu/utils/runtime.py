"""Runtime/platform facts shared by the Pallas kernels, the driver entry
points, and the bench: which backends are TPU-class (Mosaic-lowerable),
the per-core VMEM capacity, and the persistent-compilation-cache policy.
One definition each — the kernels' dispatch thresholds and the two
entry-point parents must never drift apart.
"""

from __future__ import annotations

import os
import sys

from raft_ncup_tpu.utils.knobs import knob_int

# Platform strings that are definitely NOT TPU-class. A denylist, not
# `backend == "tpu"`: TPU-class plugins report their own platform strings
# (the axon tunnel does) and must get the real Mosaic compile.
NON_TPU_BACKENDS = ("cpu", "gpu", "cuda", "rocm")

# Per-core VMEM capacity (~16 MiB on current TPUs —
# /opt/skills/guides/pallas_guide.md "Memory Hierarchy").
VMEM_BYTES = knob_int("RAFT_NCUP_VMEM_BYTES")

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".cache")


def host_fingerprint() -> str:
    """Stable-ish host id (cpu model + core count, sha1/8). Used to key
    CPU perf baselines (cross-host CPU numbers differ >2x — r2 data) and
    to segregate the persistent XLA cache per machine: XLA:CPU AOT
    entries bake machine features (+prefer-no-scatter etc.) that other
    hosts load with 'could lead to SIGILL' errors — observed r4 when a
    different session's cache entries landed in this repo's .cache/."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            model = next(
                (l.split(":", 1)[1].strip() for l in f if "model name" in l),
                "unknown",
            )
    except OSError:
        model = "unknown"
    raw = f"{model}|{os.cpu_count()}"
    return hashlib.sha1(raw.encode()).hexdigest()[:8]


def force_platform(platform: str) -> None:
    """Force this process onto ``platform`` before any backend init. Both
    writes are required: the axon boot hook bakes JAX_PLATFORMS=axon into
    jax.config at interpreter start, so the env var alone cannot override
    it, and child processes inherit only the env var."""
    import jax

    os.environ["JAX_PLATFORMS"] = platform
    jax.config.update("jax_platforms", platform)


def is_tpu_class_backend() -> bool:
    """Whether the current default backend can lower Mosaic kernels."""
    import jax

    return jax.default_backend() not in NON_TPU_BACKENDS


def enable_compilation_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compilation cache — the dryrun and bench children
    are compile-bound (minutes of XLA CPU compile for the 8-device SPMD
    train step), so a warm cache turns repeat runs on one machine into
    seconds and removes the watchdog-timeout risk entirely."""
    import jax

    # Per-host subdir: XLA:CPU AOT entries are machine-feature-specific
    # (see host_fingerprint) and /root/repo/.cache is shared between the
    # builder's, the judge's, and the driver's sessions — which may run
    # on different machines.
    path = os.path.join(
        cache_dir or DEFAULT_CACHE_DIR, f"xla-{host_fingerprint()}"
    )
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - older jax knob names
        print(f"compilation cache unavailable: {e}", file=sys.stderr)


def wipe_compilation_cache_for_retry(
    remaining_s: float, cache_dir: str | None = None
) -> bool:
    """Crash-retry policy shared by the dryrun and bench parents: a fast
    child crash may be a poisoned cache (machine-feature-specific AOT
    results can SIGILL), but wiping is only worth it when a retry will
    actually run — otherwise a warm cache is destroyed for nothing and
    every later run pays the multi-minute cold compile again. Returns
    True iff the cache existed, the budget allows a retry, and the cache
    was wiped."""
    if remaining_s <= 120:
        return False
    import shutil

    path = os.path.join(
        cache_dir or DEFAULT_CACHE_DIR, f"xla-{host_fingerprint()}"
    )
    if not os.path.isdir(path):
        return False
    shutil.rmtree(path, ignore_errors=True)
    return True
