"""The single env-knob registry: every ``RAFT_NCUP_*``/``BENCH_*``
environment variable the repo reads is declared here ONCE — name, type,
default, one doc line — and read ONLY through the ``knob_*`` getters
below. Lint rule JGL013 (analysis/rules/jgl013_env_knobs.py) enforces
both halves statically: a bare ``os.environ`` read of a matching name
anywhere else is a finding, and so is a registered knob nobody reads.
The getters enforce the same contract at runtime by raising on names
missing from the registry.

The registry is data the tooling consumes three ways:

- the getters (runtime reads),
- JGL013, which AST-parses the ``Knob("NAME", ...)`` literal calls
  (first argument must stay a string literal — the linter cannot
  evaluate expressions, and neither should a human auditing the knob
  surface),
- :func:`catalog_markdown`, which emits the knob table docs/PERF.md
  carries (``python -m raft_ncup_tpu.utils.knobs``); a tier-1 test pins
  that every registered name appears there.

``kind`` tokens and their getter semantics:

- ``str`` / ``raw``: the env string when set, else the default
  (:func:`knob_str` / :func:`knob_raw`; ``raw`` knobs default to None).
- ``int`` / ``float``: parsed env value (:func:`knob_int` /
  :func:`knob_float`).
- ``flag``: opt-IN boolean — true only when the env value is exactly
  ``"1"`` (:func:`knob_flag`).
- ``enabled``: opt-OUT boolean — true unless the env value is exactly
  ``"0"`` (:func:`knob_enabled`).
- ``posint``: positive-int override or None meaning "auto" — unset,
  non-int, and non-positive all mean no override
  (:func:`knob_positive_int`; the correlation tuning-knob semantics
  formerly in ``ops/corr._env_int``).

Defaults that depend on runtime context (accelerator vs CPU, device
count) are passed by the call site via the getters' ``default=``
argument; the registered default column then documents the rule rather
than a literal value.

Pure stdlib, no jax: importable from ``fleet/`` and ``observability/``
(JGL010) and parseable by the analysis package without executing
anything heavier than this module.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    kind: str  # str | raw | int | float | flag | enabled | posint
    default: Optional[str]  # documented default; None = unset/auto
    doc: str


KNOBS: Tuple[Knob, ...] = (
    # ----------------------------------------------------- model / ops
    Knob("RAFT_NCUP_NCONV_IMPL", "str", "xla",
         "Normalized-convolution implementation: 'xla' or 'pallas' "
         "(falls back per shape when the kernel cannot lower)."),
    Knob("RAFT_NCUP_CORR_QUERY_BLOCK", "posint", "512",
         "Pallas correlation query-block size; smaller blocks buy band "
         "rows inside the VMEM budget (ROADMAP item 1 sweep surface)."),
    Knob("RAFT_NCUP_CORR_BAND_ROWS", "posint", None,
         "Pallas correlation band-rows override; unset = the "
         "VMEM-budget band plan decides."),
    Knob("RAFT_NCUP_CORR_ROW_CHUNK", "posint", "8",
         "Row-chunk size the on-the-fly correlation scan traces with; "
         "larger chunks amortize the scan at more peak memory."),
    Knob("RAFT_NCUP_VMEM_BYTES", "int", "16777216",
         "Per-core VMEM capacity assumed by kernel band planning."),
    Knob("RAFT_NCUP_EARLYEXIT", "flag", "0",
         "Enable in-graph per-sample early exit for converged flow in "
         "the serving forward (docs/PERF.md 'Early exit')."),
    Knob("RAFT_NCUP_EARLYEXIT_TOL", "float", "0.05",
         "Early-exit convergence tolerance: mean |flow delta| per "
         "sample in LOW-RES pixels below which a lane freezes."),
    # ------------------------------------------------- runtime drivers
    Knob("RAFT_NCUP_PLATFORM", "raw", None,
         "Force the jax platform ('cpu', 'tpu'); the --platform flag's "
         "env fallback."),
    Knob("RAFT_NCUP_CHAOS", "raw", None,
         "Deterministic fault-injection spec (resilience/chaos.py); "
         "the --chaos flag's env fallback."),
    Knob("RAFT_NCUP_COMPILATION_CACHE", "flag", "0",
         "Opt into the persistent XLA compilation cache in train.py "
         "(accelerator hosts only; see train.py for the CPU caveat)."),
    Knob("RAFT_NCUP_TELEMETRY", "enabled", "1",
         "Process-default telemetry hub enable; '0' creates the "
         "default hub disabled."),
    Knob("RAFT_NCUP_FLIGHT_DIR", "raw", None,
         "Flight-recorder directory for the process-default telemetry "
         "hub and serve.py's --flight_dir default."),
    Knob("RAFT_NCUP_COST_LEDGER", "enabled", "1",
         "Compiled-executable cost ledger enable; '0' disables "
         "harvesting."),
    Knob("RAFT_NCUP_CPU_PEAK_FLOPS", "raw", None,
         "Override the nominal per-host CPU peak FLOP/s used for CPU "
         "MFU; unset = cores x 4.8e10."),
    # ------------------------------------------------------ bench: run
    Knob("BENCH_BUDGET_S", "float", "840",
         "Total bench wall-clock budget in seconds; remaining rows are "
         "skipped once it is exhausted."),
    Knob("BENCH_MESH", "raw", None,
         "Mesh spec 'data,model' for the sharded bench rows; the "
         "--mesh flag's env fallback."),
    Knob("BENCH_TRACE_DIR", "raw", None,
         "Directory for bench JAX traces; unset disables tracing."),
    Knob("BENCH_CORR_IMPL", "str", "volume",
         "Correlation implementation the main bench rows run "
         "('volume', 'onthefly', 'pallas')."),
    Knob("BENCH_ALLOW_FULL_ON_CPU", "flag", "0",
         "Run the full-resolution bench shape on a CPU host (normally "
         "refused: it would blow the budget)."),
    Knob("BENCH_STRICT_GUARDS", "flag", "0",
         "Escalate bench guard-rail violations (recompiles, host "
         "transfers) from warnings to hard failures."),
    # ----------------------------------------------------- bench: skip
    Knob("BENCH_SKIP_TRAIN", "flag", "0", "Skip the train bench row."),
    Knob("BENCH_SKIP_VAL", "flag", "0", "Skip the val bench row."),
    Knob("BENCH_SKIP_SERVE", "flag", "0", "Skip the serve bench row."),
    Knob("BENCH_SKIP_STREAM", "flag", "0",
         "Skip the streaming bench row."),
    Knob("BENCH_SKIP_FLEET", "flag", "0", "Skip the fleet bench row."),
    Knob("BENCH_SKIP_ELASTICITY", "flag", "0",
         "Skip the elasticity bench row."),
    Knob("BENCH_SKIP_BF16", "flag", "0", "Skip the bf16 bench row."),
    Knob("BENCH_SKIP_HIGHRES", "flag", "0",
         "Skip the high-resolution bench row."),
    Knob("BENCH_SKIP_UHD", "flag", "0", "Skip the 4K/UHD bench row."),
    Knob("BENCH_SKIP_PIPELINE", "flag", "0",
         "Skip the iteration-pipelined bench row."),
    Knob("BENCH_SKIP_EARLYEXIT", "flag", "0",
         "Skip the early-exit bench row."),
    Knob("BENCH_SKIP_TELEMETRY_COMPARE", "flag", "0",
         "Skip the telemetry-overhead comparison window in the serve "
         "and fleet rows."),
    # --------------------------------------------------- bench: sizing
    Knob("BENCH_TRAIN_LOOP_STEPS", "int", "6",
         "Steps the train bench row runs."),
    Knob("BENCH_VAL_LOOP_BATCHES", "int", "8",
         "Batches per val bench rep."),
    Knob("BENCH_VAL_LOOP_REPS", "int", "5", "Val bench reps."),
    Knob("BENCH_SERVE_REQUESTS", "int", "16",
         "Requests the serve bench row issues."),
    Knob("BENCH_STREAM_STREAMS", "int", "4",
         "Concurrent streams in the streaming bench row."),
    Knob("BENCH_STREAM_FRAMES", "int", "6",
         "Frames per stream in the streaming bench row."),
    Knob("BENCH_FLEET_REPLICAS", "int", "2",
         "Replica count the fleet bench row spawns."),
    Knob("BENCH_FLEET_REQUESTS", "int", "12",
         "Requests the fleet bench row routes."),
    Knob("BENCH_ELASTICITY_LOW", "int", "4",
         "Low-tide request count for the elasticity bench row."),
    Knob("BENCH_ELASTICITY_HIGH", "int", "48",
         "High-tide request count for the elasticity bench row."),
    Knob("BENCH_ELASTICITY_GRACE_S", "float", "120",
         "Scale-settle grace period for the elasticity bench row."),
    Knob("BENCH_HIGHRES_SIZE", "str", "1088,1920",
         "High-resolution bench row frame size 'H,W'."),
    Knob("BENCH_HIGHRES_ITERS", "int", "32 on accelerator, 2 on CPU",
         "RAFT iterations for the high-resolution bench row."),
    Knob("BENCH_HIGHRES_REPS", "int", "3 on accelerator, 2 on CPU",
         "High-resolution bench reps."),
    Knob("BENCH_HIGHRES_COMPARE", "enabled", "1",
         "Also time the unsharded reference window when a mesh is "
         "active ('0' skips the comparison)."),
    Knob("BENCH_UHD_SIZE", "str", "2176,3840",
         "UHD bench row frame size 'H,W'."),
    Knob("BENCH_UHD_ITERS", "int", "32 on accelerator, 1 on CPU",
         "RAFT iterations for the UHD bench row."),
    Knob("BENCH_UHD_REPS", "int", "3 on accelerator, 2 on CPU",
         "UHD bench reps."),
    Knob("BENCH_UHD_CORR", "str", "pallas on accelerator, onthefly on CPU",
         "Correlation implementation for the UHD bench row."),
    Knob("BENCH_PIPELINE_SEGMENTS", "posint", None,
         "Pipeline segment count; unset = largest of 4, 2 that fits "
         "the device count, else 1."),
    Knob("BENCH_PIPELINE_SIZE", "str", "256,448",
         "Pipeline bench row frame size 'H,W'."),
    Knob("BENCH_PIPELINE_ITERS", "int", "32 on accelerator, 4 on CPU",
         "RAFT iterations for the pipeline bench row (quantized down "
         "to a segment boundary)."),
    Knob("BENCH_PIPELINE_BATCHES", "int", "2 x segments",
         "Micro-batches streamed through the pipeline bench row."),
    Knob("BENCH_PIPELINE_COMPARE", "enabled", "1",
         "Also time the monolithic (single-segment) reference window "
         "('0' skips the comparison)."),
    Knob("BENCH_EARLYEXIT_TOL", "float", "0.016",
         "Convergence tolerance the early-exit bench row measures with "
         "(low-res px; default tuned for the untrained bench weights)."),
    Knob("BENCH_EARLYEXIT_ITERS", "int", "4",
         "Iteration budget for the early-exit bench row (both windows). "
         "Default sized for the untrained bench weights, whose flow "
         "deltas plateau instead of decaying: converged lanes exit "
         "around iteration 2, and the quality price grows with every "
         "budgeted-but-skipped iteration, so a small budget keeps the "
         "measured EPE delta inside EARLYEXIT_EPE_BUDGET."),
    Knob("BENCH_EARLYEXIT_REQUESTS", "int", "12",
         "Mixed-resolution requests the early-exit bench row streams."),
)


def _build_registry() -> Dict[str, Knob]:
    by_name: Dict[str, Knob] = {}
    for knob in KNOBS:
        if knob.name in by_name:
            raise ValueError(f"duplicate env knob declaration: {knob.name}")
        by_name[knob.name] = knob
    return by_name


_BY_NAME: Dict[str, Knob] = _build_registry()


def get(name: str) -> Knob:
    """The :class:`Knob` declared for ``name``; raises ``KeyError`` for
    names missing from the registry — the runtime half of JGL013."""
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unregistered env knob {name!r}: declare it in "
            "raft_ncup_tpu/utils/knobs.py (lint rule JGL013)"
        ) from None


def knob_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """The raw env string when set; else ``default`` when given (the
    call site owns context-dependent defaults); else the registered
    default."""
    knob = get(name)
    raw = os.environ.get(name)
    if raw is not None:
        return raw
    return default if default is not None else knob.default


def knob_str(name: str, default: Optional[str] = None) -> str:
    """Like :func:`knob_raw` but for knobs that always resolve to a
    string (a registered or call-site default exists)."""
    value = knob_raw(name, default)
    if value is None:
        raise ValueError(f"env knob {name} has no value and no default")
    return value


def knob_int(name: str, default: Optional[str] = None) -> int:
    return int(knob_str(name, default))


def knob_float(name: str, default: Optional[str] = None) -> float:
    return float(knob_str(name, default))


def knob_flag(name: str) -> bool:
    """Opt-in boolean: true only when the env value is exactly '1'."""
    get(name)
    return os.environ.get(name) == "1"


def knob_enabled(name: str) -> bool:
    """Opt-out boolean: true unless the env value is exactly '0'."""
    get(name)
    return os.environ.get(name, "1") != "0"


def knob_positive_int(name: str) -> Optional[int]:
    """Positive-int override or None meaning "auto": unset, non-int,
    and non-positive values all mean "no override" (the correlation
    tuning-knob parse shared by row-chunk / query-block / band-rows)."""
    get(name)
    raw = os.environ.get(name)
    if not raw:
        return None
    try:
        v = int(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def catalog_markdown() -> str:
    """The knob catalog as a markdown table (the docs/PERF.md block;
    ``python -m raft_ncup_tpu.utils.knobs`` prints it)."""
    lines = [
        "| Knob | Kind | Default | Meaning |",
        "| --- | --- | --- | --- |",
    ]
    for knob in sorted(KNOBS, key=lambda k: k.name):
        default = "unset" if knob.default is None else f"`{knob.default}`"
        lines.append(
            f"| `{knob.name}` | {knob.kind} | {default} | {knob.doc} |"
        )
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    print(catalog_markdown(), end="")
