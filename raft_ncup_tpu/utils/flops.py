"""Paper-FLOPs accounting and MFU estimation for the RAFT/NCUP models.

The reference records no FLOPs or throughput anywhere (BASELINE.md); this
module provides an analytic per-forward FLOP count from the architecture
constants (reference anchors: encoders core/extractor.py:118-192, corr
matmul core/corr.py:13-21, update block core/update.py:79-141, NCUP
core/upsampler.py:143-177 + core/nconv_modules.py:25-136) so the bench can
report MFU = achieved FLOPs/s over the chip's peak. When a compiled
executable is at hand, prefer XLA's own ``cost_analysis()['flops']`` —
``bench.py`` uses that and falls back to this estimate.

Counting convention: one conv = 2*k*k*Cin*Cout*Hout*Wout FLOPs (MAC = 2).
Elementwise/normalization work is ignored (sub-1% for these models).
"""

from __future__ import annotations

from raft_ncup_tpu.config import ModelConfig

# Peak dense-matmul FLOPs/s per chip (bf16), public spec-sheet numbers.
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def _conv(k: int, cin: int, cout: int, h: int, w: int) -> float:
    return 2.0 * k * k * cin * cout * h * w


def _basic_encoder_flops(h: int, w: int, out_dim: int) -> float:
    """BasicEncoder on one (h, w) image (reference: core/extractor.py:118-192):
    7x7/2 stem to 64, three 2-block residual stages 64(s1)/96(s2)/128(s2),
    1x1 head to ``out_dim``."""
    f = 0.0
    h2, w2 = h // 2, w // 2
    f += _conv(7, 3, 64, h2, w2)  # stem
    # layer1: two blocks at 64ch, stride 1, (h/2, w/2)
    f += 4 * _conv(3, 64, 64, h2, w2)
    # layer2: 64->96 stride 2 at (h/4, w/4) incl. 1x1 downsample shortcut
    h4, w4 = h // 4, w // 4
    f += _conv(3, 64, 96, h4, w4) + _conv(3, 96, 96, h4, w4)
    f += _conv(1, 64, 96, h4, w4)
    f += 2 * _conv(3, 96, 96, h4, w4)
    # layer3: 96->128 stride 2 at (h/8, w/8)
    h8, w8 = h // 8, w // 8
    f += _conv(3, 96, 128, h8, w8) + _conv(3, 128, 128, h8, w8)
    f += _conv(1, 96, 128, h8, w8)
    f += 2 * _conv(3, 128, 128, h8, w8)
    f += _conv(1, 128, out_dim, h8, w8)  # head
    return f


def _update_block_flops(h8: int, w8: int, corr_planes: int) -> float:
    """BasicMotionEncoder + SepConvGRU + FlowHead per iteration at 1/8 res
    (reference: core/update.py:79-141)."""
    f = 0.0
    # motion encoder
    f += _conv(1, corr_planes, 256, h8, w8)
    f += _conv(3, 256, 192, h8, w8)
    f += _conv(7, 2, 128, h8, w8)
    f += _conv(3, 128, 64, h8, w8)
    f += _conv(3, 192 + 64, 126, h8, w8)
    # SepConvGRU: two sequential GRUs (1x5 then 5x1), three k=5 separable
    # convs each, cin=256 cout=128 — 6 convs total per iteration.
    f += 6 * (2.0 * 5 * 256 * 128 * h8 * w8)
    # flow head
    f += _conv(3, 128, 256, h8, w8) + _conv(3, 256, 2, h8, w8)
    return f


def _ncup_flops(cfg: ModelConfig, H: int, W: int, batch_mult: int) -> float:
    """One NCUP x4 upsampling pass: Simple weights-net at the x4 LR grid
    (H/4) + NConvUNet at full res with channels_to_batch (reference:
    core/upsampler.py:143-177, core/interp_weights_est.py:10-47,
    core/nconv_modules.py:25-136)."""
    up = cfg.upsampler
    f = 0.0
    # weights estimation at the LR grid of the x4 stage = (H/4, W/4);
    # input = data(2) + guidance(128) = 130 channels.
    h4, w4 = H // 4, W // 4
    chans = (130,) + tuple(up.weights_est_num_ch) + (2,)
    for k, cin, cout in zip(up.weights_est_filter_sz, chans[:-1], chans[1:]):
        f += _conv(k, cin, cout, h4, w4)
    # NConvUNet on (B*2, 1ch) full-res maps; every NConv2d = two convs
    # (conv(c*x) and conv(c)). Shared 5x5 encoder at full + half res,
    # 3x3 decoder at full res, 1x1 head. mult = channels_multiplier.
    m = up.channels_multiplier
    ke, kd, ko = up.encoder_filter_sz, up.decoder_filter_sz, up.out_filter_sz
    f_unet = 0.0
    f_unet += 2 * _conv(ke, 1, m, H, W)  # encoder at full res
    f_unet += 2 * _conv(ke, m, m, H // 2, W // 2)  # encoder at half res
    f_unet += 2 * _conv(kd, 2 * m, m, H, W)  # decoder (skip concat)
    f_unet += 2 * _conv(ko, m, 1, H, W)  # head
    f += batch_mult * f_unet  # channels_to_batch: run per flow channel
    return f


def forward_flops(
    cfg: ModelConfig, batch: int, height: int, width: int, iters: int
) -> float:
    """Analytic FLOPs for one test-mode forward of ``cfg`` at the given
    input shape. Returns total FLOPs for the whole batch."""
    H, W = height, width
    h8, w8 = H // 8, W // 8
    f = 0.0
    f += 2 * _basic_encoder_flops(H, W, cfg.fnet_dim)  # fnet on both frames
    f += _basic_encoder_flops(H, W, cfg.hidden_dim + cfg.context_dim)  # cnet
    if cfg.corr_impl == "volume":
        # all-pairs matmul (reference: core/corr.py:47-55)
        f += 2.0 * (h8 * w8) ** 2 * cfg.fnet_dim
    else:
        # on-the-fly: per-iteration windowed dot products, L levels x K^2 taps
        K2 = (2 * cfg.resolved_corr_radius + 1) ** 2
        f += iters * cfg.corr_levels * K2 * 2.0 * h8 * w8 * cfg.fnet_dim
    f += iters * _update_block_flops(h8, w8, cfg.corr_planes)
    if cfg.variant == "raft_nc_dbl":
        f += iters * _ncup_flops(cfg, H, W, batch_mult=2)
    else:
        # convex-mask head (reference: core/update.py:123-126) + unfold blend
        f += iters * (_conv(3, 128, 256, h8, w8) + _conv(1, 256, 576, h8, w8))
    return batch * f


def train_step_flops(
    cfg: ModelConfig, batch: int, height: int, width: int, iters: int
) -> float:
    """Forward + backward ~= 3x forward (standard paper accounting)."""
    return 3.0 * forward_flops(cfg, batch, height, width, iters)


def peak_flops(tpu_gen: str | None) -> float | None:
    """Per-chip peak bf16 FLOPs/s for a TPU generation string (e.g. 'v5e'),
    None when unknown."""
    if not tpu_gen:
        return None
    return TPU_PEAK_FLOPS.get(tpu_gen.lower())
