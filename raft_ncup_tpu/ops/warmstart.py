"""Forward flow interpolation for warm-starting the next frame.

Splats each pixel's flow to where it lands in the next frame, then fills
the full grid by nearest-neighbor interpolation — the reference's
scipy-``griddata`` warm start used by video-sequence evaluation
(reference: core/utils/utils.py:28-56, used at evaluate.py:38-42).

Host-side numpy: this runs once per frame between device steps, on the
(H/8, W/8, 2) low-res flow, so a cKDTree nearest query is cheap and avoids
pulling scipy's slower ``griddata`` wrapper into the loop.
"""

from __future__ import annotations

import numpy as np


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """(H, W, 2) flow at frame t -> (H, W, 2) estimate for frame t+1.

    Points whose destination leaves the open interval (0, W)x(0, H) are
    dropped (matching the reference's strict inequalities,
    core/utils/utils.py:43); if nothing survives, returns zeros.
    """
    from scipy.spatial import cKDTree  # deferred: scipy only needed here

    flow = np.asarray(flow, dtype=np.float32)
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    ht, wd = flow.shape[:2]
    dx, dy = flow[..., 0], flow[..., 1]
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))

    x1 = (x0 + dx).ravel()
    y1 = (y0 + dy).ravel()
    dxr, dyr = dx.ravel(), dy.ravel()

    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    if not valid.any():
        return np.zeros_like(flow)
    pts = np.stack([x1[valid], y1[valid]], axis=1)
    vals = np.stack([dxr[valid], dyr[valid]], axis=1)

    query = np.stack([x0.ravel(), y0.ravel()], axis=1)
    _, idx = cKDTree(pts).query(query, k=1)
    return vals[idx].reshape(ht, wd, 2).astype(np.float32)
