"""Forward flow interpolation for warm-starting the next frame.

Splats each pixel's flow to where it lands in the next frame, then fills
the full grid by nearest-neighbor interpolation — the reference's
scipy-``griddata`` warm start used by video-sequence evaluation
(reference: core/utils/utils.py:28-56, used at evaluate.py:38-42).

Two implementations of the same math:

- :func:`forward_interpolate` — host numpy + cKDTree. The original
  port: exact Euclidean nearest-neighbor query over the splatted float
  points. Kept as the parity reference and for host-side tooling.
- :func:`forward_interpolate_jax` — pure JAX, traceable, device-
  resident. Same strict-inequality validity mask, same
  nearest-neighbor fill computed by a chunked brute-force distance
  argmin (the low-res grid is small — (H/8)*(W/8) points — so the
  all-pairs distance matrix is a few dozen MB at 1080p and chunking
  bounds the transient). This is what lets per-stream recurrent state
  stay in HBM between frames: the streaming engine
  (``raft_ncup_tpu/streaming/``) and the Sintel warm-start submission
  path trace it into the same program as the gather/scatter around it,
  deleting the per-frame device→host pull the host version forced
  (the last JGL008-allowlisted pull in the inference path, now gone).

Parity: tests/test_warmstart.py pins the JAX splat against the host
cKDTree version on dense, sparse-survivor, and all-points-out-of-bounds
fixtures. Exact ties in the nearest query are measure-zero for
continuous flow fields; both sides break them by index order on the
fixtures used.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def forward_interpolate(flow: np.ndarray) -> np.ndarray:
    """(H, W, 2) flow at frame t -> (H, W, 2) estimate for frame t+1.

    Points whose destination leaves the open interval (0, W)x(0, H) are
    dropped (matching the reference's strict inequalities,
    core/utils/utils.py:43); if nothing survives, returns zeros.
    Host numpy + scipy cKDTree; see :func:`forward_interpolate_jax` for
    the traceable device equivalent.
    """
    from scipy.spatial import cKDTree  # deferred: scipy only needed here

    flow = np.asarray(flow, dtype=np.float32)
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    ht, wd = flow.shape[:2]
    dx, dy = flow[..., 0], flow[..., 1]
    x0, y0 = np.meshgrid(np.arange(wd), np.arange(ht))

    x1 = (x0 + dx).ravel()
    y1 = (y0 + dy).ravel()
    dxr, dyr = dx.ravel(), dy.ravel()

    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    if not valid.any():
        return np.zeros_like(flow)
    pts = np.stack([x1[valid], y1[valid]], axis=1)
    vals = np.stack([dxr[valid], dyr[valid]], axis=1)

    query = np.stack([x0.ravel(), y0.ravel()], axis=1)
    _, idx = cKDTree(pts).query(query, k=1)
    return vals[idx].reshape(ht, wd, 2).astype(np.float32)


def forward_interpolate_jax(
    flow: jax.Array, chunk: int = 1024
) -> jax.Array:
    """Traceable (H, W, 2) forward splat + nearest fill, all on device.

    Mirrors :func:`forward_interpolate` exactly: splat destinations are
    the float points ``(x0 + dx, y0 + dy)``, validity is the same strict
    open interval, and every grid cell takes the value of its nearest
    surviving point (Euclidean, index-order tie-break — the same winner
    ``jnp.argmin``'s first-minimum rule picks). If no point survives the
    bounds check, the result is all zeros.

    The nearest query is a brute-force masked distance argmin instead of
    a KD-tree: at warm-start resolution (1/8 of the frame) the grid has
    a few thousand points, so the (chunk, H*W) distance block is small
    and MXU-shaped. ``chunk`` bounds the transient: queries are
    processed ``chunk`` rows at a time via ``lax.map`` (peak extra
    memory ``chunk * H*W * 4`` bytes).

    Data-dependent work (validity count) is handled with masking, not
    shape changes, so one compilation serves every frame.
    """
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    ht, wd = flow.shape[:2]
    n = ht * wd
    flow = flow.astype(jnp.float32)
    dx = flow[..., 0].ravel()
    dy = flow[..., 1].ravel()
    x0, y0 = jnp.meshgrid(
        jnp.arange(wd, dtype=jnp.float32),
        jnp.arange(ht, dtype=jnp.float32),
    )
    qx, qy = x0.ravel(), y0.ravel()

    x1 = qx + dx
    y1 = qy + dy
    valid = (x1 > 0) & (x1 < wd) & (y1 > 0) & (y1 < ht)
    any_valid = valid.any()
    # Invalid points park at +inf so every real query beats them; if NO
    # point is valid argmin degenerates to index 0 and the final select
    # zeroes the whole field.
    inf = jnp.float32(jnp.inf)
    px = jnp.where(valid, x1, inf)
    py = jnp.where(valid, y1, inf)
    vals = jnp.stack([dx, dy], axis=1)  # (N, 2)

    # chunk and n are static python ints (n comes from the shape), so
    # this is trace-time arithmetic, not a tracer round-trip.
    c = min(max(1, chunk), n)
    n_pad = (-n) % c
    qxp = jnp.pad(qx, (0, n_pad))
    qyp = jnp.pad(qy, (0, n_pad))
    q = jnp.stack([qxp, qyp], axis=1).reshape(-1, c, 2)

    def nearest(q_block: jax.Array) -> jax.Array:
        d2 = (q_block[:, 0, None] - px[None, :]) ** 2 + (
            q_block[:, 1, None] - py[None, :]
        ) ** 2  # (c, N)
        return jnp.argmin(d2, axis=1)

    idx = lax.map(nearest, q).reshape(-1)[:n]
    out = vals[idx].reshape(ht, wd, 2)
    return jnp.where(any_valid, out, jnp.zeros_like(out))


def forward_interpolate_batch(
    flow: jax.Array, chunk: int = 1024
) -> jax.Array:
    """Batched traceable splat: (B, H, W, 2) -> (B, H, W, 2).

    vmap of :func:`forward_interpolate_jax` — each stream's warm start
    is independent, so a corrupt or cold batch row can never leak into
    its batch-mates (the streaming engine's isolation contract rides on
    this row-independence).
    """
    return jax.vmap(lambda f: forward_interpolate_jax(f, chunk))(flow)
