"""Pixel-adaptive convolution (PAC) primitives, functional JAX.

The reference carries NVIDIA's PAC suite with hand-written autograd
Functions (reference: core/pac_modules.py:90-329). In JAX the einsum
forward *is* the implementation — autodiff derives the backward — so this
module is the ``native_impl`` code paths (reference:
core/pac_modules.py:371-424,440-443,462-467,481-489) re-expressed in
channel-last layout:

- patches are (B, H, W, k*k, C) stacks of dilated shifted slices
  (k is small, so k^2 XLA slices fuse cleanly; no im2col materialization
  beyond what the einsum needs);
- the adapting kernel is a Gaussian on guidance-feature differences from
  the window center;
- transposed conv = zero-stuff by stride, asymmetric pad, stride-1 PAC
  conv with the spatially transposed weight.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def extract_patches(
    x: jax.Array,
    ksize: int,
    dilation: int = 1,
    pad_lo: Optional[tuple[int, int]] = None,
    pad_hi: Optional[tuple[int, int]] = None,
) -> jax.Array:
    """Stride-1 sliding windows: (B, H, W, C) -> (B, H', W', k*k, C).

    ``pad_lo``/``pad_hi`` are per-dim (top/left, bottom/right) paddings;
    default is the 'same' padding (k-1)*d // 2 on both sides.
    """
    span = (ksize - 1) * dilation
    if pad_lo is None:
        pad_lo = (span // 2, span // 2)
    if pad_hi is None:
        pad_hi = (span - span // 2, span - span // 2)
    x = jnp.pad(
        x,
        ((0, 0), (pad_lo[0], pad_hi[0]), (pad_lo[1], pad_hi[1]), (0, 0)),
    )
    H_out = x.shape[1] - span
    W_out = x.shape[2] - span
    rows = []
    for i in range(ksize):
        for j in range(ksize):
            rows.append(
                x[:, i * dilation : i * dilation + H_out,
                  j * dilation : j * dilation + W_out, :]
            )
    return jnp.stack(rows, axis=3)


def pac_gaussian_kernel(
    guide: jax.Array,
    ksize: int,
    dilation: int = 1,
    channel_wise: bool = False,
) -> jax.Array:
    """Adapting kernel K = exp(-0.5 ||g_i - g_center||^2) over each window
    (reference: core/pac_modules.py:377-404 native path, gaussian type).

    Returns (B, H, W, k*k) — or (B, H, W, k*k, C) when ``channel_wise``.
    """
    patches = extract_patches(guide, ksize, dilation)
    center = guide[:, :, :, None, :]
    d2 = (patches - center) ** 2
    if not channel_wise:
        d2 = d2.sum(axis=-1)
    return jnp.exp(-0.5 * d2)


def zero_stuff_mask(
    shape_hw: tuple[int, int], stride: int, dtype=jnp.float32
) -> jax.Array:
    """(1, H*s', W*s', 1) indicator of real (non-stuffed) positions in a
    zero-stuffed grid of an (H, W) input — size (H-1)*s+1 per dim."""
    h, w = shape_hw
    oh, ow = (h - 1) * stride + 1, (w - 1) * stride + 1
    m = jnp.zeros((1, oh, ow, 1), dtype)
    return m.at[:, ::stride, ::stride, :].set(1.0)


def _zero_stuff(x: jax.Array, stride: int) -> jax.Array:
    """(B, H, W, C) -> (B, (H-1)*s+1, (W-1)*s+1, C) with x at stride
    positions (the conv-transpose identity-kernel expansion)."""
    if stride == 1:
        return x
    B, H, W, C = x.shape
    out = jnp.zeros((B, (H - 1) * stride + 1, (W - 1) * stride + 1, C), x.dtype)
    return out.at[:, ::stride, ::stride, :].set(x)


def pacconv2d(
    x: jax.Array,
    kernel: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    dilation: int = 1,
    pad_lo: Optional[tuple[int, int]] = None,
    pad_hi: Optional[tuple[int, int]] = None,
) -> jax.Array:
    """Stride-1 PAC convolution (reference: core/pac_modules.py:440-443).

    x: (B, H, W, Cin); kernel: (B, H', W', k*k) from
    :func:`pac_gaussian_kernel`; weight: (k*k, Cin, Cout).
    """
    ksize = int(round(weight.shape[0] ** 0.5))
    patches = extract_patches(x, ksize, dilation, pad_lo, pad_hi)
    return _pac_contract(patches, kernel, weight, bias)


def _pac_contract(patches, kernel, weight, bias):
    out = jnp.einsum(
        "bhwkc,bhwk,kco->bhwo", patches, kernel, weight,
        preferred_element_type=jnp.float32,
    )
    if bias is not None:
        out = out + bias
    return out


def pacconv_transpose2d(
    x: jax.Array,
    kernel: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    stride: int = 2,
    padding: int = 0,
    output_padding: int = 0,
    dilation: int = 1,
) -> jax.Array:
    """Transposed PAC convolution (reference: core/pac_modules.py:462-467):
    zero-stuff by ``stride``, pad (k-1)*d - p (+output_padding at
    bottom/right), then stride-1 PAC conv. ``kernel`` is computed from
    guidance at the OUTPUT resolution; ``weight`` is (k*k, Cin, Cout).
    """
    stuffed = _zero_stuff(x, stride)
    ksize = int(round(weight.shape[0] ** 0.5))
    pad = (ksize - 1) * dilation - padding
    return pacconv2d(
        stuffed, kernel, weight, bias, dilation,
        pad_lo=(pad, pad),
        pad_hi=(pad + output_padding, pad + output_padding),
    )


def pacpool2d(
    x: jax.Array, kernel: jax.Array, ksize: int, dilation: int = 1
) -> jax.Array:
    """Kernel-weighted window sum per channel (reference:
    core/pac_modules.py:481-489, stride 1)."""
    patches = extract_patches(x, ksize, dilation)
    return jnp.einsum("bhwkc,bhwk->bhwc", patches, kernel)
