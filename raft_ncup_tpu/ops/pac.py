"""Pixel-adaptive convolution (PAC) primitives, functional JAX.

The reference carries NVIDIA's PAC suite with hand-written autograd
Functions (reference: core/pac_modules.py:90-329). In JAX the einsum
forward *is* the implementation — autodiff derives the backward — so this
module is the ``native_impl`` code paths (reference:
core/pac_modules.py:371-424,440-443,462-467,481-489) re-expressed in
channel-last layout:

- patches are (B, H, W, k*k, C) stacks of dilated shifted slices
  (k is small, so k^2 XLA slices fuse cleanly; no im2col materialization
  beyond what the einsum needs);
- the adapting kernel is a Gaussian on guidance-feature differences from
  the window center;
- transposed conv = zero-stuff by stride, asymmetric pad, stride-1 PAC
  conv with the spatially transposed weight.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def extract_patches(
    x: jax.Array,
    ksize: int,
    dilation: int = 1,
    pad_lo: Optional[tuple[int, int]] = None,
    pad_hi: Optional[tuple[int, int]] = None,
) -> jax.Array:
    """Stride-1 sliding windows: (B, H, W, C) -> (B, H', W', k*k, C).

    ``pad_lo``/``pad_hi`` are per-dim (top/left, bottom/right) paddings;
    default is the 'same' padding (k-1)*d // 2 on both sides.
    """
    span = (ksize - 1) * dilation
    if pad_lo is None:
        pad_lo = (span // 2, span // 2)
    if pad_hi is None:
        pad_hi = (span - span // 2, span - span // 2)
    x = jnp.pad(
        x,
        ((0, 0), (pad_lo[0], pad_hi[0]), (pad_lo[1], pad_hi[1]), (0, 0)),
    )
    H_out = x.shape[1] - span
    W_out = x.shape[2] - span
    rows = []
    for i in range(ksize):
        for j in range(ksize):
            rows.append(
                x[:, i * dilation : i * dilation + H_out,
                  j * dilation : j * dilation + W_out, :]
            )
    return jnp.stack(rows, axis=3)


def pac_gaussian_kernel(
    guide: jax.Array,
    ksize: int,
    dilation: int = 1,
    channel_wise: bool = False,
) -> jax.Array:
    """Adapting kernel K = exp(-0.5 ||g_i - g_center||^2) over each window
    (reference: core/pac_modules.py:377-404 native path, gaussian type).

    Returns (B, H, W, k*k) — or (B, H, W, k*k, C) when ``channel_wise``.
    """
    patches = extract_patches(guide, ksize, dilation)
    center = guide[:, :, :, None, :]
    d2 = (patches - center) ** 2
    if not channel_wise:
        d2 = d2.sum(axis=-1)
    return jnp.exp(-0.5 * d2)


def smooth_kernel_2d(kind: str) -> jax.Array:
    """Fixed smoothing kernels for the ``smooth_kernel_type`` options
    (reference: core/pac_modules.py:566-580): 'gaussian' is the separable
    [.25, .5, .25] stencil; 'average_{sz}' is a box filter."""
    if kind == "gaussian":
        s1 = jnp.asarray([0.25, 0.5, 0.25], jnp.float32)
    elif kind.startswith("average_"):
        sz = int(kind.split("_")[-1])
        s1 = jnp.full((sz,), 1.0 / sz)
    else:
        raise ValueError(f"unknown fixed smooth kernel {kind!r}")
    return s1[:, None] * s1[None, :]


def _smoothed_center(
    guide: jax.Array,
    smooth_kernel: jax.Array,
    ksize: int,
    stride: int,
    pad: tuple[int, int],
) -> jax.Array:
    """Window-center feature as a smoothed (depthwise-filtered) guide, the
    ``smooth_kernel_type != 'none'`` branch (reference:
    core/pac_modules.py:380-387): conv the guide with the small kernel at
    padding ``pad - (ksize - smooth_sz)//2`` (cropping when negative) so
    each output aligns with its window's center."""
    sh, sw = smooth_kernel.shape
    sp_h = pad[0] - (ksize - sh) // 2
    sp_w = pad[1] - (ksize - sw) // 2

    def crop_pad(x, amount, axis):
        if amount >= 0:
            cfg = [(0, 0)] * x.ndim
            cfg[axis] = (amount, amount)
            return jnp.pad(x, cfg)
        return jax.lax.slice_in_dim(x, -amount, x.shape[axis] + amount, axis=axis)

    g = crop_pad(crop_pad(guide, sp_h, 1), sp_w, 2)
    C = g.shape[-1]
    w = jnp.broadcast_to(smooth_kernel[:, :, None, None], (sh, sw, 1, C))
    out = jax.lax.conv_general_dilated(
        g.astype(smooth_kernel.dtype), w,
        window_strides=(stride, stride), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=C,
    )
    return out


def pac_kernel2d(
    guide: jax.Array,
    ksize: int,
    *,
    stride: int = 1,
    dilation: int = 1,
    padding: int = 0,
    kernel_type: str = "gaussian",
    inv_alpha: Optional[jax.Array] = None,
    inv_lambda: Optional[jax.Array] = None,
    asym: bool = False,
    smooth_kernel: Optional[jax.Array] = None,
    channel_wise: bool = False,
    normalize_kernel: bool = False,
    mask: Optional[jax.Array] = None,
    pad_lo: Optional[tuple[int, int]] = None,
    pad_hi: Optional[tuple[int, int]] = None,
) -> tuple[jax.Array, Optional[jax.Array]]:
    """General adapting-kernel computation — the full ``packernel2d``
    capability surface (reference: core/pac_modules.py:332-424, native
    path), channel-last:

    - ``kernel_type``: 'gaussian' -> exp(-0.5 d2); 'inv' ->
      alpha + (d2 + 1e-4)^(0.5 lambda) with learnable alpha/lambda;
      ``asym`` relu's the guide difference before squaring ('_asym').
    - ``smooth_kernel``: window-center feature is a smoothed guide
      instead of the center tap.
    - ``channel_wise``: keep per-channel kernels (B, H, W, k*k, C).
    - ``mask``: (B, H, W, 1) validity; the kernel is masked and, unless
      ``normalize_kernel``, scaled by (mask coverage / full coverage);
      returns the output-resolution mask as the second element.
    - ``normalize_kernel``: divide by the window sum.

    Returns ``(kernel, mask_out)``; ``mask_out`` is None without ``mask``.
    ``pad_lo``/``pad_hi`` override the symmetric ``padding`` (the
    transposed wrappers need the asymmetric 'same' split for even kernel
    sizes).
    """
    pad = (padding, padding)
    lo = pad if pad_lo is None else pad_lo
    hi = pad if pad_hi is None else pad_hi
    patches = extract_patches(guide, ksize, dilation, lo, hi)
    patches = patches[:, ::stride, ::stride]

    if smooth_kernel is None:
        center = patches[:, :, :, (ksize * ksize) // 2, :]
    else:
        center = _smoothed_center(guide, smooth_kernel, ksize, stride, lo)
    diff = patches - center[:, :, :, None, :]
    if asym:
        diff = jax.nn.relu(diff)
    d2 = diff * diff
    if not channel_wise:
        d2 = d2.sum(axis=-1)

    if kernel_type == "gaussian":
        kernel = jnp.exp(-0.5 * d2)
    elif kernel_type == "inv":
        # alpha/lambda broadcast over a trailing per-channel axis when
        # channel_wise (reference: core/pac_modules.py:400-403).
        a = jnp.reshape(inv_alpha, (1, 1, 1, 1, -1) if channel_wise else (1, 1, 1, -1))
        lam = jnp.reshape(inv_lambda, (1, 1, 1, 1, -1) if channel_wise else (1, 1, 1, -1))
        if not channel_wise:
            d2 = d2[..., None]
        kernel = a + jnp.power(d2 + 1e-4, 0.5 * lam)
        if not channel_wise and kernel.shape[-1] == 1:
            kernel = kernel[..., 0]
    else:
        raise ValueError(f"unknown kernel_type {kernel_type!r}")

    per_channel = kernel.ndim == 5  # (B, H', W', k*k[, C])
    norm = None
    mask_out = None
    if mask is not None or normalize_kernel:
        # In-bounds indicator: taps landing on zero padding don't count
        # (reference mask_pattern, core/pac_modules.py:353-356).
        ones = extract_patches(
            jnp.ones((*guide.shape[:3], 1), guide.dtype),
            ksize, dilation, lo, hi,
        )[:, ::stride, ::stride, :, 0]
    if mask is not None:
        mask = mask.astype(guide.dtype)
        mpat = extract_patches(mask, ksize, dilation, lo, hi)
        mpat = mpat[:, ::stride, ::stride, :, 0]
        if not normalize_kernel:
            norm = mpat.sum(axis=3, keepdims=True) / ones.sum(
                axis=3, keepdims=True
            )
            if per_channel:
                norm = norm[..., None]
    else:
        mpat = ones if normalize_kernel else None
    if mpat is not None:
        kernel = kernel * (mpat[..., None] if per_channel else mpat)
    if normalize_kernel:
        norm = kernel.sum(axis=3, keepdims=True)
    if norm is not None:
        empty = (norm == 0).astype(kernel.dtype)
        kernel = kernel / (norm + empty)
        if mask is not None:
            mask_out = 1.0 - empty.reshape(
                kernel.shape[0], *kernel.shape[1:3], -1
            )[..., :1]
    return kernel, mask_out


def zero_stuff_mask(
    shape_hw: tuple[int, int], stride: int, dtype=jnp.float32
) -> jax.Array:
    """(1, H*s', W*s', 1) indicator of real (non-stuffed) positions in a
    zero-stuffed grid of an (H, W) input — size (H-1)*s+1 per dim."""
    h, w = shape_hw
    oh, ow = (h - 1) * stride + 1, (w - 1) * stride + 1
    m = jnp.zeros((1, oh, ow, 1), dtype)
    return m.at[:, ::stride, ::stride, :].set(1.0)


def _zero_stuff(x: jax.Array, stride: int) -> jax.Array:
    """(B, H, W, C) -> (B, (H-1)*s+1, (W-1)*s+1, C) with x at stride
    positions (the conv-transpose identity-kernel expansion)."""
    if stride == 1:
        return x
    B, H, W, C = x.shape
    out = jnp.zeros((B, (H - 1) * stride + 1, (W - 1) * stride + 1, C), x.dtype)
    return out.at[:, ::stride, ::stride, :].set(x)


def pacconv2d(
    x: jax.Array,
    kernel: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    dilation: int = 1,
    pad_lo: Optional[tuple[int, int]] = None,
    pad_hi: Optional[tuple[int, int]] = None,
    stride: int = 1,
    shared_filters: bool = False,
) -> jax.Array:
    """PAC convolution (reference: core/pac_modules.py:427-449 native).

    x: (B, H, W, Cin); kernel: (B, H', W', k*k) from
    :func:`pac_gaussian_kernel` / :func:`pac_kernel2d`; weight:
    (k*k, Cin, Cout) — or (k*k,) with ``shared_filters`` (one spatial
    filter applied to every channel, reference: :439-441).
    """
    ksize = int(round(weight.shape[0] ** 0.5))
    patches = extract_patches(x, ksize, dilation, pad_lo, pad_hi)
    patches = patches[:, ::stride, ::stride]
    return _pac_contract(patches, kernel, weight, bias, shared_filters)


def _pac_contract(patches, kernel, weight, bias, shared_filters=False):
    if shared_filters:
        out = jnp.einsum(
            "bhwkc,bhwk,k->bhwc", patches, kernel, weight.reshape(-1),
            preferred_element_type=jnp.float32,
        )
    else:
        out = jnp.einsum(
            "bhwkc,bhwk,kco->bhwo", patches, kernel, weight,
            preferred_element_type=jnp.float32,
        )
    if bias is not None:
        out = out + bias
    return out


def pacconv_transpose2d(
    x: jax.Array,
    kernel: jax.Array,
    weight: jax.Array,
    bias: Optional[jax.Array] = None,
    stride: int = 2,
    padding: int = 0,
    output_padding: int = 0,
    dilation: int = 1,
) -> jax.Array:
    """Transposed PAC convolution (reference: core/pac_modules.py:462-467):
    zero-stuff by ``stride``, pad (k-1)*d - p (+output_padding at
    bottom/right), then stride-1 PAC conv. ``kernel`` is computed from
    guidance at the OUTPUT resolution; ``weight`` is (k*k, Cin, Cout).
    """
    stuffed = _zero_stuff(x, stride)
    ksize = int(round(weight.shape[0] ** 0.5))
    pad = (ksize - 1) * dilation - padding
    return pacconv2d(
        stuffed, kernel, weight, bias, dilation,
        pad_lo=(pad, pad),
        pad_hi=(pad + output_padding, pad + output_padding),
    )


def pacpool2d(
    x: jax.Array,
    kernel: jax.Array,
    ksize: int,
    dilation: int = 1,
    stride: int = 1,
    padding: Optional[int] = None,
) -> jax.Array:
    """Kernel-weighted window sum per channel (reference:
    core/pac_modules.py:475-494 native). ``kernel`` is (B, H', W', k*k)
    (shared across channels) or (B, H', W', k*k, C) (channel-wise).
    ``padding=None`` keeps the historical 'same' default."""
    pad = None if padding is None else (padding, padding)
    patches = extract_patches(x, ksize, dilation, pad, pad)
    patches = patches[:, ::stride, ::stride]
    if kernel.ndim == 5:
        return jnp.einsum("bhwkc,bhwkc->bhwc", patches, kernel)
    return jnp.einsum("bhwkc,bhwk->bhwc", patches, kernel)
