"""All-pairs correlation volume + multi-scale windowed lookup.

Two interchangeable implementations behind one signature:

- ``build_corr_pyramid`` + ``corr_lookup`` materialize the O((HW)^2) volume
  once per pair (reference semantics: core/corr.py:13-44). The einsum maps
  straight onto the MXU; the 4-level pyramid is built with 2x2 average
  pooling. Fast at training resolutions; the volume at 1/8 res of a 400x720
  crop is ~100 MB/pair in fp32.

- ``corr_lookup_onthefly`` never materializes the volume. Because the
  lookup bilinearly samples the volume over its *second* pair of spatial
  dims for a fixed query pixel, and correlation is linear in fmap2,
  sample-then-dot == dot-then-sample:

      bilerp_q <f1(p), f2(q)> = <f1(p), bilerp_q f2(q)>

  (zero padding also agrees: an out-of-bounds tap contributes 0 either
  way). So we bilinearly sample fmap2 at the 81 window taps and contract
  with fmap1 on the fly, chunked over query rows to bound memory. This is
  the memory-efficient path for 1080p / 32-iter inference where the full
  volume would be several GB (SURVEY.md §5 "long-context" analogue).

A fused Pallas kernel for the lookup lives in
``raft_ncup_tpu.ops.corr_pallas`` and is validated against these.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp

from raft_ncup_tpu.ops.geometry import avg_pool2, grid_sample
from raft_ncup_tpu.utils.knobs import knob_positive_int

ROW_CHUNK_ENV = "RAFT_NCUP_CORR_ROW_CHUNK"
_DEFAULT_ROW_CHUNK = 8


def effective_row_chunk() -> int:
    """The row-chunk size ``corr_lookup_onthefly`` traces with when the
    caller passes none: the ``RAFT_NCUP_CORR_ROW_CHUNK`` override if
    set (a tuning knob — larger chunks amortize the scan at more peak
    memory; the 4K fallback/sharded paths are where it matters), else
    8. Recorded in the cost-ledger meta (:func:`corr_tuning_meta`) so
    the choice behind a warmed executable is visible to
    ``scripts/flip_recommendations.py`` and ROADMAP item 1's
    autotuner."""
    return knob_positive_int(ROW_CHUNK_ENV) or _DEFAULT_ROW_CHUNK


def corr_tuning_meta() -> dict:
    """Effective correlation tuning-knob values — one flat dict the
    compiled-executable cost ledger (inference/costs.py) stamps into
    every forward/metric entry's meta: the onthefly ``row_chunk`` plus
    the Pallas kernel's query-block / band-rows knobs
    (``ops.corr_pallas.tuning_meta``). The autotuner's sweep surface:
    persisted next to the XLA cost facts, keyed like the executables."""
    meta = {"corr_row_chunk": effective_row_chunk()}
    try:
        from raft_ncup_tpu.ops import corr_pallas

        meta.update(corr_pallas.tuning_meta())
    except ImportError:  # pragma: no cover - jax builds without pallas
        pass
    return meta


class CorrPyramid(NamedTuple):
    """Materialized correlation pyramid.

    ``levels[l]`` has shape (B, H1*W1, H2/2^l, W2/2^l): all-pairs
    correlation between every query pixel of fmap1 and the (pooled) pixels
    of fmap2, pre-divided by sqrt(dim) (reference: core/corr.py:47-55).
    """

    levels: tuple[jax.Array, ...]
    query_hw: tuple[int, int]


def _delta_window(radius: int, dtype=jnp.float32) -> jax.Array:
    """(K, K, 2) window offsets, K = 2r+1.

    Tap (i, j) offsets the *x* coordinate by (i - r) and the *y* coordinate
    by (j - r): the reference builds ``delta`` from ``meshgrid(dy, dx)`` and
    adds it to (x, y)-ordered centroids (core/corr.py:31-37), so the first
    window axis varies the x offset. Preserving this ordering keeps the
    lookup's output channel order — and therefore motion-encoder weights —
    compatible with reference checkpoints.
    """
    d = jnp.arange(-radius, radius + 1, dtype=dtype)
    di, dj = jnp.meshgrid(d, d, indexing="ij")
    return jnp.stack([di, dj], axis=-1)  # [..., 0] -> x offset, [..., 1] -> y


def build_corr_pyramid(
    fmap1: jax.Array, fmap2: jax.Array, num_levels: int = 4, dtype=None
) -> CorrPyramid:
    """Compute the all-pairs correlation volume and its average pyramid.

    Args:
      fmap1, fmap2: (B, H, W, C) feature maps (cast to ``dtype``, default
        float32 like the reference's ``fmap.float()`` at
        core/raft.py:103-104).
      dtype: storage dtype of the volume — the dominant memory term, so
        the precision policy's bf16 presets halve it here
        (``PrecisionPolicy.corr_jnp``). The dot products ACCUMULATE in
        f32 regardless (``preferred_element_type``); only storage
        narrows. Lookup arithmetic re-widens via ``grid_sample``'s
        promotion, so coordinates never demote.
    """
    B, H, W, C = fmap1.shape
    dtype = dtype or jnp.float32
    f1 = fmap1.reshape(B, H * W, C).astype(dtype)
    f2 = fmap2.reshape(B, H * W, C).astype(dtype)
    corr = jnp.einsum(
        "bxc,byc->bxy", f1, f2, preferred_element_type=jnp.float32
    ) / math.sqrt(C)
    corr = corr.astype(dtype).reshape(B, H * W, H, W)

    levels = [corr]
    for _ in range(num_levels - 1):
        n, q, h, w = levels[-1].shape
        pooled = avg_pool2(levels[-1].reshape(n * q, h, w, 1))
        levels.append(pooled.reshape(n, q, pooled.shape[1], pooled.shape[2]))
    return CorrPyramid(levels=tuple(levels), query_hw=(H, W))


def corr_lookup(pyramid: CorrPyramid, coords: jax.Array, radius: int) -> jax.Array:
    """Sample (2r+1)^2 windows around ``coords / 2^l`` at every level.

    Reference: core/corr.py:23-44.

    Args:
      pyramid: from :func:`build_corr_pyramid`.
      coords: (B, H, W, 2) query positions in fmap2 pixel coordinates.
    Returns:
      (B, H, W, L * (2r+1)^2) at the promoted (volume, coords) dtype —
      float32 whenever coords are f32 (the policy's coord contract),
      level-major then window-tap order.
    """
    B, H, W, _ = coords.shape
    K = 2 * radius + 1
    delta = _delta_window(radius)  # (K, K, 2)

    out = []
    for lvl, corr in enumerate(pyramid.levels):
        _, _, Hl, Wl = corr.shape
        centroid = coords.reshape(B, H * W, 1, 1, 2) / (2**lvl)
        coords_lvl = centroid + delta[None, None]  # (B, HW, K, K, 2)
        # Fold queries into the batch dim for the gather.
        vol = corr.reshape(B * H * W, Hl, Wl, 1)
        c = coords_lvl.reshape(B * H * W, K, K, 2)
        sampled = grid_sample(vol, c)  # (B*HW, K, K, 1)
        out.append(sampled.reshape(B, H, W, K * K))
    return jnp.concatenate(out, axis=-1)


def _pool_fmap_pyramid(fmap2: jax.Array, num_levels: int) -> list[jax.Array]:
    """Average-pool fmap2 into a pyramid.

    Pooling the *features* then correlating equals pooling the correlation
    volume (reference pools the volume, core/corr.py:19-21) because the
    2x2 mean acts on the fmap2 axes only and correlation is linear in
    fmap2.
    """
    levels = [fmap2]
    for _ in range(num_levels - 1):
        levels.append(avg_pool2(levels[-1]))
    return levels


def corr_lookup_onthefly(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int = 4,
    row_chunk: int | None = None,
    levels: Sequence[int] | None = None,
    dtype=None,
) -> jax.Array:
    """Windowed correlation lookup without materializing the volume.

    Equivalent to ``corr_lookup(build_corr_pyramid(f1, f2), coords, r)`` up
    to float associativity; O(B * HW * L * K^2 * C) compute per call but
    O(B * row_chunk * W * K^2 * C) peak memory.

    Args:
      fmap1, fmap2: (B, H, W, C).
      coords: (B, H, W, 2).
      row_chunk: query rows processed per scan step (H % row_chunk may be
        nonzero; handled by padding). ``None`` (default) resolves via
        :func:`effective_row_chunk` — 8, overridable with
        ``RAFT_NCUP_CORR_ROW_CHUNK`` (the knob that tunes the 4K
        fallback path; its value rides the cost-ledger meta).
      levels: pyramid level indices to compute (default: all
        ``num_levels``); the Pallas dispatcher uses this to source only
        the levels whose slab exceeds its VMEM budget.
      dtype: feature/pyramid dtype (default f32; the precision policy's
        ``corr_jnp`` under bf16 presets — halves the resident pyramid).
        The tap sampling promotes back through the f32 coords and the
        contraction accumulates in f32, so the output stays f32.
    """
    B, H, W, C = fmap1.shape
    K = 2 * radius + 1
    scale = 1.0 / math.sqrt(C)
    dtype = dtype or jnp.float32
    if row_chunk is None:
        row_chunk = effective_row_chunk()
    level_ids = tuple(range(num_levels)) if levels is None else tuple(levels)
    f2_levels = _pool_fmap_pyramid(fmap2.astype(dtype), num_levels)
    f1 = fmap1.astype(dtype)
    delta = _delta_window(radius)

    pad_rows = (-H) % row_chunk
    f1p = jnp.pad(f1, ((0, 0), (0, pad_rows), (0, 0), (0, 0)))
    cp = jnp.pad(coords.astype(jnp.float32), ((0, 0), (0, pad_rows), (0, 0), (0, 0)))
    n_chunks = (H + pad_rows) // row_chunk

    f1c = f1p.reshape(B, n_chunks, row_chunk, W, C).transpose(1, 0, 2, 3, 4)
    cc = cp.reshape(B, n_chunks, row_chunk, W, 2).transpose(1, 0, 2, 3, 4)

    def chunk_fn(carry, xs):
        f1_chunk, coords_chunk = xs  # (B, rc, W, C), (B, rc, W, 2)
        per_level = []
        for lvl in level_ids:
            centroid = coords_chunk[:, :, :, None, None, :] / (2**lvl)
            taps = centroid + delta[None, None, None]  # (B, rc, W, K, K, 2)
            sampled = grid_sample(f2_levels[lvl], taps)  # (B, rc, W, K, K, C)
            corr = jnp.einsum(
                "brwijc,brwc->brwij", sampled, f1_chunk,
                preferred_element_type=jnp.float32,
            ) * scale
            per_level.append(corr.reshape(*corr.shape[:3], K * K))
        return carry, jnp.concatenate(per_level, axis=-1)

    _, chunks = jax.lax.scan(chunk_fn, None, (f1c, cc))
    # (n_chunks, B, rc, W, L*K*K) -> (B, H, W, L*K*K)
    out = chunks.transpose(1, 0, 2, 3, 4).reshape(B, H + pad_rows, W, -1)
    return out[:, :H]
