from raft_ncup_tpu.ops.geometry import (  # noqa: F401
    adaptive_area_resize,
    bilinear_resize_align_corners,
    convex_upsample,
    coords_grid,
    grid_sample,
    upsample_nearest,
    upflow,
)
from raft_ncup_tpu.ops.corr import (  # noqa: F401
    CorrPyramid,
    build_corr_pyramid,
    corr_lookup,
    corr_lookup_onthefly,
)
from raft_ncup_tpu.ops.nconv import (  # noqa: F401
    downsample_data_conf,
    nconv2d,
    positivity,
    zero_stuff_upsample,
)
from raft_ncup_tpu.ops.padding import InputPadder  # noqa: F401
from raft_ncup_tpu.ops.warmstart import (  # noqa: F401
    forward_interpolate,
    forward_interpolate_batch,
    forward_interpolate_jax,
)
