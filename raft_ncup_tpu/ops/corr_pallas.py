"""Pallas TPU kernel: fused, volume-free correlation-window lookup.

The XLA paths (raft_ncup_tpu.ops.corr) either materialize the O((HW)^2)
all-pairs volume (`volume`) or bilinearly gather fmap2 taps (`onthefly`).
This kernel fuses the per-level dot product INTO the windowed lookup, so
the volume never exists anywhere — the §2a(a) design from SURVEY.md:

- Every tap of a query's (2r+1)^2 window shares the same fractional
  offset: the window is an integer-aligned grid shifted by one sub-pixel
  amount, so the whole K x K window equals a 2 x 2 bilinear blend of a
  (K+1) x (K+1) integer-aligned patch of correlations.
- That patch is `sum_c f1[q, c] * f2[iy : iy+K+1, ix : ix+K+1, c]` — a
  dynamic-start slice of the VMEM-resident fmap2 level followed by a
  lane reduction on the VPU. No gather, no roll, and HBM traffic is
  fmap2 once per query block instead of a volume pass.

Kernel shape (round-3 redesign; the round-2 version looped one query at a
time with scalar work per step — VERDICT.md weak #3): queries are
processed in GROUPS of 8 so every vector op runs on (8, 128)-tiled
operands:

- Integer window origins are precomputed on the XLA side and shipped as
  an int32 array in SMEM (the Mosaic-idiomatic home for indices that
  drive dynamic slices); fractional offsets ride along in VMEM.
- Per group, 8 dynamic-start patch loads fill a VMEM scratch
  (8, K+1, K+1, C); the correlation reduce, the 2x2 bilinear blend, and
  the output store are then single vectorized ops over the whole group
  (sublane dim = 8 queries, lane dim = C/taps).

Zero-padding semantics (out-of-bounds taps contribute zero, matching
``grid_sample``) come from pre-padding each level with K+2 zeros per
side; window starts are clamped into the padded array, and any fully-OOB
window lands entirely inside the zero margin.

VMEM budget: the padded level must stay resident on-chip next to the
pipeline's block buffers. The budget is derived from the per-core VMEM
capacity (~16 MiB on current TPUs — /opt/skills/guides/pallas_guide.md
"Memory Hierarchy"; override with RAFT_NCUP_VMEM_BYTES) minus the blocked
operands' double buffers. Dispatch is PER LEVEL: at 1080p levels 0-1
(~42 MB and ~15.3 MB padded, both over the 0.9x budget) fall back to
the XLA on-the-fly path while levels 2-3 still take the kernel
(round-2 gated all-or-nothing on level 0 — VERDICT.md weak #4; exact
counts pinned by tests/test_pallas_lowering.py).

The kernel is forward-only; ``corr_lookup_pallas`` wraps it in a
``jax.custom_vjp`` whose backward runs the XLA on-the-fly path's VJP, so
the op stays trainable. (reference semantics: core/corr.py:23-44)
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu provides the SMEM/VMEM memory-space constants on TPU builds
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
except ImportError:  # pragma: no cover - CPU-only jax builds
    pltpu = None
    _SMEM = None

from raft_ncup_tpu.utils.runtime import VMEM_BYTES as _VMEM_BYTES

_QUERY_BLOCK = 512
_GROUP = 8  # queries per vectorized inner step (sublane tile)

# Trace-time per-level dispatch tally, mirroring ops.nconv: callers that
# label a measurement "corr=pallas" (bench.py) use this to tell whether
# the kernel took any level at all or everything fell back to XLA
# onthefly (partial fallback — e.g. 1080p levels 0-1 — is by design and
# still counts as the kernel running).
_dispatch_counts = {"kernel": 0, "fallback": 0, "levels_total": 0}


def reset_dispatch_counts() -> None:
    for k in _dispatch_counts:
        _dispatch_counts[k] = 0


def dispatch_counts() -> dict:
    """Copy of the per-level dispatch tally since the last reset (counts
    trace-time decisions, one per pyramid level per TRACE — a custom_vjp
    backward trace, a shape-driven retrace, or a concurrent thread each
    add their own tallies, so the counts are only interpretable between
    a reset and a single lowering in a single thread, the discipline
    bench.py follows)."""
    return dict(_dispatch_counts)


def _padded_hw(h: int, w: int, radius: int) -> tuple[int, int, int]:
    # A fully-OOB window is clamped to the array edge and must land
    # entirely inside the zero margin: K + 2 zeros per side.
    pad = 2 * radius + 3
    return h + 2 * pad, w + 2 * pad, pad


def _level_vmem_bytes(
    h: int,
    w: int,
    channels: int,
    radius: int,
    query_block: int = _QUERY_BLOCK,
    itemsize: int = 4,
) -> int:
    """Bytes of VMEM the kernel needs for one (h, w) level: the resident
    padded fmap2 slab + double-buffered query blocks + the group scratch,
    all at ``itemsize`` bytes per element (the precision policy's
    compute dtype — 2 under the bf16 presets, which is exactly the
    dispatch-threshold doubling ROADMAP item 3 wanted; the frac/out
    blocks stay f32 but are a few percent of the slab, so budgeting them
    at ``itemsize`` keeps the threshold ratio an exact itemsize ratio)."""
    hp, wp, _ = _padded_hw(h, w, radius)
    K1 = 2 * radius + 2
    slab = hp * wp * channels
    blocks = 2 * query_block * (channels + 2 + (K1 - 1) ** 2)  # f1+frac+out, x2 pipeline
    scratch = _GROUP * K1 * K1 * channels
    return itemsize * (slab + blocks + scratch)


def fits_vmem(
    h: int, w: int, channels: int, radius: int = 4, dtype=None
) -> bool:
    """Whether a (h, w, channels) fmap2 LEVEL fits the kernel's VMEM
    budget at ``dtype``'s element size (default float32). Dispatch
    inside :func:`corr_lookup_pallas` applies this per pyramid level at
    the precision policy's corr dtype — bf16 halves every per-level
    byte count, so levels rejected at f32 can stay on-chip; callers
    gating on the full-res shape get the level-0 answer."""
    itemsize = 4 if dtype is None else int(jnp.dtype(dtype).itemsize)
    return _level_vmem_bytes(
        h, w, channels, radius, itemsize=itemsize
    ) <= int(0.9 * _VMEM_BYTES)


def _lookup_kernel(
    ibase_ref, f1_ref, frac_ref, f2_ref, out_ref, scratch_ref, *, radius
):
    """One (batch, query-block) program, vectorized over groups of _GROUP.

    ibase_ref:   (Q, 2) int32, SMEM — clamped window origins (x, y) in the
                 padded level.
    f1_ref:      (Q, C) compute dtype — query features, pre-scaled by
                 1/sqrt(C).
    frac_ref:    (Q, 2) float32 — sub-pixel offsets (fx, fy).
    f2_ref:      (Hp, Wp, C) compute dtype — zero-padded fmap2 level
                 (bf16 under the bf16 policies: the resident slab is the
                 VMEM term, so narrow STORAGE is the dispatch-threshold
                 win; the reduce below upcasts, so ACCUMULATION is f32).
    out_ref:     (Q, K, K) float32 — window values in natural (y, x) order;
                 the caller transposes to the reference's x-major tap order
                 (core/corr.py:31-37).
    scratch_ref: (G, K+1, K+1, C) compute-dtype VMEM scratch.
    """
    K = 2 * radius + 1
    G = _GROUP

    def body(i, _):
        base = i * G
        # G dynamic-start patch loads (the only per-query work), stashed
        # at static group offsets.
        for g in range(G):
            ix = ibase_ref[base + g, 0]
            iy = ibase_ref[base + g, 1]
            scratch_ref[g] = f2_ref[pl.ds(iy, K + 1), pl.ds(ix, K + 1), :]
        patch = scratch_ref[...].astype(jnp.float32)  # (G, K+1, K+1, C)
        f1g = f1_ref[pl.ds(base, G), :].astype(jnp.float32)  # (G, C)
        corr = jnp.sum(patch * f1g[:, None, None, :], axis=-1)  # (G,K+1,K+1)
        fr = frac_ref[pl.ds(base, G), :]  # (G, 2)
        fx = fr[:, 0][:, None, None]
        fy = fr[:, 1][:, None, None]
        win = (
            (1 - fy) * (1 - fx) * corr[:, :K, :K]
            + (1 - fy) * fx * corr[:, :K, 1:]
            + fy * (1 - fx) * corr[:, 1:, :K]
            + fy * fx * corr[:, 1:, 1:]
        )
        out_ref[pl.ds(base, G)] = win
        return 0

    jax.lax.fori_loop(0, out_ref.shape[0] // G, body, 0)


def _lookup_one_level(
    f1: jax.Array,  # (B, N, C) pre-scaled query features, N = H*W
    f2l: jax.Array,  # (B, Hl, Wl, C) pooled fmap2 level
    coords: jax.Array,  # (B, N, 2)
    radius: int,
    level: int,
    interpret: bool = False,
    query_block: int = _QUERY_BLOCK,
) -> jax.Array:
    B, N, C = f1.shape
    _, Hl, Wl, _ = f2l.shape
    # Feature operands keep their (policy-chosen) dtype end to end: the
    # VMEM-resident slab and the f1 blocks are what the budget counts.
    fdt = f1.dtype
    K = 2 * radius + 1
    Hp, Wp, pad = _padded_hw(Hl, Wl, radius)
    f2p = jnp.pad(f2l, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    # Window origin + sub-pixel offset per query, computed on the XLA side
    # so the kernel's SMEM operand is plain int32 indices.
    cl = coords.astype(jnp.float32) / (2.0**level)
    c0 = jnp.floor(cl)
    frac = cl - c0  # (B, N, 2): (fx, fy)
    lim = jnp.asarray([Wp - (K + 1), Hp - (K + 1)], jnp.int32)
    ibase = jnp.clip(c0.astype(jnp.int32) - radius + pad, 0, lim)

    qblk = min(query_block, max(_GROUP, (N + _GROUP - 1) // _GROUP * _GROUP))
    qblk = max(qblk - qblk % _GROUP, _GROUP)
    n_pad = (-N) % qblk
    if n_pad:
        f1 = jnp.pad(f1, ((0, 0), (0, n_pad), (0, 0)))
        frac = jnp.pad(frac, ((0, 0), (0, n_pad), (0, 0)))
        ibase = jnp.pad(ibase, ((0, 0), (0, n_pad), (0, 0)))
    n_blocks = (N + n_pad) // qblk

    if pltpu is None:  # pragma: no cover - jax builds without pallas-tpu
        raise NotImplementedError(
            "corr_lookup_pallas requires jax.experimental.pallas.tpu"
        )
    # Integer window origins live in SMEM (the home for indices driving
    # dynamic slices); interpret mode keeps the default space since the
    # CPU interpreter has no SMEM emulation for blocked operands.
    ibase_spec = pl.BlockSpec(
        (None, qblk, 2),
        lambda b, i: (b, i, 0),
        **({} if interpret else {"memory_space": _SMEM}),
    )
    K1 = K + 1

    out = pl.pallas_call(
        functools.partial(_lookup_kernel, radius=radius),
        grid=(B, n_blocks),
        scratch_shapes=[pltpu.VMEM((_GROUP, K1, K1, C), fdt)],
        in_specs=[
            ibase_spec,
            pl.BlockSpec((None, qblk, C), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, qblk, 2), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Hp, Wp, C), lambda b, i: (b, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, qblk, K, K), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N + n_pad, K, K), jnp.float32),
        interpret=interpret,
    )(
        ibase,
        f1.astype(fdt),
        frac.astype(jnp.float32),
        f2p.astype(fdt),
    )
    # (B, N, K_y, K_x) -> x-major taps (reference order).
    return out[:, :N].transpose(0, 1, 3, 2).reshape(B, N, K * K)


def _forward(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int,
    interpret: bool = False,
    dtype=None,
) -> jax.Array:
    """Volume-free fused lookup over all pyramid levels, with PER-LEVEL
    dispatch: levels whose padded slab fits VMEM at ``dtype``'s element
    size take the kernel, the rest take the equivalent XLA on-the-fly
    path (1080p levels 0-1 at f32; level 1 re-qualifies at bf16 —
    tests/test_precision.py pins the threshold ratio)."""
    from raft_ncup_tpu.ops.corr import _pool_fmap_pyramid, corr_lookup_onthefly

    B, H, W, C = fmap1.shape
    scale = 1.0 / math.sqrt(C)
    dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
    f1 = (fmap1.reshape(B, H * W, C) * scale).astype(dtype)
    f2_levels = _pool_fmap_pyramid(fmap2.astype(dtype), num_levels)
    cflat = coords.astype(jnp.float32).reshape(B, H * W, 2)

    K2 = (2 * radius + 1) ** 2
    outs: dict[int, jax.Array] = {}
    fallback = []
    _dispatch_counts["levels_total"] += num_levels
    if pltpu is None:
        # jax builds without pallas-tpu: the kernel can't declare its VMEM
        # scratch there even in interpret mode, so every level routes to
        # the equivalent XLA path. Warn so benchmark rows labeled 'pallas'
        # aren't silently measuring the fallback.
        import warnings

        warnings.warn(
            "pallas-tpu unavailable; corr_impl='pallas' is running the "
            "XLA onthefly fallback",
            stacklevel=2,
        )
    for lvl, f2l in enumerate(f2_levels):
        if pltpu is not None and fits_vmem(
            f2l.shape[1], f2l.shape[2], C, radius, dtype=dtype
        ):
            _dispatch_counts["kernel"] += 1
            outs[lvl] = _lookup_one_level(
                f1, f2l, cflat, radius, lvl, interpret=interpret
            )
        else:
            _dispatch_counts["fallback"] += 1
            fallback.append(lvl)
    if fallback:
        if pltpu is not None and len(fallback) == num_levels:
            # Same mislabeled-measurement hazard as the pltpu-is-None
            # branch above: every level rejected by fits_vmem means
            # corr_impl='pallas' is measuring pure XLA onthefly.
            import warnings

            warnings.warn(
                f"all {num_levels} corr pyramid levels exceed the VMEM "
                "budget; corr_impl='pallas' is running the XLA onthefly "
                "fallback for every level",
                stacklevel=2,
            )
        fb = corr_lookup_onthefly(
            fmap1, fmap2, coords, radius, num_levels, levels=tuple(fallback),
            dtype=dtype,
        ).reshape(B, H * W, len(fallback) * K2)
        for j, lvl in enumerate(fallback):
            outs[lvl] = fb[..., j * K2 : (j + 1) * K2]

    return jnp.concatenate(
        [outs[lvl] for lvl in range(num_levels)], axis=-1
    ).reshape(B, H, W, num_levels * K2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def corr_lookup_pallas(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int = 4,
    interpret: bool = False,
    dtype=None,
) -> jax.Array:
    """Fused correlation lookup: (B,H,W,C) x2 + (B,H,W,2) ->
    (B, H, W, L*(2r+1)^2) float32. Equivalent to the XLA paths in
    ``raft_ncup_tpu.ops.corr`` up to float associativity; never
    materializes the correlation volume. ``dtype`` (static; default
    f32) is the feature/slab dtype the per-level VMEM dispatch budgets
    with — the precision policy's ``corr_jnp``. The backward always
    differentiates the f32 XLA path: gradients stay full precision
    regardless of the forward's storage dtype (f32 master weights)."""
    return _forward(
        fmap1, fmap2, coords, radius, num_levels, interpret, dtype
    )


def _fwd(fmap1, fmap2, coords, radius, num_levels, interpret, dtype):
    out = _forward(
        fmap1, fmap2, coords, radius, num_levels, interpret, dtype
    )
    return out, (fmap1, fmap2, coords)


def _bwd(radius, num_levels, interpret, dtype, res, g):
    from raft_ncup_tpu.ops.corr import corr_lookup_onthefly

    fmap1, fmap2, coords = res
    # Backward through the mathematically equivalent XLA implementation —
    # autodiff of the gather path gives exact gradients for the same
    # function value.
    _, vjp = jax.vjp(
        lambda a, b, c: corr_lookup_onthefly(a, b, c, radius, num_levels),
        fmap1,
        fmap2,
        coords,
    )
    return vjp(g)


corr_lookup_pallas.defvjp(_fwd, _bwd)
