"""Pallas TPU kernel: fused, volume-free correlation-window lookup.

The XLA paths (raft_ncup_tpu.ops.corr) either materialize the O((HW)^2)
all-pairs volume (`volume`) or bilinearly gather fmap2 taps (`onthefly`).
This kernel fuses the per-level dot product INTO the windowed lookup, so
the volume never exists anywhere — the §2a(a) design from SURVEY.md:

- Every tap of a query's (2r+1)^2 window shares the same fractional
  offset: the window is an integer-aligned grid shifted by one sub-pixel
  amount, so the whole K x K window equals a 2 x 2 bilinear blend of a
  (K+1) x (K+1) integer-aligned patch of correlations.
- That patch is `sum_c f1[q, c] * f2[iy : iy+K+1, ix : ix+K+1, c]` — a
  dynamic-start slice of the VMEM-resident fmap2 level (dynamic starts on
  the major and sublane dims, full lanes; the layout Mosaic supports)
  followed by a lane reduction on the VPU. No gather, no roll, and HBM
  traffic is fmap2 once per query block instead of a volume pass.

Zero-padding semantics (out-of-bounds taps contribute zero, matching
``grid_sample``) come from pre-padding each level with K+2 zeros per
side; window starts are clamped into the padded array, and any fully-OOB
window lands entirely inside the zero margin.

VMEM budget: the padded level must fit on-chip (~6.6 MB for the 368x768
training crop's level 0 at C=256). `fits_vmem` reports whether a shape
qualifies; the model falls back to the XLA on-the-fly path otherwise
(1080p belongs to `onthefly` — see tests/test_highres.py).

The kernel is forward-only; ``corr_lookup_pallas`` wraps it in a
``jax.custom_vjp`` whose backward runs the XLA on-the-fly path's VJP, so
the op stays trainable. (reference semantics: core/corr.py:23-44)
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_ncup_tpu.ops.corr import (
    _pool_fmap_pyramid,
    corr_lookup_onthefly,
)

_VMEM_BUDGET = 10 * 1024 * 1024  # padded fmap2 level + working set


def _padded_hw(h: int, w: int, radius: int) -> tuple[int, int, int]:
    # A fully-OOB window is clamped to the array edge and must land
    # entirely inside the zero margin: K + 2 zeros per side.
    pad = 2 * radius + 3
    return h + 2 * pad, w + 2 * pad, pad


def fits_vmem(h: int, w: int, channels: int, radius: int = 4) -> bool:
    """Whether the level-0 fmap2 slab fits the kernel's VMEM budget."""
    hp, wp, _ = _padded_hw(h, w, radius)
    return hp * wp * channels * 4 <= _VMEM_BUDGET


def _lookup_kernel(f1_ref, coords_ref, f2_ref, out_ref, *, radius, pad, level):
    """One (batch, query-block) program.

    f1_ref:     (Q, C) float32 — query features, pre-scaled by 1/sqrt(C).
    coords_ref: (Q, 2) float32 — full-res query centers (x, y).
    f2_ref:     (Hp, Wp, C) float32 — zero-padded fmap2 level.
    out_ref:    (Q, K, K) float32 — window values in natural (y, x) order;
                the caller transposes to the reference's x-major tap order
                (core/corr.py:31-37).
    """
    K = 2 * radius + 1
    Hp, Wp = f2_ref.shape[0], f2_ref.shape[1]
    inv = 1.0 / (2.0**level)

    def body(q, _):
        cx = coords_ref[q, 0] * inv
        cy = coords_ref[q, 1] * inv
        x0 = jnp.floor(cx)
        y0 = jnp.floor(cy)
        fx = cx - x0
        fy = cy - y0
        ix = jnp.clip(x0.astype(jnp.int32) - radius + pad, 0, Wp - (K + 1))
        iy = jnp.clip(y0.astype(jnp.int32) - radius + pad, 0, Hp - (K + 1))
        patch = f2_ref[pl.ds(iy, K + 1), pl.ds(ix, K + 1), :]  # (K+1,K+1,C)
        f1q = f1_ref[q, :]  # (C,)
        corr = (patch * f1q[None, None, :]).sum(-1)  # (K+1, K+1): y, x
        win = (
            (1 - fy) * (1 - fx) * corr[:K, :K]
            + (1 - fy) * fx * corr[:K, 1:]
            + fy * (1 - fx) * corr[1:, :K]
            + fy * fx * corr[1:, 1:]
        )
        out_ref[q] = win
        return 0

    jax.lax.fori_loop(0, out_ref.shape[0], body, 0)


def _lookup_one_level(
    f1: jax.Array,  # (B, N, C) pre-scaled query features, N = H*W
    f2l: jax.Array,  # (B, Hl, Wl, C) pooled fmap2 level
    coords: jax.Array,  # (B, N, 2)
    radius: int,
    level: int,
    interpret: bool = False,
    query_block: int = 512,
) -> jax.Array:
    B, N, C = f1.shape
    _, Hl, Wl, _ = f2l.shape
    K = 2 * radius + 1
    Hp, Wp, pad = _padded_hw(Hl, Wl, radius)
    f2p = jnp.pad(f2l, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    qblk = min(query_block, N)
    n_pad = (-N) % qblk
    if n_pad:
        f1 = jnp.pad(f1, ((0, 0), (0, n_pad), (0, 0)))
        coords = jnp.pad(coords, ((0, 0), (0, n_pad), (0, 0)))
    n_blocks = (N + n_pad) // qblk

    out = pl.pallas_call(
        functools.partial(
            _lookup_kernel, radius=radius, pad=pad, level=level
        ),
        grid=(B, n_blocks),
        in_specs=[
            pl.BlockSpec((None, qblk, C), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, qblk, 2), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Hp, Wp, C), lambda b, i: (b, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, qblk, K, K), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N + n_pad, K, K), jnp.float32),
        interpret=interpret,
    )(
        f1.astype(jnp.float32),
        coords.astype(jnp.float32),
        f2p.astype(jnp.float32),
    )
    # (B, N, K_y, K_x) -> x-major taps (reference order).
    return out[:, :N].transpose(0, 1, 3, 2).reshape(B, N, K * K)


def _forward(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int,
    interpret: bool = False,
) -> jax.Array:
    """Volume-free fused lookup over all pyramid levels."""
    B, H, W, C = fmap1.shape
    scale = 1.0 / math.sqrt(C)
    f1 = (fmap1.reshape(B, H * W, C) * scale).astype(jnp.float32)
    f2_levels = _pool_fmap_pyramid(fmap2.astype(jnp.float32), num_levels)
    cflat = coords.astype(jnp.float32).reshape(B, H * W, 2)

    outs = [
        _lookup_one_level(f1, f2l, cflat, radius, lvl, interpret=interpret)
        for lvl, f2l in enumerate(f2_levels)
    ]
    K = 2 * radius + 1
    return jnp.concatenate(outs, axis=-1).reshape(
        B, H, W, num_levels * K * K
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def corr_lookup_pallas(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int = 4,
    interpret: bool = False,
) -> jax.Array:
    """Fused correlation lookup: (B,H,W,C) x2 + (B,H,W,2) ->
    (B, H, W, L*(2r+1)^2). Equivalent to the XLA paths in
    ``raft_ncup_tpu.ops.corr`` up to float associativity; never
    materializes the correlation volume."""
    return _forward(fmap1, fmap2, coords, radius, num_levels, interpret)


def _fwd(fmap1, fmap2, coords, radius, num_levels, interpret):
    out = _forward(fmap1, fmap2, coords, radius, num_levels, interpret)
    return out, (fmap1, fmap2, coords)


def _bwd(radius, num_levels, interpret, res, g):
    fmap1, fmap2, coords = res
    # Backward through the mathematically equivalent XLA implementation —
    # autodiff of the gather path gives exact gradients for the same
    # function value.
    _, vjp = jax.vjp(
        lambda a, b, c: corr_lookup_onthefly(a, b, c, radius, num_levels),
        fmap1,
        fmap2,
        coords,
    )
    return vjp(g)


corr_lookup_pallas.defvjp(_fwd, _bwd)
