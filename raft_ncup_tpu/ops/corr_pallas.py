"""Pallas TPU kernel: fused, volume-free correlation-window lookup.

The XLA paths (raft_ncup_tpu.ops.corr) either materialize the O((HW)^2)
all-pairs volume (`volume`) or bilinearly gather fmap2 taps (`onthefly`).
This kernel fuses the per-level dot product INTO the windowed lookup, so
the volume never exists anywhere — the §2a(a) design from SURVEY.md:

- Every tap of a query's (2r+1)^2 window shares the same fractional
  offset: the window is an integer-aligned grid shifted by one sub-pixel
  amount, so the whole K x K window equals a 2 x 2 bilinear blend of a
  (K+1) x (K+1) integer-aligned patch of correlations.
- That patch is `sum_c f1[q, c] * f2[iy : iy+K+1, ix : ix+K+1, c]` — a
  dynamic-start slice of the VMEM-resident fmap2 level followed by a
  lane reduction on the VPU. No gather, no roll, and HBM traffic is
  fmap2 once per query block instead of a volume pass.

Kernel shape (round-3 redesign; the round-2 version looped one query at a
time with scalar work per step — VERDICT.md weak #3): queries are
processed in GROUPS of 8 so every vector op runs on (8, 128)-tiled
operands:

- Integer window origins are precomputed on the XLA side and shipped as
  an int32 array in SMEM (the Mosaic-idiomatic home for indices that
  drive dynamic slices); fractional offsets ride along in VMEM.
- Per group, 8 dynamic-start patch loads fill a VMEM scratch
  (8, K+1, K+1, C); the correlation reduce, the 2x2 bilinear blend, and
  the output store are then single vectorized ops over the whole group
  (sublane dim = 8 queries, lane dim = C/taps).

Zero-padding semantics (out-of-bounds taps contribute zero, matching
``grid_sample``) come from pre-padding each level with K+2 zeros per
side; window starts are clamped into the padded array, and any fully-OOB
window lands entirely inside the zero margin.

VMEM budget: the RESIDENT kernel keeps the whole padded level on-chip
next to the pipeline's block buffers. The budget is derived from the
per-core VMEM capacity (~16 MiB on current TPUs —
/opt/skills/guides/pallas_guide.md "Memory Hierarchy"; override with
RAFT_NCUP_VMEM_BYTES) minus the blocked operands' double buffers.

Banded tier (round-15 redesign — the correlation memory wall,
ROADMAP item 4): levels whose padded slab exceeds the resident budget
no longer fall straight back to XLA. The level is split into horizontal
BANDS of ``band_rows`` origin rows; each program touches only its
band's slab plus a ``K+2``-row halo, sized by :func:`band_plan` so
``band_slab + query blocks + scratch`` fits the same ``fits_vmem``
budget at the policy itemsize. Mechanics:

- The zero-padded level stays in HBM (``memory_space=ANY``); one band
  slab of ``band_rows + K + 2`` rows is DMA'd into a single VMEM
  scratch (``pltpu.make_async_copy``) when the band changes — the slab
  is NOT double-buffered, which is exactly what lets a 4K level-0 band
  fit where a blocked operand's double buffer would not.
- Queries are assigned XLA-side to the band containing their clamped
  window origin (``ibase`` already computes it), stable-argsorted by
  band, and a per-(batch) chunk table — the (band, query-block,
  lo, hi, fresh-band) segments of the sorted query array, i.e. the
  ``(B, band, query_block)`` grid with its empty cells compressed out —
  ships as a scalar-prefetch operand in SMEM
  (``pltpu.PrefetchScalarGridSpec``) and drives every block index map.
- The kernel grid is ``(B, chunk)`` with a MASKED group loop: groups
  outside the chunk's ``[lo, hi)`` sorted-query range are skipped, and
  boundary groups accumulate masked contributions, so a query block
  straddling a band boundary is completed by its neighbouring chunks
  (consecutive out-block revisits — the sanctioned accumulation
  pattern). Out-of-band taps read the band's own zero/halo rows, so
  zero-padding semantics stay BITWISE identical to the resident kernel.

Dispatch is PER LEVEL and THREE-TIER: resident kernel -> banded kernel
-> XLA onthefly (counted separately in ``dispatch_counts``). At 1080p
f32, levels 0-1 (~42 MB / ~15.3 MB padded, both over the 0.9x resident
budget) now take the BANDED kernel and levels 2-3 the resident one; at
4K (2176x3840) every level qualifies for a kernel tier at f32 and bf16
(exact counts pinned by tests/test_pallas_lowering.py). The XLA
fallback remains only for jax builds without pallas-tpu and for band
overrides that reject.

Tuning knobs (the first real surface for ROADMAP item 1's autotuner;
recorded in the cost-ledger meta via ``ops.corr.corr_tuning_meta``):
``RAFT_NCUP_CORR_QUERY_BLOCK`` (queries per block, default 512) and
``RAFT_NCUP_CORR_BAND_ROWS`` (band origin rows; default: largest that
fits the budget, multiple-of-8 preferred).

The kernel is forward-only; ``corr_lookup_pallas`` wraps it in a
``jax.custom_vjp`` whose backward runs the XLA on-the-fly path's VJP, so
the op stays trainable. (reference semantics: core/corr.py:23-44)
"""

from __future__ import annotations

import functools
import math
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu provides the SMEM/VMEM memory-space constants on TPU builds
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
except ImportError:  # pragma: no cover - CPU-only jax builds
    pltpu = None
    _SMEM = None

from raft_ncup_tpu.utils.knobs import knob_positive_int
from raft_ncup_tpu.utils.runtime import VMEM_BYTES as _VMEM_BYTES

_QUERY_BLOCK = 512
_GROUP = 8  # queries per vectorized inner step (sublane tile)

QUERY_BLOCK_ENV = "RAFT_NCUP_CORR_QUERY_BLOCK"
BAND_ROWS_ENV = "RAFT_NCUP_CORR_BAND_ROWS"


def effective_query_block() -> int:
    """The query-block size both kernel tiers trace with: the
    ``RAFT_NCUP_CORR_QUERY_BLOCK`` override when set, else 512. A
    tuning knob (ROADMAP item 1): smaller blocks shrink the
    double-buffered block term of the VMEM budget, buying band rows."""
    return knob_positive_int(QUERY_BLOCK_ENV) or _QUERY_BLOCK


def band_rows_override() -> int | None:
    """``RAFT_NCUP_CORR_BAND_ROWS`` when set (an expert/autotuner knob:
    it wins over :func:`band_plan`'s budget-derived choice), else None
    = auto."""
    return knob_positive_int(BAND_ROWS_ENV)


def tuning_meta() -> dict:
    """The kernel's effective tuning-knob values, as recorded into the
    cost-ledger entry meta of every compiled executable
    (inference/costs.py) — the surface ROADMAP item 1's autotuner
    sweeps."""
    return {
        "corr_query_block": effective_query_block(),
        "corr_band_rows": band_rows_override() or "auto",
    }


# Trace-time per-level dispatch tally, mirroring ops.nconv: callers that
# label a measurement "corr=pallas" (bench.py) use this to tell which
# tier carried each pyramid level — resident kernel, banded kernel, or
# the XLA onthefly fallback (partial mixes are by design at large
# shapes and still count as the kernel running). Guarded by a lock:
# concurrent traces (two warmups on different threads) must not lose
# increments, even though a mixed tally is only interpretable under the
# single-thread discipline documented on dispatch_counts().
_counts_lock = threading.Lock()
_dispatch_counts = {
    "kernel": 0, "banded": 0, "fallback": 0, "levels_total": 0,
}


def reset_dispatch_counts() -> None:
    with _counts_lock:
        for k in _dispatch_counts:
            _dispatch_counts[k] = 0


def dispatch_counts() -> dict:
    """Copy of the per-level dispatch tally since the last reset.

    Three tier keys plus the denominator: ``kernel`` (whole level
    VMEM-resident), ``banded`` (level banded + DMA'd per band, see
    module docstring), ``fallback`` (XLA onthefly), and
    ``levels_total``. Counts trace-time decisions, one per pyramid
    level per TRACE — a custom_vjp backward trace, a shape-driven
    retrace, or a concurrent thread each add their own tallies, so the
    counts are only interpretable between a reset and a single lowering
    in a single thread, the discipline bench.py follows (mutation
    itself is lock-guarded, so concurrent traces can't lose counts)."""
    with _counts_lock:
        return dict(_dispatch_counts)


def _count(tier: str, n: int = 1) -> None:
    with _counts_lock:
        _dispatch_counts[tier] += n


def _padded_hw(h: int, w: int, radius: int) -> tuple[int, int, int]:
    # A fully-OOB window is clamped to the array edge and must land
    # entirely inside the zero margin: K + 2 zeros per side.
    pad = 2 * radius + 3
    return h + 2 * pad, w + 2 * pad, pad


def _level_vmem_bytes(
    h: int,
    w: int,
    channels: int,
    radius: int,
    query_block: int | None = None,
    itemsize: int = 4,
) -> int:
    """Bytes of VMEM the kernel needs for one (h, w) level: the resident
    padded fmap2 slab + double-buffered query blocks + the group scratch,
    all at ``itemsize`` bytes per element (the precision policy's
    compute dtype — 2 under the bf16 presets, which is exactly the
    dispatch-threshold doubling ROADMAP item 3 wanted; the frac/out
    blocks stay f32 but are a few percent of the slab, so budgeting them
    at ``itemsize`` keeps the threshold ratio an exact itemsize ratio)."""
    if query_block is None:
        query_block = effective_query_block()
    hp, wp, _ = _padded_hw(h, w, radius)
    K1 = 2 * radius + 2
    slab = hp * wp * channels
    blocks = 2 * query_block * (channels + 2 + (K1 - 1) ** 2)  # f1+frac+out, x2 pipeline
    scratch = _GROUP * K1 * K1 * channels
    return itemsize * (slab + blocks + scratch)


def fits_vmem(
    h: int, w: int, channels: int, radius: int = 4, dtype=None
) -> bool:
    """Whether a (h, w, channels) fmap2 LEVEL fits the kernel's VMEM
    budget at ``dtype``'s element size (default float32). Dispatch
    inside :func:`corr_lookup_pallas` applies this per pyramid level at
    the precision policy's corr dtype — bf16 halves every per-level
    byte count, so levels rejected at f32 can stay on-chip; callers
    gating on the full-res shape get the level-0 answer."""
    itemsize = 4 if dtype is None else int(jnp.dtype(dtype).itemsize)
    return _level_vmem_bytes(
        h, w, channels, radius, itemsize=itemsize
    ) <= int(0.9 * _VMEM_BYTES)


def _band_geometry(
    hp: int, radius: int, band_rows: int
) -> tuple[int, int]:
    """(origin_rows, n_bands) for a padded level of height ``hp``: the
    ONE derivation of the band count, shared by :func:`band_plan` and
    the kernel-side geometry in :func:`_banded_lookup_one_level` so the
    planned count and the DMA/chunk-table layout can never drift.
    Clamped window origins span [0, hp - (K+1)] (the ``lim`` clip), so
    ``origin_rows = hp - K`` rows need band coverage."""
    origin_rows = hp - (2 * radius + 1)
    return origin_rows, max(1, -(-origin_rows // band_rows))


def _band_halo(radius: int) -> int:
    # Rows a band's slab extends past its last origin row: a window
    # origin on the band's final row reads K+1 rows, so K+1 is the hard
    # floor; K+2 keeps one spare row of the zero margin in-slab so a
    # clamped fully-OOB window stays entirely inside zeros even at the
    # band seam (mirrors the K+2 pad of _padded_hw).
    return 2 * radius + 3


def _banded_vmem_bytes(
    h: int,
    w: int,
    channels: int,
    radius: int,
    band_rows: int,
    query_block: int | None = None,
    itemsize: int = 4,
) -> int:
    """Bytes of VMEM the BANDED kernel needs for one (h, w) level at
    ``band_rows`` origin rows per band: the single-buffered band slab
    (``band_rows + K + 2`` padded rows — the level itself stays in HBM
    and the slab is DMA'd, so no pipeline double buffer) + the same
    double-buffered query blocks and group scratch as the resident
    kernel, all at ``itemsize`` (the policy's corr dtype — bf16 halves
    every term, exactly the threshold doubling the resident tier
    already has; tests/test_precision.py pins the ratio for this budget
    too)."""
    if query_block is None:
        query_block = effective_query_block()
    _, wp, _ = _padded_hw(h, w, radius)
    K1 = 2 * radius + 2
    slab = (band_rows + _band_halo(radius)) * wp * channels
    blocks = 2 * query_block * (channels + 2 + (K1 - 1) ** 2)
    scratch = _GROUP * K1 * K1 * channels
    return itemsize * (slab + blocks + scratch)


def band_plan(
    h: int,
    w: int,
    channels: int,
    radius: int = 4,
    dtype=None,
    query_block: int | None = None,
) -> tuple[int, int] | None:
    """Band geometry for a level too large for the resident kernel:
    ``(band_rows, n_bands)``, or ``None`` when not even a 1-row band
    fits the budget (the level then falls back to XLA onthefly).

    ``band_rows`` is the largest count whose banded budget
    (:func:`_banded_vmem_bytes`) fits 0.9x VMEM at ``dtype``'s element
    size, rounded down to a multiple of 8 when >= 8 (sublane-friendly
    DMA rows); ``RAFT_NCUP_CORR_BAND_ROWS`` overrides it unconditionally
    (the autotuner's sweep knob — an expert override is trusted, the
    budget check is for the AUTO choice). ``n_bands`` partitions the
    clamped window-origin rows of the PADDED level."""
    if query_block is None:
        query_block = effective_query_block()
    itemsize = 4 if dtype is None else int(jnp.dtype(dtype).itemsize)
    hp, _, _ = _padded_hw(h, w, radius)
    origin_rows, _ = _band_geometry(hp, radius, 1)
    override = band_rows_override()
    if override is not None:
        band_rows = max(1, min(override, origin_rows))
    else:
        budget = int(0.9 * _VMEM_BYTES)
        fixed = _banded_vmem_bytes(
            h, w, channels, radius, 0, query_block, itemsize
        )
        if fixed > budget:
            return None  # blocks+scratch+halo alone blow the budget
        per_row = itemsize * (w + 2 * (2 * radius + 3)) * channels
        band_rows = (budget - fixed) // per_row
        if band_rows < 1:
            return None
        band_rows = int(min(band_rows, origin_rows))
        if band_rows >= 8:
            band_rows -= band_rows % 8
    return band_rows, _band_geometry(hp, radius, band_rows)[1]


def _lookup_kernel(
    ibase_ref, f1_ref, frac_ref, f2_ref, out_ref, scratch_ref, *, radius
):
    """One (batch, query-block) program, vectorized over groups of _GROUP.

    ibase_ref:   (Q, 2) int32, SMEM — clamped window origins (x, y) in the
                 padded level.
    f1_ref:      (Q, C) compute dtype — query features, pre-scaled by
                 1/sqrt(C).
    frac_ref:    (Q, 2) float32 — sub-pixel offsets (fx, fy).
    f2_ref:      (Hp, Wp, C) compute dtype — zero-padded fmap2 level
                 (bf16 under the bf16 policies: the resident slab is the
                 VMEM term, so narrow STORAGE is the dispatch-threshold
                 win; the reduce below upcasts, so ACCUMULATION is f32).
    out_ref:     (Q, K, K) float32 — window values in natural (y, x) order;
                 the caller transposes to the reference's x-major tap order
                 (core/corr.py:31-37).
    scratch_ref: (G, K+1, K+1, C) compute-dtype VMEM scratch.
    """
    K = 2 * radius + 1
    G = _GROUP

    def body(i, _):
        base = i * G
        # G dynamic-start patch loads (the only per-query work), stashed
        # at static group offsets.
        for g in range(G):
            ix = ibase_ref[base + g, 0]
            iy = ibase_ref[base + g, 1]
            scratch_ref[g] = f2_ref[pl.ds(iy, K + 1), pl.ds(ix, K + 1), :]
        patch = scratch_ref[...].astype(jnp.float32)  # (G, K+1, K+1, C)
        f1g = f1_ref[pl.ds(base, G), :].astype(jnp.float32)  # (G, C)
        corr = jnp.sum(patch * f1g[:, None, None, :], axis=-1)  # (G,K+1,K+1)
        fr = frac_ref[pl.ds(base, G), :]  # (G, 2)
        fx = fr[:, 0][:, None, None]
        fy = fr[:, 1][:, None, None]
        win = (
            (1 - fy) * (1 - fx) * corr[:, :K, :K]
            + (1 - fy) * fx * corr[:, :K, 1:]
            + fy * (1 - fx) * corr[:, 1:, :K]
            + fy * fx * corr[:, 1:, 1:]
        )
        out_ref[pl.ds(base, G)] = win
        return 0

    jax.lax.fori_loop(0, out_ref.shape[0] // G, body, 0)


def _lookup_one_level(
    f1: jax.Array,  # (B, N, C) pre-scaled query features, N = H*W
    f2l: jax.Array,  # (B, Hl, Wl, C) pooled fmap2 level
    coords: jax.Array,  # (B, N, 2)
    radius: int,
    level: int,
    interpret: bool = False,
    query_block: int = _QUERY_BLOCK,
) -> jax.Array:
    B, N, C = f1.shape
    _, Hl, Wl, _ = f2l.shape
    # Feature operands keep their (policy-chosen) dtype end to end: the
    # VMEM-resident slab and the f1 blocks are what the budget counts.
    fdt = f1.dtype
    K = 2 * radius + 1
    Hp, Wp, pad = _padded_hw(Hl, Wl, radius)
    f2p = jnp.pad(f2l, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    # Window origin + sub-pixel offset per query, computed on the XLA side
    # so the kernel's SMEM operand is plain int32 indices.
    cl = coords.astype(jnp.float32) / (2.0**level)
    c0 = jnp.floor(cl)
    frac = cl - c0  # (B, N, 2): (fx, fy)
    lim = jnp.asarray([Wp - (K + 1), Hp - (K + 1)], jnp.int32)
    ibase = jnp.clip(c0.astype(jnp.int32) - radius + pad, 0, lim)

    qblk = min(query_block, max(_GROUP, (N + _GROUP - 1) // _GROUP * _GROUP))
    qblk = max(qblk - qblk % _GROUP, _GROUP)
    n_pad = (-N) % qblk
    if n_pad:
        f1 = jnp.pad(f1, ((0, 0), (0, n_pad), (0, 0)))
        frac = jnp.pad(frac, ((0, 0), (0, n_pad), (0, 0)))
        ibase = jnp.pad(ibase, ((0, 0), (0, n_pad), (0, 0)))
    n_blocks = (N + n_pad) // qblk

    if pltpu is None:  # pragma: no cover - jax builds without pallas-tpu
        raise NotImplementedError(
            "corr_lookup_pallas requires jax.experimental.pallas.tpu"
        )
    # Integer window origins live in SMEM (the home for indices driving
    # dynamic slices); interpret mode keeps the default space since the
    # CPU interpreter has no SMEM emulation for blocked operands.
    ibase_spec = pl.BlockSpec(
        (None, qblk, 2),
        lambda b, i: (b, i, 0),
        **({} if interpret else {"memory_space": _SMEM}),
    )
    K1 = K + 1

    out = pl.pallas_call(
        functools.partial(_lookup_kernel, radius=radius),
        grid=(B, n_blocks),
        scratch_shapes=[pltpu.VMEM((_GROUP, K1, K1, C), fdt)],
        in_specs=[
            ibase_spec,
            pl.BlockSpec((None, qblk, C), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, qblk, 2), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, Hp, Wp, C), lambda b, i: (b, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, qblk, K, K), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, N + n_pad, K, K), jnp.float32),
        interpret=interpret,
    )(
        ibase,
        f1.astype(fdt),
        frac.astype(jnp.float32),
        f2p.astype(fdt),
    )
    # (B, N, K_y, K_x) -> x-major taps (reference order).
    return out[:, :N].transpose(0, 1, 3, 2).reshape(B, N, K * K)


def _banded_lookup_kernel(
    tbl_ref, ibase_ref, f1_ref, frac_ref, f2_ref, out_ref,
    slab_ref, scratch_ref, sem, *, radius, qblk, band_rows,
):
    """One (batch, chunk) program of the banded tier.

    tbl_ref:     (B, n_chunks, 5) int32, SMEM (scalar prefetch) — per
                 chunk: band id, query-block id, [lo, hi) sorted-query
                 range, fresh-band flag (1 = DMA a new band slab).
    ibase_ref:   (Q, 2) int32, SMEM — clamped window origins per SORTED
                 query: (x in the padded level, y LOCAL to the band).
    f1_ref:      (Q, C) compute dtype — sorted query features.
    frac_ref:    (Q, 2) float32 — sorted sub-pixel offsets (fx, fy).
    f2_ref:      (B, Hb, Wp, C) compute dtype, HBM (memory_space=ANY) —
                 the whole zero-padded level; never resident.
    out_ref:     (Q, K, K) float32 — window values in SORTED query
                 order, natural (y, x); revisited consecutively by the
                 chunks of one query block (accumulation pattern).
    slab_ref:    (band_rows + K + 2, Wp, C) VMEM scratch — the band
                 slab, DMA'd from HBM on a fresh-band chunk. Single
                 buffered: this is what the banded budget counts.
    scratch_ref: (G, K+1, K+1, C) VMEM scratch (as the resident kernel).
    sem:         DMA completion semaphore.
    """
    K = 2 * radius + 1
    K1 = K + 1
    G = _GROUP
    b = pl.program_id(0)
    j = pl.program_id(1)
    band = tbl_ref[b, j, 0]
    lo = tbl_ref[b, j, 2]
    hi = tbl_ref[b, j, 3]
    base_q = tbl_ref[b, j, 1] * qblk

    @pl.when(tbl_ref[b, j, 4] == 1)
    def _copy_band():
        # Synchronous band-slab DMA: consecutive chunks of one band skip
        # it (fresh flag 0), so the level streams from HBM once per band
        # plus halo overlap. No double buffer — the whole point of the
        # banded budget (see _banded_vmem_bytes).
        cp = pltpu.make_async_copy(
            f2_ref.at[b, pl.ds(band * band_rows, slab_ref.shape[0])],
            slab_ref,
            sem,
        )
        cp.start()
        cp.wait()

    @pl.when(lo == base_q)
    def _init_block():
        # First chunk of this query block zero-inits the out block; the
        # block stays VMEM-resident across its (consecutive) chunks.
        out_ref[...] = jnp.zeros_like(out_ref)

    def body(i, _):
        gbase = i * G
        q0 = base_q + gbase

        @pl.when((q0 + G > lo) & (q0 < hi))
        def _group():
            # Masked group: same vectorized math as the resident kernel,
            # reading the band slab with band-local row origins; lanes
            # outside [lo, hi) (a boundary group's neighbours from the
            # adjacent band) are computed against this band's slab —
            # memory-safe via the band-local clamp — and masked out of
            # the accumulate, so the neighbouring chunk supplies them.
            for g in range(G):
                ix = ibase_ref[gbase + g, 0]
                iy = ibase_ref[gbase + g, 1]
                scratch_ref[g] = slab_ref[
                    pl.ds(iy, K + 1), pl.ds(ix, K + 1), :
                ]
            patch = scratch_ref[...].astype(jnp.float32)
            f1g = f1_ref[pl.ds(gbase, G), :].astype(jnp.float32)
            corr = jnp.sum(patch * f1g[:, None, None, :], axis=-1)
            fr = frac_ref[pl.ds(gbase, G), :]
            fx = fr[:, 0][:, None, None]
            fy = fr[:, 1][:, None, None]
            win = (
                (1 - fy) * (1 - fx) * corr[:, :K, :K]
                + (1 - fy) * fx * corr[:, :K, 1:]
                + fy * (1 - fx) * corr[:, 1:, :K]
                + fy * fx * corr[:, 1:, 1:]
            )
            qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (G, 1, 1), 0)
            mask = (qpos >= lo) & (qpos < hi)
            cur = out_ref[pl.ds(gbase, G)]
            out_ref[pl.ds(gbase, G)] = cur + jnp.where(mask, win, 0.0)
        return 0

    jax.lax.fori_loop(0, out_ref.shape[0] // G, body, 0)


def _banded_lookup_one_level(
    f1: jax.Array,  # (B, N, C) pre-scaled query features, N = H*W
    f2l: jax.Array,  # (B, Hl, Wl, C) pooled fmap2 level
    coords: jax.Array,  # (B, N, 2)
    radius: int,
    level: int,
    band_rows: int,
    interpret: bool = False,
    query_block: int | None = None,
) -> jax.Array:
    """Banded variant of :func:`_lookup_one_level` for levels whose
    padded slab exceeds the resident VMEM budget (module docstring,
    "Banded tier"). Bitwise-equal to the resident kernel: identical
    per-query math, only regrouped — the parity is pinned by
    tests/test_corr_pallas.py."""
    B, N, C = f1.shape
    _, Hl, Wl, _ = f2l.shape
    fdt = f1.dtype
    K = 2 * radius + 1
    K1 = K + 1
    halo = _band_halo(radius)
    Hp, Wp, pad = _padded_hw(Hl, Wl, radius)
    _, n_bands = _band_geometry(Hp, radius, band_rows)
    # Zero-pad rows so every band slab (band_rows + halo rows from its
    # first origin row) is in-bounds; the extra rows are zeros, i.e.
    # exactly the margin the clamped-origin semantics already rely on.
    extra = n_bands * band_rows + halo - Hp
    f2p = jnp.pad(
        f2l, ((0, 0), (pad, pad + extra), (pad, pad), (0, 0))
    ).astype(fdt)

    cl = coords.astype(jnp.float32) / (2.0**level)
    c0 = jnp.floor(cl)
    frac = cl - c0  # (B, N, 2): (fx, fy)
    lim = jnp.asarray([Wp - K1, Hp - K1], jnp.int32)
    ib = jnp.clip(c0.astype(jnp.int32) - radius + pad, 0, lim)
    band_id = ib[..., 1] // band_rows  # (B, N)
    # Window origins as the kernel reads them: x in the padded level,
    # y LOCAL to the query's own band slab.
    ibase = jnp.stack(
        [ib[..., 0], ib[..., 1] - band_id * band_rows], axis=-1
    )

    # Stable argsort-by-band: queries of one band become contiguous (and
    # keep raster order within it); the inverse permutation restores the
    # caller's order after the kernel.
    order = jnp.argsort(band_id, axis=1, stable=True)

    def take(x):
        return jnp.take_along_axis(x, order[..., None], axis=1)

    f1_s, frac_s, ibase_s = take(f1), take(frac), take(ibase)
    band_s = jnp.take_along_axis(band_id, order, axis=1)

    qblk = query_block or effective_query_block()
    qblk = min(qblk, max(_GROUP, (N + _GROUP - 1) // _GROUP * _GROUP))
    qblk = max(qblk - qblk % _GROUP, _GROUP)
    n_pad = (-N) % qblk
    if n_pad:
        f1_s = jnp.pad(f1_s, ((0, 0), (0, n_pad), (0, 0)))
        frac_s = jnp.pad(frac_s, ((0, 0), (0, n_pad), (0, 0)))
        ibase_s = jnp.pad(ibase_s, ((0, 0), (0, n_pad), (0, 0)))
        # Padding queries ride the last band (edge mode) so they extend
        # its final chunk instead of minting a fresh one; their ibase is
        # (0, 0) — in-slab reads, results dropped by the [:N] slice.
        band_s = jnp.pad(band_s, ((0, 0), (0, n_pad)), mode="edge")
    Nq = N + n_pad
    n_blocks = Nq // qblk

    # Chunk table: the sorted query array cut at every query-block start
    # and band change — the (band x query_block) grid with empty cells
    # compressed out. At most n_blocks + n_bands - 1 segments; unused
    # slots become dummy chunks (lo == hi == Nq, clamped to the last
    # block and band, fresh=0) that fetch nothing new and mask all work.
    n_chunks = n_blocks + n_bands - 1
    pos = jnp.arange(Nq, dtype=jnp.int32)
    newband = jnp.concatenate(
        [jnp.ones((B, 1), bool), band_s[:, 1:] != band_s[:, :-1]], axis=1
    )
    is_start = newband | ((pos % qblk) == 0)[None, :]
    starts = jnp.sort(
        jnp.where(is_start, pos[None], Nq).astype(jnp.int32), axis=1
    )[:, :n_chunks]
    ends = jnp.minimum(
        jnp.concatenate(
            [starts[:, 1:], jnp.full((B, 1), Nq, jnp.int32)], axis=1
        ),
        Nq,
    )
    blk = jnp.minimum(starts // qblk, n_blocks - 1)
    bnd = jnp.take_along_axis(
        band_s, jnp.minimum(starts, Nq - 1), axis=1
    ).astype(jnp.int32)
    fresh = jnp.concatenate(
        [
            jnp.ones((B, 1), jnp.int32),
            (bnd[:, 1:] != bnd[:, :-1]).astype(jnp.int32),
        ],
        axis=1,
    )
    fresh = jnp.where(starts < Nq, fresh, 0)  # dummies never DMA
    tbl = jnp.stack([bnd, blk, starts, ends, fresh], axis=-1)

    if pltpu is None:  # pragma: no cover - guarded by _forward dispatch
        raise NotImplementedError(
            "corr_lookup_pallas requires jax.experimental.pallas.tpu"
        )
    ibase_spec = pl.BlockSpec(
        (None, qblk, 2),
        lambda b, j, t: (b, t[b, j, 1], 0),
        **({} if interpret else {"memory_space": _SMEM}),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, n_chunks),
        in_specs=[
            ibase_spec,
            pl.BlockSpec(
                (None, qblk, C), lambda b, j, t: (b, t[b, j, 1], 0)
            ),
            pl.BlockSpec(
                (None, qblk, 2), lambda b, j, t: (b, t[b, j, 1], 0)
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),  # level stays in HBM
        ],
        out_specs=pl.BlockSpec(
            (None, qblk, K, K), lambda b, j, t: (b, t[b, j, 1], 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((band_rows + halo, Wp, C), fdt),
            pltpu.VMEM((_GROUP, K1, K1, C), fdt),
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _banded_lookup_kernel,
            radius=radius,
            qblk=qblk,
            band_rows=band_rows,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Nq, K, K), jnp.float32),
        interpret=interpret,
    )(
        tbl,
        ibase_s,
        f1_s.astype(fdt),
        frac_s.astype(jnp.float32),
        f2p,
    )
    inv = jnp.argsort(order, axis=1)
    out = jnp.take_along_axis(out, inv[..., None, None], axis=1)
    # (B, N, K_y, K_x) -> x-major taps (reference order).
    return out[:, :N].transpose(0, 1, 3, 2).reshape(B, N, K * K)


def _forward(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int,
    interpret: bool = False,
    dtype=None,
) -> jax.Array:
    """Volume-free fused lookup over all pyramid levels, with PER-LEVEL
    THREE-TIER dispatch at ``dtype``'s element size: levels whose
    padded slab fits VMEM take the resident kernel, levels too large
    for residency but with a fitting :func:`band_plan` take the banded
    kernel, and only the remainder takes the equivalent XLA on-the-fly
    path (at 1080p f32 levels 0-1 are banded, 2-3 resident; at 4K every
    level lands on a kernel tier — tests/test_pallas_lowering.py pins
    the exact counts, tests/test_precision.py the bf16 threshold
    ratios)."""
    from raft_ncup_tpu.ops.corr import _pool_fmap_pyramid, corr_lookup_onthefly

    B, H, W, C = fmap1.shape
    scale = 1.0 / math.sqrt(C)
    dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
    f1 = (fmap1.reshape(B, H * W, C) * scale).astype(dtype)
    f2_levels = _pool_fmap_pyramid(fmap2.astype(dtype), num_levels)
    cflat = coords.astype(jnp.float32).reshape(B, H * W, 2)

    qblk = effective_query_block()
    K2 = (2 * radius + 1) ** 2
    outs: dict[int, jax.Array] = {}
    fallback = []
    _count("levels_total", num_levels)
    if pltpu is None:
        # jax builds without pallas-tpu: the kernel can't declare its VMEM
        # scratch there even in interpret mode, so every level routes to
        # the equivalent XLA path. Warn so benchmark rows labeled 'pallas'
        # aren't silently measuring the fallback.
        import warnings

        warnings.warn(
            "pallas-tpu unavailable; corr_impl='pallas' is running the "
            "XLA onthefly fallback",
            stacklevel=2,
        )
    for lvl, f2l in enumerate(f2_levels):
        Hl, Wl = f2l.shape[1], f2l.shape[2]
        if pltpu is not None and fits_vmem(Hl, Wl, C, radius, dtype=dtype):
            _count("kernel")
            outs[lvl] = _lookup_one_level(
                f1, f2l, cflat, radius, lvl, interpret=interpret,
                query_block=qblk,
            )
        elif pltpu is not None and (
            plan := band_plan(Hl, Wl, C, radius, dtype=dtype,
                              query_block=qblk)
        ):
            _count("banded")
            outs[lvl] = _banded_lookup_one_level(
                f1, f2l, cflat, radius, lvl, band_rows=plan[0],
                interpret=interpret, query_block=qblk,
            )
        else:
            _count("fallback")
            fallback.append(lvl)
    if fallback:
        if pltpu is not None and len(fallback) == num_levels:
            # Same mislabeled-measurement hazard as the pltpu-is-None
            # branch above: every level rejected by BOTH kernel tiers
            # (resident fits_vmem AND band_plan) means
            # corr_impl='pallas' is measuring pure XLA onthefly.
            import warnings

            warnings.warn(
                f"all {num_levels} corr pyramid levels exceed the VMEM "
                "budget; corr_impl='pallas' is running the XLA onthefly "
                "fallback for every level",
                stacklevel=2,
            )
        fb = corr_lookup_onthefly(
            fmap1, fmap2, coords, radius, num_levels, levels=tuple(fallback),
            dtype=dtype,
        ).reshape(B, H * W, len(fallback) * K2)
        for j, lvl in enumerate(fallback):
            outs[lvl] = fb[..., j * K2 : (j + 1) * K2]

    return jnp.concatenate(
        [outs[lvl] for lvl in range(num_levels)], axis=-1
    ).reshape(B, H, W, num_levels * K2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def corr_lookup_pallas(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int = 4,
    interpret: bool = False,
    dtype=None,
) -> jax.Array:
    """Fused correlation lookup: (B,H,W,C) x2 + (B,H,W,2) ->
    (B, H, W, L*(2r+1)^2) float32. Equivalent to the XLA paths in
    ``raft_ncup_tpu.ops.corr`` up to float associativity; never
    materializes the correlation volume. ``dtype`` (static; default
    f32) is the feature/slab dtype the per-level THREE-TIER dispatch
    (resident kernel -> banded kernel -> XLA onthefly) budgets with —
    the precision policy's ``corr_jnp``. The backward always
    differentiates the f32 XLA path: gradients stay full precision
    regardless of the forward's storage dtype (f32 master weights)."""
    return _forward(
        fmap1, fmap2, coords, radius, num_levels, interpret, dtype
    )


def _fwd(fmap1, fmap2, coords, radius, num_levels, interpret, dtype):
    out = _forward(
        fmap1, fmap2, coords, radius, num_levels, interpret, dtype
    )
    return out, (fmap1, fmap2, coords)


def _bwd(radius, num_levels, interpret, dtype, res, g):
    from raft_ncup_tpu.ops.corr import corr_lookup_onthefly

    fmap1, fmap2, coords = res
    # Backward through the mathematically equivalent XLA implementation —
    # autodiff of the gather path gives exact gradients for the same
    # function value.
    _, vjp = jax.vjp(
        lambda a, b, c: corr_lookup_onthefly(a, b, c, radius, num_levels),
        fmap1,
        fmap2,
        coords,
    )
    return vjp(g)


corr_lookup_pallas.defvjp(_fwd, _bwd)
