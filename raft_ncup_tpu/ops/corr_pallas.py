"""Pallas TPU kernel for the multi-scale correlation-window lookup.

The XLA paths (raft_ncup_tpu.ops.corr) express the (2r+1)^2-tap bilinear
window sample as a general gather. This kernel exploits the window's
structure instead: every tap of a query's window shares the same
fractional offset — the window is an integer-aligned grid shifted by one
sub-pixel amount — so the whole K x K window equals a 2 x 2 bilinear blend
of a (K+1) x (K+1) integer-aligned patch of the volume. Per query that is
one dynamic-start patch load from VMEM plus four shifted multiply-adds,
with no gather anywhere.

Zero-padding semantics (out-of-bounds taps contribute zero, matching
``grid_sample``) come from pre-padding each level with K+2 zeros per side:
window starts are clamped into the padded array, and any fully-OOB window
lands entirely inside the zero margin.

The kernel is forward-only; ``corr_lookup_pallas`` wraps it in a
``jax.custom_vjp`` whose backward runs the XLA on-the-fly path's VJP, so
the op stays trainable. (reference semantics: core/corr.py:23-44)
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_ncup_tpu.ops.corr import (
    _pool_fmap_pyramid,
    corr_lookup_onthefly,
)

_VMEM_BUDGET = 8 * 1024 * 1024  # soft cap per volume block


def _query_block(hp: int, wp: int) -> int:
    """Largest power-of-two query block whose volume slab fits the budget."""
    q = 256
    while q > 8 and q * hp * wp * 4 > _VMEM_BUDGET:
        q //= 2
    return q


def _lookup_kernel(coords_ref, vol_ref, out_ref, *, radius, pad, level):
    """One (query-block) program: sample the K x K window per query.

    coords_ref: (Q, 2) float32 — full-res query centers (x, y).
    vol_ref:    (Q, Hp, Wp) float32 — per-query padded volume slab.
    out_ref:    (Q, K, K) float32 — window values in natural (y, x) order;
                the caller transposes to the reference's x-major tap order
                (core/corr.py:31-37). Mosaic cannot reshape/transpose the
                9x9 tile in-kernel.
    """
    K = 2 * radius + 1
    Hp, Wp = vol_ref.shape[1], vol_ref.shape[2]
    inv = 1.0 / (2.0**level)

    def body(q, _):
        cx = coords_ref[q, 0] * inv
        cy = coords_ref[q, 1] * inv
        x0 = jnp.floor(cx)
        y0 = jnp.floor(cy)
        fx = cx - x0
        fy = cy - y0
        ix = jnp.clip(x0.astype(jnp.int32) - radius + pad, 0, Wp - (K + 1))
        iy = jnp.clip(y0.astype(jnp.int32) - radius + pad, 0, Hp - (K + 1))
        # Mosaic allows dynamic-start slicing on the sublane dim but not
        # the lane (minor) dim, and dynamic rotates only on the lane dim:
        # slice rows dynamically, rotate columns so the window starts at
        # lane 0, then static-slice. The clamp above keeps
        # [iy, iy+K] x [ix, ix+K] in bounds, so the rotation never wraps
        # real data into the window.
        rows = vol_ref[q, pl.ds(iy, K + 1), :]  # (K+1, Wp)
        # pltpu.roll requires a non-negative shift; left-rotate by ix ==
        # right-rotate by Wp - ix (ix == 0 must stay 0, not Wp).
        rows = pltpu.roll(rows, jnp.where(ix == 0, 0, Wp - ix), 1)
        patch = rows[:, : K + 1]  # rows = y, cols = x
        win = (
            (1 - fy) * (1 - fx) * patch[:K, :K]
            + (1 - fy) * fx * patch[:K, 1:]
            + fy * (1 - fx) * patch[1:, :K]
            + fy * fx * patch[1:, 1:]
        )
        out_ref[q] = win
        return 0

    jax.lax.fori_loop(0, out_ref.shape[0], body, 0)


def _lookup_one_level(
    vol: jax.Array,  # (N, Hl, Wl) per-query volume, N = B*H*W
    coords: jax.Array,  # (N, 2)
    radius: int,
    level: int,
    interpret: bool = False,
) -> jax.Array:
    N, Hl, Wl = vol.shape
    K = 2 * radius + 1
    pad = K + 2
    volp = jnp.pad(vol, ((0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = Hl + 2 * pad, Wl + 2 * pad

    qblk = _query_block(Hp, Wp)
    n_pad = (-N) % qblk
    if n_pad:
        volp = jnp.pad(volp, ((0, n_pad), (0, 0), (0, 0)))
        coords = jnp.pad(coords, ((0, n_pad), (0, 0)))
    n_blocks = (N + n_pad) // qblk

    out = pl.pallas_call(
        functools.partial(
            _lookup_kernel, radius=radius, pad=pad, level=level
        ),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((qblk, 2), lambda i: (i, 0)),
            pl.BlockSpec((qblk, Hp, Wp), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((qblk, K, K), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N + n_pad, K, K), jnp.float32),
        interpret=interpret,
    )(coords.astype(jnp.float32), volp.astype(jnp.float32))
    # (N, K_y, K_x) -> x-major taps (reference order).
    return out[:N].transpose(0, 2, 1).reshape(N, K * K)


def _forward(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int,
    interpret: bool = False,
) -> jax.Array:
    """Materialize the pyramid (einsum on the MXU), then kernel-sample it."""
    B, H, W, C = fmap1.shape
    f1 = fmap1.reshape(B, H * W, C).astype(jnp.float32)
    f2_levels = _pool_fmap_pyramid(fmap2.astype(jnp.float32), num_levels)
    scale = 1.0 / math.sqrt(C)

    cflat = coords.astype(jnp.float32).reshape(B * H * W, 2)
    outs = []
    for lvl, f2l in enumerate(f2_levels):
        Hl, Wl = f2l.shape[1], f2l.shape[2]
        vol = (
            jnp.einsum(
                "bqc,byxc->bqyx",
                f1,
                f2l,
                preferred_element_type=jnp.float32,
            )
            * scale
        ).reshape(B * H * W, Hl, Wl)
        outs.append(
            _lookup_one_level(vol, cflat, radius, lvl, interpret=interpret)
        )
    K = 2 * radius + 1
    return jnp.concatenate(outs, axis=-1).reshape(
        B, H, W, num_levels * K * K
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def corr_lookup_pallas(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    num_levels: int = 4,
    interpret: bool = False,
) -> jax.Array:
    """Fused correlation lookup: (B,H,W,C) x2 + (B,H,W,2) ->
    (B, H, W, L*(2r+1)^2). Equivalent to the XLA paths in
    ``raft_ncup_tpu.ops.corr`` up to float associativity."""
    return _forward(fmap1, fmap2, coords, radius, num_levels, interpret)


def _fwd(fmap1, fmap2, coords, radius, num_levels, interpret):
    out = _forward(fmap1, fmap2, coords, radius, num_levels, interpret)
    return out, (fmap1, fmap2, coords)


def _bwd(radius, num_levels, interpret, res, g):
    fmap1, fmap2, coords = res
    # Backward through the mathematically equivalent XLA implementation —
    # autodiff of the gather path gives exact gradients for the same
    # function value.
    _, vjp = jax.vjp(
        lambda a, b, c: corr_lookup_onthefly(a, b, c, radius, num_levels),
        fmap1,
        fmap2,
        coords,
    )
    return vjp(g)


corr_lookup_pallas.defvjp(_fwd, _bwd)
