"""Pallas TPU kernel: fused normalized convolution (SURVEY.md §2a(b)).

The XLA path (raft_ncup_tpu.ops.nconv.nconv2d) issues two convolutions —
``conv(conf * data)`` and ``conv(conf)`` — plus a divide and a scale
(reference semantics: core/nconv_modules.py:164-199). On TPU these NCUP
convolutions are pathological for the MXU: 1-2 channels at FULL image
resolution (XLA pads channels toward 128 lanes, so the arithmetic is
~1% useful), run 12 times per forward at e.g. 368x768. They are
memory-bound shift-and-accumulate stencils, not matmuls.

This kernel computes the whole NConv2d in ONE pass over a VMEM-resident
image slab, as an unrolled shift-multiply-accumulate:

- Both operands (``conf``, ``data*conf``) are zero-padded outside the
  kernel; every kernel tap is then a STATIC slice of the slab (conv tap
  offsets are compile-time constants), so the inner loop is pure
  (8, 128)-tiled VPU work — no gathers, no dynamic indexing, no MXU
  channel padding waste.
- The divide, bias, and confidence propagation (``conv(conf)/sum(w)``)
  fuse into the same pass, so HBM traffic is one read of each operand
  and one write of each output — the fusion XLA is not guaranteed to
  find across the conv/divide boundary.

Supported surface = exactly what NCUP uses (stride 1, groups 1, odd
square kernels, SAME padding); anything else — or a slab past the VMEM
budget (1080p full-res) — falls back to the XLA composition, per shape,
at trace time.

Forward-only; ``nconv2d_fused`` wraps the kernel in ``jax.custom_vjp``
whose backward differentiates the XLA composition (same values =>
correct gradients), keeping the op trainable.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - jax builds without pallas-tpu
    pltpu = None

from raft_ncup_tpu.utils.runtime import VMEM_BYTES as _VMEM_BYTES


def fits_vmem(h: int, w: int, cin: int, cout: int, k: int) -> bool:
    """Whether one batch element's working set fits the VMEM budget:
    two padded input slabs + two output slabs + accumulators."""
    hp, wp = h + k - 1, w + k - 1
    slabs = 2 * hp * wp * cin + 2 * h * w * cout + 2 * h * w * cout
    return 4 * slabs <= int(0.75 * _VMEM_BYTES)


# The kernel body unrolls cout * k * k * cin Python loop iterations
# (one vector FMA each). NCUP's nconvs are 1-2 channels (5x5x2x2 = 100
# iterations); past a few hundred the unrolled Mosaic program blows up
# compile time and VMEM register pressure, so cap it and let XLA take
# those shapes.
MAX_UNROLL = 256


def supported(weight_shape, stride: int, groups: int) -> bool:
    kh, kw, cin, cout = (
        weight_shape[0], weight_shape[1], weight_shape[2], weight_shape[3],
    )
    return (
        kh == kw
        and kh % 2 == 1
        and stride == 1
        and groups == 1
        and kh * kw * cin * cout <= MAX_UNROLL
    )


def _kernel(dc_ref, c_ref, w_ref, wsum_ref, bias_ref, out_ref, cout_ref, *,
            k: int, cin: int, cout: int, eps: float):
    """One batch element, channel-FIRST so the (H, W) image plane rides
    the (sublane, lane) vector tiles — channels-last with Cin/Cout of
    1-2 would waste 126/128 lanes.

    dc_ref/c_ref: (Cin, Hp, Wp) padded slabs of data*conf and conf;
    w_ref: (k, k, Cin, Cout); wsum_ref/bias_ref: (1, Cout);
    outputs (Cout, H, W)."""
    H, W = out_ref.shape[1], out_ref.shape[2]
    for co in range(cout):
        acc_x = jnp.zeros((H, W), jnp.float32)
        acc_c = jnp.zeros((H, W), jnp.float32)
        for ky in range(k):
            for kx in range(k):
                for ci in range(cin):
                    w = w_ref[ky, kx, ci, co]
                    acc_x += w * dc_ref[ci, ky : ky + H, kx : kx + W]
                    acc_c += w * c_ref[ci, ky : ky + H, kx : kx + W]
        out_ref[co] = acc_x / (acc_c + eps) + bias_ref[0, co]
        cout_ref[co] = acc_c / wsum_ref[0, co]


def _forward(data, conf, weight, bias, eps, interpret):
    B, H, W, Cin = data.shape
    k = weight.shape[0]
    Cout = weight.shape[-1]
    p = k // 2
    f32 = jnp.float32
    # NHWC -> NCHW, pad the image plane.
    dc = jnp.pad(
        (data * conf).astype(f32).transpose(0, 3, 1, 2),
        ((0, 0), (0, 0), (p, p), (p, p)),
    )
    cp = jnp.pad(
        conf.astype(f32).transpose(0, 3, 1, 2),
        ((0, 0), (0, 0), (p, p), (p, p)),
    )
    wsum = weight.sum(axis=(0, 1, 2)).reshape(1, Cout).astype(f32)
    b = (
        bias.reshape(1, Cout).astype(f32)
        if bias is not None
        else jnp.zeros((1, Cout), f32)
    )
    Hp, Wp = H + 2 * p, W + 2 * p

    out, conf_out = pl.pallas_call(
        functools.partial(_kernel, k=k, cin=Cin, cout=Cout, eps=eps),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((None, Cin, Hp, Wp), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec((None, Cin, Hp, Wp), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec((k, k, Cin, Cout), lambda b: (0, 0, 0, 0)),
            pl.BlockSpec((1, Cout), lambda b: (0, 0)),
            pl.BlockSpec((1, Cout), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, Cout, H, W), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec((None, Cout, H, W), lambda b: (b, 0, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Cout, H, W), f32),
            jax.ShapeDtypeStruct((B, Cout, H, W), f32),
        ],
        interpret=interpret,
    )(dc, cp, weight.astype(f32), wsum, b)
    # NCHW -> NHWC; restore the input dtype so flipping impl never
    # changes the op's output dtype (the XLA path preserves it).
    out = out.transpose(0, 2, 3, 1).astype(data.dtype)
    conf_out = conf_out.transpose(0, 2, 3, 1).astype(conf.dtype)
    return out, conf_out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def nconv2d_fused(data, conf, weight, bias, eps: float = 1e-20,
                  interpret: bool = False):
    """Fused NConv2d forward: returns ``(out, conf_out)`` equivalent to
    the XLA composition in :func:`raft_ncup_tpu.ops.nconv.nconv2d`
    (stride 1, groups 1, odd square kernel) up to float associativity.

    ``bias`` may be None. Caller is responsible for gating via
    :func:`supported` and :func:`fits_vmem`.
    """
    return _forward(data, conf, weight, bias, eps, interpret)


def _reference(data, conf, weight, bias, eps):
    from raft_ncup_tpu.ops.nconv import nconv2d

    # impl='xla' explicitly: with RAFT_NCUP_NCONV_IMPL=pallas exported the
    # env default would re-dispatch straight back to the fused kernel and
    # the backward would recurse without a base case.
    return nconv2d(data, conf, weight, bias, eps=eps, impl="xla")


def _fwd(data, conf, weight, bias, eps, interpret):
    out = _forward(data, conf, weight, bias, eps, interpret)
    return out, (data, conf, weight, bias)


def _bwd(eps, interpret, res, g):
    data, conf, weight, bias = res
    if bias is None:
        _, vjp = jax.vjp(
            lambda d, c, w: _reference(d, c, w, None, eps), data, conf, weight
        )
        gd, gc, gw = vjp(g)
        return gd, gc, gw, None
    _, vjp = jax.vjp(
        lambda d, c, w, b: _reference(d, c, w, b, eps), data, conf, weight, bias
    )
    return vjp(g)


nconv2d_fused.defvjp(_fwd, _bwd)
