"""Pure-function geometry/sampling ops (NHWC, TPU-friendly).

These match the sampling semantics of the reference exactly — in
particular PyTorch's ``grid_sample(align_corners=True, padding='zeros')``
(reference: core/utils/utils.py:59-73) and the convex 8x upsampling built
from softmax masks + unfold (reference: core/raft.py:73-84) — because the
sub-pixel behavior of these ops silently changes EPE.

Everything here is shape-polymorphic, jit-safe (static shapes in, static
shapes out) and differentiable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def coords_grid(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """Pixel-coordinate grid, shape (B, H, W, 2) with [..., 0]=x, [..., 1]=y.

    NHWC analogue of reference: core/utils/utils.py:76-79 (which returns
    (B, 2, H, W) with channel 0 = x).
    """
    y, x = jnp.meshgrid(
        jnp.arange(ht, dtype=dtype), jnp.arange(wd, dtype=dtype), indexing="ij"
    )
    grid = jnp.stack([x, y], axis=-1)
    return jnp.broadcast_to(grid[None], (batch, ht, wd, 2))


def grid_sample(img: jax.Array, coords: jax.Array) -> jax.Array:
    """Bilinear sampling at pixel coordinates with zero padding.

    Matches ``F.grid_sample(mode='bilinear', padding_mode='zeros',
    align_corners=True)`` after the pixel->normalized->pixel round trip of
    the reference wrapper (core/utils/utils.py:59-73): each of the four
    corner taps contributes 0 iff that *tap* is out of bounds.

    Args:
      img:    (B, H, W, C)
      coords: (B, ..., 2) pixel coordinates; [..., 0] = x in [0, W-1],
              [..., 1] = y in [0, H-1] (out-of-range allowed).

    Returns:
      (B, ..., C) sampled values.
    """
    B, H, W, C = img.shape
    # Coordinate/weight arithmetic runs at the WIDER of the two dtypes:
    # a narrow-storage image (the bf16 correlation volume under the
    # precision policy, docs/PRECISION.md) must not demote the query
    # coordinates — bf16 cannot represent integer pixel positions above
    # 256, and the policy pins coord_dtype to f32. For the historical
    # f32/f32 call the promotion is the identity.
    wdt = jnp.promote_types(img.dtype, coords.dtype)
    x = coords[..., 0].astype(wdt)
    y = coords[..., 1].astype(wdt)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    dx = x - x0
    dy = y - y0

    flat_img = img.reshape(B, H * W, C)
    batch_shape = x.shape  # (B, ...)

    out = jnp.zeros(batch_shape + (C,), dtype=wdt)
    taps = (
        (x0, y0, (1.0 - dx) * (1.0 - dy)),
        (x0 + 1.0, y0, dx * (1.0 - dy)),
        (x0, y0 + 1.0, (1.0 - dx) * dy),
        (x0 + 1.0, y0 + 1.0, dx * dy),
    )
    for tx, ty, w in taps:
        valid = (tx >= 0) & (tx <= W - 1) & (ty >= 0) & (ty <= H - 1)
        xi = jnp.clip(tx, 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(ty, 0, H - 1).astype(jnp.int32)
        flat_idx = (yi * W + xi).reshape(B, -1)
        v = jnp.take_along_axis(flat_img, flat_idx[..., None], axis=1)
        v = v.reshape(batch_shape + (C,))
        out = out + jnp.where(valid, w, 0.0)[..., None] * v
    return out


def bilinear_resize_align_corners(x: jax.Array, out_hw: tuple[int, int]) -> jax.Array:
    """Bilinear resize with ``align_corners=True`` semantics.

    Matches ``F.interpolate(mode='bilinear', align_corners=True)`` used by
    the x8 flow upsampling on the mask-free path (reference:
    core/utils/utils.py:82-84) and the Bilinear upsampler baseline
    (reference: core/upsampler.py:213-220). ``jax.image.resize`` uses
    half-pixel centers, so this is built on :func:`grid_sample` instead.

    Args:
      x: (B, H, W, C).
      out_hw: (H_out, W_out).
    """
    B, H, W, C = x.shape
    oh, ow = out_hw

    def axis_coords(n_in: int, n_out: int) -> jax.Array:
        if n_out == 1:
            return jnp.zeros((1,), dtype=x.dtype)
        scale = (n_in - 1) / (n_out - 1)
        return jnp.arange(n_out, dtype=x.dtype) * scale

    ys = axis_coords(H, oh)
    xs = axis_coords(W, ow)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    coords = jnp.broadcast_to(
        jnp.stack([gx, gy], axis=-1)[None], (B, oh, ow, 2)
    )
    return grid_sample(x, coords)


def upflow(flow: jax.Array, factor: int = 8, align_corners: bool = True) -> jax.Array:
    """Bilinear flow upsampling: resize x ``factor`` and scale values.

    Reference: core/utils/utils.py:82-84 (with the explicit
    ``align_corners`` the reference's call site expected, SURVEY.md §0.3).
    """
    B, H, W, _ = flow.shape
    if align_corners:
        up = bilinear_resize_align_corners(flow, (H * factor, W * factor))
    else:
        up = jax.image.resize(flow, (B, H * factor, W * factor, 2), "bilinear")
    return factor * up


def upsample_nearest(x: jax.Array, factor: int) -> jax.Array:
    """Nearest-neighbor integer upsampling (``F.interpolate(mode='nearest')``
    for integer factors: out[i] = in[i // factor])."""
    x = jnp.repeat(x, factor, axis=1)
    x = jnp.repeat(x, factor, axis=2)
    return x


def adaptive_area_resize(x: jax.Array, out_hw: tuple[int, int]) -> jax.Array:
    """``F.interpolate(mode='area')`` (= adaptive average pooling) for sizes
    related by integer ratios — the only shapes the reference exercises
    (guidance resize at core/upsampler.py:150: H/8 -> H/4, i.e. 2x up, which
    under area interpolation is nearest replication; and integer-factor
    downsampling elsewhere)."""
    B, H, W, C = x.shape
    oh, ow = out_hw
    if oh == H and ow == W:
        return x
    if oh >= H and ow >= W:
        if oh % H == 0 and ow % W == 0:
            return jnp.repeat(jnp.repeat(x, oh // H, axis=1), ow // W, axis=2)
        raise NotImplementedError("area upsample only for integer factors")
    if H % oh == 0 and W % ow == 0:
        fh, fw = H // oh, W // ow
        x = x.reshape(B, oh, fh, ow, fw, C)
        return x.mean(axis=(2, 4))
    raise NotImplementedError("area resize only for integer ratios")


def avg_pool2(x: jax.Array) -> jax.Array:
    """2x2 stride-2 average pooling, VALID (odd trailing row/col dropped),
    matching ``F.avg_pool2d(x, 2, stride=2)`` used for the correlation
    pyramid (reference: core/corr.py:20). x: (B, H, W, C)."""
    B, H, W, C = x.shape
    h2, w2 = H // 2, W // 2
    x = x[:, : h2 * 2, : w2 * 2, :].reshape(B, h2, 2, w2, 2, C)
    return x.mean(axis=(2, 4))


def extract_3x3_patches(x: jax.Array) -> jax.Array:
    """3x3 patch extraction with zero padding 1, matching the tap ordering
    of ``F.unfold(x, [3, 3], padding=1)``: tap k = ky * 3 + kx reads input
    pixel (h - 1 + ky, w - 1 + kx).

    Args:
      x: (B, H, W, C).
    Returns:
      (B, H, W, 9, C).
    """
    B, H, W, C = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    rows = [
        xp[:, ky : ky + H, kx : kx + W, :] for ky in range(3) for kx in range(3)
    ]
    return jnp.stack(rows, axis=3)


def convex_upsample(flow: jax.Array, mask: jax.Array, factor: int = 8) -> jax.Array:
    """RAFT's learned convex-combination upsampling.

    Reference: core/raft.py:73-84. The mask channel layout matches the
    reference's ``view(N, 1, 9, f, f, H, W)`` on a (9*f*f)-channel tensor:
    channel c = k * f * f + i * f + j, where k indexes the 3x3 neighborhood
    (row-major) and (i, j) the sub-pixel position. Keeping this layout makes
    reference checkpoints importable weight-for-weight.

    Args:
      flow: (B, H, W, 2) low-res flow.
      mask: (B, H, W, 9 * factor * factor) unnormalized mask logits.
    Returns:
      (B, H*factor, W*factor, 2) upsampled flow with values scaled by
      ``factor``.
    """
    B, H, W, _ = flow.shape
    f = factor
    m = mask.reshape(B, H, W, 9, f, f)
    m = jax.nn.softmax(m, axis=3)
    patches = extract_3x3_patches(factor * flow)  # (B, H, W, 9, 2)
    up = jnp.einsum("bhwkij,bhwkc->bhwijc", m, patches)  # (B, H, W, f, f, 2)
    up = up.transpose(0, 1, 3, 2, 4, 5).reshape(B, H * f, W * f, 2)
    return up
