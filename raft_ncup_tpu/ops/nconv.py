"""Normalized-convolution primitives (the math under NCUP).

The core op is a pair of convolutions sharing one kernel with non-negative
weights (reference: core/nconv_modules.py:164-199):

    out  = conv(data * conf, w) / (conv(conf, w) + eps) [+ bias]
    cout = conv(conf, w) / sum(w)        # propagated confidence

plus the confidence-aware downsampling (max-pool confidence, gather data at
the confidence argmax, reference: core/nconv_modules.py:94-104) and the
zero-stuffing scatter that lifts low-res data onto the high-res grid
(reference: core/upsampler.py:208).

Non-negativity is enforced by a softplus reparameterization — the
functional analogue of the reference's forward-pre-hook ``EnforcePos``
machinery (core/nconv_modules.py:218-269); no hooks needed in JAX: the
positive weight is simply recomputed from the raw parameter every call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Trace-time dispatch tally for the fused-kernel path: callers that label a
# measurement "nconv=pallas" (bench.py) must be able to tell whether the
# fused kernel actually ran or every call silently fell back to XLA
# (ADVICE r3: a baseline pinned under '+nconv_pallas' that measured the
# XLA path would poison every later comparison).
_dispatch_counts = {"fused": 0, "fallback": 0}


def reset_dispatch_counts() -> None:
    _dispatch_counts["fused"] = 0
    _dispatch_counts["fallback"] = 0


def dispatch_counts() -> dict:
    """Copy of the {'fused', 'fallback'} tally since the last reset.
    Counts trace-time decisions (one per distinct nconv2d call site per
    TRACE), not runtime executions — extra traces in the same process
    (custom_vjp backward, retraces, concurrent threads) inflate the
    tally, so values are only interpretable between a reset and a single
    lowering in a single thread (bench.py's discipline)."""
    return dict(_dispatch_counts)


def positivity(raw: jax.Array, pos_fn: str = "softplus") -> jax.Array:
    """Map a raw parameter to a non-negative kernel.

    Reference: core/nconv_modules.py:254-269 (``_pos``). The softplus uses
    beta=10: softplus_10(x) = log(1 + exp(10 x)) / 10.
    """
    pos_fn = pos_fn.lower()
    if pos_fn == "softplus":
        return jax.nn.softplus(10.0 * raw) / 10.0
    if pos_fn == "exp":
        return jnp.exp(raw)
    if pos_fn == "sigmoid":
        return jax.nn.sigmoid(raw)
    if pos_fn == "softmax":
        # Per-output-channel softmax over (kh, kw, in).
        o = raw.shape[-1]
        flat = raw.reshape(-1, o)
        return jax.nn.softmax(flat, axis=0).reshape(raw.shape)
    raise ValueError(f"unknown pos_fn: {pos_fn!r}")


def nconv2d(
    data: jax.Array,
    conf: jax.Array,
    weight: jax.Array,
    bias: jax.Array | None = None,
    *,
    eps: float = 1e-20,
    stride: int = 1,
    groups: int = 1,
    propagate_conf: bool = True,
    impl: str | None = None,
) -> tuple[jax.Array, jax.Array | None]:
    """Normalized convolution with confidence propagation.

    Args:
      data, conf: (B, H, W, Cin) NHWC.
      weight: (kh, kw, Cin/groups, Cout) HWIO, already non-negative (apply
        :func:`positivity` first).
      bias: (Cout,) or None.
      impl: 'xla' (two convs + divide) or 'pallas' (fused single-pass
        kernel, raft_ncup_tpu.ops.nconv_pallas) — default comes from env
        RAFT_NCUP_NCONV_IMPL ('xla' until hardware timing proves the
        kernel). 'pallas' silently falls back to 'xla' for unsupported
        configurations (stride/groups/even kernels) or slabs past the
        VMEM budget, per shape at trace time.
    Returns:
      (out, conf_out), both (B, H', W', Cout); SAME padding for odd kernels
      (reference pads kernel//2, core/nconv_modules.py:143-144).
    """
    from raft_ncup_tpu.utils.knobs import knob_str

    impl = impl or knob_str("RAFT_NCUP_NCONV_IMPL")
    if impl == "pallas":
        from raft_ncup_tpu.ops import nconv_pallas as npk

        from raft_ncup_tpu.utils.runtime import is_tpu_class_backend

        fused_ok = (
            # Mosaic lowers only on TPU-class backends; cpu/gpu fall back.
            is_tpu_class_backend()
            and npk.supported(weight.shape, stride, groups)
            and npk.fits_vmem(
                data.shape[1], data.shape[2], data.shape[3],
                weight.shape[-1], weight.shape[0],
            )
        )
        if fused_ok:
            _dispatch_counts["fused"] += 1
            out, conf_out = npk.nconv2d_fused(data, conf, weight, bias, eps)
            return out, (conf_out if propagate_conf else None)
        _dispatch_counts["fallback"] += 1
        import warnings

        warnings.warn(
            "nconv impl='pallas' fell back to XLA for shape "
            f"data={tuple(data.shape)} weight={tuple(weight.shape)} "
            f"stride={stride} groups={groups} (backend tpu-class: "
            f"{is_tpu_class_backend()}) — measurements labeled "
            "nconv=pallas did NOT run the fused kernel here",
            stacklevel=2,
        )
    kh, kw = weight.shape[0], weight.shape[1]
    pad = ((kh // 2, kh // 2), (kw // 2, kw // 2))
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, ("NHWC", "HWIO", "NHWC"))

    def conv(x: jax.Array) -> jax.Array:
        return jax.lax.conv_general_dilated(
            x,
            weight,
            window_strides=(stride, stride),
            padding=pad,
            dimension_numbers=dn,
            feature_group_count=groups,
        )

    denom = conv(conf)
    nomin = conv(data * conf)
    out = nomin / (denom + eps)
    if bias is not None:
        out = out + bias
    if propagate_conf:
        # conf_out = conv(conf) / sum_k(w) per output channel
        # (reference: core/nconv_modules.py:180-194).
        s = weight.sum(axis=(0, 1, 2))
        conf_out = denom / s
    else:
        conf_out = None
    return out, conf_out


def downsample_data_conf(
    data: jax.Array, conf: jax.Array, pooling_type: str = "conf_based"
) -> tuple[jax.Array, jax.Array]:
    """2x2 stride-2 confidence-aware downsampling.

    Max-pools the confidence and gathers data at the confidence argmax
    ('conf_based') or max-pools data directly ('max_pooling'); the pooled
    confidence is divided by 4 (the Jacobian of the scale change —
    reference: core/nconv_modules.py:94-104).

    Args:
      data, conf: (B, H, W, C) with H, W even.
    """
    B, H, W, C = conf.shape
    cb = conf.reshape(B, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 5, 2, 4)
    cb = cb.reshape(B, H // 2, W // 2, C, 4)
    conf_ds = cb.max(axis=-1) / 4.0
    if pooling_type == "conf_based":
        idx = cb.argmax(axis=-1)
        db = data.reshape(B, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 5, 2, 4)
        db = db.reshape(B, H // 2, W // 2, C, 4)
        data_ds = jnp.take_along_axis(db, idx[..., None], axis=-1)[..., 0]
    elif pooling_type == "max_pooling":
        db = data.reshape(B, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 5, 2, 4)
        data_ds = db.reshape(B, H // 2, W // 2, C, 4).max(axis=-1)
    else:
        raise ValueError(f"unknown pooling_type: {pooling_type!r}")
    return data_ds, conf_ds


def zero_stuff_upsample(x: jax.Array, scale_h: int, scale_w: int) -> jax.Array:
    """Scatter low-res samples into a zeroed high-res grid at stride
    centers: ``out[:, sH//2::sH, sW//2::sW] = x`` (reference:
    core/upsampler.py:179-210).

    Args:
      x: (B, H, W, C).
    Returns:
      (B, H*scale_h, W*scale_w, C) zeros except at the stuffed positions.
    """
    B, H, W, C = x.shape
    out = jnp.zeros((B, H * scale_h, W * scale_w, C), dtype=x.dtype)
    return out.at[:, scale_h // 2 :: scale_h, scale_w // 2 :: scale_w, :].set(x)
