"""Input padding to stride-8-divisible shapes (reference:
core/utils/utils.py:7-25)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


class InputPadder:
    """Pads NHWC images so H and W are divisible by 8 (replicate padding).

    mode='sintel' centers the vertical padding; mode='kitti' puts all
    vertical padding below the image (the reference's torch pad spec
    ``[wl, wr, 0, pad_ht]`` is (left, right, top, bottom)). Horizontal
    padding is centered in both modes.

    ``bucket`` > 0 additionally rounds the PADDED height and width up to
    multiples of ``bucket`` (which must itself be divisible by the
    stride/divisor). KITTI's native resolutions differ by a few pixels
    frame to frame, so without bucketing every distinct shape compiles
    its own eval executable; with e.g. ``bucket=64`` the whole training
    split collapses onto a small fixed shape set, making the number of
    compiled programs bounded and known up front
    (inference/pipeline.ShapeCachedForward pairs its LRU with this).
    """

    def __init__(
        self,
        dims: tuple[int, ...],
        mode: str = "sintel",
        divisor: int = 8,
        bucket: int = 0,
    ):
        # dims is NHWC (B, H, W, C) or HWC (H, W, C). ``divisor`` > 8 is
        # used by spatially-sharded eval: the 1/8-res feature height must
        # divide the mesh's spatial axis, so images pad to 8 * spatial
        # (models/raft.py falls back to the pathological GSPMD partition
        # of the corr lookup otherwise).
        if len(dims) == 4:
            self.ht, self.wd = dims[1], dims[2]
        else:
            self.ht, self.wd = dims[0], dims[1]
        d = divisor
        if bucket:
            if bucket % d or bucket % 8:
                raise ValueError(
                    f"pad bucket {bucket} must be a multiple of the "
                    f"divisor ({d}) and of the stride (8)"
                )
            pad_ht = -self.ht % bucket
            pad_wd = -self.wd % bucket
        else:
            pad_ht = (((self.ht // d) + 1) * d - self.ht) % d
            pad_wd = (((self.wd // 8) + 1) * 8 - self.wd) % 8
        wpad = (pad_wd // 2, pad_wd - pad_wd // 2)
        if mode == "sintel":
            self._pad = ((pad_ht // 2, pad_ht - pad_ht // 2), wpad)
        else:
            self._pad = ((0, pad_ht), wpad)

    @property
    def pad_spec(self) -> tuple[tuple[int, int], tuple[int, int]]:
        """Static ``((top, bottom), (left, right))`` amounts — hashable,
        so it can key a compiled executable and drive the in-graph unpad
        crop (inference/metrics.unpad_in_graph)."""
        return self._pad

    def pad(self, *inputs: jax.Array) -> list[jax.Array]:
        spec = ((0, 0), self._pad[0], self._pad[1], (0, 0))
        return [jnp.pad(x, spec, mode="edge") for x in inputs]

    def unpad(self, x: jax.Array) -> jax.Array:
        (t, b), (l, r) = self._pad
        ht, wd = x.shape[-3], x.shape[-2]
        return x[..., t : ht - b, l : wd - r, :]
