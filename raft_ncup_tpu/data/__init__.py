from raft_ncup_tpu.data.augment import (
    ColorJitter,
    FlowAugmentor,
    SparseFlowAugmentor,
    resize_sparse_flow_map,
)
from raft_ncup_tpu.data.datasets import (
    HD1K,
    KITTI,
    FlowDataset,
    FlyingChairs,
    FlyingThings3D,
    MixedDataset,
    MpiSintel,
    fetch_training_set,
)
from raft_ncup_tpu.data.device_prefetch import DevicePrefetcher
from raft_ncup_tpu.data.loader import FlowLoader
from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset

__all__ = [
    "ColorJitter",
    "FlowAugmentor",
    "SparseFlowAugmentor",
    "resize_sparse_flow_map",
    "FlowDataset",
    "FlyingChairs",
    "FlyingThings3D",
    "MpiSintel",
    "KITTI",
    "HD1K",
    "MixedDataset",
    "fetch_training_set",
    "DevicePrefetcher",
    "FlowLoader",
    "SyntheticFlowDataset",
]
