"""Device-side batch prefetching: overlap host→device transfer with compute.

RAFT's recurrent step chains 12 GRU iterations, so every training step is
latency-bound — there is no slack inside the step to hide input stalls.
The FlowLoader already overlaps *decode/augment* with training (its own
thread pool + host-batch queue), but the host→device transfer and the
global-array assembly still sat on the critical path in the train loop:
``jnp.asarray``/``global_batch`` ran serially between dispatching step N
and step N+1.

:class:`DevicePrefetcher` closes that gap. A single worker thread pulls
host batches from the wrapped iterator, moves each to device (the batch
sharding's layout, so jit dispatch does no re-layout) and parks up to
``depth`` device-resident batches in a bounded queue. In steady state the
consumer's ``next()`` returns an array that is already on device — the
accelerator never waits on the host for input.

Contracts:

- **Order-preserving**: one worker thread, one FIFO queue — batches come
  out in exactly the wrapped iterator's order, contents untouched (only
  ``drop_keys`` removed and leaves transferred).
- **Exception propagation**: any error in the worker (including errors
  the wrapped iterator raises, e.g. FlowLoader surfacing a decode
  failure) is re-raised from the consumer's ``next()``.
- **Clean shutdown**: ``close()`` (or the context manager) stops the
  worker even while it is blocked on a full queue, joins it, and closes
  the wrapped iterator. Safe to call more than once.

Transfer policy lives in :func:`raft_ncup_tpu.parallel.multihost.
device_put_batch`: ``jax.device_put`` against the batch sharding on the
single-process path, ``jax.make_array_from_process_local_data`` on a pod.
"""

from __future__ import annotations

import queue
import sys
import threading
from typing import Any, Iterable, Iterator, Mapping, Optional

# Queue sentinel: the wrapped iterator was exhausted (finite iterators —
# FlowLoader.batches() is infinite, but tests and epoch-bounded consumers
# are not).
_END = object()


class DevicePrefetcher:
    """Wrap an iterator of host batch dicts; yield device-resident batches
    ``depth`` steps ahead of the consumer.

    Parameters
    ----------
    batches:
        Iterator/iterable of ``dict[str, np.ndarray]`` host batches (the
        FlowLoader contract).
    depth:
        Number of device batches staged ahead of compute. ``>= 2`` keeps
        one batch in flight while the next transfers — the minimum for
        full overlap of transfer with the compiled step.
    mesh / shardings:
        Forwarded to :func:`device_put_batch`; ``None`` means default
        device placement (single chip, no mesh).
    drop_keys:
        Batch keys removed before transfer (non-array metadata such as
        ``extra_info``).
    """

    def __init__(
        self,
        batches: Iterable[Mapping[str, Any]],
        *,
        depth: int = 2,
        mesh=None,
        shardings: Optional[dict] = None,
        drop_keys: tuple[str, ...] = ("extra_info",),
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = iter(batches)
        self._mesh = mesh
        self._shardings = shardings
        self._drop_keys = frozenset(drop_keys or ())
        self._stop = threading.Event()
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._thread = threading.Thread(
            target=self._worker, name="device-prefetch", daemon=True
        )
        self._thread.start()

    # ---------------------------------------------------------- worker side

    def _transfer(self, batch: Mapping[str, Any]) -> dict:
        from raft_ncup_tpu.parallel.multihost import device_put_batch

        host = {k: v for k, v in batch.items() if k not in self._drop_keys}
        return device_put_batch(host, self._mesh, self._shardings)

    def _put(self, item) -> bool:
        """Bounded put that keeps checking for shutdown — a consumer that
        stopped pulling must not strand the worker on a full queue."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    batch = next(self._it)
                except StopIteration:
                    self._put(_END)
                    return
                if not self._put(self._transfer(batch)):
                    return
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            self._put(e)
        finally:
            # The worker is the only thread ever executing the wrapped
            # generator, and it is suspended (not executing) here — so
            # this is the one place its close() is always legal. A close
            # failure has no consumer left to surface to, but it must not
            # vanish either (JGL007): log it to stderr.
            close = getattr(self._it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception as e:
                    print(
                        f"device-prefetch: wrapped iterator close failed: "
                        f"{e}",
                        file=sys.stderr,
                    )

    # -------------------------------------------------------- consumer side

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.5)
            except queue.Empty:
                if not self._thread.is_alive():
                    raise RuntimeError(
                        "device-prefetch worker died without delivering a "
                        "batch or an exception"
                    ) from None
                continue
            if item is _END:
                self._stop.set()  # exhausted: later next() calls stay StopIteration
                raise StopIteration
            if isinstance(item, BaseException):
                self.close()
                raise item
            return item

    def close(self) -> None:
        """Stop the worker, join it, close the wrapped iterator. Idempotent."""
        self._stop.set()
        # Drain so a worker blocked on a full queue can observe the stop
        # flag on its next put attempt instead of spinning a full timeout.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)

    def __enter__(self) -> "DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
