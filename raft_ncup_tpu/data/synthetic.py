"""Procedural flow pairs for tests and data-free benchmarking.

Two generators, selected by ``style``:

- ``"smooth"`` — a random textured image, a smooth random flow field,
  and the backward-warped second frame. Cheap and fully dense, but the
  flow has no discontinuities by construction.
- ``"rigid"`` — a piecewise-rigid scene: a background plus 2-4 textured
  shapes, each with its own similarity motion (rotation/scale/shift).
  Both frames are rendered independently from the surface parameters
  (the FlyingChairs recipe — reference: core/datasets.py:169-186 only
  *loads* such data; here it is generated), so the ground-truth flow is
  exact, sharply discontinuous at shape boundaries, and includes real
  occlusion. This is the split that can distinguish guided (NCUP)
  upsampling from naive bilinear: the paper's gains live at motion
  boundaries (reference: core/upsampler.py:75-210, README.md:11).

Used when ``DataConfig.synthetic_ok`` is set and the requested dataset
roots are absent, so the full train loop stays exercisable anywhere.
"""

from __future__ import annotations

from typing import Optional

import cv2
import numpy as np

cv2.setNumThreads(0)


def _smooth_noise(rng, shape_hw, scale: int, channels: int) -> np.ndarray:
    h, w = shape_hw
    low = rng.normal(size=(max(2, h // scale), max(2, w // scale), channels))
    return cv2.resize(
        low.astype(np.float32), (w, h), interpolation=cv2.INTER_CUBIC
    ).reshape(h, w, channels)


def _norm255(t: np.ndarray) -> np.ndarray:
    """Normalize a texture to [0, 255] once, so both frames sampling it
    stay photometrically consistent."""
    return (t - t.min()) / (np.ptp(t) + 1e-6) * 255.0


def make_pair(
    rng: np.random.Generator,
    size_hw: tuple[int, int],
    max_mag: float = 12.0,
) -> dict:
    """One synthetic sample: textured frame, smooth flow, warped frame."""
    h, w = size_hw
    img1 = _norm255(_smooth_noise(rng, (h, w), 8, 3)).astype(np.uint8)

    flow = _smooth_noise(rng, (h, w), 32, 2) * (max_mag / 2.0)
    flow = flow.astype(np.float32)

    # Backward warp: image2(x) = image1(x - flow) so that flow maps
    # image1 -> image2 forward.
    xx, yy = np.meshgrid(np.arange(w, dtype=np.float32),
                         np.arange(h, dtype=np.float32))
    map_x = xx - flow[..., 0]
    map_y = yy - flow[..., 1]
    img2 = cv2.remap(
        img1, map_x, map_y, cv2.INTER_LINEAR, borderMode=cv2.BORDER_REFLECT
    )
    valid = np.ones((h, w), np.float32)
    return {
        "image1": img1,
        "image2": img2,
        "flow": flow,
        "valid": valid,
    }


class _Similarity:
    """2D similarity motion ``M(p) = s·R(p-c) + c + d`` (vectorized)."""

    def __init__(self, center, angle: float, scale: float, shift):
        self.c = np.asarray(center, np.float32)
        self.d = np.asarray(shift, np.float32)
        cos, sin = np.cos(angle) * scale, np.sin(angle) * scale
        self.A = np.array([[cos, -sin], [sin, cos]], np.float32)
        self.Ainv = np.linalg.inv(self.A).astype(np.float32)

    def forward(self, pts: np.ndarray) -> np.ndarray:
        return (pts - self.c) @ self.A.T + self.c + self.d

    def inverse(self, pts: np.ndarray) -> np.ndarray:
        return (pts - self.c - self.d) @ self.Ainv.T + self.c


def _sample_tex(tex: np.ndarray, pts: np.ndarray) -> np.ndarray:
    """Bilinear-sample an (H, W, C) texture at (H, W, 2) xy points."""
    return cv2.remap(
        tex, pts[..., 0], pts[..., 1], cv2.INTER_LINEAR,
        borderMode=cv2.BORDER_REFLECT,
    )


def make_rigid_pair(
    rng: np.random.Generator,
    size_hw: tuple[int, int],
    max_mag: float = 12.0,
    n_shapes: tuple[int, int] = (2, 4),
) -> dict:
    """One piecewise-rigid sample: 2-4 moving textured shapes over a
    moving background, both frames rendered from the surface parameters,
    flow exact everywhere (including occluded pixels, as in Sintel GT).
    """
    h, w = size_hw
    xx, yy = np.meshgrid(np.arange(w, dtype=np.float32),
                         np.arange(h, dtype=np.float32))
    pts = np.stack([xx, yy], axis=-1)  # (h, w, 2) xy

    def motion(max_shift, max_rot_deg, max_log_scale, center):
        ang = np.deg2rad(rng.uniform(-max_rot_deg, max_rot_deg))
        s = np.exp(rng.uniform(-max_log_scale, max_log_scale))
        theta = rng.uniform(0, 2 * np.pi)
        r = rng.uniform(0.25, 1.0) * max_shift
        return _Similarity(center, ang, s,
                           (r * np.cos(theta), r * np.sin(theta)))

    # Background: its own (small) similarity motion about the image center.
    bg_tex = _norm255(_smooth_noise(rng, (h, w), 8, 3))
    bg_m = motion(max_mag / 4.0, 2.0, 0.02, ((w - 1) / 2.0, (h - 1) / 2.0))
    img1 = bg_tex.copy()
    img2 = _sample_tex(bg_tex, bg_m.inverse(pts))
    flow = (bg_m.forward(pts) - pts).astype(np.float32)

    # Shapes, painted back-to-front; the frame-1 mask overwrites the flow,
    # so the topmost surface wins exactly where it is visible in frame 1.
    for _ in range(rng.integers(n_shapes[0], n_shapes[1] + 1)):
        c = np.array([rng.uniform(0.2 * w, 0.8 * w),
                      rng.uniform(0.2 * h, 0.8 * h)], np.float32)
        ax = rng.uniform(0.10, 0.28, size=2) * min(h, w)
        th = rng.uniform(0, np.pi)
        rect = rng.random() < 0.4

        def inside(p, c=c, ax=ax, th=th, rect=rect):
            loc = (p - c) @ np.array(
                [[np.cos(th), np.sin(th)], [-np.sin(th), np.cos(th)]],
                np.float32,
            ).T
            u, v = loc[..., 0] / ax[0], loc[..., 1] / ax[1]
            return (np.maximum(np.abs(u), np.abs(v)) <= 1.0 if rect
                    else u * u + v * v <= 1.0)

        tex = _norm255(_smooth_noise(rng, (h, w), int(rng.choice([4, 8])), 3))
        m = motion(0.85 * max_mag, 8.0, 0.05, c)

        mask1 = inside(pts)
        img1[mask1] = tex[mask1]
        flow[mask1] = (m.forward(pts) - pts)[mask1]

        back = m.inverse(pts)  # frame-2 pixel -> frame-1 surface point
        mask2 = inside(back)
        img2[mask2] = _sample_tex(tex, back)[mask2]

    valid = np.ones((h, w), np.float32)
    return {
        "image1": np.clip(img1, 0, 255).astype(np.uint8),
        "image2": np.clip(img2, 0, 255).astype(np.uint8),
        "flow": flow.astype(np.float32),
        "valid": valid,
    }


def flow_boundary_mask(
    flow: np.ndarray, thresh: float = 2.0, band_px: int = 3
) -> np.ndarray:
    """Boolean mask of pixels within ``band_px`` of a flow discontinuity
    (forward-difference gradient magnitude above ``thresh`` px). The
    boundary-band EPE over this mask is the metric on which guided
    upsampling is expected to beat bilinear (reference claim:
    core/upsampler.py:75-210)."""
    gx = np.abs(np.diff(flow, axis=1, append=flow[:, -1:])).sum(-1)
    gy = np.abs(np.diff(flow, axis=0, append=flow[-1:])).sum(-1)
    edge = ((gx + gy) > thresh).astype(np.uint8)
    k = np.ones((2 * band_px + 1, 2 * band_px + 1), np.uint8)
    return cv2.dilate(edge, k).astype(bool)


class SyntheticFlowDataset:
    """Fixed-length procedural dataset compatible with FlowLoader."""

    def __init__(
        self,
        size_hw: tuple[int, int],
        length: int = 512,
        seed: int = 0,
        max_mag: float = 12.0,
        style: str = "smooth",
    ):
        if style not in ("smooth", "rigid"):
            raise ValueError(f"unknown synthetic style: {style!r}")
        self.size_hw = tuple(size_hw)
        self.length = length
        self.seed = seed
        self.max_mag = max_mag
        self.style = style
        self.is_test = False

    def __len__(self) -> int:
        return self.length

    def sample(self, index: int, rng: Optional[np.random.Generator] = None):
        # Content depends only on (seed, index); the loader-provided rng is
        # unused so the pair is stable across epochs.
        gen = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(index)])
        )
        make = make_rigid_pair if self.style == "rigid" else make_pair
        return make(gen, self.size_hw, self.max_mag)
