"""Procedural flow pairs for tests and data-free benchmarking.

Generates a random textured image, a smooth random flow field, and the
backward-warped second frame; the pair is a consistent (image1, image2,
flow) training sample without any dataset on disk. Used when
``DataConfig.synthetic_ok`` is set and the requested dataset roots are
absent, so the full train loop stays exercisable anywhere.
"""

from __future__ import annotations

from typing import Optional

import cv2
import numpy as np

cv2.setNumThreads(0)


def _smooth_noise(rng, shape_hw, scale: int, channels: int) -> np.ndarray:
    h, w = shape_hw
    low = rng.normal(size=(max(2, h // scale), max(2, w // scale), channels))
    return cv2.resize(
        low.astype(np.float32), (w, h), interpolation=cv2.INTER_CUBIC
    ).reshape(h, w, channels)


def make_pair(
    rng: np.random.Generator,
    size_hw: tuple[int, int],
    max_mag: float = 12.0,
) -> dict:
    """One synthetic sample: textured frame, smooth flow, warped frame."""
    h, w = size_hw
    img1 = _smooth_noise(rng, (h, w), 8, 3)
    img1 = (img1 - img1.min()) / (np.ptp(img1) + 1e-6) * 255.0
    img1 = img1.astype(np.uint8)

    flow = _smooth_noise(rng, (h, w), 32, 2) * (max_mag / 2.0)
    flow = flow.astype(np.float32)

    # Backward warp: image2(x) = image1(x - flow) so that flow maps
    # image1 -> image2 forward.
    xx, yy = np.meshgrid(np.arange(w, dtype=np.float32),
                         np.arange(h, dtype=np.float32))
    map_x = xx - flow[..., 0]
    map_y = yy - flow[..., 1]
    img2 = cv2.remap(
        img1, map_x, map_y, cv2.INTER_LINEAR, borderMode=cv2.BORDER_REFLECT
    )
    valid = np.ones((h, w), np.float32)
    return {
        "image1": img1,
        "image2": img2,
        "flow": flow,
        "valid": valid,
    }


class SyntheticFlowDataset:
    """Fixed-length procedural dataset compatible with FlowLoader."""

    def __init__(
        self,
        size_hw: tuple[int, int],
        length: int = 512,
        seed: int = 0,
        max_mag: float = 12.0,
    ):
        self.size_hw = tuple(size_hw)
        self.length = length
        self.seed = seed
        self.max_mag = max_mag
        self.is_test = False

    def __len__(self) -> int:
        return self.length

    def sample(self, index: int, rng: Optional[np.random.Generator] = None):
        # Content depends only on (seed, index); the loader-provided rng is
        # unused so the pair is stable across epochs.
        gen = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(index)])
        )
        return make_pair(gen, self.size_hw, self.max_mag)
