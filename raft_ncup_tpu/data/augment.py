"""Host-side numpy augmentation for optical-flow training pairs.

Covers the reference's dense and sparse augmentors (reference:
core/utils/augmentor.py:13-118 and :120-244) with the same transform
distributions — photometric jitter, occlusion eraser, random scale/stretch,
flips, crop — but written against an explicit ``np.random.Generator``
instead of global RNG state, so the pipeline is reproducible per sample
index regardless of worker scheduling.

Color jitter reimplements torchvision ``ColorJitter`` semantics in
vectorized numpy (random order of brightness/contrast/saturation/hue with
uniformly sampled factors).
"""

from __future__ import annotations

from dataclasses import dataclass

import cv2
import numpy as np

cv2.setNumThreads(0)
cv2.ocl.setUseOpenCL(False)


# ------------------------------------------------------------ color jitter


def _rgb_to_hsv(rgb: np.ndarray) -> np.ndarray:
    """(H, W, 3) float RGB in [0,1] -> HSV with hue in [0,1)."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = rgb.max(axis=-1)
    minc = rgb.min(axis=-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    h = np.where(
        maxc == r,
        (g - b) / dz,
        np.where(maxc == g, 2.0 + (b - r) / dz, 4.0 + (r - g) / dz),
    )
    h = np.where(delta == 0, 0.0, h / 6.0) % 1.0
    return np.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv: np.ndarray) -> np.ndarray:
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    choices = np.stack(
        [
            np.stack([v, t, p], -1),
            np.stack([q, v, p], -1),
            np.stack([p, v, t], -1),
            np.stack([p, q, v], -1),
            np.stack([t, p, v], -1),
            np.stack([v, p, q], -1),
        ]
    )
    iy, ix = np.indices(i.shape)
    return choices[i, iy, ix]


@dataclass(frozen=True)
class ColorJitter:
    """torchvision-style photometric jitter in numpy.

    Factors: brightness/contrast/saturation multiply by U(max(0,1-x), 1+x);
    hue shifts by U(-hue, hue) turns. Ops run in a random order
    (reference photometric config: core/utils/augmentor.py:30,136).
    """

    brightness: float = 0.4
    contrast: float = 0.4
    saturation: float = 0.4
    hue: float = 0.5 / 3.14

    def __call__(self, img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        x = img.astype(np.float32) / 255.0
        ops = rng.permutation(4)
        fb = rng.uniform(max(0, 1 - self.brightness), 1 + self.brightness)
        fc = rng.uniform(max(0, 1 - self.contrast), 1 + self.contrast)
        fs = rng.uniform(max(0, 1 - self.saturation), 1 + self.saturation)
        fh = rng.uniform(-self.hue, self.hue)
        for op in ops:
            if op == 0:
                x = x * fb
            elif op == 1:
                gray_mean = (
                    0.299 * x[..., 0] + 0.587 * x[..., 1] + 0.114 * x[..., 2]
                ).mean()
                x = x * fc + gray_mean * (1 - fc)
            elif op == 2:
                gray = (
                    0.299 * x[..., 0] + 0.587 * x[..., 1] + 0.114 * x[..., 2]
                )[..., None]
                x = x * fs + gray * (1 - fs)
            else:
                hsv = _rgb_to_hsv(np.clip(x, 0.0, 1.0))
                hsv[..., 0] = (hsv[..., 0] + fh) % 1.0
                x = _hsv_to_rgb(hsv)
            x = np.clip(x, 0.0, 1.0)
        return (x * 255.0 + 0.5).astype(np.uint8)


# --------------------------------------------------------------- augmentors


def _eraser(
    img2: np.ndarray, rng: np.random.Generator, prob: float, bounds=(50, 100)
) -> np.ndarray:
    """Occlusion: paint 1-2 mean-color rectangles onto img2 w.p. ``prob``
    (reference: core/utils/augmentor.py:50-63)."""
    ht, wd = img2.shape[:2]
    if rng.random() < prob:
        img2 = img2.copy()
        mean_color = img2.reshape(-1, 3).mean(axis=0)
        for _ in range(rng.integers(1, 3)):
            x0 = rng.integers(0, wd)
            y0 = rng.integers(0, ht)
            dx = rng.integers(bounds[0], bounds[1])
            dy = rng.integers(bounds[0], bounds[1])
            img2[y0 : y0 + dy, x0 : x0 + dx, :] = mean_color
    return img2


def _rand_crop_offsets(
    rng: np.random.Generator, shape, crop_size, margins=(0, 0)
) -> tuple[int, int]:
    my, mx = margins
    max_y = shape[0] - crop_size[0]
    max_x = shape[1] - crop_size[1]
    y0 = int(np.clip(rng.integers(0, max(max_y + my, 1)), 0, max_y))
    x0 = int(np.clip(rng.integers(-mx, max(max_x + mx, 1 - mx)), 0, max_x))
    return y0, x0


@dataclass(frozen=True)
class FlowAugmentor:
    """Dense-flow augmentation (reference: core/utils/augmentor.py:13-118)."""

    crop_size: tuple[int, int]
    min_scale: float = -0.2
    max_scale: float = 0.5
    do_flip: bool = True
    spatial_aug_prob: float = 0.8
    stretch_prob: float = 0.8
    max_stretch: float = 0.2
    h_flip_prob: float = 0.5
    v_flip_prob: float = 0.1
    asymmetric_color_aug_prob: float = 0.2
    eraser_aug_prob: float = 0.5

    def __call__(self, img1, img2, flow, rng: np.random.Generator):
        jitter = ColorJitter()
        # Photometric: asymmetric per-frame w.p. 0.2, else one jitter over
        # both frames stacked (reference: core/utils/augmentor.py:34-48).
        if rng.random() < self.asymmetric_color_aug_prob:
            img1 = jitter(img1, rng)
            img2 = jitter(img2, rng)
        else:
            stack = jitter(np.concatenate([img1, img2], axis=0), rng)
            img1, img2 = np.split(stack, 2, axis=0)

        img2 = _eraser(img2, rng, self.eraser_aug_prob)

        # Spatial: random log2 scale + optional anisotropic stretch, clamped
        # so the scaled image fits crop+8 (reference: :65-87).
        ht, wd = img1.shape[:2]
        min_scale = max(
            (self.crop_size[0] + 8) / float(ht),
            (self.crop_size[1] + 8) / float(wd),
        )
        scale = 2.0 ** rng.uniform(self.min_scale, self.max_scale)
        scale_x = scale_y = scale
        if rng.random() < self.stretch_prob:
            scale_x *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
            scale_y *= 2.0 ** rng.uniform(-self.max_stretch, self.max_stretch)
        scale_x = max(scale_x, min_scale)
        scale_y = max(scale_y, min_scale)

        if rng.random() < self.spatial_aug_prob:
            interp = cv2.INTER_LINEAR
            img1 = cv2.resize(img1, None, fx=scale_x, fy=scale_y, interpolation=interp)
            img2 = cv2.resize(img2, None, fx=scale_x, fy=scale_y, interpolation=interp)
            flow = cv2.resize(flow, None, fx=scale_x, fy=scale_y, interpolation=interp)
            flow = flow * np.array([scale_x, scale_y], np.float32)

        if self.do_flip:
            if rng.random() < self.h_flip_prob:
                img1 = img1[:, ::-1]
                img2 = img2[:, ::-1]
                flow = flow[:, ::-1] * np.array([-1.0, 1.0], np.float32)
            if rng.random() < self.v_flip_prob:
                img1 = img1[::-1]
                img2 = img2[::-1]
                flow = flow[::-1] * np.array([1.0, -1.0], np.float32)

        y0, x0 = _rand_crop_offsets(rng, img1.shape, self.crop_size)
        ys = slice(y0, y0 + self.crop_size[0])
        xs = slice(x0, x0 + self.crop_size[1])
        return (
            np.ascontiguousarray(img1[ys, xs]),
            np.ascontiguousarray(img2[ys, xs]),
            np.ascontiguousarray(flow[ys, xs]),
        )


def resize_sparse_flow_map(flow, valid, fx=1.0, fy=1.0):
    """Resize sparse flow by scattering valid points to their nearest pixel
    in the target grid (reference: core/utils/augmentor.py:159-191)."""
    ht, wd = flow.shape[:2]
    xx, yy = np.meshgrid(np.arange(wd), np.arange(ht))
    coords = np.stack([xx, yy], axis=-1).reshape(-1, 2).astype(np.float32)
    flow_flat = flow.reshape(-1, 2).astype(np.float32)
    keep = valid.reshape(-1) >= 1

    coords1 = coords[keep] * np.array([fx, fy], np.float32)
    flow1 = flow_flat[keep] * np.array([fx, fy], np.float32)

    ht1 = int(round(ht * fy))
    wd1 = int(round(wd * fx))
    xi = np.round(coords1[:, 0]).astype(np.int32)
    yi = np.round(coords1[:, 1]).astype(np.int32)
    inside = (xi > 0) & (xi < wd1) & (yi > 0) & (yi < ht1)

    flow_img = np.zeros((ht1, wd1, 2), np.float32)
    valid_img = np.zeros((ht1, wd1), np.int32)
    flow_img[yi[inside], xi[inside]] = flow1[inside]
    valid_img[yi[inside], xi[inside]] = 1
    return flow_img, valid_img


@dataclass(frozen=True)
class SparseFlowAugmentor:
    """Sparse-flow (KITTI/HD1K) augmentation (reference:
    core/utils/augmentor.py:120-244): symmetric-only color jitter with
    weaker factors, isotropic scale (no stretch), h-flip only, and a crop
    window biased by (y 20, x 50) margins."""

    crop_size: tuple[int, int]
    min_scale: float = -0.2
    max_scale: float = 0.5
    do_flip: bool = False
    spatial_aug_prob: float = 0.8
    h_flip_prob: float = 0.5
    eraser_aug_prob: float = 0.5

    def __call__(self, img1, img2, flow, valid, rng: np.random.Generator):
        jitter = ColorJitter(0.3, 0.3, 0.3, 0.3 / 3.14)
        stack = jitter(np.concatenate([img1, img2], axis=0), rng)
        img1, img2 = np.split(stack, 2, axis=0)

        img2 = _eraser(img2, rng, self.eraser_aug_prob)

        ht, wd = img1.shape[:2]
        min_scale = max(
            (self.crop_size[0] + 1) / float(ht),
            (self.crop_size[1] + 1) / float(wd),
        )
        scale = max(
            2.0 ** rng.uniform(self.min_scale, self.max_scale), min_scale
        )

        if rng.random() < self.spatial_aug_prob:
            img1 = cv2.resize(img1, None, fx=scale, fy=scale, interpolation=cv2.INTER_LINEAR)
            img2 = cv2.resize(img2, None, fx=scale, fy=scale, interpolation=cv2.INTER_LINEAR)
            flow, valid = resize_sparse_flow_map(flow, valid, fx=scale, fy=scale)

        if self.do_flip and rng.random() < self.h_flip_prob:
            img1 = img1[:, ::-1]
            img2 = img2[:, ::-1]
            flow = flow[:, ::-1] * np.array([-1.0, 1.0], np.float32)
            valid = valid[:, ::-1]

        y0, x0 = _rand_crop_offsets(
            rng, img1.shape, self.crop_size, margins=(20, 50)
        )
        ys = slice(y0, y0 + self.crop_size[0])
        xs = slice(x0, x0 + self.crop_size[1])
        return (
            np.ascontiguousarray(img1[ys, xs]),
            np.ascontiguousarray(img2[ys, xs]),
            np.ascontiguousarray(flow[ys, xs]),
            np.ascontiguousarray(valid[ys, xs]),
        )
