"""Threaded, host-sharded batch loader.

The reference feeds training from a 4-worker PyTorch DataLoader
(reference: core/datasets.py:240-241). Here the loader is a plain Python
iterator designed for the JAX input model: it yields dicts of stacked
numpy arrays (one host-local batch, ready for ``jax.device_put`` against a
batch sharding), shards sample indices across hosts by
``jax.process_index()``, decodes/augments in a thread pool (cv2/PIL
release the GIL), and keeps a bounded prefetch queue of ready batches.

Determinism: each sample's augmentation RNG is
``np.random.default_rng(SeedSequence(seed, epoch, index))`` — independent
of worker scheduling, stable across restarts.
"""

from __future__ import annotations

import queue
import sys
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np

from raft_ncup_tpu.resilience.retry import RetryStats, retry_io


def _stack_batch(samples: list[dict]) -> dict:
    # Preserve native dtypes: images stay uint8 (4x less host memory and
    # host->device traffic than float32; the model normalizes on device),
    # flow/valid stay float32.
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        if key == "extra_info":
            out[key] = vals
        else:
            out[key] = np.stack([np.asarray(v) for v in vals])
            if out[key].dtype not in (np.uint8, np.float32):
                out[key] = out[key].astype(np.float32)
    return out


class FlowLoader:
    """Iterate shuffled, augmented, host-sharded batches forever.

    ``shard_index``/``num_shards`` default to this host's
    ``jax.process_index()`` / ``jax.process_count()`` so each host of a
    multi-host pod reads a disjoint slice of every epoch — the TPU
    replacement for the reference's single-process DataLoader.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 1234,
        num_workers: int = 4,
        prefetch: int = 2,
        shard_index: Optional[int] = None,
        num_shards: Optional[int] = None,
        io_retries: int = 3,
        io_retry_backoff_s: float = 0.05,
    ):
        if shard_index is None or num_shards is None:
            import jax

            shard_index = jax.process_index()
            num_shards = jax.process_count()
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        # 0 means "no parallelism" (torch DataLoader semantics); the
        # thread-pool producer still needs one worker thread.
        self.num_workers = max(1, num_workers)
        self.prefetch = prefetch
        self.shard_index = shard_index
        self.num_shards = num_shards
        # Transient-IO resilience (resilience/retry.py): reads retry with
        # bounded backoff; samples that keep failing are quarantined for
        # the rest of the run and substituted so batches keep their
        # shape. `retry_stats` is this run's accounting (log.txt).
        self.io_retries = io_retries
        self.io_retry_backoff_s = io_retry_backoff_s
        self.retry_stats = RetryStats()
        # Guarded by _io_lock: pool workers fail concurrently, and the
        # check-then-quarantine must not double-quarantine an index.
        self._quarantined: set = set()
        self._io_lock = threading.Lock()
        if len(self) == 0:
            raise ValueError(
                f"dataset of {len(dataset)} samples yields zero batches for "
                f"shard {shard_index}/{num_shards} at batch_size={batch_size}"
                f" (drop_last={drop_last}) — check the dataset roots"
            )

    def _shard_size(self) -> int:
        return len(
            range(self.shard_index, len(self.dataset), self.num_shards)
        )

    def __len__(self) -> int:
        n = self._shard_size()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch])
            ).permutation(n)
        else:
            order = np.arange(n)
        return order[self.shard_index :: self.num_shards]

    def _read_sample(self, epoch: int, index: int) -> dict:
        """One retried dataset read. The augmentation rng is rebuilt
        from (seed, epoch, index) INSIDE every attempt: a sample() that
        consumed random draws before hitting a transient error would
        otherwise hand its retry an advanced generator, silently
        breaking the loader's per-(seed, epoch, index) determinism —
        and with it the bitwise kill/resume guarantee."""

        def attempt() -> dict:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch, index])
            )
            return self.dataset.sample(index, rng)

        return retry_io(
            attempt,
            attempts=self.io_retries,
            base_delay_s=self.io_retry_backoff_s,
            stats=self.retry_stats,
            desc=f"dataset read index={index}",
            log=self._log_retry,
        )

    def _quarantine(self, index: int, why: str) -> None:
        with self._io_lock:
            already = index in self._quarantined
            self._quarantined.add(index)
        if not already:
            self.retry_stats.quarantine(index)
            self._log_retry(f"dataset read index={index} {why}; quarantined")

    def _load_one(self, epoch: int, index: int) -> dict:
        index = int(index)
        with self._io_lock:
            quarantined = index in self._quarantined
        if quarantined:
            return self._substitute(epoch, index)
        try:
            return self._read_sample(epoch, index)
        except OSError as e:
            # Poison sample: the read failed through every retry. Losing
            # one sample must not kill a 100k-step run — quarantine the
            # index (never read again this run) and substitute a
            # neighbor so the batch keeps its shape. The quarantine list
            # is accounted in retry_stats and surfaced in log.txt.
            self._quarantine(index, f"failed permanently ({e})")
            return self._substitute(epoch, index)

    def _substitute(self, epoch: int, index: int) -> dict:
        """Deterministic stand-in for a quarantined sample: the next
        non-quarantined index of THIS host's epoch shard (wrapping,
        shard order) — never an index another host also serves, so a
        multihost global batch cannot double-load a sample. Read through
        the same retry/quarantine policy (with the substitute's own
        (seed, epoch, sub) rng), so a flaky substitute read cannot kill
        the run either. When every shard index ends up quarantined the
        data source is gone, not flaky: raise a clear error instead of
        spinning."""
        shard = self._epoch_indices(epoch)
        hits = np.nonzero(shard == index)[0]
        pos = int(hits[0]) if len(hits) else 0
        for off in range(1, len(shard)):
            sub = int(shard[(pos + off) % len(shard)])
            with self._io_lock:
                quarantined = sub in self._quarantined
            if quarantined:
                continue
            try:
                return self._read_sample(epoch, sub)
            except OSError as e:
                self._quarantine(sub, f"failed permanently ({e})")
        raise RuntimeError(
            f"all {len(self._quarantined)} reachable shard samples are "
            "quarantined after exhausting IO retries — the data source "
            "is unavailable, not flaky "
            f"({self.retry_stats.summary()})"
        )

    @staticmethod
    def _log_retry(msg: str) -> None:
        # stderr: stdout is a parsed protocol stream in the harnesses
        # that wrap child trainers (bench JSON tail, LOSS= lines).
        print(f"FlowLoader {msg}", file=sys.stderr)

    def batches(
        self, start_epoch: int = 0, start_batch: int = 0
    ) -> Iterator[dict]:
        """Infinite stream of batches, epoch after epoch.

        ``start_batch`` skips the first k batches of the start epoch
        without loading them — the loader is deterministic per
        (seed, epoch, index), so resuming at (epoch, batch) reproduces the
        exact stream an uninterrupted run would have seen."""
        stop = threading.Event()
        out: queue.Queue = queue.Queue(maxsize=max(1, self.prefetch))

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    out.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    epoch = start_epoch
                    skip = start_batch * self.batch_size
                    while not stop.is_set():
                        idx = self._epoch_indices(epoch)
                        limit = (
                            len(idx) - len(idx) % self.batch_size
                            if self.drop_last
                            else len(idx)
                        )
                        first = min(skip, limit)
                        skip = 0
                        for s in range(first, limit, self.batch_size):
                            chunk = idx[s : s + self.batch_size]
                            samples = list(
                                pool.map(
                                    lambda i: self._load_one(epoch, i), chunk
                                )
                            )
                            if not _put(_stack_batch(samples)):
                                return
                        epoch += 1
            except BaseException as e:  # surface worker errors to consumer
                _put(e)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                item = out.get()
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def one_epoch(self, epoch: int = 0) -> Iterator[dict]:
        """A single pass over this host's shard (for validation loops)."""
        idx = self._epoch_indices(epoch)
        limit = (
            len(idx) - len(idx) % self.batch_size if self.drop_last else len(idx)
        )
        with ThreadPoolExecutor(self.num_workers) as pool:
            for s in range(0, limit, self.batch_size):
                chunk = idx[s : s + self.batch_size]
                samples = list(
                    pool.map(lambda i: self._load_one(epoch, i), chunk)
                )
                yield _stack_batch(samples)
