"""Threaded, host-sharded batch loader.

The reference feeds training from a 4-worker PyTorch DataLoader
(reference: core/datasets.py:240-241). Here the loader is a plain Python
iterator designed for the JAX input model: it yields dicts of stacked
numpy arrays (one host-local batch, ready for ``jax.device_put`` against a
batch sharding), shards sample indices across hosts by
``jax.process_index()``, decodes/augments in a thread pool (cv2/PIL
release the GIL), and keeps a bounded prefetch queue of ready batches.

Determinism: each sample's augmentation RNG is
``np.random.default_rng(SeedSequence(seed, epoch, index))`` — independent
of worker scheduling, stable across restarts.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Optional

import numpy as np


def _stack_batch(samples: list[dict]) -> dict:
    # Preserve native dtypes: images stay uint8 (4x less host memory and
    # host->device traffic than float32; the model normalizes on device),
    # flow/valid stay float32.
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        if key == "extra_info":
            out[key] = vals
        else:
            out[key] = np.stack([np.asarray(v) for v in vals])
            if out[key].dtype not in (np.uint8, np.float32):
                out[key] = out[key].astype(np.float32)
    return out


class FlowLoader:
    """Iterate shuffled, augmented, host-sharded batches forever.

    ``shard_index``/``num_shards`` default to this host's
    ``jax.process_index()`` / ``jax.process_count()`` so each host of a
    multi-host pod reads a disjoint slice of every epoch — the TPU
    replacement for the reference's single-process DataLoader.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        *,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 1234,
        num_workers: int = 4,
        prefetch: int = 2,
        shard_index: Optional[int] = None,
        num_shards: Optional[int] = None,
    ):
        if shard_index is None or num_shards is None:
            import jax

            shard_index = jax.process_index()
            num_shards = jax.process_count()
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        # 0 means "no parallelism" (torch DataLoader semantics); the
        # thread-pool producer still needs one worker thread.
        self.num_workers = max(1, num_workers)
        self.prefetch = prefetch
        self.shard_index = shard_index
        self.num_shards = num_shards
        if len(self) == 0:
            raise ValueError(
                f"dataset of {len(dataset)} samples yields zero batches for "
                f"shard {shard_index}/{num_shards} at batch_size={batch_size}"
                f" (drop_last={drop_last}) — check the dataset roots"
            )

    def _shard_size(self) -> int:
        return len(
            range(self.shard_index, len(self.dataset), self.num_shards)
        )

    def __len__(self) -> int:
        n = self._shard_size()
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch])
            ).permutation(n)
        else:
            order = np.arange(n)
        return order[self.shard_index :: self.num_shards]

    def _load_one(self, epoch: int, index: int) -> dict:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch, int(index)])
        )
        return self.dataset.sample(int(index), rng)

    def batches(
        self, start_epoch: int = 0, start_batch: int = 0
    ) -> Iterator[dict]:
        """Infinite stream of batches, epoch after epoch.

        ``start_batch`` skips the first k batches of the start epoch
        without loading them — the loader is deterministic per
        (seed, epoch, index), so resuming at (epoch, batch) reproduces the
        exact stream an uninterrupted run would have seen."""
        stop = threading.Event()
        out: queue.Queue = queue.Queue(maxsize=max(1, self.prefetch))

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    out.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    epoch = start_epoch
                    skip = start_batch * self.batch_size
                    while not stop.is_set():
                        idx = self._epoch_indices(epoch)
                        limit = (
                            len(idx) - len(idx) % self.batch_size
                            if self.drop_last
                            else len(idx)
                        )
                        first = min(skip, limit)
                        skip = 0
                        for s in range(first, limit, self.batch_size):
                            chunk = idx[s : s + self.batch_size]
                            samples = list(
                                pool.map(
                                    lambda i: self._load_one(epoch, i), chunk
                                )
                            )
                            if not _put(_stack_batch(samples)):
                                return
                        epoch += 1
            except BaseException as e:  # surface worker errors to consumer
                _put(e)

        thread = threading.Thread(target=producer, daemon=True)
        thread.start()
        try:
            while True:
                item = out.get()
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    def one_epoch(self, epoch: int = 0) -> Iterator[dict]:
        """A single pass over this host's shard (for validation loops)."""
        idx = self._epoch_indices(epoch)
        limit = (
            len(idx) - len(idx) % self.batch_size if self.drop_last else len(idx)
        )
        with ThreadPoolExecutor(self.num_workers) as pool:
            for s in range(0, limit, self.batch_size):
                chunk = idx[s : s + self.batch_size]
                samples = list(
                    pool.map(lambda i: self._load_one(epoch, i), chunk)
                )
                yield _stack_batch(samples)
