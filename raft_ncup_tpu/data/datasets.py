"""Optical-flow dataset registry.

Index construction mirrors the reference's glob logic for FlyingChairs,
FlyingThings3D, MpiSintel, KITTI and HD1K (reference: core/datasets.py:102-204)
— but samples are plain numpy dicts in channel-last layout, augmentation
takes an explicit per-sample RNG derived from (seed, epoch, index), and
dataset mixing is an index-level concatenation with replication factors
rather than mutating list multiplication.

Sample dict: ``image1``/``image2`` (H, W, 3) uint8, ``flow`` (H, W, 2)
float32, ``valid`` (H, W) float32. Test-split samples carry ``extra_info``
instead of flow.
"""

from __future__ import annotations

import os
import os.path as osp
from glob import glob
from typing import Optional, Sequence

import numpy as np

from raft_ncup_tpu.config import DataConfig, PACKAGED_CHAIRS_SPLIT
from raft_ncup_tpu.data.augment import FlowAugmentor, SparseFlowAugmentor
from raft_ncup_tpu.io import read_flow_kitti, read_gen


class FlowDataset:
    """Base: a list of (image1, image2, flow) paths plus an augmentor."""

    def __init__(self, aug_params: Optional[dict] = None, sparse: bool = False):
        self.sparse = sparse
        self.augmentor = None
        if aug_params is not None:
            cls = SparseFlowAugmentor if sparse else FlowAugmentor
            self.augmentor = cls(**aug_params)
        self.is_test = False
        self.flow_list: list[str] = []
        self.image_list: list[list[str]] = []
        self.extra_info: list = []

    def __len__(self) -> int:
        return len(self.image_list)

    def sample(self, index: int, rng: Optional[np.random.Generator] = None):
        """Load (and optionally augment) one training pair."""
        if self.is_test:
            img1 = read_gen(self.image_list[index][0])
            img2 = read_gen(self.image_list[index][1])
            return {
                "image1": img1,
                "image2": img2,
                "extra_info": self.extra_info[index],
            }

        index %= len(self.image_list)
        if self.sparse:
            flow, valid = read_flow_kitti(self.flow_list[index])
        else:
            flow, valid = read_gen(self.flow_list[index]), None

        img1 = read_gen(self.image_list[index][0])
        img2 = read_gen(self.image_list[index][1])
        flow = np.asarray(flow, np.float32)

        if self.augmentor is not None:
            if rng is None:
                rng = np.random.default_rng()
            if self.sparse:
                img1, img2, flow, valid = self.augmentor(
                    img1, img2, flow, valid, rng
                )
            else:
                img1, img2, flow = self.augmentor(img1, img2, flow, rng)

        if valid is None:
            # Dense datasets mark |flow| >= 1000 invalid (reference:
            # core/datasets.py:88).
            valid = (
                (np.abs(flow[..., 0]) < 1000) & (np.abs(flow[..., 1]) < 1000)
            )
        return {
            "image1": np.ascontiguousarray(img1, np.uint8),
            "image2": np.ascontiguousarray(img2, np.uint8),
            "flow": np.ascontiguousarray(flow, np.float32),
            "valid": np.ascontiguousarray(valid, np.float32),
        }


class MpiSintel(FlowDataset):
    """reference: core/datasets.py:102-118."""

    def __init__(
        self,
        aug_params=None,
        split="training",
        root="datasets/Sintel",
        dstype="clean",
    ):
        super().__init__(aug_params)
        flow_root = osp.join(root, split, "flow")
        image_root = osp.join(root, split, dstype)
        if split == "test":
            self.is_test = True
        if not osp.isdir(image_root):
            return
        for scene in sorted(os.listdir(image_root)):
            images = sorted(glob(osp.join(image_root, scene, "*.png")))
            for i in range(len(images) - 1):
                self.image_list.append([images[i], images[i + 1]])
                self.extra_info.append((scene, i))
            if split != "test":
                self.flow_list += sorted(
                    glob(osp.join(flow_root, scene, "*.flo"))
                )


class FlyingChairs(FlowDataset):
    """reference: core/datasets.py:121-135 — the 1/2-label split file picks
    training vs validation pairs."""

    def __init__(
        self,
        aug_params=None,
        split="train",
        root="datasets/FlyingChairs_release/data",
        split_file=PACKAGED_CHAIRS_SPLIT,
    ):
        super().__init__(aug_params)
        images = sorted(glob(osp.join(root, "*_img*.png")))
        flows = sorted(glob(osp.join(root, "*_flow.flo")))
        if not flows:
            return
        assert len(images) // 2 == len(flows)
        split_list = np.loadtxt(split_file, dtype=np.int32)
        want = 1 if split in ("train", "training") else 2
        for i in range(len(flows)):
            if split_list[i] == want:
                self.flow_list.append(flows[i])
                self.image_list.append([images[2 * i], images[2 * i + 1]])


class FlyingThings3D(FlowDataset):
    """reference: core/datasets.py:138-166 — left camera, both temporal
    directions; optional webp/npz compressed form."""

    def __init__(
        self,
        aug_params=None,
        root="datasets/FlyingThings3D",
        dstype="frames_cleanpass",
        load_compressed=False,
    ):
        super().__init__(aug_params)
        cam = "left"
        img_dstype = dstype + ("_webp" if load_compressed else "")
        img_ext = "*.webp" if load_compressed else "*.png"
        flow_ext = "*.npz" if load_compressed else "*.pfm"
        image_seq_dirs = sorted(glob(osp.join(root, img_dstype, "TRAIN/*/*")))
        flow_seq_dirs = sorted(glob(osp.join(root, "optical_flow/TRAIN/*/*")))
        for direction in ("into_future", "into_past"):
            image_dirs = sorted(osp.join(f, cam) for f in image_seq_dirs)
            flow_dirs = sorted(
                osp.join(f, direction, cam) for f in flow_seq_dirs
            )
            for idir, fdir in zip(image_dirs, flow_dirs):
                images = sorted(glob(osp.join(idir, img_ext)))
                flows = sorted(glob(osp.join(fdir, flow_ext)))
                for i in range(len(flows) - 1):
                    if direction == "into_future":
                        self.image_list.append([images[i], images[i + 1]])
                        self.flow_list.append(flows[i])
                    else:
                        self.image_list.append([images[i + 1], images[i]])
                        self.flow_list.append(flows[i + 1])


class KITTI(FlowDataset):
    """reference: core/datasets.py:169-185."""

    def __init__(self, aug_params=None, split="training", root="datasets/KITTI"):
        super().__init__(aug_params, sparse=True)
        if split == "testing":
            self.is_test = True
        root = osp.join(root, split)
        images1 = sorted(glob(osp.join(root, "image_2/*_10.png")))
        images2 = sorted(glob(osp.join(root, "image_2/*_11.png")))
        for img1, img2 in zip(images1, images2):
            self.extra_info.append([osp.basename(img1)])
            self.image_list.append([img1, img2])
        if split == "training":
            self.flow_list = sorted(glob(osp.join(root, "flow_occ/*_10.png")))


class HD1K(FlowDataset):
    """reference: core/datasets.py:188-204."""

    def __init__(self, aug_params=None, root="datasets/HD1k"):
        super().__init__(aug_params, sparse=True)
        seq_ix = 0
        while True:
            flows = sorted(
                glob(osp.join(root, "hd1k_flow_gt", f"flow_occ/{seq_ix:06d}_*.png"))
            )
            images = sorted(
                glob(osp.join(root, "hd1k_input", f"image_2/{seq_ix:06d}_*.png"))
            )
            if not flows:
                break
            for i in range(len(flows) - 1):
                self.flow_list.append(flows[i])
                self.image_list.append([images[i], images[i + 1]])
            seq_ix += 1


class MixedDataset:
    """Weighted concatenation of datasets — the functional replacement for
    the reference's ``100*sintel_clean + ... + things`` list replication
    (reference: core/datasets.py:93-96,231). An index table maps the mixed
    index to (dataset, local index)."""

    def __init__(self, parts: Sequence[tuple[FlowDataset, int]]):
        self.parts = [(ds, int(w)) for ds, w in parts if len(ds) > 0]
        self._table: list[tuple[int, int]] = []
        for di, (ds, w) in enumerate(self.parts):
            self._table.extend(
                (di, i) for _ in range(w) for i in range(len(ds))
            )

    def __len__(self) -> int:
        return len(self._table)

    def sample(self, index: int, rng: Optional[np.random.Generator] = None):
        di, li = self._table[index]
        return self.parts[di][0].sample(li, rng)


def fetch_training_set(
    stage: str,
    image_size: tuple[int, int],
    data_cfg: DataConfig | None = None,
    train_ds: str = "C+T+K+S+H",
):
    """Build the per-stage training mixture (reference:
    core/datasets.py:207-238): per-stage augmentation ranges and the
    sintel-stage 100/100/200/5/1 mixture.

    With ``data_cfg.synthetic_ok`` set, an empty result (no dataset on
    disk) falls back to procedurally generated pairs so the training path
    stays exercisable on data-free hosts."""
    cfg = data_cfg or DataConfig()
    ds = _fetch_training_set(stage, image_size, cfg, train_ds)
    if len(ds) == 0 and cfg.synthetic_ok:
        from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset

        return SyntheticFlowDataset(
            tuple(image_size), length=512, style=cfg.synthetic_style
        )
    return ds


def _fetch_training_set(
    stage: str,
    image_size: tuple[int, int],
    cfg: DataConfig,
    train_ds: str,
):
    crop = tuple(image_size)

    if stage == "chairs":
        aug = dict(crop_size=crop, min_scale=-0.1, max_scale=1.0, do_flip=True)
        return FlyingChairs(
            aug, split="training", root=cfg.root_chairs,
            split_file=cfg.chairs_split_file,
        )
    if stage == "things":
        aug = dict(crop_size=crop, min_scale=-0.4, max_scale=0.8, do_flip=True)
        clean = FlyingThings3D(
            aug, root=cfg.root_things, dstype="frames_cleanpass",
            load_compressed=cfg.compressed_ft,
        )
        final = FlyingThings3D(
            aug, root=cfg.root_things, dstype="frames_finalpass",
            load_compressed=cfg.compressed_ft,
        )
        return MixedDataset([(clean, 1), (final, 1)])
    if stage == "sintel":
        aug = dict(crop_size=crop, min_scale=-0.2, max_scale=0.6, do_flip=True)
        things = FlyingThings3D(
            aug, root=cfg.root_things, dstype="frames_cleanpass",
            load_compressed=cfg.compressed_ft,
        )
        clean = MpiSintel(aug, split="training", root=cfg.root_sintel, dstype="clean")
        final = MpiSintel(aug, split="training", root=cfg.root_sintel, dstype="final")
        if train_ds == "C+T+K+S+H":
            kitti = KITTI(
                dict(crop_size=crop, min_scale=-0.3, max_scale=0.5, do_flip=True),
                split="training", root=cfg.root_kitti,
            )
            hd1k = HD1K(
                dict(crop_size=crop, min_scale=-0.5, max_scale=0.2, do_flip=True),
                root=cfg.root_hd1k,
            )
            return MixedDataset(
                [(clean, 100), (final, 100), (kitti, 200), (hd1k, 5), (things, 1)]
            )
        return MixedDataset([(clean, 100), (final, 100), (things, 1)])
    if stage == "kitti":
        aug = dict(crop_size=crop, min_scale=-0.2, max_scale=0.4, do_flip=False)
        return KITTI(aug, split="training", root=cfg.root_kitti)
    raise ValueError(f"unknown training stage: {stage!r}")
