"""raft_ncup_tpu — a TPU-native (JAX/XLA/Pallas) optical-flow framework.

A from-scratch rebuild of the capabilities of RAFT-NCUP (Eldesokey &
Felsberg, VISAPP 2021; reference implementation in PyTorch), designed
TPU-first:

- NHWC layouts, bfloat16-friendly compute, static shapes, `lax.scan` over
  the recurrent refinement iterations.
- Correlation volume either materialized (fast at training resolutions) or
  computed on the fly (memory-efficient at 1080p), with a Pallas kernel for
  the fused lookup.
- Data/spatial parallelism expressed with `jax.sharding.Mesh` + `jax.jit`
  sharding annotations; XLA inserts the collectives (psum for gradients,
  halo exchanges for spatially-sharded convolutions).

Package map (mirrors the reference's capability inventory, SURVEY.md §2):

- ``ops``        pure-function numerics: sampling, correlation, normalized
                 convolution, resize/padding.
- ``nn``         flax.linen modules: encoders, update blocks, NCUP stack.
- ``models``     model orchestration (RAFT / RAFT-NCUP) as scan-based
                 functional forward passes.
- ``data``       dataset indexes, augmentation, flow file I/O, loaders.
- ``training``   loss, optimizers/schedules, train state, training loop.
- ``evaluation`` validation + leaderboard submission writers.
- ``parallel``   mesh construction and sharded train/eval steps.
- ``utils``      flow visualization, torch checkpoint import, profiling.
"""

__version__ = "0.1.0"

from raft_ncup_tpu.config import (  # noqa: F401
    DataConfig,
    ModelConfig,
    TrainConfig,
    UpsamplerConfig,
)
