"""Bounded admission queue with load-shedding and backpressure hints.

The queueing-theory fact this module encodes: with open-loop arrivals,
an unbounded queue converts overload into unbounded latency — every
request is eventually answered, none in useful time. A bounded queue
with explicit shedding converts the same overload into a fast, honest
``shed`` + ``retry_after_s`` for the marginal request while the admitted
ones keep their latency. The capacity bound is therefore the p99
contract, not a buffer size.

``offer`` never blocks (the caller is a client thread); ``pop_batch``
is the dispatcher's side: it blocks for the first request, then greedily
pops FIFO-adjacent requests sharing the same shape key — dynamic
micro-batching that never reorders across shapes (a request behind a
different-shaped head waits its turn; pad bucketing upstream makes
same-key runs the common case).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, List, Optional

from raft_ncup_tpu.serving.request import FlowRequest


class AdmissionQueue:
    """Thread-safe bounded FIFO of admitted :class:`FlowRequest`.

    With ``telemetry`` bound (observability/; ``name`` is the gauge
    prefix, e.g. ``serve`` → ``serve_queue_depth``), every ``offer`` /
    ``pop_batch`` / ``close`` publishes the live depth as a registry
    gauge (value + peak) — before this, the depth between an offer and
    the next pop was unobservable from outside, inferable only from
    shed events once the queue was already full.
    """

    def __init__(self, capacity: int, *, telemetry=None, name: str = "queue"):
        self.capacity = max(1, int(capacity))
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._paused = False
        self._tel = telemetry
        self._depth_gauge = f"{name}_queue_depth"

    def _publish_depth(self) -> None:
        # Callers hold self._cond: len() is the true instantaneous depth.
        if self._tel is not None:
            self._tel.gauge_set(self._depth_gauge, len(self._q))

    def __len__(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def offer(self, request: FlowRequest) -> bool:
        """Admit ``request`` or refuse immediately (full / closed).

        Returns True on admission. Never blocks: shedding is a decision,
        not a wait — the caller turns False into an explicit ``shed``
        response with a retry hint.
        """
        with self._cond:
            if self._closed or len(self._q) >= self.capacity:
                return False
            self._q.append(request)
            self._publish_depth()
            self._cond.notify()
            return True

    def close(self) -> None:
        """Stop admitting; queued requests remain poppable (drain)."""
        with self._cond:
            self._closed = True
            self._paused = False
            self._publish_depth()
            self._cond.notify_all()

    def set_paused(self, paused: bool) -> None:
        """While paused, ``pop_batch`` yields nothing — even if the
        consumer was already parked inside it when the pause landed (the
        flag lives in the condition's predicate, so a pause that
        happens-before a submit deterministically beats the wakeup).
        Admission is unaffected: requests queue up against capacity."""
        with self._cond:
            self._paused = bool(paused)
            self._cond.notify_all()

    def pop_batch(
        self,
        max_n: int,
        timeout: Optional[float] = None,
        key_fn: Optional[Callable[[FlowRequest], object]] = None,
        distinct_fn: Optional[Callable[[FlowRequest], object]] = None,
    ) -> List[FlowRequest]:
        """Pop the head plus up to ``max_n - 1`` FIFO-adjacent requests
        sharing its ``key_fn`` value (default: ``shape_key``).

        Blocks up to ``timeout`` for the first request; returns ``[]``
        on timeout or when closed-and-empty (the dispatcher's exit
        signal). Requests with a different key stay queued in order.

        ``distinct_fn`` (the streaming engine's batching rule): at most
        ONE popped request per distinct value — a second frame of the
        same stream must read the slot state its predecessor writes, so
        it cannot share a batch with it. A duplicate is *skipped in
        place* (it keeps its queue position and its per-stream FIFO
        order) and the scan continues to later same-key requests; the
        scan still stops at the first different-key request, so batches
        never reorder across shapes.
        """
        key_fn = key_fn or (lambda r: r.shape_key)
        with self._cond:
            while self._paused or not self._q:
                if self._closed and not self._q:
                    return []
                if not self._cond.wait(timeout):
                    return []
            head = self._q.popleft()
            batch = [head]
            want = key_fn(head)
            if distinct_fn is None:
                while (
                    self._q
                    and len(batch) < max_n
                    and key_fn(self._q[0]) == want
                ):
                    batch.append(self._q.popleft())
                self._publish_depth()
                return batch
            seen = {distinct_fn(head)}
            i = 0
            while i < len(self._q) and len(batch) < max_n:
                req = self._q[i]
                if key_fn(req) != want:
                    break  # never reorder across shape keys
                d = distinct_fn(req)
                if d in seen:
                    i += 1  # same stream: keeps its position and order
                    continue
                del self._q[i]
                batch.append(req)
                seen.add(d)
            self._publish_depth()
            return batch
