"""Deterministic synthetic traffic: the open-loop stream the chaos tests
and the ``serve_*`` bench row drive the server with.

A schedule is fully determined by ``(seed, n_requests, interval_s,
chaos)`` — same inputs, same frame pairs, same arrival offsets, same
fault coordinates — so a failing chaos test replays exactly
(``resilience/chaos.py``'s contract, extended to serving):

- ``burst@N`` — request ``N`` arrives as a burst: ``burst_size``
  requests due at the same instant (the overload that must produce
  explicit sheds, not unbounded queueing).
- ``poison@N`` — request ``N``'s first frame is all-NaN float32 (the
  poison the dispatcher must quarantine away from its batch-mates).
- ``sigterm@N`` — :func:`replay` delivers a real SIGTERM to the process
  right after submitting ``N`` requests; with a
  ``PreemptionHandler`` installed the driver stops submitting and the
  server drains (the graceful-drain contract, mid-flight).

Frames come from ``data/synthetic.SyntheticFlowDataset`` (content keyed
on ``(seed, index)`` only), so traffic is cheap to generate on the
submitting thread and identical across processes.
"""

from __future__ import annotations

import os
import signal as signal_mod
import time
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.resilience.chaos import ChaosSpec


class SyntheticTraffic:
    """Deterministic open-loop request schedule.

    Iterating yields ``(due_s, image1, image2)`` tuples ordered by
    ``due_s`` (seconds from stream start). ``interval_s`` is the steady
    inter-arrival gap; a ``burst@N`` chaos event expands request ``N``
    into ``burst_size`` simultaneous arrivals (all sharing N's due
    time), modeling a thundering herd on top of the steady stream.
    """

    def __init__(
        self,
        size_hw: Tuple[int, int],
        n_requests: int,
        *,
        seed: int = 0,
        interval_s: float = 0.0,
        burst_size: int = 8,
        chaos: Optional[ChaosSpec] = None,
        style: str = "smooth",
    ):
        self.size_hw = tuple(size_hw)
        self.n_requests = int(n_requests)
        self.interval_s = float(interval_s)
        self.burst_size = max(1, int(burst_size))
        self.chaos = chaos or ChaosSpec()
        # Length covers the steady stream plus every burst expansion
        # that actually fires (a burst@N with N past the stream's end
        # never emits).
        live_bursts = sum(
            1 for i in self.chaos.burst_requests if i < self.n_requests
        )
        total = self.n_requests + live_bursts * (self.burst_size - 1)
        self._ds = SyntheticFlowDataset(
            self.size_hw, length=max(1, total), seed=seed, style=style
        )
        self._total = total

    def __len__(self) -> int:
        return self._total

    def __iter__(self) -> Iterator[Tuple[float, np.ndarray, np.ndarray]]:
        emitted = 0
        for i in range(self.n_requests):
            due = i * self.interval_s
            copies = (
                self.burst_size if i in self.chaos.burst_requests else 1
            )
            for _ in range(copies):
                sample = self._ds.sample(emitted)
                img1, img2 = sample["image1"], sample["image2"]
                if i in self.chaos.poison_requests:
                    img1 = np.full(img1.shape, np.nan, np.float32)
                emitted += 1
                yield due, img1, img2


def replay(
    server,
    traffic: SyntheticTraffic,
    *,
    deadline_s: Optional[float] = None,
    preempt=None,
    sigterm_after: Optional[int] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> Tuple[List, bool]:
    """Drive ``server`` with ``traffic`` open-loop; returns
    ``(handles, interrupted)``.

    Open-loop means submissions happen at their due times regardless of
    completions — the server's admission control, not the driver's
    politeness, is what bounds the queue. ``preempt`` is an installed
    ``resilience/preemption.PreemptionHandler``; once its flag is set
    (e.g. by the ``sigterm_after`` self-signal, or an external SIGTERM)
    the driver stops submitting immediately — the caller then invokes
    ``server.drain()`` for the flush. ``interrupted`` reports whether
    the stream was cut short that way.
    """
    handles: List = []
    t0 = clock()
    for due, img1, img2 in traffic:
        if preempt is not None and preempt.requested:
            return handles, True
        delay = due - (clock() - t0)
        if delay > 0:
            sleep(delay)
        handles.append(server.submit(img1, img2, deadline_s=deadline_s))
        if sigterm_after is not None and len(handles) == sigterm_after:
            # A REAL signal through the real handler (the chaos
            # contract): the next loop iteration observes the flag.
            os.kill(os.getpid(), signal_mod.SIGTERM)
    return handles, bool(preempt is not None and preempt.requested)
