"""Serving request/response protocol and per-run accounting.

Every request submitted to the server terminates in exactly ONE of five
explicit statuses — there is no silent-drop path, and a client can
always distinguish "retry later" from "your input is bad" from "the
server failed":

- ``ok``       — flow computed; ``flow`` holds the (H, W, 2) field and
  ``iters`` the budget level it was computed at (the anytime contract:
  fewer iterations under load is a coarser but valid answer).
- ``shed``     — admission refused (queue at capacity, or the server is
  draining). ``retry_after_s`` carries the backpressure hint.
- ``timeout``  — the request's deadline expired while it waited in the
  queue; no compute was spent on it.
- ``rejected`` — the request itself is poison (bad shape/dtype/ndim at
  admission, or non-finite pixels found at dispatch) and was quarantined
  away from its batch-mates; ``detail`` says why.
- ``error``    — the server failed internally while processing the
  batch; the fault is the server's, not the request's.

``ServeStats`` follows ``resilience/retry.RetryStats``'s discipline:
thread-safe (submit callers, the dispatcher, and the drain worker all
mutate it concurrently), mutated only through ``note_*`` methods, and
rendered into one summary line so a run that survived on shedding and
quarantine says so. Each ``note_*`` additionally mirrors into the
telemetry registry under the canonical ``snake_case`` counter name
(``observability.telemetry.LEGACY_KEY_ALIASES["serve"]`` — the pinned
alias table); the legacy summary/report keys here never change.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

from raft_ncup_tpu.observability.telemetry import LEGACY_KEY_ALIASES

_SERVE_CANON = LEGACY_KEY_ALIASES["serve"]

def nearest_rank_ms(latencies_s: Sequence[float], p: float) -> Optional[float]:
    """Nearest-rank percentile of a latency sample, in milliseconds.

    The textbook estimator — value at index ``ceil(p*n) - 1`` of the
    sorted sample (p50 of 16 values is the 8th smallest, not the 9th a
    floor-index would give) — shared by serve.py and bench.py so the
    reported ``serve_p50_ms``/``serve_p99_ms`` mean the same thing
    everywhere. ``None`` on an empty sample.
    """
    if not latencies_s:
        return None
    xs = sorted(latencies_s)
    idx = max(0, math.ceil(p * len(xs)) - 1)
    return round(xs[min(idx, len(xs) - 1)] * 1000.0, 1)


STATUS_OK = "ok"
STATUS_SHED = "shed"
STATUS_TIMEOUT = "timeout"
STATUS_REJECTED = "rejected"
STATUS_ERROR = "error"

TERMINAL_STATUSES = (
    STATUS_OK,
    STATUS_SHED,
    STATUS_TIMEOUT,
    STATUS_REJECTED,
    STATUS_ERROR,
)


@dataclass
class FlowRequest:
    """One frame pair awaiting flow. ``deadline`` is an absolute time on
    the server's clock (``None`` = no deadline); ``shape_key`` is filled
    at admission — the padded (H, W) bucket the request batches under."""

    request_id: int
    image1: Any  # host array-likes; validated at admission/dispatch
    image2: Any
    deadline: Optional[float] = None
    submit_time: float = 0.0
    shape_key: Optional[tuple] = None
    pad_spec: Optional[tuple] = None
    native_hw: Optional[tuple] = None
    # Cross-process trace id adopted from an inbound TraceContext (a
    # fleet router's wire header) — carried onto this request's spans so
    # one trace_id reassembles the journey across the process boundary
    # (observability/spans.py; docs/OBSERVABILITY.md).
    trace_id: Optional[str] = None


@dataclass
class FlowResponse:
    """Terminal answer for one request (see module docstring)."""

    request_id: int
    status: str
    flow: Optional[Any] = None  # (H, W, 2) numpy, native shape; ok only
    iters: Optional[int] = None  # budget level the flow was computed at
    latency_s: Optional[float] = None  # submit -> completion
    retry_after_s: Optional[float] = None  # shed only: backpressure hint
    detail: str = ""

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


class ServeHandle:
    """Thread-safe completion handle handed back by ``submit``.

    ``result(timeout)`` blocks until the terminal response exists; the
    server completes each handle exactly once (a second completion is a
    server bug and raises)."""

    __slots__ = ("_event", "_response")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._response: Optional[FlowResponse] = None

    def complete(self, response: FlowResponse) -> None:
        if self._event.is_set():
            raise RuntimeError(
                f"handle for request {response.request_id} completed twice"
            )
        self._response = response
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> FlowResponse:
        if not self._event.wait(timeout):
            raise TimeoutError("serve handle not completed in time")
        assert self._response is not None
        return self._response


@dataclass(eq=False)  # a counter object: identity, not value, equality
class ServeStats:
    """Per-run serving accounting, rendered into the drain report.

    Mutate through the ``note_*`` methods only (the admission path, the
    dispatcher thread, and the drain worker all write concurrently)."""

    submitted: int = 0
    accepted: int = 0
    completed: int = 0  # ok responses delivered
    shed: int = 0
    timeouts: int = 0
    rejected: int = 0
    errors: int = 0
    batches: int = 0
    padded_rows: int = 0  # dummy rows added to reach a fixed batch program
    quarantined: List[int] = field(default_factory=list)  # poison request ids
    # Telemetry hub to mirror into (observability/; None = no mirror).
    # The local fields above stay the report()/summary() source of truth.
    telemetry: Optional[Any] = field(default=None, repr=False, compare=False)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def _mirror(self, field_name: str, delta: int = 1) -> None:
        # Outside the stats lock: the registry has its own, and holding
        # both would order them differently on different call paths.
        if self.telemetry is not None:
            self.telemetry.inc(_SERVE_CANON[field_name], delta)

    def note_submitted(self) -> None:
        with self._lock:
            self.submitted += 1
        self._mirror("submitted")

    def note_accepted(self) -> None:
        with self._lock:
            self.accepted += 1
        self._mirror("accepted")

    def note_completed(self) -> None:
        with self._lock:
            self.completed += 1
        self._mirror("completed")

    def note_shed(self) -> None:
        with self._lock:
            self.shed += 1
        self._mirror("shed")

    def note_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1
        self._mirror("timeouts")

    def note_error(self) -> None:
        with self._lock:
            self.errors += 1
        self._mirror("errors")

    def note_batch(self, padded_rows: int) -> None:
        with self._lock:
            self.batches += 1
            self.padded_rows += padded_rows
        self._mirror("batches")
        if padded_rows:
            self._mirror("padded_rows", padded_rows)

    def note_rejected(self, request_id: int, *,
                      quarantine: bool = False) -> None:
        """``quarantine=True`` marks a dispatch-time poison quarantine
        (the request made it into a batch and was isolated there);
        admission-time validation rejects count as ``rejected`` only —
        the drain report's ``quarantined=[...]`` list means exactly
        "poison isolated from live batch-mates"."""
        with self._lock:
            self.rejected += 1
            if quarantine and request_id not in self.quarantined:
                self.quarantined.append(request_id)
        self._mirror("rejected")
        if quarantine and self.telemetry is not None:
            self.telemetry.event(
                "serve_request_quarantined", request_id=request_id
            )

    def summary(self) -> str:
        q = ",".join(str(i) for i in self.quarantined) or "-"
        return (
            f"submitted={self.submitted} accepted={self.accepted} "
            f"completed={self.completed} shed={self.shed} "
            f"timeouts={self.timeouts} rejected={self.rejected} "
            f"errors={self.errors} batches={self.batches} "
            f"padded_rows={self.padded_rows} quarantined=[{q}]"
        )
