"""Load-adaptive anytime iteration budget with hysteresis.

RAFT refines flow iteratively: each GRU iteration improves the estimate,
and stopping early yields a coarser but structurally valid field
(PAPERS.md: arXiv:2003.12039 — "RAFT: Recurrent All-Pairs Field
Transforms"; the reference evaluates the same checkpoint at 12, 24 and
32 iterations). That makes iteration count a native latency/quality knob
the serving tier can turn under load — trade EPE for p99 the way
efficient-correlation work trades memory for resolution (PAPERS.md:
"Efficient All-Pairs Correlation Volume Sampling").

Two constraints shape the controller:

1. **The level set is small and FIXED** (``levels``, descending, e.g.
   ``(24, 16, 8)``). Every level is one compiled executable per (shape,
   batch) — a continuous knob would compile a fresh program per value
   and recompile-storm the exact burst it exists to absorb.
2. **Moves have hysteresis.** Degrading is immediate (occupancy ≥
   ``high_water`` ⇒ one level down — a burst must not wait), but
   recovering requires ``recover_patience`` CONSECUTIVE decisions at or
   below ``low_water``: the gap between the watermarks plus the patience
   window keeps the controller from flapping between two executables at
   a load sitting exactly on a threshold (each flap re-warms nothing —
   both programs stay cached — but flapping quality per-request is a
   worse client contract than a stable coarser answer).

The controller is pure host-side bookkeeping, driven once per batch
assembly with the queue depth the dispatcher just observed — no clock,
no device work, deterministic for tests (tests/test_serving.py pins the
drop/recover trajectories).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class IterationBudgetController:
    """Map admission-queue occupancy to a GRU iteration budget."""

    def __init__(
        self,
        levels: Sequence[int],
        capacity: int,
        high_water: float = 0.75,
        low_water: float = 0.25,
        recover_patience: int = 4,
        segments: int = 1,
    ):
        levels = tuple(int(x) for x in levels)
        if not levels or any(x <= 0 for x in levels):
            raise ValueError(f"iteration levels must be positive: {levels!r}")
        if list(levels) != sorted(levels, reverse=True):
            raise ValueError(
                f"iteration levels must be strictly descending: {levels!r}"
            )
        # Pipelined deployments (inference/pipe_schedule.py) add a third
        # constraint: every level must land on a scan-segment boundary,
        # or a degraded budget would need its own tick executable —
        # exactly the recompile storm constraint 1 exists to prevent.
        # Validated at CONSTRUCTION (the level set and mesh are both
        # deploy-time choices; a mid-burst decide() must never be the
        # first place the mismatch surfaces). segments=1 (default, no
        # pipeline) imposes nothing.
        from raft_ncup_tpu.inference.pipe_schedule import (
            validate_segment_levels,
        )

        validate_segment_levels(levels, segments)
        self.segments = int(segments)
        if not 0.0 <= low_water < high_water <= 1.0:
            raise ValueError(
                f"want 0 <= low_water < high_water <= 1, got "
                f"{low_water}/{high_water}"
            )
        self.levels = levels
        self.capacity = max(1, int(capacity))
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.recover_patience = max(1, int(recover_patience))
        self._level = 0  # index into levels; 0 = full quality
        self._calm = 0  # consecutive at/below-low_water decisions
        self.drops = 0
        self.recoveries = 0
        self.slo_drops = 0  # drops where the SLO verdict was the cause
        self.decisions: List[int] = [0] * len(levels)  # per-level counts
        # Executed-iterations EWMA (early exit, docs/PERF.md): None until
        # the first observation — an unfed controller is BITWISE the
        # worst-case controller (expected_scale() == 1.0).
        self._exec_ewma: Optional[float] = None
        self.exec_alpha = 0.25

    @property
    def level(self) -> int:
        return self._level

    @property
    def iters(self) -> int:
        """Current budget without making a decision (reporting only)."""
        return self.levels[self._level]

    # ------------------------------------------- expected-iteration model

    def note_executed(self, executed_iters: float) -> None:
        """Feed one batch's mean EXECUTED iteration count (early exit,
        docs/PERF.md "Early exit"): the EWMA turns the per-batch counts
        the dispatch path already observes into the controller's model
        of what a request actually costs. Clamped into
        ``(1, levels[0])`` — a bogus observation (zero, negative, or
        above the top budget) must not corrupt the occupancy scale."""
        x = min(float(self.levels[0]), max(1.0, float(executed_iters)))
        if self._exec_ewma is None:
            self._exec_ewma = x
        else:
            a = self.exec_alpha
            self._exec_ewma = a * x + (1.0 - a) * self._exec_ewma

    @property
    def expected_iters(self) -> float:
        """The controller's per-request cost model: the executed-iters
        EWMA when early exit has been feeding it, else the worst case
        (the top level — exactly the pre-early-exit assumption)."""
        if self._exec_ewma is None:
            return float(self.levels[0])
        return self._exec_ewma

    def expected_scale(self) -> float:
        """Fraction of the worst-case budget a request is EXPECTED to
        cost (1.0 when never fed — the unfed controller is bitwise the
        PR-12 controller). Scales occupancy in :meth:`decide`: a queue
        of requests that exit after half their budget is only half the
        work the same depth represented under worst-case accounting, so
        the controller admits more depth at the same watermarks — more
        admitted load at the same p99."""
        return min(1.0, self.expected_iters / float(self.levels[0]))

    def decide(self, queue_depth: int, slo_degraded: bool = False) -> int:
        """One decision: observe ``queue_depth`` (and the SLO verdict),
        maybe move one level, return the iteration budget for the batch
        being assembled.

        ``slo_degraded`` is the second degrade input (observability/slo
        — docs/OBSERVABILITY.md): a paging burn rate degrades exactly
        like a high-water occupancy observation, immediately and with
        the same one-level-per-decision pacing — queue depth says "work
        is piling up HERE", the SLO verdict says "the objective is
        burning" (which queue depth alone misses when the damage shows
        as shed rate or tail latency rather than backlog). Recovery is
        the same earned-calm path for both: the SLO must stop paging
        AND occupancy must sit at/below low_water for the patience
        window.
        """
        # Occupancy is EXPECTED-WORK occupancy: raw depth scaled by the
        # executed-iters model (expected_scale() == 1.0 until early exit
        # feeds note_executed — worst-case accounting, the exact PR-12
        # behavior). The SLO verdict is deliberately NOT scaled: a
        # burning objective degrades immediately regardless of how cheap
        # the model thinks a request is.
        occ = min(
            1.0,
            (max(0, int(queue_depth)) / self.capacity)
            * self.expected_scale(),
        )
        if occ >= self.high_water or slo_degraded:
            self._calm = 0
            if self._level < len(self.levels) - 1:
                self._level += 1
                self.drops += 1
                if slo_degraded and occ < self.high_water:
                    # Occupancy alone would NOT have degraded here: this
                    # drop is the telemetry loop driving the knob.
                    self.slo_drops += 1
        elif occ <= self.low_water:
            self._calm += 1
            if self._calm >= self.recover_patience and self._level > 0:
                self._level -= 1
                self.recoveries += 1
                self._calm = 0
        else:
            # Between the watermarks: hold level, reset patience — a
            # recovery must be earned by sustained calm, not by load
            # oscillating through the low band.
            self._calm = 0
        self.decisions[self._level] += 1
        return self.levels[self._level]

    def summary(self) -> str:
        per = " ".join(
            f"{it}it={n}" for it, n in zip(self.levels, self.decisions)
        )
        return (
            f"budget: level={self._level} ({self.iters} iters) "
            f"expected={self.expected_iters:.1f} "
            f"drops={self.drops} recoveries={self.recoveries} [{per}]"
        )
