"""The flow-serving front-end: dynamic micro-batching over a bounded
executable set, with admission control, deadlines, anytime iteration
budgets, poison quarantine, and graceful drain.

Data path (one dispatcher thread, clients on their own threads):

1. **submit** (client thread): cheap metadata validation (ndim / dtype /
   size caps — malformed requests are ``rejected`` before they occupy
   queue capacity; the default size ceiling is UHD 2176x3840, servable
   since the banded corr tier broke the 4K memory wall — docs/PERF.md
   "Banded dispatch"), pad-spec computation (``InputPadder`` with the
   configured bucket, so the request's batching key is its PADDED
   shape), then a non-blocking ``AdmissionQueue.offer`` — a full queue
   sheds with a ``retry_after_s`` hint derived from the live service-
   time EMA.
2. **assemble** (dispatcher): pop a FIFO run of same-padded-shape
   requests, expire the ones past their deadline (``timeout``, zero
   compute), scan the survivors' pixels for non-finite values — a NaN
   input is *quarantined alone* (``rejected`` + ``ServeStats``
   accounting, the ``resilience/retry.py`` discipline) while its
   batch-mates proceed untouched.
3. **budget**: one ``IterationBudgetController.decide`` per batch with
   the queue depth just observed — under burst the GRU iteration count
   steps down a fixed level set (coarser but valid flow; RAFT's anytime
   property), with hysteresis on the way back up.
4. **stage + dispatch**: host-side ``np.pad`` to the padded shape (host
   pad, not ``jnp.pad`` — the staging path must not compile tiny device
   programs), zero-row batch padding up to the nearest allowed batch
   size, then ``ShapeCachedForward.forward_device`` — one compiled
   program per (padded shape, batch size, iters), LRU-bounded, with
   ``DispatchThrottle`` capping in-flight programs per backend.
5. **complete** (drain worker): ``AsyncDrain`` performs the sanctioned
   ``jax.device_get`` off the dispatch thread, the callback unpads each
   row back to its native shape (host slicing) and completes the
   request's handle with latency accounting.

**Drain contract** (``drain()``, reused by serve.py's SIGTERM path via
``resilience/preemption.PreemptionHandler``): stop admitting (new
submits shed with ``detail="draining"``), flush every request already
admitted — through compute, not dropped — then tear down the dispatcher
and drain worker and return the final ``ServeStats``. Nothing admitted
is ever silently lost; everything refused is told so explicitly.

Invariants inherited from the rest of the stack: the steady-state
serving loop performs zero implicit host transfers and zero recompiles
(tests/test_serving.py pins both under ``analysis/guards.py``; bench.py
records them as ``serve_recompiles`` / ``serve_host_transfers``). The
per-batch result pull is the *product* here, not a leak — it flows
through the one sanctioned explicit ``jax.device_get`` in the
``AsyncDrain`` worker.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as np

from raft_ncup_tpu.config import ServeConfig
from raft_ncup_tpu.inference.pipeline import (
    AsyncDrain,
    DispatchThrottle,
    ShapeCachedForward,
    env_earlyexit_tol,
)
from raft_ncup_tpu.observability import get_telemetry
from raft_ncup_tpu.ops.padding import InputPadder
from raft_ncup_tpu.serving.admission import AdmissionQueue
from raft_ncup_tpu.serving.budget import IterationBudgetController
from raft_ncup_tpu.serving.request import (
    STATUS_ERROR,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHED,
    STATUS_TIMEOUT,
    FlowRequest,
    FlowResponse,
    ServeHandle,
    ServeStats,
)

_POLL_S = 0.05  # dispatcher wake cadence while the queue is idle


class FlowServer:
    """Serve flow requests against one model + variables set.

    ``clock`` is injectable (tests drive deadlines deterministically);
    it must be monotonic. The server owns one dispatcher thread from
    construction until :meth:`drain`.
    """

    def __init__(
        self,
        model,
        variables: dict,
        cfg: Optional[ServeConfig] = None,
        *,
        mesh=None,
        clock: Callable[[], float] = time.monotonic,
        telemetry=None,
    ):
        self.cfg = cfg or ServeConfig()
        self._clock = clock
        # The telemetry hub (observability/; docs/OBSERVABILITY.md):
        # stats mirror into its registry under the canonical counter
        # names, spans trace each batch's queue-wait / assembly /
        # pad+stage / dispatch / drain stages with request/batch
        # correlation ids, and report() reads the per-stage p50/p99
        # back out. None binds the process-wide default hub.
        self._tel = telemetry if telemetry is not None else get_telemetry()
        self.stats = ServeStats(telemetry=self._tel)
        # The machine-readable health answer (observability/health.py;
        # docs/OBSERVABILITY.md): STARTING here, WARMING/READY through
        # warmup (or READY at the first completed batch), READY ⇄
        # DEGRADED driven by the hub's SLO verdicts, DRAINING in
        # drain() — the exact scrape surface serve.py --healthz_file
        # exposes to a fleet router.
        self.health = self._tel.health("serve", fresh=True)
        # Mesh-first serving (docs/SHARDING.md): an explicit `mesh=`
        # wins; otherwise ServeConfig.mesh = (data, spatial) builds one.
        # Every compiled serving program is then a single SPMD program —
        # batches sharded over `data`, image height over `spatial` — and
        # request pads round up to the mesh divisor.
        from raft_ncup_tpu.parallel.mesh import resolve_config_mesh

        mesh, self._pad_divisor = resolve_config_mesh(mesh, self.cfg.mesh)
        self.mesh = mesh
        # The per-ServeConfig precision policy (docs/PRECISION.md): every
        # compiled serving program — warmup set included — runs under it,
        # and its fingerprint rides every executable key. None inherits
        # the model's own policy (ShapeCachedForward's default).
        self._fwd = ShapeCachedForward(
            model, variables, mesh=mesh, cache_size=self.cfg.cache_size,
            policy=self.cfg.precision, telemetry=self._tel,
        )
        self._queue = AdmissionQueue(
            self.cfg.queue_capacity, telemetry=self._tel, name="serve"
        )
        self.budget = IterationBudgetController(
            self.cfg.iter_levels,
            capacity=self.cfg.queue_capacity,
            high_water=self.cfg.high_water,
            low_water=self.cfg.low_water,
            recover_patience=self.cfg.recover_patience,
            # Under a pipelined mesh every budget level must land on a
            # scan-segment boundary (inference/pipe_schedule.py) —
            # surface a level-set/mesh mismatch HERE, at server
            # construction, not mid-burst in decide().
            segments=(
                int(mesh.shape.get("pipe", 1)) if mesh is not None else 1
            ),
        )
        # Early exit (docs/PERF.md "Early exit"): resolved from the env
        # knobs ONCE at construction — executable identity must not flip
        # mid-run with the environment. None = detection off, the exact
        # pre-early-exit serving path and executables.
        self._earlyexit_tol = env_earlyexit_tol()
        self._throttle = DispatchThrottle(self.cfg.inflight)
        self._drainer = AsyncDrain(depth=self.cfg.drain_depth)
        self._handles: dict[int, ServeHandle] = {}
        # Batches handed to the AsyncDrain worker and not yet delivered:
        # the safety net that keeps a drain-worker failure (device_get
        # error, callback bug) from leaving handles uncompleted forever
        # — AsyncDrain surfaces worker errors from a LATER submit/close,
        # so without this registry the error would be attributed to the
        # wrong batch and the failed batch's clients would hang.
        self._inflight: dict[int, list] = {}
        self._inflight_seq = 0
        self._inflight_lock = threading.Lock()
        self._service_ema: Optional[float] = None  # seconds per pair
        self._ema_lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        # The warmed (padded_h, padded_w, batch, iters) executable set,
        # recorded by warmup(): the replica identity a fleet router
        # routes shape-aware against (serve.py threads it into the
        # healthz file via Telemetry.identity; docs/FLEET.md).
        self.warmed: list = []
        self._draining = threading.Event()
        self._drained = False
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="flow-serve-dispatch",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------ admission

    def submit(
        self,
        image1,
        image2,
        *,
        deadline_s: Optional[float] = None,
        request_id: Optional[int] = None,
        trace_id: Optional[str] = None,
    ) -> ServeHandle:
        """Submit one frame pair; returns immediately with a handle.

        The handle completes with exactly one terminal status (see
        ``serving/request.py``). ``deadline_s`` is seconds from now
        (default ``cfg.default_deadline_s``; ``None`` = no deadline).
        ``request_id`` lets a fleet router supply ITS correlation id as
        the request's identity — the replica-side spans then carry the
        router-side id verbatim, so one ``request_id`` reassembles the
        journey across the process boundary (docs/FLEET.md;
        scripts/postmortem.py). Caller owns uniqueness. ``trace_id``
        adopts an inbound cross-process trace context: every span this
        request touches carries it, so the fleet's one-trace-per-request
        contract holds on the replica side too.
        """
        self.stats.note_submitted()
        handle = ServeHandle()
        if request_id is not None:
            rid = int(request_id)
        else:
            with self._id_lock:
                rid = self._next_id
                self._next_id += 1
        if self._draining.is_set():
            self.stats.note_shed()
            handle.complete(FlowResponse(
                rid, STATUS_SHED, retry_after_s=self._retry_after(),
                detail="draining",
            ))
            return handle
        err = self._admission_error(image1) or self._admission_error(image2)
        if err is None and image1.shape != image2.shape:
            err = f"frame shapes differ: {image1.shape} vs {image2.shape}"
        if err is not None:
            self.stats.note_rejected(rid)
            handle.complete(FlowResponse(rid, STATUS_REJECTED, detail=err))
            return handle
        h, w = int(image1.shape[0]), int(image1.shape[1])
        padder = InputPadder((h, w, 3), mode="sintel",
                             divisor=self._pad_divisor,
                             bucket=self.cfg.pad_bucket)
        (t, b), (le, r) = padder.pad_spec
        deadline_s = (
            deadline_s if deadline_s is not None
            else self.cfg.default_deadline_s
        )
        now = self._clock()
        req = FlowRequest(
            request_id=rid,
            image1=image1,
            image2=image2,
            deadline=None if deadline_s is None else now + deadline_s,
            submit_time=now,
            shape_key=(h + t + b, w + le + r),
            pad_spec=padder.pad_spec,
            native_hw=(h, w),
            trace_id=None if trace_id is None else str(trace_id),
        )
        self._handles[rid] = handle
        if not self._queue.offer(req):
            self._handles.pop(rid, None)
            self.stats.note_shed()
            handle.complete(FlowResponse(
                rid, STATUS_SHED, retry_after_s=self._retry_after(),
                detail="admission queue full",
            ))
            return handle
        self.stats.note_accepted()
        return handle

    def _admission_error(self, image) -> Optional[str]:
        shape = getattr(image, "shape", None)
        dtype = getattr(image, "dtype", None)
        if shape is None or dtype is None:
            return f"not an array: {type(image).__name__}"
        if len(shape) != 3 or shape[-1] != 3:
            return f"want (H, W, 3), got shape {tuple(shape)}"
        if np.dtype(dtype).kind not in "uif":
            return f"non-numeric dtype {dtype}"
        h, w = int(shape[0]), int(shape[1])
        mh, mw = self.cfg.max_image_hw
        if h < self.cfg.min_image_hw or w < self.cfg.min_image_hw:
            return f"image {h}x{w} below minimum {self.cfg.min_image_hw}"
        if h > mh or w > mw:
            return f"image {h}x{w} exceeds maximum {mh}x{mw}"
        return None

    def _retry_after(self) -> float:
        with self._ema_lock:
            per_pair = self._service_ema
        if per_pair is None:
            return self.cfg.default_retry_after_s
        # Time for the current backlog to clear is the honest hint.
        return round((len(self._queue) + 1) * per_pair, 4)

    # ------------------------------------------------------------- dispatch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._queue.pop_batch(self.cfg.max_batch,
                                          timeout=_POLL_S)
            if not batch:
                if self._queue.closed and not len(self._queue):
                    return
                continue
            depth = len(self._queue) + len(batch)
            try:
                self._process(batch, depth)
            except BaseException as e:  # noqa: BLE001 — per-request status
                # The fault is the server's (XLA error, drain-worker
                # failure...): every still-pending request in the batch
                # gets an explicit `error` terminal status (requests the
                # batch already resolved — timeouts, rejects — keep
                # theirs); the server keeps serving later batches. A
                # drain-WORKER error re-raises from a later submit, so
                # the batches it actually stranded are flushed from the
                # in-flight registry, not blamed on this batch alone.
                self._fail_inflight(e)
                for req in batch:
                    if self._complete(req.request_id, FlowResponse(
                        req.request_id, STATUS_ERROR, detail=repr(e),
                    )):
                        self.stats.note_error()

    def _process(self, batch: list, depth: int) -> None:
        # Batch correlation id, minted up front so every span and event
        # of this batch's journey carries it (the drain worker reuses it
        # as the in-flight registry token).
        with self._inflight_lock:
            token = self._inflight_seq
            self._inflight_seq += 1
        now = self._clock()
        live = []
        with self._tel.span(
            "serve_batch_assembly", batch_id=token, batch_size=len(batch)
        ):
            for req in batch:
                if req.deadline is not None and now > req.deadline:
                    self.stats.note_timeout()
                    self._complete(req.request_id, FlowResponse(
                        req.request_id, STATUS_TIMEOUT,
                        latency_s=now - req.submit_time,
                        detail="deadline expired in queue",
                    ))
                    continue
                # Per-request queue wait (submit -> batch assembly),
                # correlated to both the request and the batch. Recorded
                # for every request that reached assembly alive —
                # including one about to be quarantined, whose journey
                # the flight recorder must still reassemble.
                self._tel.observe_ms(
                    "serve_queue_wait", (now - req.submit_time) * 1e3,
                    request_id=req.request_id, batch_id=token,
                    **({"trace_id": req.trace_id}
                       if req.trace_id is not None else {}),
                )
                poison = self._poison_error(req)
                if poison is not None:
                    self.stats.note_rejected(
                        req.request_id, quarantine=True
                    )
                    # Fault trigger: the quarantine decision plus the
                    # recent timeline, banked before the batch-mates'
                    # dispatch overwrites the ring's oldest entries.
                    self._tel.flight_dump(
                        "poison_quarantine",
                        request_id=req.request_id, batch_id=token,
                        detail=poison,
                    )
                    self._complete(req.request_id, FlowResponse(
                        req.request_id, STATUS_REJECTED, detail=poison,
                    ))
                    continue
                live.append(req)
        if not live:
            return
        # First assembly of a server that never warmed up: it is
        # serving, so it is READY. Guarded on the pre-ready states only
        # — an unconditional ready() here would undo an SLO-driven
        # DEGRADED on the very next batch.
        if self.health.state in ("starting", "warming"):
            self.health.ready("serving")
        # The budget decision reads BOTH degrade inputs: the queue depth
        # the dispatcher just observed, and the hub's SLO verdict — the
        # telemetry loop driving the anytime knob (docs/OBSERVABILITY.md).
        iters = self.budget.decide(
            depth, slo_degraded=self._tel.slo_paging("serve")
        )
        self._tel.gauge_set("serve_iter_budget", iters)
        ph, pw = live[0].shape_key
        with self._tel.span(
            "serve_pad_stage", batch_id=token, rows=len(live),
        ) as stage_span:
            rows1 = [self._stage(r.image1, r.pad_spec) for r in live]
            rows2 = [self._stage(r.image2, r.pad_spec) for r in live]
            n_rows = next(
                b for b in self.cfg.batch_sizes if b >= len(live)
            )
            pad_rows = n_rows - len(live)
            for _ in range(pad_rows):
                rows1.append(np.zeros((ph, pw, 3), np.float32))
                rows2.append(np.zeros((ph, pw, 3), np.float32))
            stage_span.set(pad_rows=pad_rows)
            img1 = np.stack(rows1)
            img2 = np.stack(rows2)
        self.stats.note_batch(pad_rows)
        t_dispatch = self._clock()
        # The dispatch span times jit dispatch + the throttle's bounded
        # wait, NOT device completion (the drain span covers dispatch ->
        # delivery); it carries the full correlation set — request ids,
        # batch id, mesh + policy fingerprints.
        from raft_ncup_tpu.utils.profiling import stage_annotation

        trace_ids = [r.trace_id for r in live if r.trace_id is not None]
        ee_tol = self._earlyexit_tol
        with self._tel.span(
            "serve_dispatch",
            batch_id=token,
            request_ids=[r.request_id for r in live],
            iters=iters,
            mesh=self._fwd.mesh_fp,
            policy=self._fwd.policy.name,
            **({"trace_ids": trace_ids} if trace_ids else {}),
            **({"earlyexit_tol": ee_tol} if ee_tol is not None else {}),
        ), stage_annotation("serve.dispatch"):
            if ee_tol is not None:
                # Detection on: the executed-iters counter rides the
                # SAME drain tree as the flow — the per-batch summary
                # reaches the host through the one sanctioned pull, no
                # second sync, no extra executable output path.
                _, flow_up, exec_iters = self._fwd.forward_device(
                    img1, img2, iters, early_exit_tol=ee_tol
                )
                drain_tree = (flow_up, exec_iters)
            else:
                _, flow_up = self._fwd.forward_device(img1, img2, iters)
                drain_tree = flow_up
            self._throttle.push(flow_up)
        with self._inflight_lock:
            self._inflight[token] = live

        def deliver(host_out, live=live, iters=iters, token=token):
            with self._inflight_lock:
                self._inflight.pop(token, None)
            done = self._clock()
            if ee_tol is not None:
                host_flow, host_exec = host_out
            else:
                host_flow, host_exec = host_out, None
            # Dispatch -> delivered: device compute + the sanctioned
            # drain-worker pull, one per batch. The pull counter is the
            # independent measurement flip_recommendations checks
            # against stats.batches for snapshot consistency.
            self._tel.inc("serve_drain_pulls_total")
            tids = [r.trace_id for r in live if r.trace_id is not None]
            exec_attrs = {}
            if host_exec is not None:
                # Executed-iters summary over the LIVE rows only — the
                # zero batch-pad rows converge instantly and would bias
                # the mean the controller budgets from.
                live_exec = np.asarray(host_exec)[: len(live)]
                exec_attrs = {
                    "iters_budgeted": iters,
                    "iters_executed_mean": round(
                        float(live_exec.mean()), 3
                    ),
                }
            self._tel.observe_ms(
                "serve_drain", (done - t_dispatch) * 1e3,
                batch_id=token,
                request_ids=[r.request_id for r in live],
                **({"trace_ids": tids} if tids else {}),
                **exec_attrs,
            )
            if host_exec is not None:
                for k in range(len(live)):
                    self._tel.hist_observe(
                        "serve_exec_iters", float(live_exec[k])
                    )
                self.budget.note_executed(float(live_exec.mean()))
            for k, req in enumerate(live):
                (t, b), (le, r) = req.pad_spec
                hh, ww = host_flow.shape[1], host_flow.shape[2]
                flow = host_flow[k, t: hh - b, le: ww - r, :]
                self.stats.note_completed()
                # Per-request end-to-end latency (submit → delivered):
                # the SLI behind the serve_p99_latency SLO — histogram
                # only, no ring record (observability/slo.py).
                self._tel.hist_observe(
                    "serve_e2e_ms", (done - req.submit_time) * 1e3
                )
                self._complete(req.request_id, FlowResponse(
                    req.request_id, STATUS_OK, flow=flow, iters=iters,
                    latency_s=done - req.submit_time,
                ))
            # Dispatch->delivery over the batch rows: the per-pair
            # SERVICE time. Measuring from submit_time would fold queue
            # wait into the EMA and make the shed hint double-count the
            # backlog exactly when sheds happen.
            self._note_service((done - t_dispatch) / len(live))

        self._drainer.submit(drain_tree, deliver)

    def _fail_inflight(self, exc: BaseException) -> None:
        """Complete every batch stranded by a drain-worker failure with
        an explicit `error` — the no-silent-loss half of the drain
        contract when the sanctioned pull itself is what broke."""
        with self._inflight_lock:
            stranded = list(self._inflight.values())
            self._inflight.clear()
        for live in stranded:
            for req in live:
                if self._complete(req.request_id, FlowResponse(
                    req.request_id, STATUS_ERROR,
                    detail=f"result drain failed: {exc!r}",
                )):
                    self.stats.note_error()

    def _poison_error(self, req: FlowRequest) -> Optional[str]:
        for name, img in (("image1", req.image1), ("image2", req.image2)):
            arr = np.asarray(img)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                return f"non-finite pixels in {name}"
        return None

    def _stage(self, image, pad_spec) -> np.ndarray:
        (t, b), (le, r) = pad_spec
        arr = np.asarray(image, np.float32)
        if t or b or le or r:
            arr = np.pad(arr, ((t, b), (le, r), (0, 0)), mode="edge")
        return arr

    def _complete(self, rid: int, response: FlowResponse) -> bool:
        """Deliver ``response`` if ``rid`` is still pending; True when a
        handle was actually completed (each request resolves once)."""
        handle = self._handles.pop(rid, None)
        if handle is None:
            return False
        handle.complete(response)
        return True

    def _note_service(self, per_pair_s: float) -> None:
        with self._ema_lock:
            prev = self._service_ema
            self._service_ema = (
                per_pair_s if prev is None
                else 0.8 * prev + 0.2 * per_pair_s
            )
            ema = self._service_ema
        # The live EMA behind retry_after_s, as a gauge: the backpressure
        # hint's basis is observable instead of inferable from hints.
        self._tel.gauge_set("serve_service_time_ema_ms", ema * 1e3)

    # ------------------------------------------------------------- lifecycle

    def warmup(self, size_hw: tuple) -> int:
        """Compile the full executable set for one native shape: every
        (batch size, iteration level) program at its padded/bucketed
        shape. Returns the number of programs compiled. Call before a
        latency-sensitive window so no request pays a compile — with pad
        bucketing, one warmup covers every native shape in the bucket.
        """
        import jax

        self.health.warming()
        h, w = size_hw
        padder = InputPadder((int(h), int(w), 3), mode="sintel",
                             divisor=self._pad_divisor,
                             bucket=self.cfg.pad_bucket)
        (t, b), (le, r) = padder.pad_spec
        ph, pw = int(h) + t + b, int(w) + le + r
        before = self._fwd.stats["compiles"]
        warmed = []
        for n in self.cfg.batch_sizes:
            zeros = np.zeros((n, ph, pw, 3), np.float32)
            for iters in self.cfg.iter_levels:
                # Warm the exact program the dispatch path will run —
                # with detection on, that is the early-exit executable
                # (no request must ever pay its compile).
                out = self._fwd.forward_device(
                    zeros, zeros, iters,
                    early_exit_tol=self._earlyexit_tol,
                )
                jax.block_until_ready(out)
                warmed.append((ph, pw, n, iters))
        self.warmed = warmed
        compiled = self._fwd.stats["compiles"] - before
        self.health.ready(f"warmup compiled {compiled} programs")
        return compiled

    def pause(self) -> None:
        """Test/ops hook: stop assembling new batches (in-flight ones
        finish). Queued and newly admitted requests wait. Deterministic:
        a pause that happens-before a submit is guaranteed to beat the
        dispatcher to it (the flag lives inside the queue's condition
        predicate — see AdmissionQueue.set_paused)."""
        self._queue.set_paused(True)

    def resume(self) -> None:
        self._queue.set_paused(False)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout: Optional[float] = None) -> ServeStats:
        """Graceful drain: stop admitting, flush everything admitted,
        tear down, return the final stats. Idempotent. Health goes
        DRAINING immediately — a healthz poller (the fleet router's
        scrape) sees it before the flush completes, which is the point:
        stop routing here NOW (the SIGTERM → exit-75 contract)."""
        self.health.draining()
        self._draining.set()
        self._queue.close()  # also clears any pause: drain must finish
        if self._thread.is_alive():
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"dispatcher did not drain within {timeout}s "
                    f"({len(self._queue)} requests still queued)"
                )
        if not self._drained:
            self._drained = True
            self._throttle.drain()
            try:
                self._drainer.close()
            except Exception as e:
                # The drain worker died with batches in flight: their
                # clients get explicit `error` responses and the failure
                # is accounted — drain still returns the final stats
                # (nothing admitted is ever silently lost).
                import sys

                print(f"serve drain worker failed: {e!r}", file=sys.stderr)
                self._fail_inflight(e)
        return self.stats

    def report(self) -> dict:
        """One JSON-able summary: stats + budget + executable accounting.

        Every pre-telemetry key survives verbatim (back-compat pinned in
        tests/test_observability.py); ``stages`` adds the per-stage
        p50/p99 latency breakdown from the span tracer alongside.
        """
        stages = {
            k: v
            for k, v in self._tel.tracer.stage_summary().items()
            if k.startswith("serve_")
        }
        return {
            "stats": self.stats.summary(),
            "budget": self.budget.summary(),
            "budget_drops": self.budget.drops,
            "budget_recoveries": self.budget.recoveries,
            "budget_slo_drops": self.budget.slo_drops,
            "budget_expected_iters": round(
                self.budget.expected_iters, 3
            ),
            "executables": dict(self._fwd.stats),
            "precision": self._fwd.policy.name,  # RESOLVED (None inherits)
            "mesh": self._fwd.mesh_fp,
            "stages": stages,
            "health": self.health.snapshot(),
        }

    def __enter__(self) -> "FlowServer":
        return self

    def __exit__(self, *exc) -> None:
        self.drain()
