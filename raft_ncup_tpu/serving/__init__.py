"""Online flow serving: admission control, backpressure, anytime
iteration budgets, and chaos-tested graceful drain.

The train and eval hot loops batch *known* work; a service faces an
open-loop request stream it does not control. This package is the
robustness layer between that stream and the bounded executable set the
inference stack already provides (``ops/padding.InputPadder(bucket=N)``
+ ``inference/pipeline.ShapeCachedForward`` LRU + ``DispatchThrottle``):

- :mod:`request` — the request/response protocol: explicit terminal
  statuses (``ok`` / ``shed`` / ``timeout`` / ``rejected`` / ``error``),
  a thread-safe completion handle, and ``ServeStats`` accounting in the
  ``resilience/retry.RetryStats`` discipline (a server that survived on
  shedding and quarantine says so).
- :mod:`admission` — a bounded FIFO admission queue with load-shedding:
  a full queue REJECTS with a ``retry_after_s`` hint instead of queueing
  unboundedly (open-loop arrivals + unbounded queue = unbounded p99).
- :mod:`budget` — the load-adaptive iteration budget controller. RAFT's
  iterative refinement is a native anytime knob (PAPERS.md:
  arXiv:2003.12039): fewer GRU iterations is a coarser but valid flow
  field, so under burst the server degrades EPE instead of latency. The
  level set is small and fixed with hysteresis between moves, so the
  compiled executable set stays bounded and recompile-free.
- :mod:`server` — :class:`~raft_ncup_tpu.serving.server.FlowServer`:
  dynamic micro-batching over the bounded shape/batch/iter program set,
  per-request deadlines, poison-request quarantine (a bad shape/dtype/
  NaN input is rejected alone; its batch-mates are unaffected), and
  graceful drain (stop admitting, flush everything admitted, report).
- :mod:`traffic` — the deterministic synthetic traffic generator and
  replay driver; ``resilience/chaos.py``'s ``burst@N`` / ``poison@N`` /
  ``sigterm@N`` events drive the end-to-end chaos tests
  (tests/test_serving.py) and the ``serve.py`` demo loop.

Semantics, the executable-set arithmetic, and the chaos matrix:
docs/SERVING.md. Bench: the guarded ``serve_*`` row in bench.py.
"""

from raft_ncup_tpu.serving.admission import AdmissionQueue  # noqa: F401
from raft_ncup_tpu.serving.budget import (  # noqa: F401
    IterationBudgetController,
)
from raft_ncup_tpu.serving.request import (  # noqa: F401
    STATUS_ERROR,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHED,
    STATUS_TIMEOUT,
    TERMINAL_STATUSES,
    FlowRequest,
    FlowResponse,
    ServeHandle,
    ServeStats,
    nearest_rank_ms,
)

# FlowServer/traffic import the inference stack (and through it jax);
# they resolve lazily (PEP 562) so the host-only consumers of the
# request protocol — the fleet router above all (JGL010: fleet/ must
# never import jax, even transitively through this package) — can
# import `raft_ncup_tpu.serving.request` without initializing a backend.
_LAZY = {
    "FlowServer": ("raft_ncup_tpu.serving.server", "FlowServer"),
    "SyntheticTraffic": ("raft_ncup_tpu.serving.traffic", "SyntheticTraffic"),
    "replay": ("raft_ncup_tpu.serving.traffic", "replay"),
}


def __getattr__(name: str):
    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    value = getattr(importlib.import_module(module), attr)
    globals()[name] = value  # cache: one lazy resolve per process
    return value

__all__ = [
    "AdmissionQueue",
    "FlowRequest",
    "FlowResponse",
    "FlowServer",
    "IterationBudgetController",
    "STATUS_ERROR",
    "STATUS_OK",
    "STATUS_REJECTED",
    "STATUS_SHED",
    "STATUS_TIMEOUT",
    "TERMINAL_STATUSES",
    "ServeHandle",
    "ServeStats",
    "SyntheticTraffic",
    "nearest_rank_ms",
    "replay",
]
