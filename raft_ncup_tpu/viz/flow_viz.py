"""Middlebury color-wheel flow visualization.

One vectorized implementation covering the capability of both wheels in the
reference (reference: core/utils/flow_viz.py:22-137 and the VCN-derived
variant :145-275 used by demo/submissions): normalize by max radius, map
angle onto the 55-color Baker et al. (ICCV 2007) wheel, desaturate toward
white for small motions, zero out unknown flow.
"""

from __future__ import annotations

import numpy as np

UNKNOWN_FLOW_THRESH = 1e7


def make_colorwheel() -> np.ndarray:
    """The 55-entry Middlebury color wheel, (55, 3) float in [0, 255]."""
    segments = [
        (15, 0, 1, False),  # RY: red fixed, green ramps up
        (6, 1, 0, True),  # YG: green fixed, red ramps down
        (4, 1, 2, False),  # GC
        (11, 2, 1, True),  # CB
        (13, 2, 0, False),  # BM
        (6, 0, 2, True),  # MR
    ]
    wheel = np.zeros((sum(s[0] for s in segments), 3))
    col = 0
    for n, fixed, ramp, down in segments:
        wheel[col : col + n, fixed] = 255
        r = np.floor(255 * np.arange(n) / n)
        wheel[col : col + n, ramp] = 255 - r if down else r
        col += n
    return wheel


def flow_to_image(
    flow: np.ndarray,
    convert_to_bgr: bool = False,
    rad_max: float | None = None,
) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) uint8 Middlebury color image.

    ``rad_max=None`` normalizes by the image's own max radius (reference
    behavior); pass a value to fix the scale across frames.
    """
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    u = flow[:, :, 0].astype(np.float64)
    v = flow[:, :, 1].astype(np.float64)

    unknown = (np.abs(u) > UNKNOWN_FLOW_THRESH) | (
        np.abs(v) > UNKNOWN_FLOW_THRESH
    )
    u = np.where(unknown, 0.0, u)
    v = np.where(unknown, 0.0, v)

    rad = np.sqrt(u**2 + v**2)
    if rad_max is None:
        rad_max = float(rad.max()) if rad.size else 0.0
    scale = rad_max + np.finfo(np.float64).eps
    u, v, rad = u / scale, v / scale, rad / scale

    wheel = make_colorwheel() / 255.0  # (ncols, 3)
    ncols = wheel.shape[0]

    angle = np.arctan2(-v, -u) / np.pi  # [-1, 1]
    fk = (angle + 1) / 2 * (ncols - 1)  # [0, ncols-1]
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = (fk - k0)[..., None]

    col = (1 - f) * wheel[k0] + f * wheel[k1]  # (H, W, 3)

    small = (rad <= 1)[..., None]
    col = np.where(small, 1 - rad[..., None] * (1 - col), col * 0.75)
    img = np.floor(255.0 * col * ~unknown[..., None]).astype(np.uint8)
    if convert_to_bgr:
        img = img[:, :, ::-1]
    return img
