"""Middlebury color-wheel flow visualization.

Both wheels of the reference are covered:

- :func:`flow_to_image` — the vectorized port of the reference's primary
  wheel (reference: core/utils/flow_viz.py:22-137): normalize by max
  radius, map angle onto the 55-color Baker et al. (ICCV 2007) wheel,
  desaturate toward white for small motions, zero out unknown flow.
- :func:`flow_to_color` — the VCN-derived second variant (reference:
  core/utils/flow_viz.py:145-275, the ``makeColorwheel``/
  ``computeColor`` pair used by demo/submissions), ported per-channel
  like the original. On shared inputs the two agree exactly
  (tests/test_io_viz.py cross-checks them pixel for pixel) — the
  reference shipped two implementations of the SAME map, so one test
  pins that our port preserved that equivalence instead of forking it.

Metric-helper parity note (VERDICT r5 missing #2-#3): the reference's
``th_rmse``/``th_epe`` error helpers (thresholded RMSE / endpoint-error
over a validity mask, core/utils side of the VCN import) have no
standalone port — their equivalents are the device-resident accumulators
in ``inference/metrics.py``: ``kind="epe"`` is the (masked) mean
endpoint error th_epe computes, ``kind="px"`` adds the 1/3/5px
thresholded fractions, and a thresholded RMSE is ``sqrt`` of the same
masked sum-of-squares fold (see that module's docstring).
"""

from __future__ import annotations

import numpy as np

UNKNOWN_FLOW_THRESH = 1e7


def make_colorwheel() -> np.ndarray:
    """The 55-entry Middlebury color wheel, (55, 3) float in [0, 255]."""
    segments = [
        (15, 0, 1, False),  # RY: red fixed, green ramps up
        (6, 1, 0, True),  # YG: green fixed, red ramps down
        (4, 1, 2, False),  # GC
        (11, 2, 1, True),  # CB
        (13, 2, 0, False),  # BM
        (6, 0, 2, True),  # MR
    ]
    wheel = np.zeros((sum(s[0] for s in segments), 3))
    col = 0
    for n, fixed, ramp, down in segments:
        wheel[col : col + n, fixed] = 255
        r = np.floor(255 * np.arange(n) / n)
        wheel[col : col + n, ramp] = 255 - r if down else r
        col += n
    return wheel


def flow_to_image(
    flow: np.ndarray,
    convert_to_bgr: bool = False,
    rad_max: float | None = None,
) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) uint8 Middlebury color image.

    ``rad_max=None`` normalizes by the image's own max radius (reference
    behavior); pass a value to fix the scale across frames.
    """
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    u = flow[:, :, 0].astype(np.float64)
    v = flow[:, :, 1].astype(np.float64)

    unknown = (np.abs(u) > UNKNOWN_FLOW_THRESH) | (
        np.abs(v) > UNKNOWN_FLOW_THRESH
    )
    u = np.where(unknown, 0.0, u)
    v = np.where(unknown, 0.0, v)

    rad = np.sqrt(u**2 + v**2)
    if rad_max is None:
        rad_max = float(rad.max()) if rad.size else 0.0
    scale = rad_max + np.finfo(np.float64).eps
    u, v, rad = u / scale, v / scale, rad / scale

    wheel = make_colorwheel() / 255.0  # (ncols, 3)
    ncols = wheel.shape[0]

    angle = np.arctan2(-v, -u) / np.pi  # [-1, 1]
    fk = (angle + 1) / 2 * (ncols - 1)  # [0, ncols-1]
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = (fk - k0)[..., None]

    col = (1 - f) * wheel[k0] + f * wheel[k1]  # (H, W, 3)

    small = (rad <= 1)[..., None]
    col = np.where(small, 1 - rad[..., None] * (1 - col), col * 0.75)
    img = np.floor(255.0 * col * ~unknown[..., None]).astype(np.uint8)
    if convert_to_bgr:
        img = img[:, :, ::-1]
    return img


def _make_colorwheel_vcn() -> np.ndarray:
    """The VCN variant's wheel (reference: core/utils/flow_viz.py:
    ``makeColorwheel``): same 55 RY/YG/GC/CB/BM/MR segments, built
    channel-by-channel the way the original does. Kept as an
    independent construction so the cross-check against
    :func:`make_colorwheel` is a real one."""
    RY, YG, GC, CB, BM, MR = 15, 6, 4, 11, 13, 6
    ncols = RY + YG + GC + CB + BM + MR
    wheel = np.zeros((ncols, 3))
    col = 0
    wheel[:RY, 0] = 255
    wheel[:RY, 1] = np.floor(255 * np.arange(RY) / RY)
    col += RY
    wheel[col:col + YG, 0] = 255 - np.floor(255 * np.arange(YG) / YG)
    wheel[col:col + YG, 1] = 255
    col += YG
    wheel[col:col + GC, 1] = 255
    wheel[col:col + GC, 2] = np.floor(255 * np.arange(GC) / GC)
    col += GC
    wheel[col:col + CB, 1] = 255 - np.floor(255 * np.arange(CB) / CB)
    wheel[col:col + CB, 2] = 255
    col += CB
    wheel[col:col + BM, 2] = 255
    wheel[col:col + BM, 0] = np.floor(255 * np.arange(BM) / BM)
    col += BM
    wheel[col:col + MR, 2] = 255 - np.floor(255 * np.arange(MR) / MR)
    wheel[col:col + MR, 0] = 255
    return wheel


def flow_to_color(
    flow: np.ndarray,
    convert_to_bgr: bool = False,
    rad_max: float | None = None,
) -> np.ndarray:
    """(H, W, 2) flow -> (H, W, 3) uint8, the VCN-derived second wheel
    (reference: core/utils/flow_viz.py:145-275 ``computeColor``).

    Per-channel port of the original's loop; on shared inputs it must
    agree with :func:`flow_to_image` exactly (the two reference
    implementations encode the same map — the cross-check test pins
    that the port kept them equivalent). Same ``rad_max`` contract:
    ``None`` normalizes per frame, a value fixes the scale across
    frames.
    """
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    u = flow[:, :, 0].astype(np.float64)
    v = flow[:, :, 1].astype(np.float64)

    unknown = (np.abs(u) > UNKNOWN_FLOW_THRESH) | (
        np.abs(v) > UNKNOWN_FLOW_THRESH
    )
    u = np.where(unknown, 0.0, u)
    v = np.where(unknown, 0.0, v)

    rad = np.sqrt(u**2 + v**2)
    if rad_max is None:
        rad_max = float(rad.max()) if rad.size else 0.0
    scale = rad_max + np.finfo(np.float64).eps
    u, v, rad = u / scale, v / scale, rad / scale

    wheel = _make_colorwheel_vcn()
    ncols = wheel.shape[0]
    a = np.arctan2(-v, -u) / np.pi
    fk = (a + 1) / 2 * (ncols - 1)
    k0 = np.floor(fk).astype(np.int32)
    k1 = k0 + 1
    k1[k1 == ncols] = 0
    f = fk - k0

    img = np.zeros((*u.shape, 3), np.uint8)
    small = rad <= 1
    for ch in range(3):
        col0 = wheel[k0, ch] / 255.0
        col1 = wheel[k1, ch] / 255.0
        col = (1 - f) * col0 + f * col1
        col = np.where(small, 1 - rad * (1 - col), col * 0.75)
        img[:, :, ch] = np.floor(255.0 * col * ~unknown).astype(np.uint8)
    if convert_to_bgr:
        img = img[:, :, ::-1]
    return img
