from raft_ncup_tpu.viz.flow_viz import (
    flow_to_color,
    flow_to_image,
    make_colorwheel,
)

__all__ = ["flow_to_color", "flow_to_image", "make_colorwheel"]
