"""SLO-driven elastic fleet sizing: the control loop that closes
ROADMAP item 3 (docs/FLEET.md "Autoscaler").

The anytime-iteration idea at fleet granularity: the server already
degrades per-request quality under load (RAFT's fixed-point iteration
structure lets it answer with fewer iterations, arXiv:2003.12039);
the fleet-level counterpart is to ADD CAPACITY instead of shedding
quality — and to give capacity back when the burn clears. Everything
the loop touches is an existing contract, composed rather than
re-implemented:

- **inputs** — SLO burn-rate paging verdicts from the replicas' healthz
  ``slo`` blocks (PR 12's multi-window burn engine, read with ``.get``
  per the wire schema-evolution contract), router queue depth (total
  dispatched-but-unanswered) and per-replica occupancy
  (``FleetRouter.inflight_of``), and the router's shed counter (a shed
  IS the demand the fleet failed to admit);
- **scale-up** — ``ReplicaSupervisor.add_replica``: the new replica
  warms its full executable set during startup and is only promoted to
  UP once its healthz advertises the warmed shapes, so the router's
  shape-aware preference never sees cold capacity (pre-warm is the
  READY gate, not a second mechanism);
- **scale-down** — the PR 13 drain contract (SIGTERM → DRAINING in
  healthz before the flush → exit 75): ZERO in-flight loss, asserted
  by the chaos tier, not by this module;
- **anti-flap** — a decision needs the SAME signal for
  ``scale_hysteresis_ticks`` consecutive ticks AND
  ``scale_cooldown_s`` since the last topology change; an oscillating
  load step whose period beats either bound holds the fleet still
  (pinned in tests/test_autoscaler.py);
- **respawn-storm bound** — per-replica crash loops are already
  bounded by the supervisor's restart budget + circuit breaker; the
  autoscaler adds its own: ``scale_fail_budget`` consecutive FAILED
  scale-ups (the spawned replica breaks or dies before READY) open
  the autoscaler breaker and no further scale-ups fire;
- **backpressure honesty** — while capacity is warming (or the fleet
  is saturated at a bound), the loop publishes its time-to-READY
  estimate to ``FleetRouter.set_scale_eta``: a shed during a cold
  scale-up answers "retry when the new replica can admit", never the
  250ms re-shed treadmill.

Host-only stdlib (JGL010 covers ``fleet/``): the loop reads healthz
dicts and counters — it must never be able to touch a device array.
Deterministic by construction: the clock is injectable and ``tick()``
is synchronous, so the fast tier asserts EXACT decision trajectories
under a fake clock; the background thread is an optional convenience
for real fleets.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

from raft_ncup_tpu.fleet.replica import DRAINING, SPAWNING, UP
from raft_ncup_tpu.fleet.topology import FleetConfig

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """One control loop per fleet: observe → decide → act, one
    decision per tick, every decision recorded.

    ``spawn_fn`` / ``drain_fn`` default to the supervisor's
    ``add_replica`` / (threaded) ``remove_replica``; tests inject
    synchronous recorders. ``clock`` defaults to ``time.monotonic``;
    tests inject a fake.
    """

    def __init__(
        self,
        cfg: FleetConfig,
        supervisor,
        router,
        *,
        telemetry=None,
        clock: Callable[[], float] = time.monotonic,
        spawn_fn: Optional[Callable[[int], None]] = None,
        drain_fn: Optional[Callable[[int], None]] = None,
    ):
        from raft_ncup_tpu.observability import get_telemetry

        self.cfg = cfg
        self.sup = supervisor
        self.router = router
        self._tel = telemetry if telemetry is not None else get_telemetry()
        self._clock = clock
        self._spawn_fn = spawn_fn or self._default_spawn
        self._drain_fn = drain_fn or self._default_drain
        self._lock = threading.RLock()
        # Anti-flap state.
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale_at: Optional[float] = None
        # In-flight topology changes (at most one of each; a loop that
        # stacks spawns is a respawn storm by construction).
        self._pending_up: Optional[tuple] = None  # (index, started_at)
        self._pending_down: Optional[int] = None
        # Time-to-READY estimate: EWMA over observed spawn→READY
        # durations, seeded with the config prior.
        self._ttr_s = float(cfg.scale_eta_prior_s)
        self._ttr_observed = 0
        self._last_shed = int(router.stats.get("shed", 0))
        self._fail_streak = 0
        self.breaker_open = False
        self.scale_ups = 0          # spawns initiated
        self.scale_ups_completed = 0
        self.scale_downs = 0        # drains initiated
        self.failed_scale_ups = 0
        self.decisions: deque = deque(maxlen=4096)
        self._loop_stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- actions

    def _default_spawn(self, i: int) -> None:
        self.sup.add_replica(i, wait_ready=False)

    def _default_drain(self, i: int) -> None:
        threading.Thread(
            target=self.sup.remove_replica, args=(i,),
            name=f"autoscaler-drain-{i}", daemon=True,
        ).start()

    # ------------------------------------------------------------- signals

    def time_to_ready_s(self) -> float:
        """The current spawn→READY estimate (the prior until a real
        scale-up has been observed) — what shed hints are floored at
        while capacity warms."""
        with self._lock:
            return self._ttr_s

    def signals(self) -> dict:
        """One coherent observation of the fleet: live/warming sets,
        occupancy, queue depth, paging, shed delta since the last
        tick. Pure reads — calling it never scales anything."""
        handles = list(self.sup.replicas)
        ups = [
            h for h in handles
            if h.state == UP and not h.circuit_open
        ]
        spawning = [h for h in handles if h.state == SPAWNING]
        draining = [h for h in handles if h.state == DRAINING]
        cap = len(ups) * self.cfg.max_inflight_per_replica
        inflight = sum(self.router.inflight_of(h.index) for h in ups)
        # Saturated by definition when nothing is admittable: an empty
        # fleet must read as pressure, not as 0% busy.
        occupancy = min(1.0, inflight / cap) if cap else 1.0
        paging = []
        burn_fast = 0.0
        for h in ups:
            slo = (h.last_healthz or {}).get("slo") or {}
            paging.extend(slo.get("paging") or [])
            for v in (slo.get("verdicts") or {}).values():
                if isinstance(v, dict):
                    burn_fast = max(
                        burn_fast, float(v.get("burn_fast") or 0.0)
                    )
        shed_total = int(self.router.stats.get("shed", 0))
        with self._lock:  # RLock: tick() calls this holding it already
            last_shed = self._last_shed
        return {
            "n_up": len(ups),
            "n_spawning": len(spawning),
            "n_draining": len(draining),
            "up_indices": sorted(h.index for h in ups),
            "occupancy": round(occupancy, 4),
            "queue_depth": inflight,
            "paging": sorted(set(paging)),
            "burn_fast": round(burn_fast, 3),
            "shed_total": shed_total,
            "shed_delta": shed_total - last_shed,
        }

    # ------------------------------------------------------------ the loop

    def tick(self) -> dict:
        """One observe→decide→act pass. Returns (and records) the
        decision: ``{"decision": "hold"|"up"|"down", "reason": ...,
        **signals}``. Synchronous and deterministic under an injected
        clock — the unit the fast tier asserts trajectories on."""
        with self._lock:
            now = self._clock()
            self._settle_pending(now)
            s = self.signals()
            self._last_shed = s["shed_total"]
            pressure = bool(
                s["paging"]
                or s["occupancy"] >= self.cfg.scale_up_occupancy
                or s["shed_delta"] > 0
            )
            calm = (
                not s["paging"]
                and s["shed_delta"] == 0
                and s["occupancy"] <= self.cfg.scale_down_occupancy
            )
            if pressure:
                self._up_streak += 1
                self._down_streak = 0
            elif calm:
                self._down_streak += 1
                self._up_streak = 0
            else:
                # The band between the thresholds: a healthy steady
                # state, not evidence for either direction.
                self._up_streak = 0
                self._down_streak = 0
            cooldown_ok = (
                self._last_scale_at is None
                or now - self._last_scale_at >= self.cfg.scale_cooldown_s
            )
            busy = (
                self._pending_up is not None
                or self._pending_down is not None
            )
            n_live = s["n_up"] + s["n_spawning"]
            decision, reason = "hold", "steady"
            if pressure and not busy:
                decision, reason = self._try_up(
                    now, s, cooldown_ok, n_live
                )
            elif calm and not busy:
                decision, reason = self._try_down(
                    now, s, cooldown_ok
                )
            elif busy:
                reason = (
                    f"topology change in flight (up={self._pending_up}, "
                    f"down={self._pending_down})"
                )
            # Backpressure honesty: publish the ETA whenever sheds
            # would otherwise lie (capacity warming, or saturated with
            # nothing the loop can add yet); clear it when calm.
            eta_active = self._pending_up is not None or pressure
            self.router.set_scale_eta(
                self._ttr_s if eta_active else None
            )
            record = {
                "t": round(now, 4),
                "decision": decision,
                "reason": reason,
                "eta_published": eta_active,
                "breaker_open": self.breaker_open,
                **s,
            }
            self.decisions.append(record)
        self._tel.event("fleet_autoscale_tick", **{
            k: v for k, v in record.items() if k != "up_indices"
        })
        return record

    def _settle_pending(self, now: float) -> None:
        if self._pending_up is not None:
            i, started = self._pending_up
            handle = None
            for h in self.sup.replicas:
                if h.index == i:
                    handle = h
                    break
            if handle is not None and handle.state == UP:
                observed = max(1e-6, now - started)
                # EWMA, half-weight on the newest observation: the
                # estimate tracks compile-time drift without a single
                # outlier owning it.
                self._ttr_s = (
                    observed if self._ttr_observed == 0
                    else 0.5 * self._ttr_s + 0.5 * observed
                )
                self._ttr_observed += 1
                self._pending_up = None
                self._fail_streak = 0
                self.scale_ups_completed += 1
                self._tel.event(
                    "fleet_scale_up_ready", replica=i,
                    time_to_ready_s=round(observed, 3),
                )
            elif handle is None or handle.state not in (SPAWNING, UP):
                # Broke, died, or was retired before ever reaching
                # READY: a failed scale-up — counted, and budgeted.
                self._pending_up = None
                self.failed_scale_ups += 1
                self._fail_streak += 1
                self._tel.event(
                    "fleet_scale_up_failed", replica=i,
                    state=None if handle is None else handle.state,
                    consecutive=self._fail_streak,
                )
                if self._fail_streak >= self.cfg.scale_fail_budget:
                    self.breaker_open = True
                    self._tel.event(
                        "fleet_autoscaler_breaker_open",
                        consecutive=self._fail_streak,
                    )
        if self._pending_down is not None:
            live = {h.index for h in self.sup.replicas}
            if self._pending_down not in live:
                self.scale_downs += 1
                self._tel.event(
                    "fleet_scale_down_done",
                    replica=self._pending_down,
                )
                self._pending_down = None

    def _try_up(self, now, s, cooldown_ok, n_live):
        if self.breaker_open:
            return "hold", (
                f"breaker open after {self._fail_streak} failed "
                "scale-up(s) — respawn storm bounded"
            )
        if n_live >= self.cfg.scale_max:
            return "hold", f"at max_replicas ({self.cfg.scale_max})"
        if self._up_streak < self.cfg.scale_hysteresis_ticks:
            return "hold", (
                f"hysteresis {self._up_streak}/"
                f"{self.cfg.scale_hysteresis_ticks}"
            )
        if not cooldown_ok:
            return "hold", "cooldown"
        taken = {h.index for h in self.sup.replicas}
        slot = next(
            (i for i in range(self.cfg.scale_max) if i not in taken),
            None,
        )
        if slot is None:
            return "hold", "no free replica slot"
        self._spawn_fn(slot)
        self._pending_up = (slot, now)
        self._last_scale_at = now
        self._up_streak = 0
        self.scale_ups += 1
        self._tel.inc("fleet_scale_ups_total")
        return "up", (
            f"spawned slot {slot} (occupancy {s['occupancy']}, "
            f"paging {s['paging']}, shed_delta {s['shed_delta']})"
        )

    def _try_down(self, now, s, cooldown_ok):
        if s["n_up"] <= self.cfg.scale_min:
            return "hold", f"at min_replicas ({self.cfg.scale_min})"
        if self._down_streak < self.cfg.scale_hysteresis_ticks:
            return "hold", (
                f"hysteresis {self._down_streak}/"
                f"{self.cfg.scale_hysteresis_ticks}"
            )
        if not cooldown_ok:
            return "hold", "cooldown"
        # Least-loaded victim; ties retire the NEWEST slot so the
        # stable low-index replicas keep their warm streams sticky.
        victim = max(
            s["up_indices"],
            key=lambda i: (-self.router.inflight_of(i), i),
        )
        self._drain_fn(victim)
        self._pending_down = victim
        self._last_scale_at = now
        self._down_streak = 0
        self._tel.inc("fleet_scale_downs_total")
        return "down", (
            f"draining slot {victim} (occupancy {s['occupancy']})"
        )

    # --------------------------------------------------- background loop

    def start(self, interval_s: Optional[float] = None) -> "FleetAutoscaler":
        """Run :meth:`tick` on a daemon thread every
        ``cfg.scale_tick_s`` (real fleets; tests call tick())."""
        interval = self.cfg.scale_tick_s if interval_s is None else interval_s
        self._loop_stop.clear()

        def _loop() -> None:
            while not self._loop_stop.wait(interval):
                try:
                    self.tick()
                except Exception as e:
                    # A control-loop error must be visible, never fatal
                    # to the fleet it sizes (JGL007: logged, not
                    # swallowed).
                    self._tel.event(
                        "fleet_autoscaler_tick_error", error=repr(e)
                    )

        self._loop_thread = threading.Thread(
            target=_loop, name="fleet-autoscaler", daemon=True
        )
        self._loop_thread.start()
        return self

    def stop(self) -> None:
        self._loop_stop.set()
        if self._loop_thread is not None and self._loop_thread.is_alive():
            self._loop_thread.join(timeout=10.0)
        # Never leave a stale ETA flooring shed hints after the loop
        # that maintained it is gone.
        self.router.set_scale_eta(None)

    def report(self) -> dict:
        """Elasticity accounting for bench/tests: every decision is in
        ``decisions``; this is the summary the elasticity_* row reads."""
        with self._lock:
            return {
                "scale_ups": self.scale_ups,
                "scale_ups_completed": self.scale_ups_completed,
                "scale_downs": self.scale_downs,
                "failed_scale_ups": self.failed_scale_ups,
                "breaker_open": self.breaker_open,
                "time_to_ready_s": round(self._ttr_s, 3),
                "time_to_ready_observed": self._ttr_observed,
                "ticks": len(self.decisions),
            }

    def __enter__(self) -> "FleetAutoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
