"""Replica process lifecycle: spawn, healthz/liveness wait, drain, reap
— and the supervisor that keeps N of them serving (docs/FLEET.md).

:class:`ChildProcess` is the ONE process-lifecycle implementation in
the repo: the fleet supervisor runs replicas through it, and the
4-process distributed test rig (tests/test_multihost.py) spawns its
jax.distributed children through it — spawn semantics, liveness checks,
signal delivery, and reap-with-timeout behave identically in both
because they are the same code.

:class:`ReplicaSupervisor` owns the fleet's robustness contracts:

- **healthz staleness**: a replica's healthz file older than
  ``FleetConfig.stale_after_s`` means the replica is DEAD even if the
  process still exists — a SIGSTOPped or wedged process lingers but
  cannot serve, and a supervisor that trusts process existence over the
  heartbeat routes traffic into a black hole. Stale replicas are
  SIGKILLed (the lingering process must not wake up later and answer a
  request the router already failed over) and enter the death path.
- **drain orchestration**: SIGTERM ⇒ the replica's healthz must show
  ``draining: true`` (the DRAINING health state precedes the flush by
  construction — serve.py writes healthz immediately on the signal) ⇒
  the child must exit ``EXIT_PREEMPTED`` (75). Both observations are
  recorded; a replica that breaks the contract is counted, not ignored.
- **bounded counted restart-with-backoff**: an unexpected death
  schedules a respawn after ``restart_backoff_s * 2^k`` (capped),
  at most ``max_restarts`` times, every attempt counted.
- **circuit breaker**: ``circuit_break_after`` consecutive failures
  without an intervening READY opens the breaker — the replica gets no
  restart and no traffic. A crash-looping replica that kept being
  restarted and kept receiving requests would convert one bad process
  into fleet-wide tail latency.

Host-only stdlib (JGL010 covers ``fleet/``): the supervisor reads JSON
heartbeats and sends signals; it can never touch a device array.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from raft_ncup_tpu.fleet.topology import FleetConfig, ReplicaSpec

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Replica states (supervisor-side view; the replica's own health states
# live inside its healthz file).
SPAWNING = "spawning"   # process started, healthz not READY yet
UP = "up"               # fresh healthz, overall ready/degraded
DRAINING = "draining"   # SIGTERM sent, drain contract in progress
DEAD = "dead"           # unexpected death, restart pending
EXITED = "exited"       # clean exit (drain completed)
BROKEN = "broken"       # circuit open or restart budget exhausted


def read_healthz(path: str) -> Optional[dict]:
    """One healthz poll: the parsed dict, or None when the file is
    missing or unparsable (an atomically-replaced file is never torn,
    so unparsable means not-yet-written or foreign)."""
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def healthz_fresh(
    hz: Optional[dict], stale_after_s: float,
    now_unix: Optional[float] = None,
) -> bool:
    """The staleness contract: a healthz payload whose ``time_unix_s``
    is older than ``stale_after_s`` (default 2x the snapshot cadence —
    the schema's own ``stale_after_s`` field) describes a replica that
    must be presumed dead, even if its process lingers."""
    if hz is None:
        return False
    ts = hz.get("time_unix_s")
    if not isinstance(ts, (int, float)):
        return False
    now = time.time() if now_unix is None else now_unix
    return (now - ts) <= stale_after_s


class ChildProcess:
    """One spawned child: argv in, (returncode, stdout, stderr) out.

    Thin, deliberately boring wrapper over ``subprocess.Popen`` so every
    multi-process harness in the repo shares one spawn/liveness/signal/
    reap implementation. stdout/stderr are captured via pipes and
    harvested at :meth:`reap` (drainer threads keep the pipes from
    filling while the child lives).
    """

    def __init__(
        self,
        argv: List[str],
        *,
        name: str = "child",
        env: Optional[dict] = None,
        cwd: Optional[str] = None,
    ):
        self.argv = list(argv)
        self.name = name
        self.env = env
        self.cwd = cwd
        self.proc: Optional[subprocess.Popen] = None
        self._out_chunks: List[str] = []
        self._err_chunks: List[str] = []
        self._drainers: List[threading.Thread] = []

    # ------------------------------------------------------------ lifecycle

    def spawn(self) -> "ChildProcess":
        if self.proc is not None:
            raise RuntimeError(f"{self.name}: already spawned")
        self.proc = subprocess.Popen(
            self.argv,
            env=self.env,
            cwd=self.cwd,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for stream, chunks in (
            (self.proc.stdout, self._out_chunks),
            (self.proc.stderr, self._err_chunks),
        ):
            t = threading.Thread(
                target=self._drain_pipe, args=(stream, chunks),
                name=f"{self.name}-pipe", daemon=True,
            )
            t.start()
            self._drainers.append(t)
        return self

    @staticmethod
    def _drain_pipe(stream, chunks: List[str]) -> None:
        try:
            for line in stream:
                chunks.append(line)
        except ValueError:
            # Pipe closed under us at reap — everything readable was read.
            pass

    @property
    def pid(self) -> Optional[int]:
        return None if self.proc is None else self.proc.pid

    @property
    def running(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def returncode(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    # -------------------------------------------------------------- signals

    def _signal(self, sig: int) -> bool:
        if self.proc is None or self.proc.poll() is not None:
            return False
        try:
            self.proc.send_signal(sig)
            return True
        except (ProcessLookupError, OSError):
            return False

    def terminate(self) -> bool:
        """SIGTERM — the graceful-drain contract signal."""
        return self._signal(signal.SIGTERM)

    def kill(self) -> bool:
        """SIGKILL — no drain, no flush, no goodbye (chaos + staleness
        escalation)."""
        return self._signal(signal.SIGKILL)

    def suspend(self) -> bool:
        """SIGSTOP — the process lingers but cannot serve (the exact
        scenario the healthz staleness contract exists for)."""
        return self._signal(signal.SIGSTOP)

    def resume(self) -> bool:
        return self._signal(signal.SIGCONT)

    # ----------------------------------------------------------------- reap

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None

    def reap(self, timeout: Optional[float] = None):
        """Wait (bounded), escalating to SIGKILL on timeout; returns
        ``(returncode, stdout, stderr)``. Idempotent."""
        if self.proc is None:
            return None, "", ""
        rc = self.wait(timeout)
        if rc is None:
            self.kill()
            rc = self.proc.wait()
        for t in self._drainers:
            t.join(timeout=5.0)
        for stream in (self.proc.stdout, self.proc.stderr):
            if stream is not None:
                stream.close()
        return rc, "".join(self._out_chunks), "".join(self._err_chunks)

    def stdout_so_far(self) -> str:
        return "".join(self._out_chunks)

    def stderr_so_far(self) -> str:
        return "".join(self._err_chunks)


def last_json_line(text: str) -> Optional[dict]:
    """The last parseable JSON object line of a child's stdout — the
    replica's final drain report (serve.py prints exactly one)."""
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


class ReplicaHandle:
    """Supervisor-side view of one replica: its spec, its current child
    process, and the counted robustness state."""

    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        self.child: Optional[ChildProcess] = None
        self.state = SPAWNING
        self.last_healthz: Optional[dict] = None
        self.spawned_at: Optional[float] = None  # monotonic, set by spawn
        self.restarts = 0
        self.deaths = 0
        self.stale_deaths = 0
        self.consecutive_failures = 0
        self.circuit_open = False
        self.restart_at: Optional[float] = None  # monotonic deadline
        self.drain_observed_draining = False
        self.drain_exit_75 = False
        self.contract_violations: List[str] = []
        self.final_report: Optional[dict] = None

    @property
    def index(self) -> int:
        return self.spec.index

    def admittable(self) -> bool:
        """May the router send NEW work here? UP only (a DRAINING
        replica finishes its in-flight work but gets nothing new; a
        DEAD/BROKEN one gets nothing at all). DEGRADED is a serving
        state and rides inside UP — the healthz 'overall' field says
        which."""
        return self.state == UP and not self.circuit_open

    def snapshot(self) -> dict:
        return {
            "index": self.index,
            "state": self.state,
            "pid": None if self.child is None else self.child.pid,
            "restarts": self.restarts,
            "deaths": self.deaths,
            "stale_deaths": self.stale_deaths,
            "consecutive_failures": self.consecutive_failures,
            "circuit_open": self.circuit_open,
            "drain_observed_draining": self.drain_observed_draining,
            "drain_exit_75": self.drain_exit_75,
            "contract_violations": list(self.contract_violations),
        }


class ReplicaSupervisor:
    """Keep ``FleetConfig.n_replicas`` serve.py replica processes
    serving; expose their liveness to the router; enforce the drain,
    staleness, restart, and circuit-breaker contracts.

    ``on_death(index, reason)`` is the router's hook: called exactly
    once per detected death (process exit, staleness escalation) so
    pending requests can fail over before their deadlines expire.
    """

    def __init__(
        self,
        cfg: FleetConfig,
        *,
        argv_prefix: Optional[List[str]] = None,
        env: Optional[dict] = None,
        on_death: Optional[Callable[[int, str], None]] = None,
        telemetry=None,
        indices: Optional[List[int]] = None,
    ):
        from raft_ncup_tpu.observability import get_telemetry

        self.cfg = cfg
        self._argv_prefix = argv_prefix or [
            sys.executable, os.path.join(_REPO_ROOT, "serve.py"),
        ]
        self._env = env
        self._on_death = on_death
        self._tel = telemetry if telemetry is not None else get_telemetry()
        # ``indices``: the replica slots THIS supervisor owns — a host
        # agent supervises only its host's placement, and the
        # autoscaler grows/shrinks the set via add_replica /
        # remove_replica. Default: the initial n_replicas.
        self.replicas: List[ReplicaHandle] = [
            ReplicaHandle(cfg.replica(i))
            for i in (
                range(cfg.n_replicas) if indices is None else indices
            )
        ]
        # Handles of replicas retired by remove_replica (scale-down):
        # their counters/violations stay in report() — elasticity must
        # not launder a replica's history by retiring it.
        self.retired: List[ReplicaHandle] = []
        self._lock = threading.RLock()
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

    def handle(self, i: int) -> ReplicaHandle:
        """The live handle for GLOBAL replica index ``i`` (handles are
        keyed by slot index, not list position — a host agent's or an
        elastically-scaled supervisor's list is sparse)."""
        with self._lock:
            for h in self.replicas:
                if h.index == i:
                    return h
        raise KeyError(f"no live replica handle for index {i}")

    # ------------------------------------------------------------ spawning

    def _spawn(self, handle: ReplicaHandle) -> None:
        spec = handle.spec
        # A dead replica's stale socket/healthz must not satisfy the
        # next incarnation's liveness checks.
        for path in (spec.socket_path, spec.healthz_path):
            try:
                os.remove(path)
            except OSError:
                pass
        argv = self._argv_prefix + self.cfg.replica_argv(spec.index)
        handle.child = ChildProcess(
            argv, name=f"replica-{spec.index}", env=self._env,
            cwd=_REPO_ROOT,
        ).spawn()
        handle.state = SPAWNING
        handle.restart_at = None
        handle.spawned_at = time.monotonic()
        self._tel.event(
            "fleet_replica_spawned", replica=spec.index,
            pid=handle.child.pid,
        )

    def start(self, wait_ready: bool = True) -> "ReplicaSupervisor":
        os.makedirs(self.cfg.base_dir, exist_ok=True)
        with self._lock:
            for handle in self.replicas:
                self._spawn(handle)
        if wait_ready:
            self.wait_ready()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-supervisor", daemon=True
        )
        self._poll_thread.start()
        return self

    def wait_ready(self, timeout: Optional[float] = None) -> None:
        """Block until every replica's healthz reads overall=ready (or
        a replica dies first, which raises with its stderr tail)."""
        deadline = time.monotonic() + (
            self.cfg.spawn_timeout_s if timeout is None else timeout
        )
        with self._lock:
            pending = {h.index for h in self.replicas}
        while pending:
            for i in sorted(pending):
                handle = self.handle(i)
                child = handle.child
                if child is not None and not child.running:
                    rc, out, err = child.reap(timeout=5.0)
                    # Kill + reap the SIBLINGS before raising: the
                    # documented `ReplicaSupervisor(cfg).start()`
                    # one-liner must not leak N-1 warmed serve.py
                    # orphans when one replica dies during warmup.
                    self.stop(drain=False)
                    raise RuntimeError(
                        f"replica {i} died during warmup (rc={rc}):\n"
                        f"{err[-2000:]}"
                    )
                hz = read_healthz(handle.spec.healthz_path)
                if hz is not None and hz.get("overall") == "ready":
                    handle.last_healthz = hz
                    handle.state = UP
                    handle.consecutive_failures = 0
                    pending.discard(i)
            if not pending:
                return
            if time.monotonic() > deadline:
                self.stop(drain=False)  # no orphans on timeout either
                raise TimeoutError(
                    f"replicas {sorted(pending)} not ready within "
                    f"{self.cfg.spawn_timeout_s}s"
                )
            time.sleep(self.cfg.poll_interval_s)

    # ------------------------------------------------------------- polling

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.cfg.poll_interval_s):
            try:
                self.poll()
            except Exception as e:
                # The supervisor reports on replicas; a poll error must
                # be visible, never fatal to the fleet.
                self._tel.event("fleet_supervisor_poll_error", error=repr(e))
                print(f"fleet supervisor poll error: {e!r}", file=sys.stderr)

    def poll(self) -> None:
        """One supervision pass: detect exits and stale heartbeats,
        run the restart schedule. Called by the background thread and
        directly by deterministic tests."""
        now = time.monotonic()
        with self._lock:
            for handle in self.replicas:
                self._poll_one(handle, now)

    def _poll_one(self, handle: ReplicaHandle, now: float) -> None:
        if handle.state in (EXITED, BROKEN):
            return
        if handle.state == DEAD:
            if (
                handle.restart_at is not None
                and now >= handle.restart_at
            ):
                handle.restarts += 1
                self._tel.inc("fleet_replica_restarts_total")
                self._tel.event(
                    "fleet_replica_restart", replica=handle.index,
                    attempt=handle.restarts,
                )
                self._spawn(handle)
            return
        child = handle.child
        if child is None:
            return
        if not child.running:
            if handle.state == DRAINING:
                # drain() owns the contract bookkeeping.
                return
            rc = child.returncode
            self._note_death(handle, f"process exited rc={rc}")
            return
        hz = read_healthz(handle.spec.healthz_path)
        if hz is not None:
            handle.last_healthz = hz
        if handle.state == SPAWNING:
            if hz is not None and hz.get("overall") == "ready":
                handle.state = UP
                handle.consecutive_failures = 0
                self._tel.event(
                    "fleet_replica_ready", replica=handle.index
                )
            elif (
                handle.spawned_at is not None
                and now - handle.spawned_at > self.cfg.spawn_timeout_s
            ):
                # A respawned replica that wedges DURING warmup (never
                # reaches ready) must not park in SPAWNING forever: the
                # spawn-timeout bound applies to every incarnation, not
                # just the initial wait_ready().
                child.kill()
                child.wait(timeout=10.0)
                self._note_death(handle, "warmup timeout")
            return
        if handle.state == UP and not healthz_fresh(
            hz, self.cfg.stale_after_s
        ):
            # The staleness contract: the process lingers, the replica
            # is dead. SIGKILL so it cannot answer after the failover.
            handle.stale_deaths += 1
            self._tel.inc("fleet_replica_stale_total")
            child.kill()
            child.wait(timeout=10.0)
            self._note_death(handle, "healthz stale")

    def _note_death(self, handle: ReplicaHandle, reason: str) -> None:
        handle.deaths += 1
        handle.consecutive_failures += 1
        self._tel.inc("fleet_replica_deaths_total")
        self._tel.event(
            "fleet_replica_death", replica=handle.index, reason=reason,
            consecutive=handle.consecutive_failures,
        )
        print(
            f"fleet: replica {handle.index} death #{handle.deaths} "
            f"({reason}); consecutive={handle.consecutive_failures}",
            file=sys.stderr,
        )
        if handle.consecutive_failures >= self.cfg.circuit_break_after:
            handle.circuit_open = True
            handle.state = BROKEN
            self._tel.inc("fleet_circuit_open_total")
            self._tel.event(
                "fleet_circuit_open", replica=handle.index,
                consecutive=handle.consecutive_failures,
            )
        elif handle.restarts >= self.cfg.max_restarts:
            handle.state = BROKEN
            self._tel.event(
                "fleet_restart_budget_exhausted", replica=handle.index,
                restarts=handle.restarts,
            )
        else:
            backoff = min(
                self.cfg.restart_backoff_max_s,
                self.cfg.restart_backoff_s
                * (2 ** max(0, handle.consecutive_failures - 1)),
            )
            handle.state = DEAD
            handle.restart_at = time.monotonic() + backoff
        if self._on_death is not None:
            self._on_death(handle.index, reason)

    # -------------------------------------------------- elastic membership

    def add_replica(
        self, i: int, wait_ready: bool = False,
        timeout: Optional[float] = None,
    ) -> ReplicaHandle:
        """Grow the supervised set by slot ``i`` (autoscaler scale-up /
        a host agent's spawn command). The new replica starts SPAWNING
        and is promoted to UP by the normal poll path once its healthz
        reads ready — the pre-warm gate: the router's shape-aware
        preference only ever sees it AFTER its warmed executable set is
        advertised. ``wait_ready=True`` blocks (autoscalers don't —
        they watch the handle across ticks)."""
        with self._lock:
            for h in self.replicas:
                if h.index == i:
                    raise ValueError(
                        f"replica slot {i} already supervised "
                        f"(state={h.state})"
                    )
            handle = ReplicaHandle(self.cfg.replica(i))
            self.replicas.append(handle)
            self._spawn(handle)
        self._tel.event("fleet_scale_up_spawn", replica=i)
        if wait_ready:
            deadline = time.monotonic() + (
                self.cfg.spawn_timeout_s if timeout is None else timeout
            )
            while handle.state == SPAWNING:
                self._poll_one(handle, time.monotonic())
                if handle.state != SPAWNING:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"scale-up replica {i} not ready within "
                        f"{self.cfg.spawn_timeout_s}s"
                    )
                time.sleep(self.cfg.poll_interval_s)
        return handle

    def remove_replica(self, i: int, drain: bool = True) -> dict:
        """Shrink the supervised set by slot ``i`` (autoscaler
        scale-down): graceful drain (SIGTERM → DRAINING → exit 75,
        ZERO in-flight loss — the existing contract, reused, not
        re-implemented), then retire the handle so the slot is free
        for a future scale-up. The retired handle's counters stay in
        :meth:`report`."""
        handle = self.handle(i)
        result = (
            self.drain(i) if drain
            else {"observed_draining": False, "returncode": None}
        )
        if not drain and handle.child is not None:
            handle.child.kill()
            handle.child.reap(timeout=10.0)
            with self._lock:
                handle.state = EXITED
        with self._lock:
            self.replicas = [h for h in self.replicas if h.index != i]
            self.retired.append(handle)
        self._tel.event(
            "fleet_scale_down_retired", replica=i,
            returncode=result.get("returncode"),
        )
        return result

    # ------------------------------------------------------ orchestration

    def drain(self, i: int, timeout: Optional[float] = None) -> dict:
        """Orchestrate one replica's graceful drain: SIGTERM ⇒ expect
        ``draining: true`` in healthz ⇒ expect exit 75. Returns the
        contract observations + the replica's final report; violations
        are recorded on the handle, never swallowed."""
        handle = self.handle(i)
        child = handle.child
        timeout = self.cfg.drain_timeout_s if timeout is None else timeout
        with self._lock:
            handle.state = DRAINING
        self._tel.event("fleet_replica_drain", replica=i)
        if child is None or not child.terminate():
            handle.contract_violations.append(
                "drain requested but process already gone"
            )
            return {"observed_draining": False, "returncode": None}
        deadline = time.monotonic() + timeout
        observed = False
        while time.monotonic() < deadline:
            hz = read_healthz(handle.spec.healthz_path)
            if hz is not None and hz.get("draining"):
                observed = True
                handle.last_healthz = hz
            if not child.running:
                break
            if observed:
                break
            time.sleep(self.cfg.poll_interval_s)
        rc, out, err = child.reap(timeout=max(0.0, deadline - time.monotonic()))
        # The final healthz (written at teardown) must still read
        # draining — DRAINING is terminal short of HALTED.
        hz = read_healthz(handle.spec.healthz_path)
        if hz is not None and hz.get("draining"):
            observed = True
            handle.last_healthz = hz
        handle.drain_observed_draining = observed
        handle.drain_exit_75 = rc == 75
        if not observed:
            handle.contract_violations.append(
                "DRAINING never observed in healthz during drain"
            )
        if rc != 75:
            handle.contract_violations.append(
                f"drain exit contract violated: rc={rc} (want 75)"
            )
        handle.final_report = last_json_line(out)
        with self._lock:
            handle.state = EXITED
        self._tel.event(
            "fleet_replica_drained", replica=i, returncode=rc,
            observed_draining=observed,
        )
        return {
            "observed_draining": observed,
            "returncode": rc,
            "report": handle.final_report,
        }

    def kill(self, i: int) -> None:
        """SIGKILL replica ``i`` (chaos killreplica): no drain, no
        flush. The death is detected and handled by the normal poll
        path — restart budget, circuit breaker, router failover all
        apply exactly as for an organic crash."""
        handle = self.handle(i)
        self._tel.event("fleet_replica_kill", replica=i)
        if handle.child is not None:
            handle.child.kill()
            handle.child.wait(timeout=10.0)
        self.poll()

    def stall(self, i: int) -> None:
        """SIGSTOP replica ``i`` (chaos stallreplica): the process
        lingers but stops heartbeating — detection rides the healthz
        staleness contract, not process liveness."""
        self._tel.event("fleet_replica_stall", replica=i)
        handle = self.handle(i)
        if handle.child is not None:
            handle.child.suspend()

    def resume(self, i: int) -> None:
        handle = self.handle(i)
        if handle.child is not None:
            handle.child.resume()

    # ------------------------------------------------------------ teardown

    def stop(self, drain: bool = True) -> Dict[int, dict]:
        """Tear the fleet down: drain every live replica (unless
        ``drain=False``), reap everything, return per-replica final
        reports."""
        self._poll_stop.set()
        if self._poll_thread is not None and self._poll_thread.is_alive():
            self._poll_thread.join(timeout=10.0)
        reports: Dict[int, dict] = {}
        with self._lock:
            handles = list(self.replicas)
        for handle in handles:
            if handle.state in (UP, SPAWNING) and drain:
                self.drain(handle.index)
            child = handle.child
            if child is not None and child.running:
                child.kill()
            if child is not None:
                rc, out, err = child.reap(timeout=10.0)
                if handle.final_report is None:
                    handle.final_report = last_json_line(out)
            reports[handle.index] = {
                **handle.snapshot(),
                "report": handle.final_report,
            }
        return reports

    def report(self) -> dict:
        """Supervisor accounting: per-replica snapshots + fleet totals
        (every restart/death/violation counted — the robustness story
        is only as honest as its bookkeeping)."""
        with self._lock:
            snaps = [h.snapshot() for h in self.replicas]
            retired = [h.snapshot() for h in self.retired]
        # Retired (scaled-down) replicas stay in the totals: elasticity
        # must not launder history by retiring a handle.
        everything = snaps + retired
        return {
            "replicas": snaps,
            "retired": retired,
            "deaths": sum(s["deaths"] for s in everything),
            "stale_deaths": sum(
                s["stale_deaths"] for s in everything
            ),
            "restarts": sum(s["restarts"] for s in everything),
            "circuits_open": sum(
                1 for s in everything if s["circuit_open"]
            ),
            "contract_violations": [
                v for s in everything for v in s["contract_violations"]
            ],
        }

    def __enter__(self) -> "ReplicaSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
