"""The fleet topology object: one frozen declarative config every other
piece reads (docs/FLEET.md).

The multi-GPU-abstraction pattern of PAPERS.md arXiv:2606.11390 applied
to process topology: the replica supervisor spawns FROM it, the router
routes FROM it, bench and chaos replay AGAINST it, and the tests assert
ON it — nothing else defines how many replicas exist, where their
sockets and healthz files live, what executable set each one warms, or
how much failover/restart budget the fleet has. A fleet whose shape is
scattered across flag defaults cannot be reasoned about when a replica
dies; one whose shape is a single validated object can.

Host-only stdlib (+ the repo's own jax-free config dataclasses): the
router process must be able to hold this object without importing jax
(JGL010's scope covers ``fleet/``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

from raft_ncup_tpu.config import ServeConfig, StreamConfig


def padded_shape(
    h: int, w: int, divisor: int = 8, bucket: int = 0
) -> Tuple[int, int]:
    """The padded (H, W) a native frame batches under — the pure-host
    mirror of ``ops/padding.InputPadder``'s pad arithmetic (height pads
    to a multiple of ``divisor`` = 8*spatial, width to a multiple of 8;
    a ``bucket`` rounds both up to multiples of itself). The router uses
    it to match a request's shape key against the replicas'
    healthz-advertised warmed executable sets without importing jax
    (tests/test_fleet.py pins it against the real InputPadder)."""
    h, w = int(h), int(w)
    if bucket:
        return h + (-h % bucket), w + (-w % bucket)
    return h + (-h % divisor), w + (-w % 8)


@dataclass(frozen=True)
class ReplicaSpec:
    """One replica's addresses, derived from :class:`FleetConfig` —
    where its wire endpoint listens (``address``: a UDS path or
    ``host:port``, the string ``fleet/wire.Transport.parse`` decides
    the family from), where it rewrites its healthz file, and where its
    flight recorder banks fault dumps."""

    index: int
    socket_path: str
    healthz_path: str
    flight_dir: str
    # Periodic registry snapshots (serve.py --telemetry_jsonl): the
    # per-replica export observability/aggregate.py merges into the
    # fleet-wide registry view.
    telemetry_jsonl: str = ""
    mesh: Optional[Tuple[int, int]] = None
    # The wire address (serve.py --replica_socket): equals socket_path
    # under the UDS transport, "host:port" under TCP. Empty only when a
    # spec is constructed by hand without one (tests) — cfg-derived
    # specs always fill it.
    address: str = ""
    # The named host this replica is placed on ("" = the single
    # implicit local host of a UDS fleet).
    host: str = ""


@dataclass(frozen=True)
class FleetConfig:
    """The whole fleet as one validated object.

    ``serve`` / ``stream`` are the per-replica subsystem configs (every
    replica runs a :class:`~raft_ncup_tpu.serving.server.FlowServer`;
    ``stream=None`` disables the per-replica StreamEngine for
    request-only fleets). ``meshes`` optionally pins a per-replica
    (data, spatial) mesh slice — the fleet analogue of the device mesh:
    which devices each replica owns is topology, not a replica-local
    flag.
    """

    # Directory holding every replica's socket, healthz file, and
    # flight dir (one tree per fleet run: the postmortem surface).
    base_dir: str
    n_replicas: int = 2
    # Native frame size the replicas warm at (the serve.py --size).
    size_hw: Tuple[int, int] = (96, 128)
    serve: ServeConfig = field(default_factory=ServeConfig)
    stream: Optional[StreamConfig] = None
    # Per-replica (data, spatial) mesh slices; None = unsharded
    # everywhere. Length must equal n_replicas when given.
    meshes: Optional[tuple] = None
    # Extra serve.py argv forwarded verbatim (model/platform flags).
    extra_args: Tuple[str, ...] = ()

    # --- healthz cadence + the staleness contract -----------------------
    # Replicas rewrite healthz on this cadence; a consumer MUST treat a
    # file whose time_unix_s is older than ``stale_after_s`` as a dead
    # replica even if the process still exists (a SIGSTOPped or wedged
    # replica lingers but cannot serve). Default: 2x the cadence — the
    # schema contract pinned in tests/test_observability.py.
    snapshot_interval_s: float = 0.25
    stale_after_factor: float = 2.0
    # Supervisor poll cadence + lifecycle timeouts.
    poll_interval_s: float = 0.1
    spawn_timeout_s: float = 120.0
    drain_timeout_s: float = 90.0

    # --- router admission + failover budgets ----------------------------
    # Outstanding (dispatched, unanswered) requests the router allows
    # per replica before it sheds AT THE ROUTER — backpressure must bite
    # before work crosses a process boundary.
    max_inflight_per_replica: int = 16
    # Shed hint when no replica has advertised anything better.
    default_retry_after_s: float = 0.25
    # How many times one request may be re-dispatched after a replica
    # death before it terminates honestly (shed/error, never silence).
    max_failovers: int = 1

    # --- supervisor restart budgets + circuit breaker -------------------
    max_restarts: int = 2  # per replica, counted
    restart_backoff_s: float = 0.25  # doubles per consecutive failure
    restart_backoff_max_s: float = 5.0
    # K consecutive failures (death/staleness without an intervening
    # healthy serve) opens the replica's circuit breaker: no restart,
    # no traffic — a crash-looping replica must stop eating requests.
    circuit_break_after: int = 3

    # --- transport + host placement -------------------------------------
    # "unix": every replica listens on a UDS path under base_dir (one
    # host, the PR 13 topology). "tcp": replica i listens on
    # tcp_host:(base_port + i) — the socket-family swap wire.py was
    # designed for; healthz/flight PATHS stay per-host-local and travel
    # to remote consumers via the HostSupervisor's wire republish.
    transport: str = "unix"
    tcp_host: str = "127.0.0.1"
    base_port: int = 0  # required > 0 under tcp; replica i = base + i
    # Named hosts and the per-replica placement over them. () = one
    # implicit host (every replica host ""). When given, placement maps
    # every replica slot 0..scale_max-1 to a host name (None =
    # round-robin over hosts); each host gets a HostSupervisor agent
    # that spawns/reaps its replicas and republishes their healthz over
    # the wire at host_control_address(host).
    hosts: Tuple[str, ...] = ()
    placement: Optional[Tuple[str, ...]] = None

    # --- elastic sizing (fleet/autoscaler.py) ---------------------------
    # n_replicas is the INITIAL size; the autoscaler moves the live
    # count inside [scale_min, scale_max] (None = pinned at n_replicas,
    # the PR 13 fixed-N behavior). Addresses/meshes are declared for
    # every slot up to scale_max — capacity is topology, not a runtime
    # discovery.
    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    # Decision cadence + anti-flap: a scale decision needs the same
    # signal for scale_hysteresis_ticks consecutive ticks AND
    # scale_cooldown_s since the last topology change — an oscillating
    # signal whose period beats either bound cannot thrash the fleet.
    scale_tick_s: float = 1.0
    scale_cooldown_s: float = 10.0
    scale_hysteresis_ticks: int = 3
    # Occupancy (fleet-wide inflight / open capacity) thresholds.
    scale_up_occupancy: float = 0.8
    scale_down_occupancy: float = 0.25
    # Consecutive FAILED scale-ups (spawned replica dies/breaks before
    # READY) that open the autoscaler's own breaker: no further
    # scale-ups — a respawn storm must be bounded at the control loop
    # too, not only per replica.
    scale_fail_budget: int = 2
    # Prior for the time-to-READY estimate (seconds) before any
    # scale-up has been observed — what shed retry_after_s hints are
    # floored at while capacity is still warming.
    scale_eta_prior_s: float = 20.0

    # --- TCP wire hardening ---------------------------------------------
    connect_timeout_s: float = 10.0
    # Router link read deadline (TCP only): silence past this triggers
    # the link reader's ping probe — half-open detection (peer vanished
    # without FIN) folded into the normal link-down failover flush.
    link_read_timeout_s: float = 30.0

    def __post_init__(self) -> None:
        if self.n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1: {self.n_replicas}")
        if not self.base_dir:
            raise ValueError("base_dir is required (sockets/healthz live there)")
        h, w = self.size_hw
        if int(h) < 16 or int(w) < 16:
            raise ValueError(f"size_hw too small for the pyramid: {self.size_hw}")
        if self.meshes is not None:
            if len(self.meshes) != self.scale_max:
                raise ValueError(
                    f"meshes has {len(self.meshes)} entries for "
                    f"{self.scale_max} replica slots — the topology "
                    "object must name every slot's mesh slice "
                    "explicitly (scale_max slots, not just the initial "
                    "n_replicas)"
                )
        if self.transport not in ("unix", "tcp"):
            raise ValueError(
                f"transport must be 'unix' or 'tcp': {self.transport!r}"
            )
        if self.transport == "tcp" and self.base_port <= 0:
            raise ValueError(
                "tcp transport needs base_port > 0 (replica i listens "
                "on tcp_host:(base_port + i); ports are topology)"
            )
        if self.placement is not None:
            if not self.hosts:
                raise ValueError("placement given without named hosts")
            if len(self.placement) != self.scale_max:
                raise ValueError(
                    f"placement has {len(self.placement)} entries for "
                    f"{self.scale_max} replica slots"
                )
            unknown = sorted(set(self.placement) - set(self.hosts))
            if unknown:
                raise ValueError(
                    f"placement names unknown hosts {unknown} "
                    f"(hosts={list(self.hosts)})"
                )
        if not (
            self.scale_min <= self.n_replicas <= self.scale_max
        ) or self.scale_min < 1:
            raise ValueError(
                f"replica bounds must satisfy 1 <= min_replicas "
                f"({self.scale_min}) <= n_replicas ({self.n_replicas}) "
                f"<= max_replicas ({self.scale_max})"
            )
        if not (
            0.0 < self.scale_down_occupancy < self.scale_up_occupancy
            <= 1.0
        ):
            raise ValueError(
                "occupancy thresholds must satisfy 0 < "
                f"scale_down_occupancy ({self.scale_down_occupancy}) < "
                f"scale_up_occupancy ({self.scale_up_occupancy}) <= 1 "
                "— an inverted band would flap by construction"
            )
        if self.scale_hysteresis_ticks < 1:
            raise ValueError(
                f"scale_hysteresis_ticks must be >= 1: "
                f"{self.scale_hysteresis_ticks}"
            )
        if self.scale_fail_budget < 1:
            raise ValueError(
                f"scale_fail_budget must be >= 1: {self.scale_fail_budget}"
            )
        for name in (
            "scale_tick_s", "scale_cooldown_s", "scale_eta_prior_s",
            "connect_timeout_s", "link_read_timeout_s",
        ):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0: {getattr(self, name)}")
        for name in (
            "snapshot_interval_s", "poll_interval_s", "spawn_timeout_s",
            "drain_timeout_s", "restart_backoff_s", "restart_backoff_max_s",
            "default_retry_after_s",
        ):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0: {getattr(self, name)}")
        if self.stale_after_factor < 1.0:
            raise ValueError(
                "stale_after_factor < 1 declares a fresh file stale: "
                f"{self.stale_after_factor}"
            )
        if self.max_inflight_per_replica < 1:
            raise ValueError(
                f"max_inflight_per_replica must be >= 1: "
                f"{self.max_inflight_per_replica}"
            )
        if self.max_failovers < 0 or self.max_restarts < 0:
            raise ValueError("failover/restart budgets must be >= 0")
        if self.circuit_break_after < 1:
            raise ValueError(
                f"circuit_break_after must be >= 1: {self.circuit_break_after}"
            )

    # ------------------------------------------------------------ derived

    @property
    def stale_after_s(self) -> float:
        """The staleness bound: healthz older than this ⇒ replica
        presumed dead even if the process lingers."""
        return self.snapshot_interval_s * self.stale_after_factor

    @property
    def scale_min(self) -> int:
        """Autoscaler floor (``min_replicas``, default: pinned at
        ``n_replicas``)."""
        return (
            self.n_replicas if self.min_replicas is None
            else self.min_replicas
        )

    @property
    def scale_max(self) -> int:
        """Autoscaler ceiling AND the number of declared replica slots
        (addresses, meshes, placement all cover ``scale_max``)."""
        return (
            self.n_replicas if self.max_replicas is None
            else self.max_replicas
        )

    def host_of(self, i: int) -> str:
        """The named host replica slot ``i`` is placed on ("" for the
        single implicit host of an unplaced fleet). Default placement
        is round-robin over ``hosts``."""
        if not self.hosts:
            return ""
        if self.placement is not None:
            return self.placement[i]
        return self.hosts[i % len(self.hosts)]

    def replicas_on(self, host: str) -> list:
        """Replica slot indices placed on ``host`` (all scale_max
        slots, live or not — slots are topology)."""
        return [
            i for i in range(self.scale_max) if self.host_of(i) == host
        ]

    def replica_address(self, i: int) -> str:
        """Replica ``i``'s wire address — the one string both ends
        parse the socket family from (``wire.Transport.parse``)."""
        if self.transport == "tcp":
            return f"{self.tcp_host}:{self.base_port + i}"
        return os.path.join(self.base_dir, f"replica_{i}.sock")

    def host_control_address(self, host: str) -> str:
        """Where ``host``'s HostSupervisor agent listens for control
        frames (healthz republish, spawn/drain commands). TCP ports
        for agents sit directly above the replica-slot ports."""
        if self.transport == "tcp":
            hosts = self.hosts or ("",)
            return (
                f"{self.tcp_host}:"
                f"{self.base_port + self.scale_max + hosts.index(host)}"
            )
        tag = host or "local"
        return os.path.join(self.base_dir, f"host_{tag}.sock")

    def replica(self, i: int) -> ReplicaSpec:
        if not 0 <= i < self.scale_max:
            raise ValueError(
                f"replica {i} out of range 0..{self.scale_max - 1}"
            )
        return ReplicaSpec(
            index=i,
            socket_path=os.path.join(self.base_dir, f"replica_{i}.sock"),
            healthz_path=os.path.join(
                self.base_dir, f"replica_{i}.healthz.json"
            ),
            flight_dir=os.path.join(self.base_dir, f"replica_{i}_flight"),
            telemetry_jsonl=os.path.join(
                self.base_dir, f"replica_{i}_telemetry.jsonl"
            ),
            mesh=None if self.meshes is None else self.meshes[i],
            address=self.replica_address(i),
            host=self.host_of(i),
        )

    def replicas(self) -> list:
        return [self.replica(i) for i in range(self.n_replicas)]

    def host_manifest(self, host: str) -> dict:
        """The JSON-able slice of this topology one HostSupervisor
        agent needs: every replica slot placed on ``host`` (its argv,
        addresses, and whether it starts immediately or is a scale-up
        slot), plus the supervision policy — so the agent process
        reconstructs ONLY what it supervises, never the whole fleet
        (``fleet/host_supervisor.ManifestConfig`` adapts it back for
        the unmodified ReplicaSupervisor)."""
        return {
            "host": host,
            "control": self.host_control_address(host),
            "base_dir": self.base_dir,
            "poll_interval_s": self.poll_interval_s,
            "spawn_timeout_s": self.spawn_timeout_s,
            "drain_timeout_s": self.drain_timeout_s,
            "snapshot_interval_s": self.snapshot_interval_s,
            "stale_after_s": self.stale_after_s,
            "max_restarts": self.max_restarts,
            "restart_backoff_s": self.restart_backoff_s,
            "restart_backoff_max_s": self.restart_backoff_max_s,
            "circuit_break_after": self.circuit_break_after,
            "replicas": [
                {
                    "index": i,
                    "start": i < self.n_replicas,
                    "address": self.replica_address(i),
                    "socket_path": self.replica(i).socket_path,
                    "healthz_path": self.replica(i).healthz_path,
                    "flight_dir": self.replica(i).flight_dir,
                    "argv": self.replica_argv(i),
                }
                for i in self.replicas_on(host)
            ],
        }

    def pad_divisor(self, i: int) -> int:
        """Replica ``i``'s pad divisor (8 * spatial under a mesh)."""
        spec = self.replica(i)
        return 8 * (spec.mesh[1] if spec.mesh else 1)

    def shape_key(self, h: int, w: int, i: int = 0) -> Tuple[int, int]:
        """The padded shape a native (h, w) request batches under on
        replica ``i`` — the key matched against the replica's
        healthz-advertised warmed executable set."""
        return padded_shape(
            h, w, divisor=self.pad_divisor(i), bucket=self.serve.pad_bucket
        )

    def replica_argv(self, i: int) -> list:
        """The serve.py argument vector that realizes replica ``i`` of
        THIS topology — the supervisor spawns exactly this; bench and
        the tests print it for reproduction. (The interpreter and the
        serve.py path are the caller's: they depend on the environment,
        not the topology.)"""
        spec = self.replica(i)
        s, st = self.serve, self.stream
        argv = [
            "--replica_socket", spec.address,
            "--replica_index", str(i),
            "--healthz_file", spec.healthz_path,
            "--flight_dir", spec.flight_dir,
            "--telemetry_jsonl", spec.telemetry_jsonl,
            "--telemetry_interval_s", str(self.snapshot_interval_s),
            "--size", str(self.size_hw[0]), str(self.size_hw[1]),
            "--queue_capacity", str(s.queue_capacity),
            "--serve_batch_sizes", ",".join(str(b) for b in s.batch_sizes),
            "--iter_levels", ",".join(str(x) for x in s.iter_levels),
            "--high_water", str(s.high_water),
            "--low_water", str(s.low_water),
            "--recover_patience", str(s.recover_patience),
            "--serve_pad_bucket", str(s.pad_bucket),
            "--serve_cache_size", str(s.cache_size),
        ]
        if s.precision is not None:
            argv += ["--serve_precision", s.precision]
        if st is None:
            argv += ["--replica_streams", "false"]
        else:
            argv += [
                "--replica_streams", "true",
                "--stream_capacity", str(st.capacity),
                "--stream_iters", str(st.iters),
                "--stream_batch_sizes", ",".join(
                    str(b) for b in st.batch_sizes
                ),
                "--stream_queue_capacity", str(st.queue_capacity),
                "--max_frame_gap", str(st.max_frame_gap),
                "--idle_timeout_s", str(st.idle_timeout_s),
                "--stream_pad_bucket", str(st.pad_bucket),
            ]
        if spec.mesh is not None:
            argv += ["--mesh", f"{spec.mesh[0]},{spec.mesh[1]}"]
        argv += list(self.extra_args)
        return argv
