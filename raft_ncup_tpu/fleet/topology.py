"""The fleet topology object: one frozen declarative config every other
piece reads (docs/FLEET.md).

The multi-GPU-abstraction pattern of PAPERS.md arXiv:2606.11390 applied
to process topology: the replica supervisor spawns FROM it, the router
routes FROM it, bench and chaos replay AGAINST it, and the tests assert
ON it — nothing else defines how many replicas exist, where their
sockets and healthz files live, what executable set each one warms, or
how much failover/restart budget the fleet has. A fleet whose shape is
scattered across flag defaults cannot be reasoned about when a replica
dies; one whose shape is a single validated object can.

Host-only stdlib (+ the repo's own jax-free config dataclasses): the
router process must be able to hold this object without importing jax
(JGL010's scope covers ``fleet/``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Tuple

from raft_ncup_tpu.config import ServeConfig, StreamConfig


def padded_shape(
    h: int, w: int, divisor: int = 8, bucket: int = 0
) -> Tuple[int, int]:
    """The padded (H, W) a native frame batches under — the pure-host
    mirror of ``ops/padding.InputPadder``'s pad arithmetic (height pads
    to a multiple of ``divisor`` = 8*spatial, width to a multiple of 8;
    a ``bucket`` rounds both up to multiples of itself). The router uses
    it to match a request's shape key against the replicas'
    healthz-advertised warmed executable sets without importing jax
    (tests/test_fleet.py pins it against the real InputPadder)."""
    h, w = int(h), int(w)
    if bucket:
        return h + (-h % bucket), w + (-w % bucket)
    return h + (-h % divisor), w + (-w % 8)


@dataclass(frozen=True)
class ReplicaSpec:
    """One replica's addresses, derived from :class:`FleetConfig` —
    where its Unix socket listens, where it rewrites its healthz file,
    and where its flight recorder banks fault dumps."""

    index: int
    socket_path: str
    healthz_path: str
    flight_dir: str
    # Periodic registry snapshots (serve.py --telemetry_jsonl): the
    # per-replica export observability/aggregate.py merges into the
    # fleet-wide registry view.
    telemetry_jsonl: str = ""
    mesh: Optional[Tuple[int, int]] = None


@dataclass(frozen=True)
class FleetConfig:
    """The whole fleet as one validated object.

    ``serve`` / ``stream`` are the per-replica subsystem configs (every
    replica runs a :class:`~raft_ncup_tpu.serving.server.FlowServer`;
    ``stream=None`` disables the per-replica StreamEngine for
    request-only fleets). ``meshes`` optionally pins a per-replica
    (data, spatial) mesh slice — the fleet analogue of the device mesh:
    which devices each replica owns is topology, not a replica-local
    flag.
    """

    # Directory holding every replica's socket, healthz file, and
    # flight dir (one tree per fleet run: the postmortem surface).
    base_dir: str
    n_replicas: int = 2
    # Native frame size the replicas warm at (the serve.py --size).
    size_hw: Tuple[int, int] = (96, 128)
    serve: ServeConfig = field(default_factory=ServeConfig)
    stream: Optional[StreamConfig] = None
    # Per-replica (data, spatial) mesh slices; None = unsharded
    # everywhere. Length must equal n_replicas when given.
    meshes: Optional[tuple] = None
    # Extra serve.py argv forwarded verbatim (model/platform flags).
    extra_args: Tuple[str, ...] = ()

    # --- healthz cadence + the staleness contract -----------------------
    # Replicas rewrite healthz on this cadence; a consumer MUST treat a
    # file whose time_unix_s is older than ``stale_after_s`` as a dead
    # replica even if the process still exists (a SIGSTOPped or wedged
    # replica lingers but cannot serve). Default: 2x the cadence — the
    # schema contract pinned in tests/test_observability.py.
    snapshot_interval_s: float = 0.25
    stale_after_factor: float = 2.0
    # Supervisor poll cadence + lifecycle timeouts.
    poll_interval_s: float = 0.1
    spawn_timeout_s: float = 120.0
    drain_timeout_s: float = 90.0

    # --- router admission + failover budgets ----------------------------
    # Outstanding (dispatched, unanswered) requests the router allows
    # per replica before it sheds AT THE ROUTER — backpressure must bite
    # before work crosses a process boundary.
    max_inflight_per_replica: int = 16
    # Shed hint when no replica has advertised anything better.
    default_retry_after_s: float = 0.25
    # How many times one request may be re-dispatched after a replica
    # death before it terminates honestly (shed/error, never silence).
    max_failovers: int = 1

    # --- supervisor restart budgets + circuit breaker -------------------
    max_restarts: int = 2  # per replica, counted
    restart_backoff_s: float = 0.25  # doubles per consecutive failure
    restart_backoff_max_s: float = 5.0
    # K consecutive failures (death/staleness without an intervening
    # healthy serve) opens the replica's circuit breaker: no restart,
    # no traffic — a crash-looping replica must stop eating requests.
    circuit_break_after: int = 3

    def __post_init__(self) -> None:
        if self.n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1: {self.n_replicas}")
        if not self.base_dir:
            raise ValueError("base_dir is required (sockets/healthz live there)")
        h, w = self.size_hw
        if int(h) < 16 or int(w) < 16:
            raise ValueError(f"size_hw too small for the pyramid: {self.size_hw}")
        if self.meshes is not None:
            if len(self.meshes) != self.n_replicas:
                raise ValueError(
                    f"meshes has {len(self.meshes)} entries for "
                    f"{self.n_replicas} replicas — the topology object "
                    "must name every replica's mesh slice explicitly"
                )
        for name in (
            "snapshot_interval_s", "poll_interval_s", "spawn_timeout_s",
            "drain_timeout_s", "restart_backoff_s", "restart_backoff_max_s",
            "default_retry_after_s",
        ):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0: {getattr(self, name)}")
        if self.stale_after_factor < 1.0:
            raise ValueError(
                "stale_after_factor < 1 declares a fresh file stale: "
                f"{self.stale_after_factor}"
            )
        if self.max_inflight_per_replica < 1:
            raise ValueError(
                f"max_inflight_per_replica must be >= 1: "
                f"{self.max_inflight_per_replica}"
            )
        if self.max_failovers < 0 or self.max_restarts < 0:
            raise ValueError("failover/restart budgets must be >= 0")
        if self.circuit_break_after < 1:
            raise ValueError(
                f"circuit_break_after must be >= 1: {self.circuit_break_after}"
            )

    # ------------------------------------------------------------ derived

    @property
    def stale_after_s(self) -> float:
        """The staleness bound: healthz older than this ⇒ replica
        presumed dead even if the process lingers."""
        return self.snapshot_interval_s * self.stale_after_factor

    def replica(self, i: int) -> ReplicaSpec:
        if not 0 <= i < self.n_replicas:
            raise ValueError(f"replica {i} out of range 0..{self.n_replicas - 1}")
        return ReplicaSpec(
            index=i,
            socket_path=os.path.join(self.base_dir, f"replica_{i}.sock"),
            healthz_path=os.path.join(
                self.base_dir, f"replica_{i}.healthz.json"
            ),
            flight_dir=os.path.join(self.base_dir, f"replica_{i}_flight"),
            telemetry_jsonl=os.path.join(
                self.base_dir, f"replica_{i}_telemetry.jsonl"
            ),
            mesh=None if self.meshes is None else self.meshes[i],
        )

    def replicas(self) -> list:
        return [self.replica(i) for i in range(self.n_replicas)]

    def pad_divisor(self, i: int) -> int:
        """Replica ``i``'s pad divisor (8 * spatial under a mesh)."""
        spec = self.replica(i)
        return 8 * (spec.mesh[1] if spec.mesh else 1)

    def shape_key(self, h: int, w: int, i: int = 0) -> Tuple[int, int]:
        """The padded shape a native (h, w) request batches under on
        replica ``i`` — the key matched against the replica's
        healthz-advertised warmed executable set."""
        return padded_shape(
            h, w, divisor=self.pad_divisor(i), bucket=self.serve.pad_bucket
        )

    def replica_argv(self, i: int) -> list:
        """The serve.py argument vector that realizes replica ``i`` of
        THIS topology — the supervisor spawns exactly this; bench and
        the tests print it for reproduction. (The interpreter and the
        serve.py path are the caller's: they depend on the environment,
        not the topology.)"""
        spec = self.replica(i)
        s, st = self.serve, self.stream
        argv = [
            "--replica_socket", spec.socket_path,
            "--replica_index", str(i),
            "--healthz_file", spec.healthz_path,
            "--flight_dir", spec.flight_dir,
            "--telemetry_jsonl", spec.telemetry_jsonl,
            "--telemetry_interval_s", str(self.snapshot_interval_s),
            "--size", str(self.size_hw[0]), str(self.size_hw[1]),
            "--queue_capacity", str(s.queue_capacity),
            "--serve_batch_sizes", ",".join(str(b) for b in s.batch_sizes),
            "--iter_levels", ",".join(str(x) for x in s.iter_levels),
            "--high_water", str(s.high_water),
            "--low_water", str(s.low_water),
            "--recover_patience", str(s.recover_patience),
            "--serve_pad_bucket", str(s.pad_bucket),
            "--serve_cache_size", str(s.cache_size),
        ]
        if s.precision is not None:
            argv += ["--serve_precision", s.precision]
        if st is None:
            argv += ["--replica_streams", "false"]
        else:
            argv += [
                "--replica_streams", "true",
                "--stream_capacity", str(st.capacity),
                "--stream_iters", str(st.iters),
                "--stream_batch_sizes", ",".join(
                    str(b) for b in st.batch_sizes
                ),
                "--stream_queue_capacity", str(st.queue_capacity),
                "--max_frame_gap", str(st.max_frame_gap),
                "--idle_timeout_s", str(st.idle_timeout_s),
                "--stream_pad_bucket", str(st.pad_bucket),
            ]
        if spec.mesh is not None:
            argv += ["--mesh", f"{spec.mesh[0]},{spec.mesh[1]}"]
        argv += list(self.extra_args)
        return argv
