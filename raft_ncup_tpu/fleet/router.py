"""The fleet router: admission, affinity, shape-aware routing, and
failover over N replica processes (docs/FLEET.md).

The router is the fleet's front door and its robustness chokepoint:

- **admission sheds HERE**, before work crosses a process boundary: a
  request the fleet cannot absorb is refused at the router with an
  honest ``retry_after_s`` aggregated from the replicas' own hints (the
  max over the replicas consulted — the router never invents a smaller
  number than a replica it asked), not serialized over a socket into a
  queue that would shed it anyway.
- **stream affinity is consistent-hash + sticky**: a video stream's
  warm HBM slot state lives on exactly one replica, so its frames must
  keep landing there; rendezvous hashing picks the home, a sticky map
  keeps it until that replica dies or drains (a replica coming BACK
  must not steal streams whose warm state now lives elsewhere).
- **request routing is shape-aware**: the replicas advertise their
  warmed ``(shape, batch, iters)`` executable sets through healthz;
  a request whose padded shape is already warm on one replica must not
  pay a cold compile on another while the first sits idle.
- **rotation is DRAINING/DEGRADED-aware**: a draining replica finishes
  its in-flight work but gets nothing new (the healthz DRAINING state
  is published BEFORE the flush for exactly this poll); a DEGRADED
  replica still serves — coarser answers beat shed ones.
- **failover respects deadlines and is bounded**: when a replica dies
  with requests in flight, each pending request is re-dispatched at
  most ``max_failovers`` times and only if its deadline still allows;
  otherwise it terminates with an honest ``shed``/``error`` — the same
  five-status protocol as ``serving/request.py``, no silent drops.

Host-only stdlib + numpy (JGL010 covers ``fleet/``): the router holds
pixels only as host ndarrays in transit and can never add a device
sync to the path it routes.
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from raft_ncup_tpu.fleet import wire
from raft_ncup_tpu.fleet.replica import ReplicaSupervisor
from raft_ncup_tpu.fleet.topology import FleetConfig
from raft_ncup_tpu.observability.spans import TraceContext, new_trace_id
from raft_ncup_tpu.serving.request import (
    STATUS_ERROR,
    STATUS_SHED,
    FlowResponse,
    ServeHandle,
)


def rendezvous_choice(key: str, candidates: Sequence[int]) -> int:
    """Highest-random-weight (rendezvous) hash: the stable
    consistent-hash choice of a replica for ``key`` — when a replica
    leaves, only ITS keys move; the rest stay put."""
    if not candidates:
        raise ValueError("no candidates")
    return max(
        candidates,
        key=lambda i: hashlib.md5(
            f"{key}:{i}".encode("utf-8")
        ).hexdigest(),
    )


class _Pending:
    """One dispatched, unanswered request held for completion or
    failover. The router keeps the staged host arrays exactly as long
    as a failover could still need them."""

    __slots__ = (
        "rid", "handle", "kind", "header", "arrays", "deadline",
        "submit_time", "replica", "failovers", "stream_id", "consulted",
        "link", "trace_id", "sent_s",
    )

    def __init__(self, rid, handle, kind, header, arrays, deadline,
                 submit_time, replica, stream_id, consulted):
        self.rid = rid
        self.handle = handle
        self.kind = kind
        self.header = header
        self.arrays = arrays
        self.deadline = deadline
        self.submit_time = submit_time
        self.replica = replica
        self.failovers = 0
        self.stream_id = stream_id
        self.consulted = set(consulted)
        # One trace per request, minted at the fleet edge: the id
        # SURVIVES failover (the re-dispatch is the same journey) and
        # rides the wire header's optional trace context so the
        # replica's spans adopt it (docs/OBSERVABILITY.md).
        self.trace_id = new_trace_id()
        self.sent_s: Optional[float] = None  # router clock at last send
        # The link incarnation that carried the dispatch: responses ride
        # the same connection, so when THIS link dies the request can
        # never be answered — even if a fresh link to the same replica
        # already exists (the reconnect race must not strand it).
        self.link = None


class _Link:
    """One live socket to one replica incarnation, with its reader
    thread. Dead links are discarded; a restarted replica gets a fresh
    link on the next dispatch.

    Half-open detection (TCP): when the socket carries a read deadline
    (``wire.set_read_timeout``; the router arms it on INET links), a
    deadline at a frame BOUNDARY (``wire.FrameTimeout``) means the link
    is idle — or the peer vanished without a FIN and will never speak
    again. The reader answers it with a ping probe: a healthy peer
    pongs before the next deadline, a half-open one fails the send
    (RST once the peer's host notices, or ``SO_SNDTIMEO`` when even
    that is gone) and the normal down path flushes this incarnation's
    in-flight requests — half-open detection folded into the existing
    incarnation-tagged failover flush, not a second mechanism. A
    deadline MID-frame arrives as ``ConnectionError`` (slow-loris /
    dying peer) and tears the link like any torn frame."""

    def __init__(self, index: int, sock: socket.socket,
                 on_message: Callable, on_down: Callable,
                 clock: Callable[[], float] = time.monotonic):
        self.index = index
        self.sock = sock
        self.alive = True
        self.send_lock = threading.Lock()
        self._on_message = on_message
        self._on_down = on_down
        self._clock = clock
        self.probes = 0  # boundary-timeout ping probes sent
        self.reader = threading.Thread(
            target=self._read_loop, name=f"fleet-link-{index}", daemon=True
        )
        self.reader.start()

    def send(self, header: dict, arrays=()) -> bool:
        with self.send_lock:
            if not self.alive:
                return False
            try:
                wire.send_msg(self.sock, header, arrays)
                return True
            except OSError:
                self.alive = False
                return False

    def _read_loop(self) -> None:
        try:
            while True:
                try:
                    msg = wire.recv_msg(self.sock)
                except wire.FrameTimeout:
                    self.probes += 1
                    if not self.send(
                        {"kind": "ping", "t0": self._clock()}
                    ):
                        break  # half-open: the send noticed first
                    continue
                if msg is None:
                    break
                self._on_message(self.index, *msg)
        except (OSError, ValueError):
            pass  # connection torn mid-frame: same as EOF below
        with self.send_lock:
            # Same lock as send(): a sender mid-send must never observe
            # alive flipping under it (JGL011).
            self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass
        self._on_down(self.index, self)


class FleetRouter:
    """Route requests and stream frames over a supervised replica
    fleet. Constructed from the same :class:`FleetConfig` the
    supervisor spawned from — topology is read, never re-declared."""

    def __init__(
        self,
        cfg: FleetConfig,
        supervisor: ReplicaSupervisor,
        *,
        telemetry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from raft_ncup_tpu.observability import get_telemetry

        self.cfg = cfg
        self.sup = supervisor
        self._clock = clock
        self._tel = telemetry if telemetry is not None else get_telemetry()
        self._lock = threading.RLock()
        self._links: Dict[int, _Link] = {}
        self._pending: Dict[int, _Pending] = {}
        # Keyed by replica slot index; accessed with .get(i, 0) — the
        # live set is elastic (autoscaler adds/retires slots), so a
        # fresh slot must not KeyError its first dispatch.
        self._inflight: Dict[int, int] = {
            i: 0 for i in range(cfg.n_replicas)
        }
        self._dispatched: Dict[int, int] = {
            i: 0 for i in range(cfg.n_replicas)
        }
        # The autoscaler's published time-to-READY estimate (None when
        # capacity isn't warming): sheds while a scale-up is still
        # compiling must tell the client to retry AFTER the new
        # replica can admit, not the default 250ms re-shed treadmill.
        self._scale_eta_s: Optional[float] = None
        self._affinity: Dict[str, int] = {}
        self._shed_hints: Dict[int, float] = {}
        self._replica_of: Dict[int, int] = {}  # rid -> last replica
        # Monotonic-clock offsets from the per-link handshake:
        # replica_mono - router_mono, estimated as pong minus
        # (ping + rtt/2). 0.0 until a pong answers (UDS on one host:
        # CLOCK_MONOTONIC is shared, so 0.0 is already correct; the
        # handshake is what keeps per-hop deltas meaningful when the
        # wire grows a TCP multi-host transport).
        self._clock_offsets: Dict[int, float] = {}
        # set_fleet_telemetry ack bookkeeping (bench's fleet
        # telemetry-overhead window toggles the replicas' hubs in place).
        self._tel_ack_cond = threading.Condition()
        self._tel_acks: set = set()
        self._next_id = 0
        self._draining = False
        self.stats = {
            "submitted": 0, "routed": 0, "shed": 0, "completed": 0,
            "failovers": 0, "failover_errors": 0, "failover_sheds": 0,
        }
        # The supervisor's death notifications flush our pending set;
        # link EOFs reach the same path first for a faster failover.
        # CHAIN any callback the supervisor was constructed with (an
        # operator's alerting hook must not be silently discarded).
        prev_on_death = supervisor._on_death

        def _on_death(index: int, reason: str) -> None:
            self._on_replica_death(index, reason)
            if prev_on_death is not None:
                prev_on_death(index, reason)

        supervisor._on_death = _on_death

    # ------------------------------------------------------------ routing

    def _admittable(self) -> List[int]:
        return [
            h.index for h in self.sup.replicas if h.admittable()
        ]

    def _warm_for(self, i: int, h: int, w: int) -> bool:
        """Does replica ``i`` advertise a warmed executable for this
        native shape? Matched on the padded (H, W) of the replica's own
        pad divisor against the healthz ``warmed`` set."""
        handle = self.sup.handle(i)
        hz = handle.last_healthz
        warmed = (hz or {}).get("warmed") or []
        ph, pw = self.cfg.shape_key(h, w, i)
        return any(
            int(entry[0]) == ph and int(entry[1]) == pw
            for entry in warmed
            if isinstance(entry, (list, tuple)) and len(entry) >= 2
        )

    def _pick_replica(
        self, *, stream_id: Optional[str], h: int, w: int,
        exclude: frozenset = frozenset(),
    ):
        """Choose a replica for one dispatch. Returns
        ``(index | None, consulted)`` — ``consulted`` is every replica
        whose capacity the decision looked at, the set the shed hint
        aggregates over."""
        candidates = [
            i for i in self._admittable() if i not in exclude
        ]
        consulted = list(candidates)
        if not candidates:
            return None, consulted
        if stream_id is not None:
            home = self._affinity.get(stream_id)
            if home is not None and home in candidates:
                candidates = [home]
            else:
                # (Re-)home by rendezvous hash over the live set; sticky
                # from here so a replica coming back cannot steal the
                # stream's now-elsewhere warm state.
                home = rendezvous_choice(stream_id, candidates)
                self._affinity[stream_id] = home
                candidates = [home]
        else:
            warm = [i for i in candidates if self._warm_for(i, h, w)]
            if warm:
                candidates = warm
        # Admission bound: shed at the router before a socket hop.
        open_cap = [
            i for i in candidates
            if self._inflight.get(i, 0) < self.cfg.max_inflight_per_replica
        ]
        if not open_cap:
            return None, consulted
        # Least in-flight wins; ties break by cumulative dispatch count
        # so a sequential open-loop (inflight always 0 at submit time)
        # still spreads over the fleet instead of pinning replica 0.
        return min(
            open_cap,
            key=lambda i: (
                self._inflight.get(i, 0), self._dispatched.get(i, 0), i,
            ),
        ), consulted

    def _retry_after(self, consulted) -> float:
        """The aggregated backpressure hint: the MAX over the hints the
        consulted replicas last shed with (never smaller than any
        replica the decision looked at), floored at the config default
        — and at the autoscaler's published time-to-READY estimate
        while a scale-up is warming: a client told "retry in 250ms"
        during a cold compile just re-sheds; a client told "retry in
        the ETA" lands on the new capacity (regression-pinned in
        tests/test_fleet.py)."""
        with self._lock:  # RLock: callers may already hold it
            hints = [
                self._shed_hints[i] for i in consulted
                if i in self._shed_hints
            ]
            floor = [self.cfg.default_retry_after_s]
            if self._scale_eta_s is not None:
                floor.append(self._scale_eta_s)
        return round(max(hints + floor), 4)

    def _link(self, i: int) -> Optional[_Link]:
        with self._lock:
            link = self._links.get(i)
            if link is not None and link.alive:
                return link
        spec = self.cfg.replica(i)
        try:
            transport = wire.Transport.parse(
                spec.address or spec.socket_path
            )
            sock = transport.connect(
                timeout_s=self.cfg.connect_timeout_s
            )
            # Bound SENDS only (SO_SNDTIMEO, not settimeout: the reader
            # thread shares this socket and must block indefinitely): a
            # frame pair can exceed the UDS buffer, and sendall to a
            # SIGSTOPped replica must fail over after seconds, not hang
            # the submitter until the staleness pass.
            import struct as _struct

            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                _struct.pack("ll", 10, 0),
            )
            if transport.is_inet:
                # Read deadline → boundary timeouts → the link reader's
                # ping probe: half-open peers (partitioned host, agent
                # SIGKILL) get flushed instead of hanging forever.
                wire.set_read_timeout(
                    sock, self.cfg.link_read_timeout_s
                )
        except (OSError, ValueError):
            return None
        link = _Link(
            i, sock, self._on_message, self._on_link_down,
            clock=self._clock,
        )
        with self._lock:
            self._links[i] = link
        # Clock handshake: ping carries the router's monotonic clock;
        # the pong (handled in _on_message) yields this link's offset.
        # Fire-and-forget — a replica that predates the handshake
        # simply never answers with t_mono and the offset stays 0.0.
        link.send({"kind": "ping", "t0": self._clock()})
        return link

    # ----------------------------------------------------------- admission

    def submit(
        self,
        image1,
        image2,
        *,
        deadline_s: Optional[float] = None,
        stream_id: Optional[str] = None,
        frame_index: Optional[int] = None,
    ) -> ServeHandle:
        """Submit one frame pair to the fleet; returns a handle that
        terminates in exactly one of the five serving statuses.
        ``stream_id`` routes by affinity through the owning replica's
        StreamEngine; without it the request rides FlowServer routing."""
        handle = ServeHandle()
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            self.stats["submitted"] += 1
        self._tel.inc("fleet_submitted_total")
        if self._draining:
            self._complete_shed(rid, handle, (), "router draining")
            return handle
        shape = getattr(image1, "shape", None)
        if shape is None or len(shape) != 3:
            handle.complete(FlowResponse(
                rid, STATUS_ERROR,
                detail=f"not an (H, W, C) array: {type(image1).__name__}",
            ))
            return handle
        h, w = int(shape[0]), int(shape[1])
        now = self._clock()
        deadline = None if deadline_s is None else now + deadline_s
        kind = "request" if stream_id is None else "frame"
        header = {"kind": kind, "id": rid}
        if deadline_s is not None:
            header["deadline_s"] = deadline_s
        if stream_id is not None:
            header["stream_id"] = stream_id
            if frame_index is not None:
                header["frame_index"] = frame_index
        with self._lock:
            target, consulted = self._pick_replica(
                stream_id=stream_id, h=h, w=w
            )
            if target is None:
                self._complete_shed(
                    rid, handle, consulted,
                    "fleet at capacity" if consulted
                    else "no admittable replica",
                )
                return handle
            pending = _Pending(
                rid, handle, kind, header, (image1, image2), deadline,
                now, target, stream_id, consulted,
            )
            self._register(pending, target)
        self._dispatch(pending, target)
        return handle

    def _register(self, pending: _Pending, target: int) -> None:
        self._pending[pending.rid] = pending
        self._inflight[target] = self._inflight.get(target, 0) + 1
        self._dispatched[target] = self._dispatched.get(target, 0) + 1
        self._replica_of[pending.rid] = target
        self.stats["routed"] += 1

    def _dispatch(self, pending: _Pending, target: int) -> None:
        # The router-side correlation id IS the replica-side request id:
        # the replica's FlowServer/StreamEngine register the request
        # under this exact id, so one `request_id` matches spans on both
        # sides of the process boundary (scripts/postmortem.py) — and
        # the trace context rides the header as an OPTIONAL field, so
        # the replica's own spans adopt the same trace_id (old replicas
        # ignore it; the JGL010 wire-compat check keeps it optional).
        now = self._clock()
        pending.sent_s = now
        with self._lock:
            clock_offset_s = self._clock_offsets.get(target, 0.0)
        pending.header["trace"] = TraceContext(
            trace_id=pending.trace_id,
            span_id=f"router-{pending.rid}",
            clock_offset_s=clock_offset_s,
            sent_s=now,
        ).to_wire()
        self._tel.event(
            "fleet_dispatch", request_id=pending.rid, replica=target,
            kind=pending.kind, stream_id=pending.stream_id,
            trace_id=pending.trace_id,
        )
        # Router-queue hop: submit -> this send (routing + any failover
        # wait). Feeds the fleet_hop_* stage breakdown in
        # telemetry_report() alongside the wire/replica/return hops.
        self._tel.hist_observe(
            "fleet_hop_router_queue_ms",
            (now - pending.submit_time) * 1e3,
        )
        link = self._link(target)
        pending.link = link
        sent = link is not None and link.send(
            pending.header, pending.arrays
        )
        if not sent:
            self._on_replica_death(target, "dispatch send failed")

    def _complete_shed(self, rid, handle, consulted, detail) -> None:
        with self._lock:
            self.stats["shed"] += 1
        self._tel.inc("fleet_shed_total")
        handle.complete(FlowResponse(
            rid, STATUS_SHED,
            retry_after_s=self._retry_after(consulted),
            detail=detail,
        ))

    # ---------------------------------------------------------- responses

    def _on_message(self, index: int, header: dict, arrays) -> None:
        kind = header.get("kind")
        if kind == "pong":
            # Clock handshake answer: offset = replica_mono - router_mono,
            # with the one-way delay approximated as rtt/2.
            t0, t_mono = header.get("t0"), header.get("t_mono")
            if t0 is not None and t_mono is not None:
                now = self._clock()
                rtt = max(0.0, now - float(t0))
                offset = float(t_mono) - (float(t0) + rtt / 2.0)
                with self._lock:
                    self._clock_offsets[index] = offset
                self._tel.event(
                    "fleet_clock_handshake", replica=index,
                    offset_s=round(offset, 6),
                    rtt_ms=round(rtt * 1e3, 3),
                )
            return
        if kind == "telemetry_ack":
            with self._tel_ack_cond:
                self._tel_acks.add(index)
                self._tel_ack_cond.notify_all()
            return
        if kind != "response":
            return
        rid = header.get("id")
        with self._lock:
            pending = self._pending.pop(rid, None)
            if pending is not None:
                self._inflight[pending.replica] = max(
                    0, self._inflight.get(pending.replica, 0) - 1
                )
        if pending is None:
            return  # failed over already; the late answer is dropped
        status = header.get("status", STATUS_ERROR)
        retry_after = header.get("retry_after_s")
        if status == STATUS_SHED and header.get("detail") == "draining":
            # A draining replica refuses work it never admitted into
            # its engine (the SIGTERM beat the socket read). That
            # refusal is re-routable — the scale-down zero-loss claim
            # is the ROUTER's to keep — so treat it like a death-
            # stranding: redispatch to a survivor within the failover
            # budget (and shed honestly, ETA-floored, only if none can
            # admit).
            self._tel.event(
                "fleet_drain_refusal_failover", request_id=rid,
                replica=index,
            )
            self._failover_one(pending, index, self._clock())
            return
        if status == STATUS_SHED:
            # Aggregate the backpressure hint: never smaller than any
            # replica this request's routing consulted.
            with self._lock:
                if retry_after is not None:
                    self._shed_hints[index] = float(retry_after)
                hints = [
                    self._shed_hints[i]
                    for i in pending.consulted | {index}
                    if i in self._shed_hints
                ]
                self.stats["shed"] += 1
            retry_after = round(max(
                hints + [float(retry_after or 0.0),
                         self.cfg.default_retry_after_s]
            ), 4)
            self._tel.inc("fleet_shed_total")
        now = self._clock()
        flow = arrays[0] if arrays else None
        with self._lock:
            self.stats["completed"] += 1
            offset = self._clock_offsets.get(pending.replica, 0.0)
        self._tel.hist_observe(
            "fleet_e2e_ms", (now - pending.submit_time) * 1e3
        )
        # Per-hop attribution (docs/OBSERVABILITY.md "Trace
        # propagation"): the replica stamps its receive/done instants on
        # its own monotonic clock; the handshake offset translates them
        # onto the router's. Clamped at 0 — the offset carries up to
        # rtt/2 of estimation error, and a hop must never read negative.
        t_recv = header.get("t_recv_s")
        t_done = header.get("t_done_s")
        if t_recv is not None and pending.sent_s is not None:
            self._tel.hist_observe(
                "fleet_hop_wire_ms",
                max(0.0, (float(t_recv) - offset - pending.sent_s) * 1e3),
            )
        if t_recv is not None and t_done is not None:
            self._tel.hist_observe(
                "fleet_hop_replica_ms",
                max(0.0, (float(t_done) - float(t_recv)) * 1e3),
            )
        if t_done is not None:
            self._tel.hist_observe(
                "fleet_hop_return_ms",
                max(0.0, (now - (float(t_done) - offset)) * 1e3),
            )
        # The trace's ROOT span: one ring record per completed request
        # carrying the trace id — what aggregate.py anchors the stitched
        # fleet tree on (and for_attr(trace_id=...) finds live).
        self._tel.observe_ms(
            "fleet_request", (now - pending.submit_time) * 1e3,
            trace_id=pending.trace_id, request_id=rid,
            replica=pending.replica, kind=pending.kind,
            span_id=f"router-{rid}",
        )
        pending.handle.complete(FlowResponse(
            rid,
            status,
            flow=flow,
            iters=header.get("iters"),
            latency_s=now - pending.submit_time,
            retry_after_s=retry_after,
            detail=header.get("detail", ""),
        ))

    # ------------------------------------------------------------ failover

    def _on_link_down(self, index: int, link: _Link) -> None:
        # Flush the requests THIS incarnation carried even when a fresh
        # link to the same replica was already installed by a racing
        # dispatch — responses ride the connection that died, so those
        # requests can never be answered (no-silent-drop contract).
        self._on_replica_death(index, "connection lost", link=link)

    def _on_replica_death(
        self, index: int, reason: str, link: Optional[_Link] = None,
    ) -> None:
        """Flush pending requests on a dead replica (``link=None``: all
        of them — supervisor-detected death) or on one dead link
        incarnation (``link=``): re-dispatch within budget and deadline,
        terminate honestly otherwise. Runs from the supervisor's poll,
        a link reader, or a failed send — whichever notices first; the
        pending map makes it idempotent."""
        with self._lock:
            popped = None
            if link is None or self._links.get(index) is link:
                popped = self._links.pop(index, None)
            stranded = [
                p for p in self._pending.values()
                if p.replica == index and (link is None or p.link is link)
            ]
            for p in stranded:
                del self._pending[p.rid]
            if link is None:
                self._inflight[index] = 0
            else:
                # Only this incarnation's requests died; a racing fresh
                # link may already carry live ones.
                self._inflight[index] = max(
                    0, self._inflight.get(index, 0) - len(stranded)
                )
            # Streams homed here must re-admit elsewhere, cold (a
            # reconnected incarnation has no warm slot state either).
            moved_streams = [
                s for s, i in self._affinity.items() if i == index
            ]
            for s in moved_streams:
                del self._affinity[s]
        for dead in {link, popped} - {None}:
            dead.alive = False
            try:
                dead.sock.close()
            except OSError:
                pass
        if not stranded and not moved_streams:
            return
        self._tel.event(
            "fleet_replica_down", replica=index, reason=reason,
            stranded=len(stranded), moved_streams=len(moved_streams),
        )
        # Fault trigger: bank the failover context (the stranded ids
        # correlate with the dead replica's own flight dumps).
        self._tel.flight_dump(
            "replica_failover", replica=index, reason=reason,
            request_ids=[p.rid for p in stranded],
            moved_streams=moved_streams,
        )
        now = self._clock()
        for p in stranded:
            self._failover_one(p, index, now)

    def _failover_one(self, p: _Pending, dead: int, now: float) -> None:
        if p.failovers >= self.cfg.max_failovers:
            with self._lock:
                self.stats["failover_errors"] += 1
            p.handle.complete(FlowResponse(
                p.rid, STATUS_ERROR,
                detail=f"replica {dead} died; failover budget "
                f"({self.cfg.max_failovers}) exhausted",
            ))
            return
        if p.deadline is not None and now >= p.deadline:
            with self._lock:
                self.stats["failover_errors"] += 1
            p.handle.complete(FlowResponse(
                p.rid, STATUS_ERROR,
                latency_s=now - p.submit_time,
                detail=f"replica {dead} died; deadline expired before "
                "failover",
            ))
            return
        with self._lock:
            target, consulted = self._pick_replica(
                stream_id=p.stream_id,
                h=int(p.arrays[0].shape[0]),
                w=int(p.arrays[0].shape[1]),
                exclude=frozenset({dead}),
            )
            if target is None:
                self.stats["failover_sheds"] += 1
                self.stats["shed"] += 1
                self._tel.inc("fleet_shed_total")
                p.handle.complete(FlowResponse(
                    p.rid, STATUS_SHED,
                    retry_after_s=self._retry_after(consulted),
                    detail=f"replica {dead} died; no admittable replica "
                    "for failover",
                ))
                return
            p.failovers += 1
            p.replica = target
            p.consulted |= set(consulted)
            self._register_failover(p, target)
            self.stats["failovers"] += 1
        self._tel.inc("fleet_failovers_total")
        self._tel.event(
            "fleet_failover", request_id=p.rid, from_replica=dead,
            to_replica=target, kind=p.kind, stream_id=p.stream_id,
            trace_id=p.trace_id,
        )
        self._dispatch(p, target)

    def _register_failover(self, pending: _Pending, target: int) -> None:
        self._pending[pending.rid] = pending
        self._inflight[target] = self._inflight.get(target, 0) + 1
        self._dispatched[target] = self._dispatched.get(target, 0) + 1
        self._replica_of[pending.rid] = target

    # ------------------------------------------------------------ queries

    def replica_of(self, rid: int) -> Optional[int]:
        """Which replica carried request ``rid`` (last dispatch) — the
        deterministic coordinate fleet chaos targets."""
        with self._lock:
            return self._replica_of.get(rid)

    def clock_offsets(self) -> Dict[int, float]:
        """Per-replica monotonic-clock offsets from the link handshake
        (replica_mono - router_mono) — what aggregate.py uses to
        translate replica-side record timestamps onto the router's
        clock when stitching the fleet trace tree."""
        with self._lock:
            return dict(self._clock_offsets)

    def set_fleet_telemetry(
        self, enabled: bool, timeout: float = 10.0,
    ) -> int:
        """Toggle every LIVE replica's telemetry hub in place over the
        wire (the fleet analogue of ``Telemetry.enabled`` — bench's
        fleet telemetry-overhead window flips it off and back on the
        SAME warm fleet, so the comparison never embeds a re-warmup).
        Returns how many replicas acked within ``timeout``; the
        router's own hub is the caller's to flip."""
        with self._lock:
            targets = [
                i for i, link in self._links.items() if link.alive
            ]
        with self._tel_ack_cond:
            self._tel_acks.clear()
        sent = set()
        for i in targets:
            link = self._link(i)
            if link is not None and link.send(
                {"kind": "set_telemetry", "enabled": bool(enabled)}
            ):
                sent.add(i)
        deadline = time.monotonic() + timeout
        with self._tel_ack_cond:
            while not sent <= self._tel_acks:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._tel_ack_cond.wait(left)
            return len(self._tel_acks & sent)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    # --------------------------------------------- autoscaler surfaces

    def inflight_of(self, i: int) -> int:
        """Outstanding dispatches on replica ``i`` — the autoscaler's
        per-replica occupancy input and its least-loaded-victim key on
        scale-down."""
        with self._lock:
            return self._inflight.get(i, 0)

    def queue_depth(self) -> int:
        """Total dispatched-but-unanswered requests (the router has no
        literal queue — backpressure sheds at admission — so depth IS
        the fleet-wide in-flight count)."""
        with self._lock:
            return sum(self._inflight.values())

    def occupancy(self) -> float:
        """Fleet-wide occupancy in [0, 1]: in-flight over the open
        capacity of the admittable set. 1.0 with NOTHING admittable —
        a fleet with no admittable replica is saturated by definition,
        not idle."""
        with self._lock:
            admittable = self._admittable()
            cap = len(admittable) * self.cfg.max_inflight_per_replica
            if cap <= 0:
                return 1.0
            used = sum(self._inflight.get(i, 0) for i in admittable)
            return min(1.0, used / cap)

    def set_scale_eta(self, eta_s: Optional[float]) -> None:
        """Publish (or clear, with ``None``) the autoscaler's
        time-to-READY estimate: every shed's ``retry_after_s`` is
        floored at it while set (see :meth:`_retry_after`)."""
        with self._lock:
            self._scale_eta_s = (
                None if eta_s is None else max(0.0, float(eta_s))
            )

    def report(self) -> dict:
        with self._lock:
            return {
                "stats": dict(self.stats),
                "per_replica_dispatched": dict(self._dispatched),
                "per_replica_inflight": dict(self._inflight),
                "affinity": dict(self._affinity),
                "shed_hints": dict(self._shed_hints),
            }

    # ----------------------------------------------------------- teardown

    def drain(self, timeout: float = 60.0) -> dict:
        """Stop admitting (new submits shed), wait for in-flight work,
        close links. The replicas' own drains are the supervisor's job —
        the router only owns its half of the no-silent-loss contract."""
        self._draining = True
        deadline = self._clock() + timeout
        while self.pending_count() and self._clock() < deadline:
            time.sleep(0.02)
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
            links = list(self._links.values())
            self._links.clear()
        for p in leftovers:
            # Bounded wait expired: the client gets an explicit error,
            # never silence.
            p.handle.complete(FlowResponse(
                p.rid, STATUS_ERROR,
                detail="router drained with request still in flight",
            ))
        for link in links:
            link.alive = False
            try:
                link.sock.close()
            except OSError:
                pass
        # Bank the router's half of the fleet trace tree: the full span
        # ring (every fleet_request root span + dispatch event) plus the
        # handshake's clock offsets — exactly what aggregate.py needs to
        # stitch this run's traces against the replicas' own drain dumps.
        self._tel.flight_dump(
            "router_drain",
            stranded=len(leftovers),
            clock_offsets={
                str(i): round(o, 6)
                for i, o in self.clock_offsets().items()
            },
        )
        return self.report()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.drain()


def replay_fleet(
    router: FleetRouter,
    items,
    *,
    supervisor: Optional[ReplicaSupervisor] = None,
    chaos=None,
    interval_s: float = 0.0,
    manager=None,
):
    """Drive a deterministic schedule through the router, firing fleet
    chaos at exact submission indices (the PR 5/6 machinery at fleet
    granularity): after submission ``n`` dispatches, ``killreplica@n``
    SIGKILLs / ``stallreplica@n`` SIGSTOPs / ``drainreplica@n`` SIGTERM-
    drains the replica that carried it. Returns the submission handles.

    Host-scale kinds need ``manager`` (a
    ``fleet/host_supervisor.FleetManager``): ``partitionhost@n`` drops
    the TCP links to the host that carried submission ``n`` (both
    directions), ``killsupervisor@n`` SIGKILLs that host's agent (its
    replicas linger until the staleness contract reaps them). The
    coordinate stays a submission index for every kind — the TARGET
    host is derived from the carrying replica's placement, so the
    blast lands deterministically.

    ``items``: dicts with ``image1``/``image2`` (+ optional
    ``stream_id``, ``frame_index``, ``deadline_s``).
    """
    handles = []
    for n, item in enumerate(items):
        with router._lock:
            rid = router._next_id  # this submission's id (sole submitter)
        handle = router.submit(
            item["image1"], item["image2"],
            deadline_s=item.get("deadline_s"),
            stream_id=item.get("stream_id"),
            frame_index=item.get("frame_index"),
        )
        handles.append(handle)
        if chaos is not None:
            target = router.replica_of(rid)
            if target is not None and supervisor is not None:
                if n in chaos.kill_replica_at:
                    supervisor.kill(target)
                if n in chaos.stall_replica_at:
                    supervisor.stall(target)
                if n in chaos.drain_replica_at:
                    threading.Thread(
                        target=supervisor.drain, args=(target,),
                        name=f"chaos-drain-{target}", daemon=True,
                    ).start()
            if target is not None and manager is not None:
                if n in chaos.partition_host_at:
                    manager.partition(manager.host_of(target))
                if n in chaos.kill_supervisor_at:
                    manager.kill_agent(manager.host_of(target))
        if interval_s:
            time.sleep(interval_s)
    return handles
