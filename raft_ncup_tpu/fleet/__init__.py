"""Fleet tier: a multi-replica router over N serve.py child processes
(ROADMAP item 2; docs/FLEET.md).

Everything below this package is ONE process — one ``FlowServer``, one
``StreamEngine``, one device mesh. "Millions of users" is a *process
topology*: N replica processes (each owning its own devices / mesh
slice) behind a router that admits, routes, and fails over WITHOUT ever
crossing into device land itself. The package is therefore host-only
stdlib + numpy by construction — lint rule JGL010 holds it to the same
no-jax contract as ``observability/``: a router that can touch a device
array can add a device sync to every request it routes.

- :mod:`topology` — one frozen declarative :class:`FleetConfig` (the
  arXiv:2606.11390 one-object pattern applied to process topology):
  replica count, per-replica serve/stream knobs + mesh slice + socket +
  healthz path, router admission bounds, failover/restart budgets.
  The supervisor, the router, bench, chaos, and the tests all read THIS
  object; nothing else defines the fleet's shape.
- :mod:`wire` — the socket frame protocol: length-prefixed JSON header
  + raw C-order ndarray payloads over a Unix domain socket or a TCP
  connection (:class:`wire.Transport` parses the family from the one
  address string both ends share; TCP links are hardened with connect
  timeouts, keepalive, and boundary-vs-mid-frame read deadlines).
- :mod:`replica` — :class:`ChildProcess` (the one process-lifecycle
  implementation: spawn, liveness/healthz wait, drain, reap — shared
  with the 4-process distributed test rig) and
  :class:`ReplicaSupervisor` (healthz polling with the staleness
  contract, SIGTERM→DRAINING→exit-75 drain orchestration, bounded
  counted restart-with-backoff, circuit breaker).
- :mod:`router` — :class:`FleetRouter`: fleet-level admission that
  sheds BEFORE work crosses a process boundary, consistent-hash stream
  affinity, shape-aware request routing against the replicas'
  healthz-advertised warmed executable sets, DRAINING/DEGRADED-aware
  rotation, and deadline-respecting single-failover retry — same
  five-status terminal protocol as ``serving/request.py``.
- :mod:`host_supervisor` — the multi-host control plane: a per-host
  :class:`HostSupervisor` agent (the unmodified ReplicaSupervisor over
  that host's slots + a wire republish of their healthz) and the
  router-side :class:`FleetManager` (fleet-level staleness: a silent
  host is a dead host — fenced, failed over).
- :mod:`autoscaler` — :class:`FleetAutoscaler`: the SLO-driven elastic
  sizing loop (occupancy/burn/shed signals, hysteresis + cooldown,
  scale-up through the READY pre-warm gate, scale-down through the
  zero-loss drain contract, fail-budget breaker, time-to-READY ETA
  published to the router's shed hints).

Chaos: ``killreplica@N`` / ``stallreplica@N`` / ``drainreplica@N`` +
the fleet-scale ``partitionhost@N`` / ``killsupervisor@N``
(resilience/chaos.py) drive the blast-radius tests in
tests/test_fleet.py. Bench: the guarded ``fleet_*`` and
``elasticity_*`` rows in bench.py.
"""

from raft_ncup_tpu.fleet.autoscaler import FleetAutoscaler  # noqa: F401
from raft_ncup_tpu.fleet.host_supervisor import (  # noqa: F401
    FleetManager,
    HostSupervisor,
)

from raft_ncup_tpu.fleet.replica import (  # noqa: F401
    ChildProcess,
    ReplicaHandle,
    ReplicaSupervisor,
    healthz_fresh,
    read_healthz,
)
from raft_ncup_tpu.fleet.router import FleetRouter, replay_fleet  # noqa: F401
from raft_ncup_tpu.fleet.topology import (  # noqa: F401
    FleetConfig,
    ReplicaSpec,
    padded_shape,
)
from raft_ncup_tpu.fleet.wire import (  # noqa: F401
    Transport,
    recv_msg,
    send_msg,
)

__all__ = [
    "ChildProcess",
    "FleetAutoscaler",
    "FleetConfig",
    "FleetManager",
    "FleetRouter",
    "HostSupervisor",
    "Transport",
    "ReplicaHandle",
    "ReplicaSpec",
    "ReplicaSupervisor",
    "healthz_fresh",
    "padded_shape",
    "read_healthz",
    "recv_msg",
    "replay_fleet",
    "send_msg",
]
