"""Socket frame protocol between the router and a replica server:
length-prefixed JSON header + raw C-order ndarray payloads over a Unix
domain socket or a TCP connection (docs/FLEET.md "Wire format").

One frame is::

    u32 big-endian header length
    header JSON (utf-8): {"kind": ..., ..., "arrays": [
        {"shape": [...], "dtype": "<numpy dtype str>"}, ...]}
    for each entry of header["arrays"]: that array's raw C-order bytes

JSON carries the control fields a human can read in a pcap; the pixel
payloads ride as raw bytes because base64-ing megabytes of frames into
JSON would triple the router's copy costs. The receiver wraps each
payload with ``np.frombuffer`` (zero-copy, read-only — every consumer
downstream stages/copies anyway).

**Schema evolution contract**: every field beyond ``kind`` is OPTIONAL
— in particular the cross-process trace context under ``TRACE_KEY``
(``observability.spans.TraceContext.to_wire``). An old replica must
parse a new router's frames (it ignores the key) and a new replica an
old router's (``TraceContext.from_wire(header.get(TRACE_KEY))`` is
``None``); consumers therefore read it with ``.get``, never a
subscript — lint rule JGL010 checks that statically for ``fleet/``.

**Addressing** (:class:`Transport`): an address string is either a
filesystem path (Unix domain socket — anything containing a path
separator, or lacking a ``host:port`` shape) or ``host:port`` (TCP).
The frame protocol is family-agnostic; what the INET family adds is
failure modes the LAN owns and the loopback never shows:

- a connect can hang on an unroutable host → :meth:`Transport.connect`
  bounds it with a timeout;
- a peer can vanish without a FIN (host partition, agent SIGKILL) and
  leave the connection half-open — ``SO_KEEPALIVE`` is armed on every
  TCP socket, and a read deadline (:func:`set_read_timeout`, raw
  ``SO_RCVTIMEO`` so sends stay governed by their own ``SO_SNDTIMEO``)
  turns eternal silence into a timeout the caller can probe on;
- a slow-loris peer can dribble a frame forever — a read timeout that
  fires MID-frame raises ``ConnectionError`` (the frame can never be
  trusted; same contract as a mid-frame EOF), while one that fires at a
  frame BOUNDARY raises :class:`FrameTimeout` (the link is merely
  idle; the router's link reader answers it with a ping probe).

Clean-EOF vs mid-frame semantics are identical across families and
pinned for both in tests/test_fleet.py.

Host-only stdlib + numpy (JGL010 covers ``fleet/``): the wire layer
must never be able to touch a device array — producers hand it host
ndarrays that were pulled at their own sanctioned boundaries.
"""

from __future__ import annotations

import errno
import json
import os
import socket
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Sanity bound on a single header (a corrupt length prefix must fail
# loudly, not allocate gigabytes).
MAX_HEADER_BYTES = 1 << 20

# The OPTIONAL trace-context header field (see the schema-evolution
# contract above): request frames may carry a serialized TraceContext
# here; response frames may echo {"trace_id": ...}.
TRACE_KEY = "trace"

_LEN = struct.Struct(">I")

# Default bound on a TCP connect (an unroutable host must fail in
# seconds, not kernel-default minutes); FleetConfig overrides per fleet.
DEFAULT_CONNECT_TIMEOUT_S = 10.0


class FrameTimeout(TimeoutError):
    """A read deadline fired at a frame BOUNDARY: the peer simply has
    nothing to say (or is half-open — the caller cannot tell yet, which
    is exactly why the router's link reader answers this with a ping
    probe: a half-open peer fails the send and the normal down path
    flushes). A deadline that fires MID-frame is ``ConnectionError``
    instead — that frame can never be trusted."""


class Transport:
    """One parsed wire address: where a replica (or host agent)
    listens, family included. ``host:port`` (port all digits, no path
    separator) is TCP; anything else is a Unix-domain-socket path.

    The parse is deliberately syntactic — the same string that appears
    in ``FleetConfig``-derived argv (``serve.py --replica_socket``)
    decides the family on both ends, so a topology is moved from UDS to
    TCP by changing addresses, nothing else.
    """

    __slots__ = ("family", "path", "host", "port")

    def __init__(self, family: int, path: str = "",
                 host: str = "", port: int = 0):
        self.family = family
        self.path = path
        self.host = host
        self.port = port

    @classmethod
    def parse(cls, address: str) -> "Transport":
        if not address:
            raise ValueError("empty wire address")
        host, sep, port = address.rpartition(":")
        if sep and host and port.isdigit() and os.sep not in address:
            return cls(socket.AF_INET, host=host, port=int(port))
        return cls(socket.AF_UNIX, path=address)

    @property
    def is_inet(self) -> bool:
        return self.family == socket.AF_INET

    def render(self) -> str:
        return f"{self.host}:{self.port}" if self.is_inet else self.path

    def connect(
        self, timeout_s: Optional[float] = DEFAULT_CONNECT_TIMEOUT_S,
    ) -> socket.socket:
        """Open a connected stream socket to this address. The connect
        itself is bounded by ``timeout_s``; the returned socket is back
        in blocking mode (read deadlines are the caller's policy —
        :func:`set_read_timeout`). TCP sockets get ``SO_KEEPALIVE`` +
        ``TCP_NODELAY`` (frames are latency-bound request/response
        pairs, never throughput-bound streams worth Nagle-batching)."""
        sock = socket.socket(self.family, socket.SOCK_STREAM)
        try:
            sock.settimeout(timeout_s)
            if self.is_inet:
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1
                )
                sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                sock.connect((self.host, self.port))
            else:
                sock.connect(self.path)
            sock.settimeout(None)
        except BaseException:
            sock.close()
            raise
        return sock

    def listen(self, backlog: int = 16) -> socket.socket:
        """Bind + listen on this address. A stale UDS path from a dead
        incarnation is removed first; TCP binds with ``SO_REUSEADDR``
        so a restarted replica is not locked out by its predecessor's
        TIME_WAIT."""
        sock = socket.socket(self.family, socket.SOCK_STREAM)
        try:
            if self.is_inet:
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
                )
                sock.bind((self.host, self.port))
            else:
                try:
                    os.remove(self.path)
                except OSError:
                    pass
                sock.bind(self.path)
            sock.listen(backlog)
        except BaseException:
            sock.close()
            raise
        return sock

    def cleanup(self) -> None:
        """Remove the UDS path at teardown (no-op for TCP)."""
        if not self.is_inet:
            try:
                os.remove(self.path)
            except OSError:
                pass


def set_read_timeout(
    sock: socket.socket, timeout_s: Optional[float],
) -> None:
    """Arm a receive deadline as raw ``SO_RCVTIMEO`` — NOT
    ``settimeout()``, which would flip the fd non-blocking and bound
    sends too; the router's links already bound sends separately with
    ``SO_SNDTIMEO`` and share one socket between a sender and a reader
    thread. A deadline that fires surfaces in :func:`recv_msg` as
    :class:`FrameTimeout` (frame boundary) or ``ConnectionError``
    (mid-frame)."""
    t = 0.0 if timeout_s is None else max(0.0, float(timeout_s))
    sec = int(t)
    usec = int(round((t - sec) * 1e6))
    sock.setsockopt(
        socket.SOL_SOCKET, socket.SO_RCVTIMEO,
        struct.pack("ll", sec, usec),
    )


def send_msg(sock: socket.socket, header: dict,
             arrays: Sequence[np.ndarray] = ()) -> None:
    """Send one frame. ``header`` must not carry an ``arrays`` key of
    its own — the descriptor list is derived from ``arrays``."""
    if "arrays" in header:
        raise ValueError("header key 'arrays' is reserved for the wire")
    payloads = []
    descs = []
    for arr in arrays:
        if not isinstance(arr, np.ndarray):
            raise TypeError(
                f"wire payloads must be host ndarrays, got "
                f"{type(arr).__name__} (pull at the producer's "
                "sanctioned boundary first)"
            )
        payloads.append(arr.tobytes())  # C-order copy if non-contiguous
        descs.append({"shape": list(arr.shape), "dtype": str(arr.dtype)})
    blob = json.dumps({**header, "arrays": descs}).encode("utf-8")
    if len(blob) > MAX_HEADER_BYTES:
        raise ValueError(f"header too large: {len(blob)} bytes")
    sock.sendall(_LEN.pack(len(blob)) + blob + b"".join(payloads))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on clean EOF at a frame boundary
    (0 bytes read). A mid-frame EOF raises — a half message means the
    peer died mid-send and the frame must not be trusted. A read
    deadline (``settimeout`` or raw ``SO_RCVTIMEO``) that fires at 0
    bytes raises :class:`FrameTimeout` (idle link, probe-able); one
    that fires mid-read raises ``ConnectionError`` (slow-loris or
    half-open peer — the frame is as dead as a torn one)."""
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except OSError as e:
            if isinstance(e, socket.timeout) or e.errno in (
                errno.EAGAIN, errno.EWOULDBLOCK,
            ):
                if got == 0:
                    raise FrameTimeout(
                        "no bytes within the read deadline"
                    ) from e
                raise ConnectionError(
                    f"read deadline mid-frame ({got}/{n} bytes): "
                    "slow-loris or half-open peer"
                ) from e
            raise
        if not chunk:
            if got == 0:
                return None
            raise ConnectionError(
                f"peer closed mid-frame ({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(
    sock: socket.socket,
) -> Optional[Tuple[dict, List[np.ndarray]]]:
    """Receive one frame; ``None`` on clean EOF (peer closed between
    frames). Returns ``(header, arrays)`` with the descriptor list
    stripped back off the header."""
    raw_len = _recv_exact(sock, _LEN.size)
    if raw_len is None:
        return None
    (n,) = _LEN.unpack(raw_len)
    if n > MAX_HEADER_BYTES:
        raise ValueError(f"frame header length {n} exceeds bound")
    try:
        blob = _recv_exact(sock, n)
        if blob is None:
            raise ConnectionError(
                "peer closed between length and header"
            )
        header = json.loads(blob.decode("utf-8"))
        descs = header.pop("arrays", [])
        arrays: List[np.ndarray] = []
        for d in descs:
            dtype = np.dtype(d["dtype"])
            shape = tuple(int(x) for x in d["shape"])
            count = 1
            for x in shape:
                count *= x
            payload = _recv_exact(sock, count * dtype.itemsize)
            if payload is None:
                raise ConnectionError("peer closed before array payload")
            arrays.append(
                np.frombuffer(payload, dtype=dtype).reshape(shape)
            )
    except FrameTimeout as e:
        # The length prefix landed, so the frame has STARTED: a read
        # deadline anywhere past it is mid-frame by definition, even if
        # an individual _recv_exact saw 0 of its own bytes.
        raise ConnectionError(
            f"read deadline mid-frame (after length prefix): {e}"
        ) from e
    return header, arrays
