"""Socket frame protocol between the router and a replica server:
length-prefixed JSON header + raw C-order ndarray payloads over a Unix
domain socket (docs/FLEET.md "Wire format").

One frame is::

    u32 big-endian header length
    header JSON (utf-8): {"kind": ..., ..., "arrays": [
        {"shape": [...], "dtype": "<numpy dtype str>"}, ...]}
    for each entry of header["arrays"]: that array's raw C-order bytes

JSON carries the control fields a human can read in a pcap; the pixel
payloads ride as raw bytes because base64-ing megabytes of frames into
JSON would triple the router's copy costs. The receiver wraps each
payload with ``np.frombuffer`` (zero-copy, read-only — every consumer
downstream stages/copies anyway).

**Schema evolution contract**: every field beyond ``kind`` is OPTIONAL
— in particular the cross-process trace context under ``TRACE_KEY``
(``observability.spans.TraceContext.to_wire``). An old replica must
parse a new router's frames (it ignores the key) and a new replica an
old router's (``TraceContext.from_wire(header.get(TRACE_KEY))`` is
``None``); consumers therefore read it with ``.get``, never a
subscript — lint rule JGL010 checks that statically for ``fleet/``.

Host-only stdlib + numpy (JGL010 covers ``fleet/``): the wire layer
must never be able to touch a device array — producers hand it host
ndarrays that were pulled at their own sanctioned boundaries.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Sanity bound on a single header (a corrupt length prefix must fail
# loudly, not allocate gigabytes).
MAX_HEADER_BYTES = 1 << 20

# The OPTIONAL trace-context header field (see the schema-evolution
# contract above): request frames may carry a serialized TraceContext
# here; response frames may echo {"trace_id": ...}.
TRACE_KEY = "trace"

_LEN = struct.Struct(">I")


def send_msg(sock: socket.socket, header: dict,
             arrays: Sequence[np.ndarray] = ()) -> None:
    """Send one frame. ``header`` must not carry an ``arrays`` key of
    its own — the descriptor list is derived from ``arrays``."""
    if "arrays" in header:
        raise ValueError("header key 'arrays' is reserved for the wire")
    payloads = []
    descs = []
    for arr in arrays:
        if not isinstance(arr, np.ndarray):
            raise TypeError(
                f"wire payloads must be host ndarrays, got "
                f"{type(arr).__name__} (pull at the producer's "
                "sanctioned boundary first)"
            )
        payloads.append(arr.tobytes())  # C-order copy if non-contiguous
        descs.append({"shape": list(arr.shape), "dtype": str(arr.dtype)})
    blob = json.dumps({**header, "arrays": descs}).encode("utf-8")
    if len(blob) > MAX_HEADER_BYTES:
        raise ValueError(f"header too large: {len(blob)} bytes")
    sock.sendall(_LEN.pack(len(blob)) + blob + b"".join(payloads))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on clean EOF at a frame boundary
    (0 bytes read). A mid-frame EOF raises — a half message means the
    peer died mid-send and the frame must not be trusted."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ConnectionError(
                f"peer closed mid-frame ({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(
    sock: socket.socket,
) -> Optional[Tuple[dict, List[np.ndarray]]]:
    """Receive one frame; ``None`` on clean EOF (peer closed between
    frames). Returns ``(header, arrays)`` with the descriptor list
    stripped back off the header."""
    raw_len = _recv_exact(sock, _LEN.size)
    if raw_len is None:
        return None
    (n,) = _LEN.unpack(raw_len)
    if n > MAX_HEADER_BYTES:
        raise ValueError(f"frame header length {n} exceeds bound")
    blob = _recv_exact(sock, n)
    if blob is None:
        raise ConnectionError("peer closed between length and header")
    header = json.loads(blob.decode("utf-8"))
    descs = header.pop("arrays", [])
    arrays: List[np.ndarray] = []
    for d in descs:
        dtype = np.dtype(d["dtype"])
        shape = tuple(int(x) for x in d["shape"])
        count = 1
        for x in shape:
            count *= x
        payload = _recv_exact(sock, count * dtype.itemsize)
        if payload is None:
            raise ConnectionError("peer closed before array payload")
        arrays.append(np.frombuffer(payload, dtype=dtype).reshape(shape))
    return header, arrays
