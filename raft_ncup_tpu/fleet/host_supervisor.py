"""Multi-host fleet control plane: one agent per named host, one
manager beside the router (docs/FLEET.md "Hosts").

The UDS fleet of PR 13 is one supervisor and N replicas on one machine.
A TCP fleet spreads the replicas over named hosts, and the split this
module implements is the smallest one that keeps every PR 13 contract
intact:

- :class:`HostSupervisor` — the per-host AGENT. It wraps the
  UNMODIFIED :class:`~raft_ncup_tpu.fleet.replica.ReplicaSupervisor`
  (spawn/healthz-staleness/drain/restart/circuit-breaker all reused,
  not re-implemented) around the replica slots its manifest places on
  this host, and REPUBLISHES their healthz over the wire — healthz
  files are host-local by design, so a remote manager can only see
  them through the agent. The agent is driven by a JSON manifest
  (:meth:`FleetConfig.host_manifest`) instead of the full FleetConfig:
  a host reconstructs only what it supervises.
- :class:`FleetManager` — the router-side view of the whole fleet. It
  spawns one agent per host (through the same :class:`ChildProcess`
  every other multi-process harness uses), polls each agent's control
  endpoint for the republished healthz, and mirrors the results into
  ordinary :class:`ReplicaHandle` objects — so ``FleetRouter`` and
  ``FleetAutoscaler`` run against a multi-host fleet unmodified (the
  manager duck-types the supervisor surface they read: ``replicas``,
  ``handle(i)``, ``add_replica``/``remove_replica``, ``_on_death``).

The fleet-level staleness contract is the per-replica one lifted one
level: a host whose agent has not successfully republished within
``stale_after_s`` is presumed DEAD — partitioned, agent-killed, or
wedged, the manager cannot tell and must not care. Every replica
placed there is declared dead (router failover fires through the same
``on_death`` hook as a local death), and the host is FENCED: the last
republished snapshot carries the replica pids, and the manager
SIGKILLs them (plus the agent child) so a replica on the far side of a
healed partition can never answer a request the router already
re-dispatched. Chaos drives exactly these paths: ``partitionhost@N``
(:meth:`FleetManager.partition` — both link directions drop, staleness
does the rest) and ``killsupervisor@N`` (:meth:`FleetManager.kill_agent`
— the agent dies, its replicas linger as orphans until the reap).

Host-only stdlib (JGL010 covers ``fleet/``): agents and the manager
move JSON frames and signals; neither can touch a device array.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from raft_ncup_tpu.fleet import wire
from raft_ncup_tpu.fleet.replica import (
    DEAD,
    SPAWNING,
    UP,
    ChildProcess,
    ReplicaHandle,
    ReplicaSupervisor,
)
from raft_ncup_tpu.fleet.topology import FleetConfig, ReplicaSpec

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class ManifestConfig:
    """Adapter: a :meth:`FleetConfig.host_manifest` dict presented as
    the config surface :class:`ReplicaSupervisor` reads — ``replica(i)``
    / ``replica_argv(i)`` / the supervision scalars. The agent process
    never holds a FleetConfig; its manifest names only its own slots,
    and this adapter is what keeps the supervisor itself unmodified."""

    def __init__(self, manifest: dict):
        self._m = manifest
        self.base_dir = manifest["base_dir"]
        self.poll_interval_s = float(manifest["poll_interval_s"])
        self.spawn_timeout_s = float(manifest["spawn_timeout_s"])
        self.drain_timeout_s = float(manifest["drain_timeout_s"])
        self.snapshot_interval_s = float(manifest["snapshot_interval_s"])
        self.stale_after_s = float(manifest["stale_after_s"])
        self.max_restarts = int(manifest["max_restarts"])
        self.restart_backoff_s = float(manifest["restart_backoff_s"])
        self.restart_backoff_max_s = float(manifest["restart_backoff_max_s"])
        self.circuit_break_after = int(manifest["circuit_break_after"])
        self._slots: Dict[int, dict] = {
            int(r["index"]): r for r in manifest["replicas"]
        }
        self.n_replicas = len(self._slots)

    @property
    def host(self) -> str:
        return self._m.get("host", "")

    @property
    def control(self) -> str:
        return self._m["control"]

    def start_indices(self) -> List[int]:
        """The slots that spawn at agent startup (``n_replicas`` of the
        fleet topology); the rest are declared scale-up capacity."""
        return sorted(i for i, r in self._slots.items() if r.get("start"))

    def all_indices(self) -> List[int]:
        return sorted(self._slots)

    def replica(self, i: int) -> ReplicaSpec:
        r = self._slots[i]
        return ReplicaSpec(
            index=i,
            socket_path=r["socket_path"],
            healthz_path=r["healthz_path"],
            flight_dir=r["flight_dir"],
            address=r["address"],
            host=self.host,
        )

    def replica_argv(self, i: int) -> list:
        return list(self._slots[i]["argv"])


class HostSupervisor:
    """The per-host agent: an unmodified ReplicaSupervisor over this
    host's slots, plus a wire control server at ``manifest.control``.

    Control frames (JSON, no array payloads; one reply per request):

    - ``{"kind": "ping"}`` → ``{"kind": "pong", "host": ...}``
    - ``{"kind": "healthz"}`` → the republish: every supervised slot's
      supervisor snapshot + last healthz payload + pid, stamped with
      the agent's ``time_unix_s`` (the fleet-level staleness clock)
    - ``{"kind": "spawn", "index": i}`` → ``add_replica(i)``
    - ``{"kind": "drain", "index": i}`` → ``remove_replica(i)``
      (graceful: the PR 13 drain contract, run host-locally)
    - ``{"kind": "stop"}`` → drain everything and shut the agent down
    """

    def __init__(
        self, manifest: dict, *,
        argv_prefix: Optional[List[str]] = None,
        env: Optional[dict] = None,
        telemetry=None,
    ):
        self.cfg = ManifestConfig(manifest)
        self.sup = ReplicaSupervisor(
            self.cfg,  # type: ignore[arg-type]  # duck-typed adapter
            argv_prefix=argv_prefix,
            env=env,
            telemetry=telemetry,
            indices=self.cfg.start_indices(),
        )
        self._transport = wire.Transport.parse(self.cfg.control)
        self._lsock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._conn_threads: List[threading.Thread] = []

    # ----------------------------------------------------------- serving

    def start(self, wait_ready: bool = True) -> "HostSupervisor":
        self.sup.start(wait_ready=wait_ready)
        self._lsock = self._transport.listen(16)
        self._lsock.settimeout(0.2)
        t = threading.Thread(
            target=self._accept_loop,
            name=f"host-agent-{self.cfg.host or 'local'}",
            daemon=True,
        )
        t.start()
        self._accept_thread = t
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed at stop()
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="host-agent-conn", daemon=True,
            )
            t.start()
            self._conn_threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                while not self._stop.is_set():
                    msg = wire.recv_msg(conn)
                    if msg is None:
                        return
                    header, _ = msg
                    reply = self._handle(header)
                    wire.send_msg(conn, reply)
                    if header.get("kind") == "stop":
                        return
        except (ConnectionError, OSError, ValueError) as e:
            # A torn control connection is the MANAGER'S failure to
            # observe, not the agent's failure to serve — log and keep
            # supervising.
            print(f"host agent conn error: {e!r}", file=sys.stderr)

    def _handle(self, header: dict) -> dict:
        kind = header.get("kind")
        if kind == "ping":
            return {"kind": "pong", "host": self.cfg.host}
        if kind == "healthz":
            return self.republish()
        if kind == "spawn":
            raw_index = header.get("index")
            if raw_index is None:
                return {"kind": "error", "op": "spawn",
                        "error": "spawn frame missing 'index'"}
            i = int(raw_index)
            try:
                self.sup.add_replica(i, wait_ready=False)
                return {"kind": "ok", "op": "spawn", "index": i}
            except (ValueError, OSError) as e:
                return {"kind": "error", "op": "spawn", "index": i,
                        "error": repr(e)}
            except KeyError as e:
                return {"kind": "error", "op": "spawn", "index": i,
                        "error": f"slot not in manifest: {e!r}"}
        if kind == "drain":
            raw_index = header.get("index")
            if raw_index is None:
                return {"kind": "error", "op": "drain",
                        "error": "drain frame missing 'index'"}
            i = int(raw_index)
            try:
                result = self.sup.remove_replica(i, drain=True)
                return {"kind": "ok", "op": "drain", "index": i,
                        "returncode": result.get("returncode")}
            except KeyError as e:
                return {"kind": "error", "op": "drain", "index": i,
                        "error": repr(e)}
        if kind == "stop":
            self._stop.set()
            return {"kind": "ok", "op": "stop"}
        return {"kind": "error", "error": f"unknown control kind {kind!r}"}

    def republish(self) -> dict:
        """The wire republish: what a remote manager knows about this
        host. Every field a consumer reads with ``.get`` (the wire
        schema-evolution contract)."""
        replicas = {}
        with self.sup._lock:
            handles = list(self.sup.replicas)
        for h in handles:
            replicas[str(h.index)] = {
                **h.snapshot(),
                "healthz": h.last_healthz,
            }
        return {
            "kind": "healthz",
            "host": self.cfg.host,
            "time_unix_s": time.time(),
            "replicas": replicas,
        }

    def run(self) -> Dict[int, dict]:
        """Serve until a ``stop`` control frame or SIGTERM, then drain
        everything (the agent's own drain contract: its replicas exit
        75 before the agent does). Returns the final reports."""
        signal.signal(signal.SIGTERM, lambda *_: self._stop.set())
        while not self._stop.wait(0.2):
            pass
        return self.stop()

    def stop(self, drain: bool = True) -> Dict[int, dict]:
        self._stop.set()
        if self._lsock is not None:
            self._lsock.close()
            self._transport.cleanup()
        return self.sup.stop(drain=drain)


class FleetManager:
    """The router-side control plane of a multi-host fleet: spawns one
    :class:`HostSupervisor` agent per named host, mirrors their wire
    republishes into local :class:`ReplicaHandle` objects, and enforces
    the FLEET-level staleness contract (silent host ⇒ dead host ⇒
    fence + failover). Duck-types the supervisor surface ``FleetRouter``
    and ``FleetAutoscaler`` read."""

    def __init__(
        self,
        cfg: FleetConfig,
        *,
        argv_prefix: Optional[List[str]] = None,
        env: Optional[dict] = None,
        on_death: Optional[Callable[[int, str], None]] = None,
        telemetry=None,
    ):
        from raft_ncup_tpu.observability import get_telemetry

        if not cfg.hosts:
            raise ValueError(
                "FleetManager needs named hosts (single-host fleets "
                "use ReplicaSupervisor directly)"
            )
        self.cfg = cfg
        self._argv_prefix = argv_prefix
        self._env = env
        self._on_death = on_death
        self._tel = telemetry if telemetry is not None else get_telemetry()
        self._lock = threading.RLock()
        self.replicas: List[ReplicaHandle] = [
            ReplicaHandle(cfg.replica(i)) for i in range(cfg.n_replicas)
        ]
        self.retired: List[ReplicaHandle] = []
        self.agents: Dict[str, ChildProcess] = {}
        self._last_heard: Dict[str, float] = {}  # host -> monotonic
        self._heard_once: set = set()  # hosts that have republished
        self._last_snapshot: Dict[str, dict] = {}  # host -> republish
        self._partitioned: set = set()
        self._dead_hosts: set = set()
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- handles

    def handle(self, i: int) -> ReplicaHandle:
        with self._lock:
            for h in self.replicas:
                if h.index == i:
                    return h
        raise KeyError(f"no live replica handle for index {i}")

    def host_of(self, i: int) -> str:
        return self.cfg.host_of(i)

    # ------------------------------------------------------------- spawn

    def start(self, wait_ready: bool = True) -> "FleetManager":
        os.makedirs(self.cfg.base_dir, exist_ok=True)
        for host in self.cfg.hosts:
            manifest = self.cfg.host_manifest(host)
            path = os.path.join(
                self.cfg.base_dir, f"host_{host}.manifest.json"
            )
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(manifest, fh, indent=2)
            argv = [
                sys.executable, "-m",
                "raft_ncup_tpu.fleet.host_supervisor",
                "--manifest", path,
            ]
            if self._argv_prefix is not None:
                argv += ["--replica_argv_prefix",
                         json.dumps(self._argv_prefix)]
            self.agents[host] = ChildProcess(
                argv, name=f"host-agent-{host}", env=self._env,
                cwd=_REPO_ROOT,
            ).spawn()
            self._last_heard[host] = time.monotonic()
            self._tel.event(
                "fleet_host_agent_spawned", host=host,
                pid=self.agents[host].pid,
            )
        if wait_ready:
            self.wait_ready()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-manager", daemon=True
        )
        self._poll_thread.start()
        return self

    def wait_ready(self, timeout: Optional[float] = None) -> None:
        """Block until every initially-started replica republishes UP
        (the agents run the real READY gates; the manager only needs to
        hear about it)."""
        deadline = time.monotonic() + (
            self.cfg.spawn_timeout_s if timeout is None else timeout
        )
        with self._lock:
            pending = {h.index for h in self.replicas}
        while pending:
            for host in self.cfg.hosts:
                agent = self.agents.get(host)
                if agent is not None and not agent.running:
                    rc, out, err = agent.reap(timeout=5.0)
                    self.stop(drain=False)
                    raise RuntimeError(
                        f"host agent {host!r} died during warmup "
                        f"(rc={rc}):\n{err[-2000:]}"
                    )
                self._poll_host(host)
            with self._lock:
                pending = {
                    h.index for h in self.replicas if h.state != UP
                }
            if not pending:
                return
            if time.monotonic() > deadline:
                self.stop(drain=False)
                raise TimeoutError(
                    f"replicas {sorted(pending)} not republished ready "
                    f"within {self.cfg.spawn_timeout_s}s"
                )
            time.sleep(self.cfg.poll_interval_s)

    # ----------------------------------------------------------- polling

    def _agent_call(self, host: str, header: dict,
                    timeout_s: float = 5.0) -> Optional[dict]:
        """One control request/reply to ``host``'s agent; None on any
        wire failure (the staleness clock, not the caller, decides what
        silence means)."""
        if host in self._partitioned:
            return None
        try:
            transport = wire.Transport.parse(
                self.cfg.host_control_address(host)
            )
            sock = transport.connect(timeout_s=timeout_s)
            try:
                wire.set_read_timeout(sock, timeout_s)
                wire.send_msg(sock, header)
                msg = wire.recv_msg(sock)
            finally:
                sock.close()
            return None if msg is None else msg[0]
        except (ConnectionError, OSError, ValueError) as e:
            self._tel.event(
                "fleet_host_agent_unreachable", host=host, error=repr(e)
            )
            return None

    def _poll_host(self, host: str) -> None:
        with self._lock:
            if host in self._dead_hosts:
                return
        reply = self._agent_call(host, {"kind": "healthz"})
        now = time.monotonic()
        if reply is not None and reply.get("kind") == "healthz":
            self._last_heard[host] = now
            self._heard_once.add(host)
            self._last_snapshot[host] = reply
            self._mirror(host, reply)
            return
        # Fleet-level staleness: steady-state silence past the
        # per-replica bound ⇒ dead host. A host that has NEVER
        # republished is still booting its agent (Python startup alone
        # beats a sub-second staleness bound) and gets the spawn bound
        # instead — warmup failures surface through wait_ready, which
        # watches the agent process itself.
        bound = (
            self.cfg.stale_after_s if host in self._heard_once
            else self.cfg.spawn_timeout_s
        )
        if now - self._last_heard.get(host, now) > bound:
            self._host_death(host, "fleet-level staleness: agent silent")

    def _mirror(self, host: str, republish: dict) -> None:
        """Fold one republish into the local handles. Supervisor-side
        states travel verbatim (the agent already ran the per-replica
        staleness/restart/breaker contracts); the manager adds only the
        fleet-level view."""
        snaps = republish.get("replicas") or {}
        with self._lock:
            for h in self.replicas:
                if self.cfg.host_of(h.index) != host:
                    continue
                snap = snaps.get(str(h.index))
                if snap is None:
                    continue
                prev = h.state
                h.state = snap.get("state", h.state)
                h.circuit_open = bool(snap.get("circuit_open"))
                h.restarts = int(snap.get("restarts", h.restarts))
                h.deaths = int(snap.get("deaths", h.deaths))
                h.stale_deaths = int(
                    snap.get("stale_deaths", h.stale_deaths)
                )
                hz = snap.get("healthz")
                if hz is not None:
                    h.last_healthz = hz
                h.remote_pid = snap.get("pid")
                if prev not in (DEAD,) and h.state == DEAD:
                    # The agent detected the death; the router still
                    # needs its failover hook fired HERE, where the
                    # pending set lives.
                    if self._on_death is not None:
                        self._on_death(h.index, "republished death")

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.cfg.poll_interval_s):
            try:
                for host in list(self.cfg.hosts):
                    self._poll_host(host)
            except Exception as e:
                # Observation must be visible, never fatal (JGL007).
                self._tel.event(
                    "fleet_manager_poll_error", error=repr(e)
                )
                print(f"fleet manager poll error: {e!r}", file=sys.stderr)

    def poll(self) -> None:
        """One synchronous supervision pass (deterministic tests)."""
        for host in list(self.cfg.hosts):
            self._poll_host(host)

    # ------------------------------------------------- fleet-level deaths

    def _host_death(self, host: str, reason: str) -> None:
        """The fleet-level staleness contract: declare every replica on
        ``host`` dead, FENCE the host (SIGKILL the lingering pids from
        its last republish + the agent child — a zombie on the far side
        of a healed partition must never answer a re-dispatched
        request), and fire the router's failover hook."""
        with self._lock:
            if host in self._dead_hosts:
                return
            self._dead_hosts.add(host)
        self._tel.event("fleet_host_death", host=host, reason=reason)
        print(f"fleet: host {host!r} dead ({reason})", file=sys.stderr)
        self._fence(host)
        with self._lock:
            victims = [
                h for h in self.replicas
                if self.cfg.host_of(h.index) == host
                and h.state not in (DEAD,)
            ]
            for h in victims:
                h.state = DEAD
                h.deaths += 1
        for h in victims:
            self._tel.event(
                "fleet_replica_death", replica=h.index,
                reason=f"host {host}: {reason}",
            )
            if self._on_death is not None:
                self._on_death(h.index, reason)

    def _fence(self, host: str) -> None:
        snapshot = self._last_snapshot.get(host) or {}
        pids = []
        for snap in (snapshot.get("replicas") or {}).values():
            pid = snap.get("pid")
            if isinstance(pid, int):
                pids.append(pid)
        agent = self.agents.get(host)
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass  # already gone — fencing is idempotent
        if agent is not None and agent.running:
            agent.kill()
            agent.wait(timeout=10.0)
        self._tel.event(
            "fleet_host_fenced", host=host, replica_pids=pids,
        )

    # --------------------------------------------------------- chaos hooks

    def partition(self, host: str) -> None:
        """Chaos ``partitionhost``: drop the control link to ``host``
        (the manager stops hearing it — and refuses reconnects, which
        is what "both directions" means for a poll-driven link). The
        staleness contract takes it from here: silence past
        ``stale_after_s`` ⇒ host death ⇒ fence ⇒ failover."""
        self._tel.event("fleet_chaos_partition_host", host=host)
        self._partitioned.add(host)

    def kill_agent(self, host: str) -> None:
        """Chaos ``killsupervisor``: SIGKILL the agent; its replicas
        linger as orphans (still heartbeating their host-local files,
        which nobody republishes anymore). Detection and reaping ride
        the same staleness → fence path as a partition."""
        self._tel.event("fleet_chaos_kill_agent", host=host)
        agent = self.agents.get(host)
        if agent is not None:
            agent.kill()
            agent.wait(timeout=10.0)

    # ------------------------------------------------- elastic forwarding

    def add_replica(self, i: int, wait_ready: bool = False,
                    timeout: Optional[float] = None) -> ReplicaHandle:
        """Scale-up slot ``i``: forwarded to its host's agent; the
        local handle mirrors SPAWNING until the republish promotes it."""
        host = self.cfg.host_of(i)
        with self._lock:
            for h in self.replicas:
                if h.index == i:
                    raise ValueError(
                        f"replica slot {i} already managed "
                        f"(state={h.state})"
                    )
            handle = ReplicaHandle(self.cfg.replica(i))
            handle.state = SPAWNING
            self.replicas.append(handle)
        reply = self._agent_call(host, {"kind": "spawn", "index": i})
        if reply is None or reply.get("kind") != "ok":
            with self._lock:
                self.replicas = [
                    h for h in self.replicas if h.index != i
                ]
            raise RuntimeError(
                f"scale-up spawn of slot {i} on host {host!r} failed: "
                f"{reply!r}"
            )
        self._tel.event("fleet_scale_up_spawn", replica=i, host=host)
        if wait_ready:
            deadline = time.monotonic() + (
                self.cfg.spawn_timeout_s if timeout is None else timeout
            )
            while handle.state == SPAWNING:
                self._poll_host(host)
                if handle.state != SPAWNING:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"scale-up replica {i} not republished ready "
                        f"within {self.cfg.spawn_timeout_s}s"
                    )
                time.sleep(self.cfg.poll_interval_s)
        return handle

    def remove_replica(self, i: int, drain: bool = True) -> dict:
        """Scale-down slot ``i``: the DRAIN RUNS ON THE HOST (the agent
        owns the SIGTERM → DRAINING → exit-75 contract); the manager
        retires its mirror handle when the agent reports back."""
        host = self.cfg.host_of(i)
        handle = self.handle(i)
        reply = self._agent_call(
            host, {"kind": "drain", "index": i},
            timeout_s=self.cfg.drain_timeout_s,
        )
        with self._lock:
            self.replicas = [h for h in self.replicas if h.index != i]
            self.retired.append(handle)
        self._tel.event(
            "fleet_scale_down_retired", replica=i, host=host,
            returncode=None if reply is None else reply.get("returncode"),
        )
        return reply or {"observed_draining": False, "returncode": None}

    # ----------------------------------------------------------- teardown

    def stop(self, drain: bool = True) -> Dict[str, Optional[dict]]:
        self._poll_stop.set()
        if self._poll_thread is not None and self._poll_thread.is_alive():
            self._poll_thread.join(timeout=10.0)
        results: Dict[str, Optional[dict]] = {}
        with self._lock:
            dead_hosts = set(self._dead_hosts)
        for host, agent in self.agents.items():
            if host not in dead_hosts and drain:
                results[host] = self._agent_call(
                    host, {"kind": "stop"},
                    timeout_s=self.cfg.drain_timeout_s,
                )
                agent.wait(timeout=self.cfg.drain_timeout_s)
            if agent.running:
                agent.kill()
            agent.reap(timeout=10.0)
            # Belt and braces: any replica pid the last republish knew
            # about must not outlive the fleet.
            self._fence_quietly(host)
        return results

    def _fence_quietly(self, host: str) -> None:
        for snap in (
            (self._last_snapshot.get(host) or {}).get("replicas") or {}
        ).values():
            pid = snap.get("pid")
            if isinstance(pid, int):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    pass

    def report(self) -> dict:
        with self._lock:
            snaps = [h.snapshot() for h in self.replicas]
            retired = [h.snapshot() for h in self.retired]
            dead_hosts = sorted(self._dead_hosts)
        return {
            "replicas": snaps,
            "retired": retired,
            "dead_hosts": dead_hosts,
            "partitioned_hosts": sorted(self._partitioned),
            "deaths": sum(s["deaths"] for s in snaps + retired),
            "stale_deaths": sum(
                s["stale_deaths"] for s in snaps + retired
            ),
        }

    def __enter__(self) -> "FleetManager":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m raft_ncup_tpu.fleet.host_supervisor --manifest M``:
    run one host agent until stopped (control frame or SIGTERM)."""
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--manifest", required=True,
        help="Path to the host manifest JSON "
             "(FleetConfig.host_manifest).",
    )
    parser.add_argument(
        "--replica_argv_prefix", default=None,
        help="JSON list overriding the replica spawn prefix "
             "(tests substitute a fake serve.py).",
    )
    args = parser.parse_args(argv)
    with open(args.manifest, encoding="utf-8") as fh:
        manifest = json.load(fh)
    prefix = (
        None if args.replica_argv_prefix is None
        else json.loads(args.replica_argv_prefix)
    )
    agent = HostSupervisor(manifest, argv_prefix=prefix)
    agent.start(wait_ready=False)
    reports = agent.run()
    print(json.dumps({
        "kind": "host_agent_final", "host": agent.cfg.host,
        "replicas": {str(k): v for k, v in reports.items()},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
