"""Asynchronous inference/eval pipeline: decode-ahead, double-buffered
device staging, bounded shape-cached executables, and a non-blocking
device→host drain.

The eval loop's steady state mirrors the train loop's (docs/PERF.md):

- **decode ahead** (:class:`SamplePrefetcher`): a thread pool decodes
  dataset samples ``lookahead`` frames ahead of consumption, order
  preserved, with the same close/exception contract as
  ``data/device_prefetch.DevicePrefetcher`` — worker errors re-raise
  from the consumer's ``next()`` and ``close()`` cancels pending work
  (the old ``_prefetch_samples`` generator silently blocked on pool
  shutdown when abandoned mid-validation and never surfaced decode
  errors until ``.result()``).
- **stage + transfer ahead** (:class:`EvalPipeline`): host batching /
  padding runs on the DevicePrefetcher's worker thread and the staged
  batch moves to device ``depth`` batches ahead of compute — the
  consumer's ``next()`` returns device-resident arrays.
- **compute** (:class:`ShapeCachedForward`): one compiled executable per
  (padded shape, iters, metric kind), bounded by an LRU (KITTI's shape
  diversity is further collapsed by pad bucketing —
  ``ops/padding.InputPadder(bucket=...)``). The metric variant folds
  ``inference/metrics.py`` into the SAME jitted program as the forward
  (``RAFT.apply(metric_head=...)``), so validation never materializes a
  full flow field on host.
- **drain** (:class:`AsyncDrain`): submissions still need full-field
  pulls; they happen on a worker thread behind dispatch — the window
  boundary's sanctioned ``jax.device_get``, moved off the hot loop.
- **bounded dispatch** (:class:`DispatchThrottle`): the number of
  in-flight compiled programs is capped per backend (1 on CPU, where
  queued programs execute concurrently on the shared host pool and
  destroy each other's intra-op parallelism; 2 on accelerators, whose
  serialized stream just wants to stay fed across dispatch gaps).

Run the whole loop under ``analysis/guards.py``
(``forbid_host_transfers`` + ``RecompileWatchdog``) and it inherits the
train loop's invariants: zero implicit host pulls, zero steady-state
recompiles (tests/test_inference_pipeline.py pins both; bench.py's
``val_*`` row records them).
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp

from raft_ncup_tpu.data.device_prefetch import DevicePrefetcher
from raft_ncup_tpu.inference import metrics as metrics_mod
from raft_ncup_tpu.inference.costs import get_cost_ledger
from raft_ncup_tpu.observability import get_telemetry
from raft_ncup_tpu.observability.telemetry import LEGACY_KEY_ALIASES
from raft_ncup_tpu.precision import resolve_policy

_EXEC_CANON = LEGACY_KEY_ALIASES["inference"]


def env_earlyexit_tol() -> Optional[float]:
    """Resolve the early-exit knobs (utils/knobs.py; docs/PERF.md "Early
    exit") to a tolerance, or None when detection is off. This is THE
    env chokepoint for early exit: the model layer takes an explicit
    ``early_exit_tol`` argument and never reads the environment, so
    compiled-program identity stays a pure function of call arguments.
    """
    from raft_ncup_tpu.utils.knobs import knob_flag, knob_float

    if not knob_flag("RAFT_NCUP_EARLYEXIT"):
        return None
    return knob_float("RAFT_NCUP_EARLYEXIT_TOL")


class SamplePrefetcher:
    """Decode dataset samples ahead of consumption, order-preserving.

    Contracts (aligned with ``DevicePrefetcher``):

    - order: samples come out exactly as ``dataset.sample(0..n-1)``;
    - exceptions: a decode error re-raises from the consumer's
      ``next()`` (after closing the pool);
    - close: cancels queued decodes and joins the pool; idempotent;
      called automatically on exhaustion and by the context manager, so
      an early-exiting consumer leaks no threads.
    """

    def __init__(self, dataset, num_workers: int = 4, lookahead: int = 8):
        self._ds = dataset
        self._n = len(dataset)
        self._pool = ThreadPoolExecutor(
            max(1, num_workers), thread_name_prefix="eval-decode"
        )
        self._futures: deque = deque()
        self._submitted = 0
        self._closed = False
        for _ in range(min(max(1, lookahead), self._n)):
            self._submit_next()

    def _submit_next(self) -> None:
        self._futures.append(
            self._pool.submit(self._ds.sample, self._submitted)
        )
        self._submitted += 1

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        if self._closed or not self._futures:
            self.close()
            raise StopIteration
        fut = self._futures.popleft()
        try:
            sample = fut.result()
        except BaseException:
            self.close()
            raise
        if self._submitted < self._n:
            self._submit_next()
        return sample

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for fut in self._futures:
            fut.cancel()
        self._futures.clear()
        # Queued work is cancelled above, so the join only waits for
        # decodes already in flight — bounded, not a full-epoch drain.
        self._pool.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "SamplePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def uniform_batches(
    samples: Iterable[dict], batch_size: int
) -> Iterator[list]:
    """Group an ordered sample stream into fixed-size same-shape batches.

    Emits a short group on shape change (KITTI's mixed native
    resolutions — pad bucketing upstream keeps those rare) and at stream
    end. Batching amortizes dispatch and fills the MXU; the reference
    evaluates strictly frame-by-frame (evaluate.py:98-104).
    """
    pending: list = []
    shape = None
    for s in samples:
        if shape is not None and s["image1"].shape != shape:
            if pending:
                yield pending
            pending = []
        shape = s["image1"].shape
        pending.append(s)
        if len(pending) == batch_size:
            yield pending
            pending = []
    if pending:
        yield pending


class EvalPipeline:
    """Double-buffered eval executor: decode → stage → transfer, all off
    the dispatch thread.

    ``stage_fn(group) -> (arrays, meta)`` turns a list of samples into a
    dict of host numpy arrays (stack + pad) plus a small host-side meta
    dict (pad spec, group size). Staging runs inside the
    DevicePrefetcher's worker thread, and the staged arrays are moved to
    device ``depth`` batches ahead — iterating yields
    ``(device_batch, meta)`` pairs whose alignment is guaranteed by the
    single-worker FIFO ordering.

    ``mesh``/``shardings`` forward to the DevicePrefetcher (same
    transfer policy as the train loop): under an SPMD eval mesh the
    worker thread device_puts each batch straight into the compiled
    program's input shardings, so jit dispatch does no re-layout — a
    default-device transfer would be resharded synchronously on the
    dispatch thread at every call, which is exactly the per-batch stall
    this pipeline exists to remove.

    Exceptions from decode or staging re-raise from ``next()``;
    ``close()`` (or the context manager) tears down both threads and the
    decode pool even mid-epoch.
    """

    def __init__(
        self,
        dataset,
        stage_fn: Callable[[list], tuple],
        *,
        batch_size: int = 1,
        depth: int = 2,
        num_workers: int = 4,
        lookahead: Optional[int] = None,
        mesh=None,
        shardings: Optional[dict] = None,
    ):
        self._sp = SamplePrefetcher(
            dataset,
            num_workers,
            lookahead or max(2 * batch_size, num_workers),
        )
        self._meta: deque = deque()
        sp, meta_q = self._sp, self._meta

        def staged():
            try:
                for group in uniform_batches(sp, batch_size):
                    arrays, meta = stage_fn(group)
                    meta_q.append(meta)
                    yield arrays
            finally:
                # DevicePrefetcher closes this generator from its worker
                # thread; propagate that to the decode pool so an
                # abandoned pipeline leaks nothing.
                sp.close()

        self._pf = DevicePrefetcher(
            staged(), depth=depth, mesh=mesh, shardings=shardings,
            drop_keys=(),
        )

    def __iter__(self) -> Iterator[tuple]:
        return self

    def __next__(self) -> tuple:
        batch = next(self._pf)
        return batch, self._meta.popleft()

    def close(self) -> None:
        self._pf.close()
        self._sp.close()

    def __enter__(self) -> "EvalPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def default_inflight() -> int:
    """How many dispatched-but-unfinished eval programs to keep in flight.

    On the CPU backend, queued XLA programs execute CONCURRENTLY on the
    shared host thread pool: two in flight halve each other's intra-op
    parallelism and thrash cache (measured ~+8% per pair on a 2-core
    host), so the eval loop keeps exactly ONE in flight and overlaps
    host decode/staging only. Accelerators execute a serialized stream —
    ``inflight=2`` leaves one queued program between pushes, which rides
    out the host's stage/dispatch gap so the device stays fed.
    ``jax.block_until_ready`` on the bounded tail is a sync, not a
    transfer: the loop stays clean under ``forbid_host_transfers``.
    """
    return 1 if jax.default_backend() == "cpu" else 2


class DispatchThrottle:
    """Bound the number of in-flight device computations in a dispatch
    loop (see :func:`default_inflight`). ``push(x)`` registers a freshly
    dispatched output; once ``inflight`` or more are pending it blocks
    until the OLDEST completes, so at most ``inflight`` programs are
    ever in flight and ``inflight - 1`` stay queued between pushes
    (``inflight=1`` ⇒ every push waits for its own program) — bounded
    software pipelining with no host transfer."""

    def __init__(self, inflight: Optional[int] = None):
        self.inflight = inflight if inflight is not None else default_inflight()
        self._pending: deque = deque()

    def push(self, x) -> None:
        self._pending.append(x)
        while len(self._pending) >= max(1, self.inflight):
            jax.block_until_ready(self._pending.popleft())

    def drain(self) -> None:
        while self._pending:
            jax.block_until_ready(self._pending.popleft())


class AsyncDrain:
    """Non-blocking, order-preserving device→host drain.

    ``submit(tree, callback)`` parks a device-array pytree on a bounded
    queue; a worker thread performs the sanctioned ``jax.device_get``
    and hands the host arrays to ``callback``. The dispatch thread never
    blocks on d2h — full-field pulls (submission writers) overlap the
    next frame's compute. A worker error re-raises from the next
    ``submit()`` or from ``close()``; ``close()`` flushes the queue and
    joins. The queue bound (``depth``) also bounds device memory pinned
    by in-flight pulls.
    """

    def __init__(self, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._exc: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._worker, name="eval-drain", daemon=True
        )
        self._thread.start()

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if self._exc is not None:
                continue  # keep consuming so the producer never deadlocks
            tree, callback = item
            try:
                callback(jax.device_get(tree))
            except BaseException as e:  # noqa: BLE001 — surfaced to producer
                self._exc = e

    def _raise_pending(self) -> None:
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    def submit(self, tree, callback: Callable) -> None:
        self._raise_pending()
        self._q.put((tree, callback))

    def close(self) -> None:
        """Flush remaining work, stop the worker, re-raise its error."""
        if self._thread.is_alive():
            self._q.put(None)
            self._thread.join()
        self._raise_pending()

    def __enter__(self) -> "AsyncDrain":
        return self

    def __exit__(self, et, ev, tb) -> None:
        if et is not None:
            # The body already failed; tear down without masking it.
            try:
                self.close()
            except Exception as e:
                print(f"AsyncDrain close after error: {e}", file=sys.stderr)
            return
        self.close()


class ShapeCachedForward:
    """Bounded LRU of compiled test-mode executables, keyed by (mesh
    fingerprint, padded shape, iters, warm-start presence, metric
    kind/pad, precision-policy fingerprint).

    Frames stream with dataset-dependent sizes, so each unique padded
    shape compiles once; the LRU bound (default 8, knob:
    ``DataConfig.eval_cache_size``) keeps KITTI-style shape diversity
    from growing the cache without limit, and ``stats`` counts
    compiles/hits/evictions so an eviction storm is visible instead of
    silent recompile churn (pair with pad bucketing,
    ``InputPadder(bucket=...)``, to make the executable set small and
    known up front).

    ``policy`` (a :mod:`raft_ncup_tpu.precision` preset name or
    ``PrecisionPolicy``; default = the model's own) selects the dtype
    policy every compiled program runs under; ``forward_device`` /
    ``metrics`` accept a per-call override. The policy fingerprint is
    part of EVERY cache key, so an f32 and a bf16 program for the same
    shape can never collide — same variables (f32 master weights), two
    executables (tests/test_inference_pipeline.py pins this).

    With ``mesh`` set (a (data, spatial) ``jax.sharding.Mesh``) every
    forward is one SPMD program: images sharded over (batch, height),
    variables/metrics replicated — the driver-level entry to
    spatially-sharded high-res eval (models/raft.py).
    """

    def __init__(
        self, model, variables: dict, mesh=None, cache_size: int = 8,
        policy=None, telemetry=None, cost_ledger=None,
    ):
        from raft_ncup_tpu.parallel.mesh import mesh_fingerprint

        self.model = model
        self.variables = variables
        self.mesh = mesh
        # Part of EVERY cache key (see _get): a sharded and an unsharded
        # program for the same shape/iters/policy are different
        # executables, and the fingerprint keeps that distinction even
        # for caches that outlive a mesh reconfiguration (or custom()
        # keys minted by subsystems that never look at self.mesh).
        self.mesh_fp = mesh_fingerprint(mesh)
        # apply()-compatible stand-ins (tests' dummy models) carry no
        # policy; they resolve to the f32 default and are never swapped.
        self.policy = (
            resolve_policy(policy)
            if policy is not None
            else resolve_policy(getattr(model, "policy", None))
        )
        self.cache_size = max(1, int(cache_size))
        self._fns: OrderedDict = OrderedDict()
        self._models_by_policy: dict = {}
        self.stats = {"compiles": 0, "hits": 0, "evictions": 0}
        # Telemetry (observability/): compile/evict land as ring events
        # keyed exactly like the cache (the full executable key string),
        # all three land as canonical counters. Hits are counter-only —
        # one ring event per warm batch would flood the span ring with
        # the steady state the ring exists to contextualize.
        self._tel = telemetry if telemetry is not None else get_telemetry()
        # The executable cost ledger (inference/costs.py; docs/PERF.md):
        # every program this cache compiles is AOT-lowered so its XLA
        # cost analysis, compile wall time, and memory stats land in the
        # ledger at COMPILE time — the warmed hot path pays one dict
        # read. The ledger key embeds the same cache key, so a re-warm
        # (LRU hit) records nothing twice.
        self.costs = (
            cost_ledger if cost_ledger is not None else get_cost_ledger()
        )
        self._backend = jax.default_backend()

    def model_for(self, policy=None):
        """Resolve (model, policy) for one call: the instance model when
        the policy matches its config, else the same-architecture model
        under the requested preset (same f32 master weights). Memoized
        per instance so the serving/streaming dispatch path pays a dict
        lookup, not a config rebuild, per batch."""
        pol = resolve_policy(policy) if policy is not None else self.policy
        own = getattr(self.model, "policy", None)
        if own is None or pol.name == own.name:
            return self.model, pol
        model = self._models_by_policy.get(pol.name)
        if model is None:
            import dataclasses

            from raft_ncup_tpu.models.raft import get_model

            cfg = dataclasses.replace(
                self.model.cfg, precision=pol.name, mixed_precision=False
            )
            model = self._models_by_policy[pol.name] = get_model(cfg)
        return model, pol

    # ------------------------------------------------------------ internals

    def _jit(self, fn, n_img_args: int, n_repl_args: int, n_out: int,
             donate: tuple = ()):
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=donate)
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        img = NamedSharding(self.mesh, P("data", "spatial"))
        return jax.jit(
            fn,
            in_shardings=(repl,) + (img,) * n_img_args + (repl,) * n_repl_args,
            out_shardings=repl if n_out == 1 else (repl,) * n_out,
            donate_argnums=donate,
        )

    @staticmethod
    def _ledger_meta(key: tuple) -> dict:
        """Structured identity for the cost-ledger entry, parsed from
        the raw (pre-mesh-fingerprint) executable key so consumers
        filter on (kind, shape, iters) instead of string-matching keys."""
        if key and isinstance(key[0], tuple):
            # forward key: (shape, iters, warm, policy_fp) — plus an
            # optional trailing ("earlyexit", tol) marker for the
            # convergence-detection twin of a shape (docs/PERF.md "Early
            # exit"): the threshold knob rides the ledger meta exactly
            # like the corr band knobs, so flip_recommendations can
            # attribute an EPE-vs-speedup trade to the tolerance that
            # produced it.
            meta = {"kind": "forward", "shape": key[0], "iters": key[1],
                    "policy": key[3]}
            for part in key[4:]:
                if (
                    isinstance(part, tuple) and len(part) == 2
                    and part[0] == "earlyexit"
                ):
                    meta["earlyexit_tol"] = part[1]
            return meta
        if key and key[0] == "metrics":
            # ("metrics", img_shape, flow_shape, extras, iters, kind,
            #  pad, warm, policy_fp) — policy distinguishes the f32 and
            # bf16 twins of one shape (they are different executables
            # with different XLA flops; a meta lookup must not conflate
            # them).
            return {"kind": "metrics", "shape": key[1], "iters": key[4],
                    "policy": key[8]}
        if key and key[0] == "custom":
            # Pipeline programs (inference/pipe_schedule.py) get full
            # structured identity: the tick's segment count rides into
            # the ledger meta so costs.record_compiled can derive
            # per-segment flops/bytes and flip_recommendations can
            # judge the pipeline against the monolithic scan.
            meta = None
            if len(key) >= 6 and key[1] == "pipe_tick":
                meta = {"kind": "pipe_tick", "shape": key[2],
                        "iters": key[3], "segments": key[4],
                        "policy": key[5]}
            elif len(key) >= 4 and key[1] == "pipe_encode":
                meta = {"kind": "pipe_encode", "shape": key[2],
                        "policy": key[3]}
            if meta is not None:
                # Optional trailing ("earlyexit", tol) marker — same
                # contract as the forward key above.
                for part in key[4:]:
                    if (
                        isinstance(part, tuple) and len(part) == 2
                        and part[0] == "earlyexit"
                    ):
                        meta["earlyexit_tol"] = part[1]
                return meta
            return {"kind": "custom"}
        return {}

    def _instrument(self, full_key: tuple, raw_key: tuple, jitfn):
        """Wrap one freshly-built jitted program so its FIRST call
        AOT-compiles (``lower().compile()`` — still exactly one XLA
        compile) and banks the executable's costs in the ledger; every
        later call is one dict read then the compiled program. Plain
        callables (tests' stand-ins) and a disabled ledger pass through
        untouched."""
        if not self.costs.enabled or not hasattr(jitfn, "lower"):
            return jitfn
        ledger, backend = self.costs, self._backend
        ledger_key = f"{backend}|{full_key}"
        meta = self._ledger_meta(raw_key)
        if meta.get("kind") in ("forward", "metrics"):
            # The correlation tuning knobs the executable was traced
            # with (onthefly row_chunk, Pallas query block / band rows
            # — ops/corr.corr_tuning_meta): the first real sweep
            # surface for ROADMAP item 1's autotuner, persisted next
            # to the XLA cost facts it will optimize against.
            from raft_ncup_tpu.ops.corr import corr_tuning_meta

            meta.update(corr_tuning_meta())
        box: dict = {}
        lock = threading.Lock()

        def warmed(*args):
            compiled = box.get("c")
            if compiled is None:
                with lock:
                    compiled = box.get("c")
                    if compiled is None:
                        try:
                            t0 = time.perf_counter()
                            compiled = jitfn.lower(*args).compile()
                            ledger.record_compiled(
                                ledger_key, compiled,
                                compile_ms=(
                                    time.perf_counter() - t0
                                ) * 1e3,
                                backend=backend, **meta,
                            )
                        except Exception as e:  # pragma: no cover
                            # Probe unavailable on this backend: serve
                            # through the plain jit wrapper (no ledger
                            # entry — `mfu` stays None, never wrong).
                            print(
                                f"cost probe unavailable for "
                                f"{ledger_key}: {e!r}", file=sys.stderr,
                            )
                            compiled = jitfn
                        box["c"] = compiled
            return compiled(*args)

        # Inspection handle (inference/pipe_schedule.tick_text; bench's
        # sharding fingerprint): the warmed executable without a second
        # lower().compile(). Empty until the first call.
        warmed._compiled_box = box
        return warmed

    def _get(self, key, build):
        # Single chokepoint for key construction: every compiled-program
        # key — forward, metric, custom — carries the mesh fingerprint.
        raw_key = tuple(key)
        key = (self.mesh_fp,) + raw_key
        fn = self._fns.get(key)
        if fn is not None:
            self._fns.move_to_end(key)
            self.stats["hits"] += 1
            self._tel.inc(_EXEC_CANON["hits"])
            return fn
        fn = self._instrument(key, raw_key, build())
        self._fns[key] = fn
        self.stats["compiles"] += 1
        self._tel.inc(_EXEC_CANON["compiles"])
        self._tel.event("inference_executable_compile", key=str(key))
        if len(self._fns) > self.cache_size:
            evicted, _ = self._fns.popitem(last=False)
            self.stats["evictions"] += 1
            self._tel.inc(_EXEC_CANON["evictions"])
            self._tel.event("inference_executable_evict", key=str(evicted))
            print(
                f"ShapeCachedForward: EVICTING compiled executable "
                f"{evicted} (LRU bound {self.cache_size}). Recurring "
                "evictions mean eval shape churn is re-paying compiles — "
                "raise eval_cache_size or bucket pads (eval_pad_bucket).",
                file=sys.stderr,
            )
        return fn

    # ------------------------------------------------------------- forwards

    def custom(self, key: tuple, build):
        """Compile-once entry for subsystem-specific jitted programs that
        want this cache's LRU bound and compiles/hits/evictions
        accounting (the streaming engine's slot-table step programs,
        keyed by batch size). ``build()`` must return the compiled-on-
        first-call callable; ``key`` is namespaced away from the forward
        and metric keys."""
        return self._get(("custom",) + tuple(key), build)

    def forward_device(
        self, image1, image2, iters: int, flow_init=None, policy=None,
        early_exit_tol: Optional[float] = None,
    ):
        """Test-mode forward; returns DEVICE arrays (flow_lr, flow_up).

        The caller owns the pull: submissions hand the result to an
        :class:`AsyncDrain`, the legacy ``__call__`` wraps it in one
        explicit ``jax.device_get``. ``policy`` overrides the instance
        precision policy for this call; the fingerprint in the key keeps
        the override's executable distinct.

        ``early_exit_tol`` (docs/PERF.md "Early exit"): compile the
        convergence-detection variant — the return becomes the 3-tuple
        ``(flow_lr, flow_up, exec_iters)`` with ``exec_iters`` the (B,)
        int32 per-sample executed-iteration count, still device-resident
        (it rides the caller's existing drain/pull; never a second
        sync). The key grows a trailing ``("earlyexit", tol)`` element,
        so detection-off callers keep their exact 4-tuple keys and
        executables — zero churn for existing deployments — while each
        tolerance is its own executable (the tolerance is baked into the
        compiled loop condition).
        """
        model, pol = self.model_for(policy)
        key = (
            tuple(image1.shape), iters, flow_init is not None,
            pol.fingerprint(),
        )
        if early_exit_tol is not None:
            key = key + (("earlyexit", float(early_exit_tol)),)

        def build():
            mesh = self.mesh
            tol = (
                None if early_exit_tol is None else float(early_exit_tol)
            )
            kw = {}
            if tol is not None:
                kw = {"early_exit_tol": tol, "return_exec_iters": True}
            if flow_init is None:

                def fn(v, i1, i2):
                    return model.apply(
                        v, i1, i2, iters=iters, test_mode=True, mesh=mesh,
                        **kw,
                    )

            else:

                def fn(v, i1, i2, finit):
                    return model.apply(
                        v, i1, i2, iters=iters, flow_init=finit,
                        test_mode=True, mesh=mesh, **kw,
                    )

            return self._jit(
                fn, 2 if flow_init is None else 3, 0,
                n_out=2 if early_exit_tol is None else 3,
            )

        args = (jnp.asarray(image1), jnp.asarray(image2))
        if flow_init is not None:
            args += (jnp.asarray(flow_init),)
        return self._get(key, build)(self.variables, *args)

    def __call__(self, image1, image2, iters: int, flow_init=None):
        """Back-compat numpy-out forward: ONE explicit batched pull for
        both outputs (the eval-side analogue of the Logger's
        one-get-per-window)."""
        return jax.device_get(
            self.forward_device(image1, image2, iters, flow_init)
        )

    def metrics(
        self, batch: dict, *, iters: int, acc, kind: str, pad=None,
        flow_init=None, policy=None,
    ):
        """Forward + on-device metric fold in ONE jitted program.

        ``batch`` holds ``image1``/``image2`` (padded) plus ``flow`` and
        optionally ``valid``/``band`` at native shape; ``pad`` is the
        static ``InputPadder.pad_spec``. Returns the updated accumulator
        (device-resident). No flow field ever reaches the host.

        ``flow_init`` (warm-start validation): a device-resident
        (B, H/8, W/8, 2) initial low-res flow; when given the program
        additionally returns the final low-res flow so the caller can
        carry it to the next frame — the return becomes
        ``(acc, flow_lr)`` instead of ``acc``, and the warm-start chain
        stays entirely on device (evaluation._run_warmstart_metric_pass
        splats it with ops/warmstart.forward_interpolate_jax).

        The accumulator is deliberately NOT donated: donating an operand
        that is still pending (each batch's ``acc`` is the previous
        batch's not-yet-computed output) makes ``jit`` dispatch wait for
        it — measured ~220 ms/call of lost overlap on the CPU backend —
        and the buffer is a handful of floats, so donation saves nothing.
        """
        extras = {
            k: batch[k] for k in ("flow", "valid", "band") if k in batch
        }
        warm = flow_init is not None
        model, pol = self.model_for(policy)
        key = (
            "metrics",
            tuple(batch["image1"].shape),
            tuple(batch["flow"].shape),
            tuple(sorted(extras)),
            iters,
            kind,
            pad,
            warm,
            pol.fingerprint(),
        )

        def build():
            mesh = self.mesh

            if warm:

                def fn(v, i1, i2, extra, acc_in, finit):
                    def head(flow_up):
                        return metrics_mod.accumulate(
                            kind,
                            acc_in,
                            flow_up,
                            extra["flow"],
                            valid=extra.get("valid"),
                            band=extra.get("band"),
                            pad=pad,
                        )

                    flow_lr, acc_out = model.apply(
                        v, i1, i2, iters=iters, flow_init=finit,
                        test_mode=True, mesh=mesh, metric_head=head,
                    )
                    return acc_out, flow_lr

                return self._jit(fn, 2, 3, n_out=2)

            def fn(v, i1, i2, extra, acc_in):
                def head(flow_up):
                    return metrics_mod.accumulate(
                        kind,
                        acc_in,
                        flow_up,
                        extra["flow"],
                        valid=extra.get("valid"),
                        band=extra.get("band"),
                        pad=pad,
                    )

                _, acc_out = model.apply(
                    v, i1, i2, iters=iters, test_mode=True, mesh=mesh,
                    metric_head=head,
                )
                return acc_out

            return self._jit(fn, 2, 2, n_out=1)

        args = (self.variables, batch["image1"], batch["image2"], extras, acc)
        if warm:
            args += (flow_init,)
        return self._get(key, build)(*args)
