"""The compiled-executable cost ledger: what each warmed program
actually costs, recorded ONCE at compile time (docs/PERF.md
"flops_per_pair and MFU").

Every bench row before this module reported ``mfu: null`` because
nothing recorded what the compiled executables cost — the analytic
estimate in ``utils/flops.py`` exists, but MFU against a spec-sheet
peak is only honest when the numerator is XLA's own accounting for the
program that actually ran. This module closes that gap with one
declarative object (the ``FleetConfig``/``PrecisionPolicy`` pattern
applied to cost accounting): a process-wide :class:`CostLedger` that
``ShapeCachedForward`` feeds at warm-up/compile time and that bench,
``scripts/flip_recommendations.py``, and the future autotuner
(ROADMAP item 1) all read.

Per warmed executable the ledger holds:

- ``flops`` / ``bytes_accessed`` from ``Compiled.cost_analysis()``,
- ``compile_ms`` (wall time of ``lower().compile()``),
- ``memory_stats`` from ``Compiled.memory_analysis()``
  (argument/output/temp/generated-code bytes — the
  ``compiled_memory_stats`` surface),

keyed by ``"<backend>|<executable key>"`` where the executable key is
the SAME tuple that keys the compiled-program LRU (mesh fingerprint,
padded shape, iters, precision fingerprint...) — so the ledger key is
stable across re-warms by construction: same shape ⇒ same key, and a
re-warm that hits the LRU records nothing twice.

Forward/metric entries' ``meta`` additionally carries the correlation
tuning knobs the executable was traced with
(``ops.corr.corr_tuning_meta``: onthefly ``corr_row_chunk``, Pallas
``corr_query_block`` / ``corr_band_rows``) — the first real knob
surface for the ROADMAP item-1 autotuner, persisted right next to the
cost facts a sweep would optimize, under the same stable keys its
tuning cache will use.

**Why this lives here and not in observability/**: reading XLA cost
analysis requires jax, and ``observability/`` is host-only stdlib by
lint rule JGL010 — telemetry must never be able to initialize a
backend. The probe therefore sits WITH the inference machinery that
already owns the compiles (``inference/pipeline.py``), runs only at
compile time (never on the hot path — a warmed call pays one dict
read), and hands downstream consumers plain host dicts.

**MFU** = achieved FLOP/s over the chip's peak. :func:`peak_flops` is
the per-backend peak table: TPU generations from the spec sheet
(``utils/flops.TPU_PEAK_FLOPS``), CPU from a nominal per-core figure
(overridable via ``RAFT_NCUP_CPU_PEAK_FLOPS``) so CPU rows report a
real — if humbling — utilization instead of ``null``. ``None`` means
the BACKEND is unknown, never "we didn't measure": the moment a chip
answers, the same code path reports real MFU with zero new code.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

from raft_ncup_tpu.utils.flops import TPU_PEAK_FLOPS
from raft_ncup_tpu.utils.knobs import knob_enabled, knob_raw

COST_LEDGER_ENV = "RAFT_NCUP_COST_LEDGER"
CPU_PEAK_ENV = "RAFT_NCUP_CPU_PEAK_FLOPS"

# Nominal peak per CPU core: 8-lane f32 FMA (AVX2) at ~3 GHz = 2 * 8 *
# 3e9 = 4.8e10 FLOP/s. Deliberately a round spec-sheet-style constant,
# not a microbenchmark: CPU MFU is an order-of-magnitude sanity figure
# (documented in docs/PERF.md), and the env override exists for hosts
# where the nominal is far off.
CPU_PEAK_FLOPS_PER_CORE = 4.8e10

_MEMORY_STAT_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def peak_flops(
    backend: Optional[str],
    device_kind: Optional[str] = None,
    tpu_gen: Optional[str] = None,
) -> Optional[float]:
    """Peak dense FLOP/s per chip for a backend, ``None`` only when the
    backend (or TPU generation) is unknown. ``tpu_gen`` wins over
    parsing ``device_kind`` (e.g. ``"TPU v5e"``)."""
    if not backend:
        return None
    backend = backend.lower()
    if backend == "cpu":
        override = knob_raw(CPU_PEAK_ENV)
        if override:
            try:
                return float(override)
            except ValueError:
                pass
        return (os.cpu_count() or 1) * CPU_PEAK_FLOPS_PER_CORE
    if backend == "tpu":
        gen = (tpu_gen or "").lower()
        if not gen and device_kind:
            m = re.search(r"v\d+[a-z]*", device_kind.lower())
            gen = m.group(0) if m else ""
        return TPU_PEAK_FLOPS.get(gen)
    return None


def mfu(
    flops_per_item: Optional[float],
    items_per_sec: Optional[float],
    peak: Optional[float],
) -> Optional[float]:
    """Model FLOPs utilization: achieved FLOP/s over ``peak``. ``None``
    when any input is unknown (an unknown backend, an unmeasured
    executable) — never 0.0, which would claim a measurement."""
    if not flops_per_item or not items_per_sec or not peak:
        return None
    return round(flops_per_item * items_per_sec / peak, 6)


def probe_compiled(compiled) -> dict:
    """Harvest one AOT-compiled executable's cost facts as a host dict:
    ``{"flops", "bytes_accessed", "memory_stats"}``. Best-effort per
    field — an XLA build that lacks one analysis yields ``None`` for
    that field, never an exception (the probe must not be able to take
    a warmup down)."""
    out: dict = {"flops": None, "bytes_accessed": None,
                 "memory_stats": {}}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            if ca.get("flops"):
                out["flops"] = float(ca["flops"])
            # XLA's key really does contain a space.
            if ca.get("bytes accessed"):
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:  # pragma: no cover - backend-specific
        pass
    try:
        ma = compiled.memory_analysis()
        out["memory_stats"] = {
            f: int(getattr(ma, f))
            for f in _MEMORY_STAT_FIELDS
            if getattr(ma, f, None) is not None
        }
    except Exception:  # pragma: no cover - backend-specific
        pass
    return out


class CostLedger:
    """Thread-safe per-process ledger of compiled-executable costs.

    ``record_compiled`` is called by the compile probe exactly once per
    (backend, executable key); re-recording the same key overwrites in
    place (idempotent — the entry describes the executable, not the
    event). ``meta`` carries the structured identity the consumers
    filter on (kind/shape/iters), parsed from the executable key by the
    probe so bench never reverse-engineers key strings.
    """

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = (
            knob_enabled(COST_LEDGER_ENV)
            if enabled is None else bool(enabled)
        )
        self._entries: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def record_compiled(
        self, key: str, compiled, *, compile_ms: Optional[float] = None,
        backend: Optional[str] = None, **meta,
    ) -> Optional[dict]:
        if not self.enabled:
            return None
        entry = probe_compiled(compiled)
        entry["key"] = str(key)
        entry["backend"] = backend
        entry["compile_ms"] = (
            None if compile_ms is None else round(float(compile_ms), 1)
        )
        entry["meta"] = {k: v for k, v in meta.items() if v is not None}
        # Pipelined executables (meta carries segments > 1, set by the
        # pipe_tick key parse in pipeline._ledger_meta): derive the
        # per-segment split of the whole-tick costs. One tick runs all
        # S segments concurrently (one per device group), so per-stage
        # work is total/S — the figure flip_recommendations compares
        # against the monolithic scan's cost to judge pipeline balance.
        segs = entry["meta"].get("segments")
        if isinstance(segs, int) and segs > 1:
            entry["flops_per_segment"] = (
                None if entry["flops"] is None
                else entry["flops"] / segs
            )
            entry["bytes_per_segment"] = (
                None if entry["bytes_accessed"] is None
                else entry["bytes_accessed"] / segs
            )
        with self._lock:
            self._entries[str(key)] = entry
        return entry

    # ---------------------------------------------------------- consumers

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def entry(self, key: str) -> Optional[dict]:
        with self._lock:
            return self._entries.get(str(key))

    def keys(self) -> list:
        with self._lock:
            return sorted(self._entries)

    def lookup(self, **meta) -> Optional[dict]:
        """First entry whose ``meta`` matches every given item (e.g.
        ``lookup(kind="forward", shape=(1, 96, 128, 3), iters=12)``) —
        how bench finds the warmed headline executable's costs."""
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            m = e.get("meta") or {}
            if all(m.get(k) == v for k, v in meta.items()):
                return e
        return None

    def snapshot(self) -> dict:
        """JSON-able dump: every entry (tuples stringified) plus
        accounting — what serve.py reports and the autotuner will read."""
        with self._lock:
            entries = {
                k: {
                    **e,
                    "meta": {
                        mk: (list(mv) if isinstance(mv, tuple) else mv)
                        for mk, mv in (e.get("meta") or {}).items()
                    },
                }
                for k, e in self._entries.items()
            }
        return {"enabled": self.enabled, "entries": entries}

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


_default_lock = threading.Lock()
_default: Optional[CostLedger] = None


def get_cost_ledger() -> CostLedger:
    """The process-wide default ledger (created on first use; honors
    ``RAFT_NCUP_COST_LEDGER=0``)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = CostLedger()
        return _default


def set_cost_ledger(ledger: Optional[CostLedger]) -> Optional[CostLedger]:
    """Swap the process default (bench/test isolation); returns the
    previous ledger."""
    global _default
    with _default_lock:
        prev, _default = _default, ledger
        return prev
