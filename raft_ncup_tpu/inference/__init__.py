"""Asynchronous inference subsystem: device-resident validation metrics
(:mod:`raft_ncup_tpu.inference.metrics`) and the double-buffered eval
executor / bounded shape cache / async d2h drain
(:mod:`raft_ncup_tpu.inference.pipeline`). ``evaluation.py``'s
validators and submission writers are built on these; docs/PERF.md
("Eval pipeline") records the measured overlap win."""

from raft_ncup_tpu.inference.pipeline import (  # noqa: F401
    AsyncDrain,
    DispatchThrottle,
    EvalPipeline,
    SamplePrefetcher,
    ShapeCachedForward,
    default_inflight,
    uniform_batches,
)
