"""Iteration-pipelined inference over the ``pipe`` mesh axis
(docs/SHARDING.md "Pipeline axis"; ROADMAP item 2).

RAFT's GRU tower is a chain of N IDENTICAL refinement iterations
(PAPERS.md: arXiv:2003.12039) — exactly the structure pipeline-parallel
frameworks exploit (PAPERS.md: PPLL, arXiv:2411.12780). This module
splits the N iterations into S contiguous SEGMENTS placed on S device
groups (the ``pipe`` axis of ``parallel/mesh.make_mesh``) and streams
micro-batches through them so every group stays busy: while stage 1
refines request B's iterations 1..N/S, stage 2 refines request A's
iterations N/S+1..2N/S. At fixed per-request latency, steady-state
throughput approaches S× without growing the batch — and segment
boundaries are the natural early-exit points ROADMAP item 5 needs.

**The tick.** Pipeline state is the models' segment carry
(models/raft.py ``encode``: net, coords1, inp, fmap1, fmap2[, up_mask])
stacked along a leading STAGE axis of size S, sharded ``P("pipe")`` so
stage s's micro-batch lives on device group s. One tick of the
schedule is ONE compiled SPMD program:

1. **inject** — the freshly encoded micro-batch overwrites stage 0's
   slot (a sharded ``.at[0].set``);
2. **refine** — ``shard_map`` over ``pipe``: every stage advances its
   resident carry by N/S iterations (the same ``lax.scan`` step body
   as the monolithic ``apply``, via ``RAFT.refine_segment``);
3. **extract** — stage S-1's refined carry is the finished micro-batch;
   ``RAFT.finalize`` upsamples it to ``(flow_lr, flow_up)`` inside the
   same program;
4. **shift** — ``jax.lax.ppermute`` hands every refined carry to the
   next stage (``collective-permute`` in the compiled HLO — the
   pipeline's handoff traffic, attributable via
   ``parallel.mesh.collective_stats``'s per-op breakout).

The state operand is DONATED, so the carry buffers are reused in place
tick over tick. A micro-batch injected at tick t completes at tick
t+S-1; M micro-batches take M+S-1 ticks (S-1 of them flush ticks whose
stage-0 slot refines zeros that are never read). Warm-up and flush
outputs are discarded by the host driver, not computed around —
schedule uniformity is what keeps the steady state at exactly one
compiled program, zero recompiles.

**CPU emulation caveat** (tests/conftest.py's 8 virtual devices): the
virtual "device groups" share one host, so the S× throughput claim is
NOT measurable here — what IS pinnable is everything load-bearing:
output parity with the monolithic scan, carry-handoff correctness at
every seam, donation, guard-clean steady state, and the
collective-permute fingerprint. The throughput claim stages for
ROADMAP item 1's chip window via bench.py's guarded
``pipeline_pairs_per_sec`` row.

**v1 scope**: the pipe axis composes with ``data``/``spatial`` sizes
of 1 only. Running spatial sharding INSIDE a pipeline stage needs the
halo-exchange-aware corr path scoped to the stage's subgroup —
staged behind the same chip window (docs/SHARDING.md).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

# The version-resolved shard_map the model's sharded corr path already
# uses (keyword-compatible across jax's experimental->top-level move).
from raft_ncup_tpu.models.raft import _shard_map

# Images enter every forward executable as f32 regardless of precision
# policy (precision.PrecisionPolicy: inputs stay f32, casts happen
# inside the model) — the carry eval_shape must trace with the same
# pinned input dtype or the stacked state would disagree with what
# encode actually produces.
IMAGE_DTYPE = jnp.float32


def split_iters(iters: int, segments: int) -> int:
    """Iteration count -> per-segment length. Segments are equal-length
    contiguous blocks, so ``segments`` must divide ``iters`` — a ragged
    last segment would need its own executable and break the
    one-program steady state."""
    iters, segments = int(iters), int(segments)
    if segments < 1:
        raise ValueError(f"segments must be >= 1, got {segments}")
    if iters < 1 or iters % segments:
        raise ValueError(
            f"iters={iters} does not split into {segments} equal scan "
            f"segments; pipelined budgets must be multiples of "
            f"{segments} (see serving/budget.py segment quantization)"
        )
    return iters // segments


def validate_segment_levels(
    levels: Sequence[int], segments: int
) -> None:
    """Budget quantization rule for a pipelined deployment: every
    iteration level must land on a SEGMENT BOUNDARY — i.e. be a
    multiple of the segment length ``levels[0] / segments`` — because a
    reduced budget runs fewer segments of the same compiled tick, and
    a budget strictly inside a segment would need a fresh executable
    per level (the recompile storm the fixed level set exists to
    prevent). E.g. ``(24, 16, 8)`` with S=2 (segment length 12) is
    INVALID (16 and 8 sit mid-segment); ``(24, 12)`` is valid."""
    segments = int(segments)
    if segments < 1:
        raise ValueError(f"segments must be >= 1, got {segments}")
    if segments == 1:
        return  # monolithic: every level is its own boundary
    levels = tuple(int(x) for x in levels)
    if not levels:
        raise ValueError("empty iteration level set")
    if levels[0] % segments:
        raise ValueError(
            f"top iteration level {levels[0]} does not split into "
            f"{segments} equal segments"
        )
    seg_len = levels[0] // segments
    bad = [x for x in levels if x % seg_len]
    if bad:
        raise ValueError(
            f"iteration levels {bad} do not quantize to the segment "
            f"boundary (multiples of {levels[0]}/{segments} = {seg_len} "
            f"iterations) required by pipe segments={segments}; with a "
            "pipelined mesh a budget level must run a whole number of "
            f"scan segments — e.g. {tuple(seg_len * k for k in range(segments, 0, -1))}"
        )


class PipelinedForward:
    """Micro-batch streaming driver for the iteration pipeline.

    Compiled programs (the per-micro-batch ``pipe_encode`` and the
    steady-state ``pipe_tick``) live in a :class:`ShapeCachedForward`
    — same LRU bound, compiles/hits/evictions accounting, telemetry,
    and cost-ledger instrumentation as every other executable, keyed
    under the pipe mesh's fingerprint plus the segment count so
    pipelined and monolithic executables can never collide.

    ``segments == 1`` is EXACTLY the monolithic path: ``forward_many``
    delegates straight to ``ShapeCachedForward.forward_device`` (one
    ``apply`` scan, no pipeline machinery, no pipe mesh) — the default
    config pays nothing for this module's existence.
    """

    def __init__(
        self, model, variables: dict, mesh=None,
        segments: Optional[int] = None, cache_size: int = 8,
        policy=None, telemetry=None, cost_ledger=None,
    ):
        from raft_ncup_tpu.inference.pipeline import ShapeCachedForward
        from raft_ncup_tpu.parallel.mesh import make_mesh

        if mesh is None and segments is not None and int(segments) > 1:
            mesh = make_mesh(
                data=1, spatial=1, pipe=int(segments),
                devices=jax.devices()[: int(segments)],
            )
        s = int(mesh.shape.get("pipe", 1)) if mesh is not None else 1
        if segments is not None and int(segments) != s:
            raise ValueError(
                f"segments={segments} disagrees with mesh pipe axis {s}"
            )
        if s > 1:
            extra = {
                k: v for k, v in mesh.shape.items()
                if k != "pipe" and int(v) > 1
            }
            if extra:
                raise ValueError(
                    f"pipe axis composes with data/spatial sizes of 1 "
                    f"only (got {dict(mesh.shape)}); spatially-sharded "
                    "pipeline stages are staged for the chip window "
                    "(docs/SHARDING.md)"
                )
        self.segments = s
        self.mesh = mesh if s > 1 else None
        self.model = model
        self.variables = variables
        self.cache = ShapeCachedForward(
            model, variables, mesh=self.mesh, cache_size=cache_size,
            policy=policy, telemetry=telemetry, cost_ledger=cost_ledger,
        )
        # Warmed tick callables by (shape, iters, segments, policy) —
        # the inspection surface tick_text() reads compiled HLO from
        # without paying a second compile.
        self._tick_handles: dict = {}

    @property
    def is_pipelined(self) -> bool:
        return self.segments > 1

    # ------------------------------------------------------------ programs

    def _carry_struct(
        self, image_shape: tuple, model, early_exit: bool = False,
    ) -> dict:
        img = jax.ShapeDtypeStruct(tuple(image_shape), IMAGE_DTYPE)
        return jax.eval_shape(
            lambda v, a, b: model.encode(v, a, b, early_exit=early_exit),
            self.variables, img, img,
        )

    def _build_encode(self, model, early_exit: bool = False):
        repl = NamedSharding(self.mesh, P())

        def enc(v, i1, i2):
            return model.encode(v, i1, i2, early_exit=early_exit)

        return jax.jit(enc, in_shardings=(repl, repl, repl),
                       out_shardings=repl)

    def _build_tick(self, model, seg_len: int, early_exit_tol=None):
        mesh = self.mesh
        s = self.segments
        perm = [(i, i + 1) for i in range(s - 1)]

        def seg_local(v, block):
            # One pipeline stage: its (1, B, ...) slot of the stacked
            # state, squeezed to the plain segment carry, advanced by
            # seg_len iterations of the SAME step body as apply().
            local = jax.tree.map(lambda x: x[0], block)
            out = model.refine_segment(
                v, local, seg_len, early_exit_tol=early_exit_tol
            )
            out = jax.tree.map(lambda x: x[None], out)
            # Carry handoff: refined stage s -> stage s+1. Stage 0's
            # incoming slot is zero-filled by ppermute (no source) and
            # immediately overwritten by the next tick's inject.
            shifted = jax.tree.map(
                lambda x: jax.lax.ppermute(x, "pipe", perm), out
            )
            return out, shifted

        def tick(v, state, fresh):
            state = jax.tree.map(
                lambda st, f: st.at[0].set(f), state, fresh
            )
            refined, shifted = _shard_map(
                seg_local, mesh=mesh,
                in_specs=(P(), P("pipe")),
                out_specs=(P("pipe"), P("pipe")),
            )(v, state)
            done = jax.tree.map(lambda x: x[s - 1], refined)
            flow_lr, flow_up = model.finalize(v, done)
            if early_exit_tol is not None:
                # The finished micro-batch's per-sample executed-iters
                # counter (quantized to segment boundaries inside
                # refine_segment) leaves with its flow — one more tiny
                # replicated output, no extra sync.
                return shifted, flow_lr, flow_up, done["exec_iters"]
            return shifted, flow_lr, flow_up

        repl = NamedSharding(self.mesh, P())
        staged = NamedSharding(self.mesh, P("pipe"))
        n_out = 3 if early_exit_tol is None else 4
        # Donating the state keeps the pipeline's carry buffers reused
        # in place tick over tick — steady-state memory is one stacked
        # carry, not one per in-flight tick.
        return jax.jit(
            tick,
            in_shardings=(repl, staged, repl),
            out_shardings=(staged,) + (repl,) * (n_out - 1),
            donate_argnums=(1,),
        )

    def _programs(
        self, image_shape: tuple, iters: int, policy=None,
        early_exit_tol=None,
    ):
        """(encode, tick, model, pol) — compiled-on-first-call via the
        cache, keyed by (shape, iters, segments, policy). Early-exit
        programs append a ``("earlyexit", tol)`` key element (exactly
        like ``forward_device``): detection-off deployments keep their
        existing keys and executables untouched."""
        model, pol = self.cache.model_for(policy)
        seg_len = split_iters(iters, self.segments)
        shape = tuple(image_shape)
        fp = pol.fingerprint()
        ee_key = ()
        if early_exit_tol is not None:
            early_exit_tol = float(early_exit_tol)
            ee_key = (("earlyexit", early_exit_tol),)
        enc = self.cache.custom(
            ("pipe_encode", shape, fp) + ee_key,
            lambda: self._build_encode(
                model, early_exit=early_exit_tol is not None
            ),
        )
        tick = self.cache.custom(
            ("pipe_tick", shape, int(iters), self.segments, fp) + ee_key,
            lambda: self._build_tick(
                model, seg_len, early_exit_tol=early_exit_tol
            ),
        )
        self._tick_handles[
            (shape, int(iters), self.segments, fp) + ee_key
        ] = tick
        return enc, tick, model, pol

    def _zero_state(self, carry_sds: dict):
        staged = NamedSharding(self.mesh, P("pipe"))
        s = self.segments
        return jax.tree.map(
            lambda sd: jax.device_put(
                jnp.zeros((s,) + tuple(sd.shape), sd.dtype), staged
            ),
            carry_sds,
        )

    def _zero_fresh(self, carry_sds: dict):
        repl = NamedSharding(self.mesh, P())
        return jax.tree.map(
            lambda sd: jax.device_put(
                jnp.zeros(tuple(sd.shape), sd.dtype), repl
            ),
            carry_sds,
        )

    # ------------------------------------------------------------- driving

    def forward_many(
        self, pairs: Sequence[tuple], iters: int, policy=None,
        early_exit_tol: Optional[float] = None,
    ) -> list:
        """Stream ``pairs`` (same-shape ``(image1, image2)`` micro-
        batches) through the pipeline; returns the per-micro-batch
        ``(flow_lr, flow_up)`` DEVICE arrays in submission order (the
        caller owns the pull, as with ``forward_device``).

        ``len(pairs)`` micro-batches take ``len(pairs) + S - 1`` ticks
        (S-1 flush ticks at the tail). The steady state is guard-clean:
        every tick after the first reuses the same two executables and
        performs no host transfer.

        ``early_exit_tol`` (docs/PERF.md "Early exit"): each result
        becomes the 3-tuple ``(flow_lr, flow_up, exec_iters)``. Under
        the pipe axis exits QUANTIZE to segment boundaries — the tick
        schedule is fixed, so a converged lane rides frozen (bitwise,
        per-iteration ``jnp.where`` inside ``refine_segment``) to the
        next seam and ``exec_iters`` bills whole segments:
        ``exec_pipe == ceil(exec_mono / seg_len) * seg_len``.
        """
        if self.segments == 1:
            return [
                self.cache.forward_device(
                    i1, i2, iters, policy=policy,
                    early_exit_tol=early_exit_tol,
                )
                for i1, i2 in pairs
            ]
        split_iters(iters, self.segments)  # validate before compiling
        pairs = list(pairs)
        if not pairs:
            return []
        shape = tuple(jnp.shape(pairs[0][0]))
        enc, tick, model, _pol = self._programs(
            shape, iters, policy, early_exit_tol=early_exit_tol
        )
        carry_sds = self._carry_struct(
            shape, model, early_exit=early_exit_tol is not None
        )
        state = self._zero_state(carry_sds)
        flush = self._zero_fresh(carry_sds)
        s = self.segments
        outs = []
        for t in range(len(pairs) + s - 1):
            if t < len(pairs):
                i1, i2 = pairs[t]
                fresh = enc(
                    self.variables, jnp.asarray(i1), jnp.asarray(i2)
                )
            else:
                fresh = flush
            if early_exit_tol is not None:
                state, flow_lr, flow_up, exec_iters = tick(
                    self.variables, state, fresh
                )
                if t >= s - 1:
                    outs.append((flow_lr, flow_up, exec_iters))
            else:
                state, flow_lr, flow_up = tick(
                    self.variables, state, fresh
                )
                if t >= s - 1:
                    outs.append((flow_lr, flow_up))
        return outs

    # ---------------------------------------------------------- inspection

    def tick_text(
        self, image_shape: tuple, iters: int, policy=None,
    ) -> Optional[str]:
        """Optimized HLO text of the WARMED tick executable — the
        program that actually served ``forward_many`` — read from the
        cache's instrumentation handle at zero compile cost. ``None``
        before the first call for this (shape, iters, policy), or when
        the cost ledger (whose AOT warm-up produces the handle) is
        disabled; ``tick_hlo`` is the always-works fallback at one
        fresh compile."""
        if self.segments == 1:
            return None
        _model, pol = self.cache.model_for(policy)
        key = (
            tuple(image_shape), int(iters), self.segments,
            pol.fingerprint(),
        )
        fn = self._tick_handles.get(key)
        box = getattr(fn, "_compiled_box", None)
        compiled = box.get("c") if box else None
        if compiled is None or not hasattr(compiled, "as_text"):
            return None
        try:
            return compiled.as_text()
        except Exception:  # pragma: no cover - backend-specific
            return None

    def tick_hlo(self, image_shape: tuple, iters: int, policy=None) -> str:
        """Optimized HLO text of the steady-state tick program, compiled
        fresh for inspection (``collective_stats`` fingerprinting in
        tests and the bench row) — the served executable in the cache is
        untouched."""
        if self.segments == 1:
            raise ValueError("segments=1 has no tick program")
        model, _pol = self.cache.model_for(policy)
        seg_len = split_iters(iters, self.segments)
        carry_sds = self._carry_struct(tuple(image_shape), model)
        state_sds = jax.tree.map(
            lambda sd: jax.ShapeDtypeStruct(
                (self.segments,) + tuple(sd.shape), sd.dtype
            ),
            carry_sds,
        )
        jt = self._build_tick(model, seg_len)
        return jt.lower(self.variables, state_sds, carry_sds).compile().as_text()
