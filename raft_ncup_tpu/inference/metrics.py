"""Device-resident validation metrics: EPE / px-threshold / KITTI F1.

The pre-refactor validators pulled two full flow fields to host every
batch (~4.4 MB/pair at 368x768 through ``jax.device_get``) and computed
EPE/F1 in NumPy — the d2h transfer sat on the critical path of every
eval step. Here the same metrics are computed ON DEVICE, inside the same
jitted program as the forward (``RAFT.apply(..., metric_head=...)``), and
carried across batches as a small accumulator vector of SUMS. Validation
pulls a handful of scalars once per window instead of flow fields once
per batch; the sums are also exactly what the multi-host reduction needs
(``allreduce_sum_across_hosts`` in evaluation.py).

Accumulator layouts (float32 sums, host-reducible):

- ``"epe"``      (2,) ``[epe_sum, n_px]`` — chairs / synthetic-smooth.
- ``"px"``       (5,) ``[epe_sum, n_px, n_lt_1px, n_lt_3px, n_lt_5px]``
  — sintel (reference: evaluate.py:111-143).
- ``"kitti"``    (4,) ``[frame_epe_mean_sum, n_frames, n_outliers,
  n_valid_px]`` — per-frame EPE mean, pixel-pooled F1 (reference:
  evaluate.py:146-182).
- ``"epe_band"`` (6,) ``[epe_sum, n_px, band_epe_sum, n_band,
  interior_epe_sum, n_interior]`` — synthetic-rigid boundary-band EPE
  (the NCUP-vs-bilinear metric, docs/PERF.md). The band mask is computed
  host-side during decode (cv2.dilate) and shipped as an input array.

Reference metric-helper parity (VERDICT r5 missing #2-#3): the
reference's VCN-derived ``th_epe``/``th_rmse`` helpers — mean endpoint
error / root-mean-square error over a validity mask, optionally
thresholded — have these accumulators as their equivalents:
``kind="epe"`` is th_epe's masked mean EPE, ``kind="px"`` adds the
1/3/5px thresholded fractions th_epe reports at its cutoffs, and a
th_rmse is the square root of the same masked fold with ``epe**2`` in
place of ``epe`` (the sums carried here are exactly the sufficient
statistics both helpers reduce to).

Padding awareness: eval inputs are padded to stride/bucket shapes
(``ops/padding.InputPadder``), so :func:`unpad_in_graph` crops the
prediction back to the ground truth's native shape INSIDE the graph —
the pad spec is static per compiled shape, so the crop is free slicing,
not a runtime mask multiply, and padded pixels can never leak into a
metric sum.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

# kind -> accumulator length; init_acc/accumulate/finalize all key on it.
ACC_SIZES = {"epe": 2, "px": 5, "kitti": 4, "epe_band": 6}


def init_acc(kind: str) -> jnp.ndarray:
    """Fresh zeroed accumulator for ``kind`` (device-resident)."""
    return jnp.zeros((ACC_SIZES[kind],), jnp.float32)


def unpad_in_graph(x: jnp.ndarray, pad) -> jnp.ndarray:
    """Crop padded NHWC predictions back to the native shape in-graph.

    ``pad`` is ``InputPadder.pad_spec`` — ``((top, bottom), (left,
    right))``, static per compiled shape — so this lowers to a free
    static slice (the in-graph unpad mask) rather than a runtime select.
    """
    (t, b), (l, r) = pad
    ht, wd = x.shape[-3], x.shape[-2]
    return x[..., t : ht - b, l : wd - r, :]


def accumulate(
    kind: str,
    acc: jnp.ndarray,
    flow_up: jnp.ndarray,
    gt: jnp.ndarray,
    valid: Optional[jnp.ndarray] = None,
    band: Optional[jnp.ndarray] = None,
    pad=None,
) -> jnp.ndarray:
    """Fold one batch into the accumulator; all args device-resident.

    ``flow_up`` is the (possibly padded) (B, H, W, 2) prediction; ``gt``
    the native-shape ground truth; ``valid`` a (B, H, W) mask in the
    reference's >= 0.5 convention (kitti only); ``band`` a (B, H, W) 0/1
    boundary mask (epe_band only). Mirrors the pre-refactor host NumPy
    formulas exactly, in the same float32 precision the host path used.
    """
    if pad is not None:
        flow_up = unpad_in_graph(flow_up, pad)
    flow_up = flow_up.astype(jnp.float32)
    gt = gt.astype(jnp.float32)
    epe = jnp.sqrt(jnp.sum((flow_up - gt) ** 2, axis=-1))  # (B, H, W)
    n = jnp.float32(epe.size)

    if kind == "epe":
        delta = jnp.stack([epe.sum(), n])
    elif kind == "px":
        delta = jnp.stack(
            [
                epe.sum(),
                n,
                jnp.sum((epe < 1.0).astype(jnp.float32)),
                jnp.sum((epe < 3.0).astype(jnp.float32)),
                jnp.sum((epe < 5.0).astype(jnp.float32)),
            ]
        )
    elif kind == "kitti":
        vm = (valid >= 0.5).astype(jnp.float32)
        mag = jnp.sqrt(jnp.sum(gt * gt, axis=-1))
        out = (epe > 3.0) & ((epe / jnp.maximum(mag, 1e-12)) > 0.05)
        nv_frame = vm.sum(axis=(1, 2))  # (B,)
        # A frame with ZERO valid pixels (occluded-out crop, corrupt
        # mask) must not poison the pool: the host path produced NaN
        # (0-valid sum / 0 count) and the NaN then swallowed the whole
        # dataset mean. Such frames contribute nothing — not a zero —
        # to either the per-frame EPE sum or the frame COUNT, so the
        # remaining frames' mean is unchanged.
        has_valid = (nv_frame > 0).astype(jnp.float32)
        frame_epe = jnp.sum(epe * vm, axis=(1, 2)) / jnp.maximum(
            nv_frame, 1.0
        )
        delta = jnp.stack(
            [
                frame_epe.sum(),
                has_valid.sum(),
                jnp.sum(out.astype(jnp.float32) * vm),
                vm.sum(),
            ]
        )
    elif kind == "epe_band":
        bm = band.astype(jnp.float32)
        delta = jnp.stack(
            [
                epe.sum(),
                n,
                jnp.sum(epe * bm),
                bm.sum(),
                jnp.sum(epe * (1.0 - bm)),
                jnp.sum(1.0 - bm),
            ]
        )
    else:
        raise ValueError(f"unknown metric kind: {kind!r}")
    return acc + delta


def finalize(kind: str, acc: np.ndarray) -> dict:
    """Host-side sums -> metric dict (call after the window's single
    ``jax.device_get`` and any cross-host reduction)."""
    acc = np.asarray(acc, np.float64)
    if kind == "epe":
        return {"epe": float(acc[0] / acc[1])}
    if kind == "px":
        return {
            "epe": float(acc[0] / acc[1]),
            "1px": float(acc[2] / acc[1]),
            "3px": float(acc[3] / acc[1]),
            "5px": float(acc[4] / acc[1]),
        }
    if kind == "kitti":
        # Degenerate pools (every frame all-invalid — acc[1] and acc[3]
        # both 0) finalize to 0.0, not NaN: 0/0 here used to propagate
        # into the dataset metric and the submission gate.
        return {
            "epe": float(acc[0] / acc[1]) if acc[1] else 0.0,
            "f1": 100.0 * float(acc[2] / acc[3]) if acc[3] else 0.0,
        }
    if kind == "epe_band":
        return {
            "epe": float(acc[0] / acc[1]),
            "bnd": float(acc[2] / acc[3]),
            "interior": float(acc[4] / acc[5]),
        }
    raise ValueError(f"unknown metric kind: {kind!r}")
