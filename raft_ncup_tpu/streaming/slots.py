"""Slot-table state for the multi-stream engine: device arrays + host
registry.

The split of responsibilities is the whole design:

- **Device** (:func:`init_slot_table`): the recurrent state itself —
  per-slot previous low-res flow, a warm flag, and (``carry_net``) the
  GRU hidden state — lives in fixed-shape HBM arrays of size
  ``capacity + 1``. It is read (gather by slot index) and written
  (scatter) ONLY inside the jitted stream step
  (``streaming/engine.py``), so state never crosses to host between
  frames. Index ``capacity`` is the **scratch slot**: zero-padded batch
  rows gather from and scatter to it, so padding can never touch a real
  stream's state. The warm flag lives on DEVICE, not in the registry,
  because the in-graph anomaly check flips it (reset-to-cold) without a
  host round-trip — the host learns about a reset asynchronously from
  the drained flags, but the next frame of that stream already reads
  the reset state correctly.

- **Host** (:class:`SlotRegistry`): pure bookkeeping — which stream
  owns which slot, last admitted frame index (staleness), last activity
  time (idle eviction), pending-frame counts (eviction safety). All of
  it is cheap metadata; none of it is recurrent state. Slot allocation
  and eviction are deterministic: the lowest-numbered free slot is
  assigned, and idle eviction scans in (last_activity, stream_id)
  order — a replayed chaos schedule evicts the same streams into the
  same slots. Freeing a slot touches NO device memory: the next owner's
  first frame dispatches with ``cold=1``, which both ignores and
  overwrites whatever the previous owner left, so slot reuse can never
  recompile or transfer.

Callers hold the engine's lock around registry calls; the registry
itself is not locked (single-owner discipline, like ``ServeStats``
note_* methods own their lock).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp


def init_slot_table(
    capacity: int, h8: int, w8: int, hidden_dim: int = 0, dtype=None
) -> dict:
    """Fresh all-cold device slot table for ``capacity`` streams.

    Arrays are sized ``capacity + 1``: the extra row is the scratch slot
    batch padding targets. ``warm`` is float32 0/1 (it multiplies into
    masks in-graph — a flag, not recurrent numerics, so it never
    narrows); everything starts cold, so a freshly admitted stream's
    first frame is bitwise a cold start regardless of history.

    ``dtype`` (default f32) is the recurrent-STATE storage dtype — the
    precision policy's ``state_jnp``: under the bf16 presets the
    per-stream flow (and optional GRU net) rows are stored bf16, halving
    the table's HBM footprint; the engine's step upcasts to the policy's
    pinned f32 coord dtype before the warm-start splat, so storage is
    narrow but coordinate arithmetic is not (docs/PRECISION.md).
    """
    dtype = dtype or jnp.float32
    table = {
        "flow": jnp.zeros((capacity + 1, h8, w8, 2), dtype),
        "warm": jnp.zeros((capacity + 1,), jnp.float32),
    }
    if hidden_dim:
        table["net"] = jnp.zeros(
            (capacity + 1, h8, w8, hidden_dim), dtype
        )
    return table


@dataclass
class StreamState:
    """Host-side metadata for one admitted stream (one slot)."""

    stream_id: str
    slot: int
    native_hw: Tuple[int, int]
    opened_at: float
    last_activity: float
    last_frame_index: Optional[int] = None
    pending: int = 0  # admitted frames not yet terminally answered
    frames_admitted: int = 0
    frames_completed: int = 0
    resets: int = 0  # in-graph anomaly cold-start resets observed
    closing: bool = False


@dataclass
class SlotRegistry:
    """Host bookkeeping: stream_id -> slot assignment and lifecycle."""

    capacity: int
    streams: Dict[str, StreamState] = field(default_factory=dict)
    evicted_total: int = 0
    peak_occupancy: int = 0
    _free: List[int] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._free = sorted(range(self.capacity), reverse=True)

    # ------------------------------------------------------------ queries

    def get(self, stream_id: str) -> Optional[StreamState]:
        return self.streams.get(stream_id)

    @property
    def occupancy(self) -> int:
        return self.capacity - len(self._free)

    def soonest_expiry_s(self, now: float, idle_timeout_s: float) -> float:
        """Honest retry hint for a shed stream admission: seconds until
        the earliest-idle stream becomes evictable (0 when a slot is
        already reclaimable)."""
        if not self.streams:
            return idle_timeout_s
        remaining = [
            max(0.0, s.last_activity + idle_timeout_s - now)
            for s in self.streams.values()
        ]
        return min(remaining)

    # ---------------------------------------------------------- lifecycle

    def admit(
        self, stream_id: str, native_hw: Tuple[int, int], now: float
    ) -> Optional[StreamState]:
        """Assign the lowest free slot to a new stream, or ``None`` when
        the table is full (the caller sheds)."""
        if not self._free:
            return None
        state = StreamState(
            stream_id=stream_id,
            slot=self._free.pop(),
            native_hw=tuple(native_hw),
            opened_at=now,
            last_activity=now,
        )
        self.streams[stream_id] = state
        self.peak_occupancy = max(self.peak_occupancy, self.occupancy)
        return state

    def release(self, stream_id: str) -> Optional[int]:
        """Free a stream's slot (close or eviction); returns the slot."""
        state = self.streams.pop(stream_id, None)
        if state is None:
            return None
        self._free.append(state.slot)
        self._free.sort(reverse=True)  # keep lowest-slot-first assignment
        return state.slot

    def evict_expired(
        self, now: float, idle_timeout_s: float
    ) -> List[StreamState]:
        """Evict every idle-expired stream with nothing in flight.

        Deterministic order (oldest activity first, stream_id breaking
        ties) so a replayed chaos run reassigns identical slots."""
        expired = sorted(
            (
                s
                for s in self.streams.values()
                if s.pending == 0
                and now - s.last_activity > idle_timeout_s
            ),
            key=lambda s: (s.last_activity, s.stream_id),
        )
        for s in expired:
            self.release(s.stream_id)
            self.evicted_total += 1
        return expired
