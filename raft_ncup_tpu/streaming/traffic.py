"""Deterministic multi-stream frame schedule: the open-loop traffic the
streaming chaos tests and the ``stream_*`` bench row drive the engine
with.

A schedule is fully determined by ``(seed, n_streams, frames_per_stream,
interval_s, chaos)``. Frames are emitted round-robin across streams
(frame f of every stream before frame f+1 of any) so co-batched streams
stay co-batched — the composition the isolation tests pin bitwise.
Chaos events address **schedule-slot indices**: stream ``s``'s frame
``f`` is slot ``f * n_streams + s`` whether or not it is emitted, so a
coordinate is stable under other chaos events (an ``abandon`` does not
renumber later slots — an event landing on a slot the abandoned stream
no longer emits is deliberately inert, never silently displaced onto a
different stream's frame):

- ``corruptframe@N`` — frame ``N``'s first image is all-NaN float32 →
  the engine's in-graph anomaly check must reset only the owning
  stream's slot.
- ``abandon@N`` — the stream owning frame ``N`` emits nothing after it
  (no close): the abandonment idle eviction must clean up.
- ``burst@N`` — at frame ``N``'s due time, ``burst_size`` extra
  single-frame streams (``burst-k``) arrive → stream admission must
  shed the overflow.
- ``sigterm@N`` — :func:`replay_streams` delivers a real SIGTERM after
  submitting ``N`` frames (the graceful-drain contract mid-window).

Per-stream content comes from ``data/synthetic.SyntheticFlowDataset``
seeded by ``(seed, stream)``, so streams are distinct but replayable.
"""

from __future__ import annotations

import os
import signal as signal_mod
import time
from typing import Callable, Iterator, List, Optional, Tuple

import numpy as np

from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.resilience.chaos import ChaosSpec


class StreamTraffic:
    """Deterministic open-loop multi-stream schedule.

    Iterating yields ``(due_s, stream_id, frame_index, image1, image2)``
    ordered by due time. ``interval_s`` is the gap between consecutive
    frame emissions (across all streams).
    """

    def __init__(
        self,
        size_hw: Tuple[int, int],
        n_streams: int,
        frames_per_stream: int,
        *,
        seed: int = 0,
        interval_s: float = 0.0,
        burst_size: int = 4,
        chaos: Optional[ChaosSpec] = None,
        style: str = "smooth",
    ):
        self.size_hw = tuple(size_hw)
        self.n_streams = int(n_streams)
        self.frames_per_stream = int(frames_per_stream)
        self.interval_s = float(interval_s)
        self.burst_size = max(1, int(burst_size))
        self.chaos = chaos or ChaosSpec()
        self._ds = [
            SyntheticFlowDataset(
                self.size_hw,
                length=max(1, self.frames_per_stream),
                seed=seed * 1000 + s,
                style=style,
            )
            for s in range(self.n_streams + 1)
        ]  # dataset n_streams feeds burst streams

    def stream_id(self, s: int) -> str:
        return f"stream-{s}"

    def __iter__(
        self,
    ) -> Iterator[Tuple[float, str, int, np.ndarray, np.ndarray]]:
        abandoned: set = set()
        burst_emitted = 0
        g = -1
        for f in range(self.frames_per_stream):
            for s in range(self.n_streams):
                g += 1
                due = g * self.interval_s
                if s not in abandoned:
                    sample = self._ds[s].sample(f)
                    img1, img2 = sample["image1"], sample["image2"]
                    if g in self.chaos.corrupt_frames:
                        img1 = np.full(img1.shape, np.nan, np.float32)
                    if g in self.chaos.abandon_frames:
                        abandoned.add(s)
                    yield due, self.stream_id(s), f, img1, img2
                if g in self.chaos.burst_requests:
                    # A thundering herd of new one-frame streams ON TOP
                    # of the steady schedule (after the steady frame, so
                    # established streams keep their slots and the
                    # overflow is what sheds).
                    for _ in range(self.burst_size):
                        sample = self._ds[self.n_streams].sample(
                            burst_emitted % self.frames_per_stream
                        )
                        burst_emitted += 1
                        yield (
                            due,
                            f"burst-{burst_emitted - 1}",
                            0,
                            sample["image1"],
                            sample["image2"],
                        )


def replay_streams(
    engine,
    traffic: StreamTraffic,
    *,
    preempt=None,
    sigterm_after: Optional[int] = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> Tuple[List, bool]:
    """Drive ``engine`` with ``traffic`` open-loop; returns
    ``(handles, interrupted)``.

    Open-loop: frames submit at their due times regardless of
    completions — the engine's admission control is what bounds the
    queue. ``preempt`` is an installed ``PreemptionHandler``; once its
    flag is set the driver stops submitting immediately and the caller
    invokes ``engine.drain()`` for the flush (``serving/traffic.replay``'s
    contract, per frame instead of per request).
    """
    handles: List = []
    t0 = clock()
    for due, stream_id, frame_index, img1, img2 in traffic:
        if preempt is not None and preempt.requested:
            return handles, True
        delay = due - (clock() - t0)
        if delay > 0:
            sleep(delay)
        handles.append(
            engine.submit(stream_id, img1, img2, frame_index=frame_index)
        )
        if sigterm_after is not None and len(handles) == sigterm_after:
            os.kill(os.getpid(), signal_mod.SIGTERM)
    return handles, bool(preempt is not None and preempt.requested)
