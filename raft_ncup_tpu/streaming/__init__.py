"""Streaming video engine: many concurrent stateful streams multiplexed
into one batched, jitted, device-resident warm-start step.

The scenario this subsystem opens (ROADMAP item 2): continuous video.
Per-stream recurrent state (previous low-res flow and optionally the
GRU hidden state) lives in a fixed-capacity HBM slot table
(``slots.py``); frames from many streams batch together through one
compiled step per batch size (``engine.py``), with the warm-start
forward splat re-expressed in pure JAX
(``ops/warmstart.forward_interpolate_jax``) so state never leaves the
device between frames. The robustness layer — bounded stream admission
with shedding, idle/abandoned-stream eviction, in-graph per-stream
anomaly reset, frame-gap staleness, graceful drain — is chaos-tested
end to end (tests/test_streaming.py; docs/STREAMING.md).
"""

from raft_ncup_tpu.config import StreamConfig
from raft_ncup_tpu.streaming.engine import (
    FrameRequest,
    StreamEngine,
    StreamStats,
)
from raft_ncup_tpu.streaming.slots import (
    SlotRegistry,
    StreamState,
    init_slot_table,
)
from raft_ncup_tpu.streaming.traffic import StreamTraffic, replay_streams

__all__ = [
    "FrameRequest",
    "SlotRegistry",
    "StreamConfig",
    "StreamEngine",
    "StreamState",
    "StreamStats",
    "StreamTraffic",
    "init_slot_table",
    "replay_streams",
]
