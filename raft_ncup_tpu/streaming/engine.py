"""The multi-stream video engine: device-resident warm start over a
fixed-capacity slot table, with per-stream fault isolation.

Data path (one dispatcher thread; clients submit from their own
threads):

1. **stream admission** (client thread, inside ``submit``): an unknown
   ``stream_id`` claims the lowest free slot; a full table first evicts
   idle-expired streams, then sheds with an honest ``retry_after_s``
   (time until the soonest slot becomes reclaimable). Slots are a HARD
   capacity — a stream without a slot cannot make progress, so stream
   overload sheds instead of queueing (``serving/admission.py``'s
   discipline lifted from requests to streams).
2. **frame admission**: metadata validation (shape/dtype, padded shape
   must equal the engine's slot-table shape, per-stream frame indices
   strictly increasing), staleness decision (index gap >
   ``max_frame_gap`` ⇒ this frame is forced COLD — a stale warm start
   is worse than none), then a non-blocking ``AdmissionQueue.offer``.
   Any /8 frame shape up to UHD (2176x3840) is a valid engine shape:
   the banded corr tier keeps the 4K per-level lookup on-kernel and
   the onthefly fallback bounds the working set, so a 4K slot table
   warms like any other (docs/PERF.md "Banded dispatch").
3. **assemble** (dispatcher): ``pop_batch(..., distinct_fn=stream)``
   pops a FIFO run of frames from DISTINCT streams — two frames of one
   stream must be chained through the slot table, never batched
   together — and zero-pads rows up to the nearest allowed batch size;
   pad rows target the scratch slot.
4. **step** (one jitted program per batch size, compiled once): gather
   prev state by slot index → in-graph forward splat
   (``ops/warmstart.forward_interpolate_jax``) masked by the device
   warm flags → batched RAFT forward (optionally seeding the GRU with
   the carried ``net``) → per-row anomaly check (non-finite or
   diverged low-res flow) → scatter the new state back, with anomalous
   rows reset to cold. State never leaves the device between frames.
5. **deliver** (drain worker): the batch's ``(flow_up, bad_flags)``
   ride ONE sanctioned ``jax.device_get`` in the ``AsyncDrain`` worker;
   anomalous rows answer ``rejected`` (their stream just went cold),
   healthy rows answer ``ok`` with the unpadded native flow.

Isolation contract (pinned bitwise in tests/test_streaming.py): a
corrupt frame affects exactly one batch row and one slot — batch-mates'
outputs are bitwise identical to an uninjected run (test-mode rows are
batch-independent and every mask is a ``jnp.where`` select, never an
arithmetic blend), and the reset stream's next frame is bitwise a cold
start. Eviction and slot reuse touch no device memory (the new owner's
first frame is forced cold), so the steady-state executable set is
exactly ``len(batch_sizes)`` programs: zero recompiles, zero implicit
host transfers (``bench.py``'s ``stream_*`` row records both).

Drain contract: ``drain()`` stops stream and frame admission, flushes
every admitted frame through compute, tears down, and returns the final
stats — nothing admitted is silently lost (``serve.py --stream`` wires
it to SIGTERM via ``resilience/preemption.PreemptionHandler`` ⇒ exit
75).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import numpy as np

from raft_ncup_tpu.config import StreamConfig
from raft_ncup_tpu.inference.pipeline import (
    AsyncDrain,
    DispatchThrottle,
    ShapeCachedForward,
)
from raft_ncup_tpu.observability import get_telemetry
from raft_ncup_tpu.observability.telemetry import LEGACY_KEY_ALIASES
from raft_ncup_tpu.ops.padding import InputPadder
from raft_ncup_tpu.serving.admission import AdmissionQueue
from raft_ncup_tpu.serving.request import (
    STATUS_ERROR,
    STATUS_OK,
    STATUS_REJECTED,
    STATUS_SHED,
    FlowResponse,
    ServeHandle,
)
from raft_ncup_tpu.streaming.slots import SlotRegistry, init_slot_table

_POLL_S = 0.05  # dispatcher wake cadence while the queue is idle


@dataclass
class FrameRequest:
    """One admitted frame of one stream, queued for dispatch."""

    request_id: int
    stream_id: str
    slot: int
    frame_index: int
    image1: np.ndarray
    image2: np.ndarray
    cold: bool  # forced cold start (first frame / gap > max_frame_gap)
    submit_time: float
    pad_spec: tuple
    shape_key: Tuple[int, int]  # padded (H, W): AdmissionQueue's key_fn
    # Cross-process trace id adopted from an inbound TraceContext (the
    # fleet router's wire header); rides this frame's spans so one
    # trace_id spans the router hop (observability/spans.py).
    trace_id: Optional[str] = None


@dataclass(eq=False)
class StreamStats:
    """Per-run streaming accounting (ServeStats' note_*-only discipline:
    submit callers, the dispatcher, and the drain worker all write).
    Each ``note`` mirrors into the telemetry registry under the
    canonical counter name (``LEGACY_KEY_ALIASES["stream"]``); the
    legacy summary keys never change."""

    submitted: int = 0
    accepted: int = 0
    completed: int = 0
    shed_streams: int = 0  # stream admission refused (table full)
    shed_frames: int = 0  # frame admission refused (queue full/draining)
    rejected: int = 0  # malformed frames (admission-time validation)
    resets: int = 0  # in-graph anomaly cold-start resets delivered
    errors: int = 0
    batches: int = 0
    padded_rows: int = 0
    streams_opened: int = 0
    streams_closed: int = 0
    streams_evicted: int = 0
    cold_starts: int = 0  # frames dispatched cold (first/gap/reset-next)
    telemetry: object = field(default=None, repr=False, compare=False)
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False
    )

    def note(self, field_name: str, delta: int = 1) -> None:
        with self._lock:
            setattr(self, field_name, getattr(self, field_name) + delta)
        if self.telemetry is not None and delta:
            self.telemetry.inc(
                LEGACY_KEY_ALIASES["stream"][field_name], delta
            )

    def summary(self) -> str:
        return (
            f"submitted={self.submitted} accepted={self.accepted} "
            f"completed={self.completed} shed_streams={self.shed_streams} "
            f"shed_frames={self.shed_frames} rejected={self.rejected} "
            f"resets={self.resets} errors={self.errors} "
            f"batches={self.batches} padded_rows={self.padded_rows} "
            f"opened={self.streams_opened} closed={self.streams_closed} "
            f"evicted={self.streams_evicted} cold_starts={self.cold_starts}"
        )


class StreamEngine:
    """Serve many concurrent video streams against one model + variables.

    ``clock`` is injectable (tests drive idle eviction and chaos
    schedules deterministically); it must be monotonic. The engine owns
    one dispatcher thread from construction until :meth:`drain`.
    """

    def __init__(
        self,
        model,
        variables: dict,
        cfg: Optional[StreamConfig] = None,
        *,
        mesh=None,
        clock: Callable[[], float] = time.monotonic,
        telemetry=None,
    ):
        self.cfg = cfg or StreamConfig()
        self._clock = clock
        # Telemetry hub (observability/): counters mirror under the
        # canonical names, slot lifecycle (admit/evict/shed/reset) lands
        # as correlated ring events, spans trace each batch's stages.
        self._tel = telemetry if telemetry is not None else get_telemetry()
        self.stats = StreamStats(telemetry=self._tel)
        # Machine-readable health (observability/health.py): STARTING →
        # WARMING/READY through warmup (or first batch), READY ⇄
        # DEGRADED via the hub's SLO verdicts, DRAINING in drain() —
        # the stream half of the serve.py --healthz_file surface.
        self.health = self._tel.health("stream", fresh=True)
        # Mesh-first streaming (docs/SHARDING.md): an explicit `mesh=`
        # wins; otherwise StreamConfig.mesh = (data, spatial) builds
        # one. The step programs then compile as SPMD — frame batches
        # sharded over `data`, frame height over `spatial`, the slot
        # table over `data` (when capacity+1 divides it) — and frames
        # pad to the mesh divisor.
        from raft_ncup_tpu.parallel.mesh import resolve_config_mesh

        mesh, self._pad_divisor = resolve_config_mesh(mesh, self.cfg.mesh)
        self.mesh = mesh
        h, w = self.cfg.frame_hw
        padder = InputPadder(
            (int(h), int(w), 3), mode="sintel",
            divisor=self._pad_divisor, bucket=self.cfg.pad_bucket,
        )
        (t, b), (le, r) = padder.pad_spec
        self._ph, self._pw = int(h) + t + b, int(w) + le + r
        self._h8, self._w8 = self._ph // 8, self._pw // 8
        self._hidden = (
            model.cfg.hidden_dim if self.cfg.carry_net else 0
        )
        # Per-engine precision policy (docs/PRECISION.md): the step
        # programs compile under it and the slot table's recurrent state
        # is STORED at its state dtype (bf16 presets halve per-stream
        # HBM; the step upcasts to the pinned f32 coord dtype before the
        # splat). None inherits the model's own policy.
        from raft_ncup_tpu.precision import resolve_policy

        self._policy = (
            resolve_policy(self.cfg.precision)
            if self.cfg.precision is not None
            else resolve_policy(getattr(model, "policy", None))
        )
        # The device slot table. Owned by the dispatcher thread after
        # construction: every step call donates it and replaces the
        # reference with the program's output, so exactly one live copy
        # exists in HBM.
        self._table = init_slot_table(
            self.cfg.capacity, self._h8, self._w8, self._hidden,
            dtype=self._policy.state_jnp,
        )
        # Serializes every step invocation that donates the table: the
        # dispatcher owns it in steady state, but warmup() also runs
        # step programs — two concurrent donors of the same buffer
        # would be a use-after-donate.
        self._table_lock = threading.Lock()
        self._fwd = ShapeCachedForward(
            model, variables, mesh=mesh, cache_size=self.cfg.cache_size,
            policy=self._policy, telemetry=self._tel,
        )
        self._queue = AdmissionQueue(
            self.cfg.queue_capacity, telemetry=self._tel, name="stream"
        )
        self._throttle = DispatchThrottle(self.cfg.inflight)
        self._drainer = AsyncDrain(depth=self.cfg.drain_depth)
        self.registry = SlotRegistry(self.cfg.capacity)
        self._reg_lock = threading.Lock()
        self._handles: dict[int, ServeHandle] = {}
        self._inflight: dict[int, list] = {}  # drain-failure safety net
        self._inflight_seq = 0
        self._inflight_lock = threading.Lock()
        self._service_ema: Optional[float] = None
        self._ema_lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self.warmed: list = []  # (ph, pw, batch, iters) set, see warmup()
        self._occupancy_sum = 0  # sampled at each dispatched batch
        self._draining = threading.Event()
        self._drained = False
        self._thread = threading.Thread(
            target=self._dispatch_loop, name="stream-dispatch", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ admission

    def submit(
        self,
        stream_id: str,
        image1,
        image2,
        *,
        frame_index: Optional[int] = None,
        request_id: Optional[int] = None,
        trace_id: Optional[str] = None,
    ) -> ServeHandle:
        """Submit the next frame pair of ``stream_id``; returns a handle.

        An unknown stream id is admitted on first use (slot allocation,
        possibly shedding). ``frame_index`` defaults to
        last-admitted + 1; explicit indices must be strictly increasing
        per stream, and a gap beyond ``max_frame_gap`` forces a cold
        start (stale warm state is never used). ``request_id`` lets a
        fleet router supply its correlation id as the frame's identity
        (docs/FLEET.md; caller owns uniqueness); ``trace_id`` adopts the
        router's inbound trace context onto this frame's spans.
        """
        self.stats.note("submitted")
        handle = ServeHandle()
        if request_id is not None:
            rid = int(request_id)
        else:
            with self._id_lock:
                rid = self._next_id
                self._next_id += 1
        if self._draining.is_set():
            self.stats.note("shed_frames")
            handle.complete(FlowResponse(
                rid, STATUS_SHED, retry_after_s=self._retry_after(),
                detail="draining",
            ))
            return handle
        err = self._frame_error(image1) or self._frame_error(image2)
        if err is None and image1.shape != image2.shape:
            err = f"frame shapes differ: {image1.shape} vs {image2.shape}"
        if err is not None:
            self.stats.note("rejected")
            handle.complete(FlowResponse(rid, STATUS_REJECTED, detail=err))
            return handle

        now = self._clock()
        native_hw = (int(image1.shape[0]), int(image1.shape[1]))
        with self._reg_lock:
            state = self.registry.get(stream_id)
            if state is None:
                evicted = self.registry.evict_expired(
                    now, self.cfg.idle_timeout_s
                )
                for s in evicted:
                    self.stats.note("streams_evicted")
                    self._tel.event(
                        "stream_slot_evicted",
                        stream_id=s.stream_id, slot=s.slot,
                    )
                state = self.registry.admit(stream_id, native_hw, now)
                if state is None:
                    self.stats.note("shed_streams")
                    self._tel.event(
                        "stream_slot_shed", stream_id=stream_id
                    )
                    hint = self.registry.soonest_expiry_s(
                        now, self.cfg.idle_timeout_s
                    )
                    handle.complete(FlowResponse(
                        rid, STATUS_SHED,
                        retry_after_s=round(hint, 4),
                        detail="stream table full",
                    ))
                    return handle
                self.stats.note("streams_opened")
                self._tel.event(
                    "stream_slot_admitted",
                    stream_id=stream_id, slot=state.slot,
                )
            if state.native_hw != native_hw:
                self.stats.note("rejected")
                handle.complete(FlowResponse(
                    rid, STATUS_REJECTED,
                    detail=(
                        f"stream {stream_id!r} is {state.native_hw}, "
                        f"got frame {native_hw}"
                    ),
                ))
                return handle
            if state.closing:
                self.stats.note("shed_frames")
                handle.complete(FlowResponse(
                    rid, STATUS_SHED, detail="stream closing",
                ))
                return handle
            last = state.last_frame_index
            idx = frame_index if frame_index is not None else (
                0 if last is None else last + 1
            )
            if last is not None and idx <= last:
                self.stats.note("rejected")
                handle.complete(FlowResponse(
                    rid, STATUS_REJECTED,
                    detail=(
                        f"out-of-order frame index {idx} (last admitted "
                        f"{last}) for stream {stream_id!r}"
                    ),
                ))
                return handle
            cold = last is None or (idx - last) > self.cfg.max_frame_gap
            req = FrameRequest(
                request_id=rid,
                stream_id=stream_id,
                slot=state.slot,
                frame_index=idx,
                image1=image1,
                image2=image2,
                cold=cold,
                submit_time=now,
                pad_spec=self._pad_spec_for(native_hw),
                shape_key=(self._ph, self._pw),
                trace_id=None if trace_id is None else str(trace_id),
            )
            self._handles[rid] = handle
            if not self._queue.offer(req):
                self._handles.pop(rid, None)
                self.stats.note("shed_frames")
                handle.complete(FlowResponse(
                    rid, STATUS_SHED, retry_after_s=self._retry_after(),
                    detail="frame queue full",
                ))
                return handle
            # Admission bookkeeping only after the offer sticks: a shed
            # frame must not advance the stream's index or keep it warm.
            state.last_frame_index = idx
            state.last_activity = now
            state.pending += 1
            state.frames_admitted += 1
        if cold:
            self.stats.note("cold_starts")
        self.stats.note("accepted")
        return handle

    def close_stream(self, stream_id: str) -> bool:
        """Stop admitting frames for ``stream_id``; its slot frees once
        everything already admitted has been answered. Returns False for
        an unknown stream."""
        with self._reg_lock:
            state = self.registry.get(stream_id)
            if state is None:
                return False
            state.closing = True
            if state.pending == 0:
                slot = self.registry.release(stream_id)
                self.stats.note("streams_closed")
                self._tel.event(
                    "stream_slot_released", stream_id=stream_id, slot=slot
                )
        return True

    def _frame_error(self, image) -> Optional[str]:
        shape = getattr(image, "shape", None)
        dtype = getattr(image, "dtype", None)
        if shape is None or dtype is None:
            return f"not an array: {type(image).__name__}"
        if len(shape) != 3 or shape[-1] != 3:
            return f"want (H, W, 3), got shape {tuple(shape)}"
        if np.dtype(dtype).kind not in "uif":
            return f"non-numeric dtype {dtype}"
        h, w = int(shape[0]), int(shape[1])
        padder = InputPadder(
            (h, w, 3), mode="sintel", divisor=self._pad_divisor,
            bucket=self.cfg.pad_bucket,
        )
        (t, b), (le, r) = padder.pad_spec
        if (h + t + b, w + le + r) != (self._ph, self._pw):
            return (
                f"frame {h}x{w} pads to {(h + t + b, w + le + r)}, but "
                f"this engine serves the {(self._ph, self._pw)} slot "
                "table (one padded shape per engine)"
            )
        return None

    def _pad_spec_for(self, native_hw: Tuple[int, int]) -> tuple:
        h, w = native_hw
        return InputPadder(
            (h, w, 3), mode="sintel", divisor=self._pad_divisor,
            bucket=self.cfg.pad_bucket,
        ).pad_spec

    def _retry_after(self) -> float:
        with self._ema_lock:
            per_frame = self._service_ema
        if per_frame is None:
            return self.cfg.default_retry_after_s
        return round((len(self._queue) + 1) * per_frame, 4)

    # ------------------------------------------------------------- dispatch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._queue.pop_batch(
                self.cfg.max_batch,
                timeout=_POLL_S,
                distinct_fn=lambda r: r.stream_id,
            )
            if not batch:
                if self._queue.closed and not len(self._queue):
                    return
                # Idle tick: abandoned streams lose their slots even
                # when no new admission forces the scan.
                with self._reg_lock:
                    evicted = self.registry.evict_expired(
                        self._clock(), self.cfg.idle_timeout_s
                    )
                for s in evicted:
                    self.stats.note("streams_evicted")
                    self._tel.event(
                        "stream_slot_evicted",
                        stream_id=s.stream_id, slot=s.slot,
                    )
                continue
            try:
                self._process(batch)
            except BaseException as e:  # noqa: BLE001 — per-frame status
                # Server-side fault (XLA error, drain-worker failure):
                # every still-pending frame in this batch answers
                # `error`; stranded in-flight batches are flushed from
                # the registry (AsyncDrain surfaces worker errors on a
                # LATER submit). The engine keeps serving.
                self._fail_inflight(e)
                for req in batch:
                    if self._complete(req.request_id, FlowResponse(
                        req.request_id, STATUS_ERROR, detail=repr(e),
                    )):
                        self._finish_frame(req)
                        self.stats.note("errors")

    def _step(self, n_rows: int):
        """The compiled slot-table step for one batch size (compiled
        once per size; ``ShapeCachedForward.custom`` accounts it)."""
        cfg = self.cfg
        # The policy-resolved model: the engine's forward computes at
        # the engine policy's dtypes regardless of which preset the
        # caller's model instance was built under.
        model, policy = self._fwd.model_for()

        def build():
            import jax
            import jax.numpy as jnp

            from raft_ncup_tpu.ops.warmstart import (
                forward_interpolate_batch,
            )

            iters, thresh = cfg.iters, cfg.anomaly_max_flow
            carry_net = bool(self._hidden)
            state_dt = policy.state_jnp
            mesh = self.mesh

            def fn(v, table, img1, img2, slot_idx, cold):
                # Storage is (possibly) narrow; the warm-start splat is
                # coordinate arithmetic, so it runs at the policy's
                # pinned f32 coord dtype. jax.named_scope labels the
                # step's stages in the compiled HLO for xprof
                # (docs/OBSERVABILITY.md).
                with jax.named_scope("stream.slot_gather"):
                    prev_flow = table["flow"][slot_idx].astype(
                        policy.coord_jnp
                    )  # (B, h8, w8, 2)
                    warm = (
                        table["warm"][slot_idx] * (1.0 - cold) > 0.5
                    )  # (B,) bool
                with jax.named_scope("stream.warmstart_splat"):
                    splat = forward_interpolate_batch(
                        prev_flow, cfg.splat_chunk
                    )
                    finit = jnp.where(
                        warm[:, None, None, None], splat,
                        jnp.zeros_like(splat),
                    )
                kwargs = {}
                if carry_net:
                    kwargs = {
                        "net_init": table["net"][slot_idx],
                        "net_warm": warm,
                    }
                flow_lr, flow_up, net_f = model.apply(
                    v, img1, img2, iters=iters, flow_init=finit,
                    test_mode=True, return_net=True, mesh=mesh, **kwargs,
                )
                # In-graph anomaly: a non-finite or diverged row resets
                # ITS slot to cold; batch-mates' rows are untouched.
                with jax.named_scope("stream.anomaly_scatter"):
                    bad = (
                        ~jnp.isfinite(flow_lr).all(axis=(1, 2, 3))
                        | ~jnp.isfinite(flow_up).all(axis=(1, 2, 3))
                        | (jnp.abs(flow_lr).max(axis=(1, 2, 3)) > thresh)
                    )
                    good = ~bad
                    gm = good[:, None, None, None]
                    new_table = dict(table)
                    # Scatter back at the table's STORAGE dtype (donation
                    # needs matching avals; bf16 presets narrow here).
                    new_flow = jnp.where(
                        gm, flow_lr, jnp.zeros_like(flow_lr)
                    ).astype(state_dt)
                    new_table["flow"] = table["flow"].at[slot_idx].set(
                        new_flow
                    )
                    new_table["warm"] = table["warm"].at[slot_idx].set(
                        good.astype(table["warm"].dtype)
                    )
                    if carry_net:
                        netf = net_f.astype(state_dt)
                        new_table["net"] = table["net"].at[slot_idx].set(
                            jnp.where(gm, netf, jnp.zeros_like(netf))
                        )
                return new_table, flow_up, bad

            # Donate the slot table: the step's scatter updates it in
            # place, so exactly one table lives in HBM.
            if mesh is None:
                return jax.jit(fn, donate_argnums=(1,))
            # SPMD step (docs/SHARDING.md): one program over the whole
            # mesh — frame batches shard over (data, spatial), the slot
            # table over `data` when its capacity+1 rows divide the
            # axis (else replicated: uneven NamedShardings are a jit
            # error, and the table is small next to the activations).
            # Donation still holds: in/out table shardings match.
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(mesh, P())
            img = NamedSharding(mesh, P("data", "spatial"))
            n_data = int(mesh.shape.get("data", 1))
            tab = (
                NamedSharding(mesh, P("data"))
                if (cfg.capacity + 1) % n_data == 0
                else repl
            )
            table_sh = {"flow": tab, "warm": tab}
            if carry_net:
                table_sh["net"] = tab
            return jax.jit(
                fn,
                in_shardings=(repl, table_sh, img, img, repl, repl),
                out_shardings=(table_sh, repl, repl),
                donate_argnums=(1,),
            )

        return self._fwd.custom(
            ("stream", n_rows, policy.fingerprint()), build
        )

    def _process(self, batch: list) -> None:
        import jax.numpy as jnp

        # Batch correlation id, minted up front so every span/event of
        # this batch carries it (doubles as the in-flight token).
        with self._inflight_lock:
            token = self._inflight_seq
            self._inflight_seq += 1
        now = self._clock()
        for req in batch:
            self._tel.observe_ms(
                "stream_queue_wait", (now - req.submit_time) * 1e3,
                request_id=req.request_id, stream_id=req.stream_id,
                batch_id=token,
                **({"trace_id": req.trace_id}
                   if req.trace_id is not None else {}),
            )
        # First assembly of an engine that never warmed up: serving ⇒
        # READY (guarded so an SLO-driven DEGRADED is not undone here).
        if self.health.state in ("starting", "warming"):
            self.health.ready("serving")
        n_rows = next(
            b for b in self.cfg.batch_sizes if b >= len(batch)
        )
        pad_rows = n_rows - len(batch)
        with self._tel.span(
            "stream_pad_stage", batch_id=token, rows=len(batch),
            pad_rows=pad_rows,
        ):
            rows1 = [self._stage(r.image1, r.pad_spec) for r in batch]
            rows2 = [self._stage(r.image2, r.pad_spec) for r in batch]
            slot_idx = [r.slot for r in batch]
            cold = [1.0 if r.cold else 0.0 for r in batch]
            scratch = self.cfg.capacity
            for _ in range(pad_rows):
                rows1.append(
                    np.zeros((self._ph, self._pw, 3), np.float32)
                )
                rows2.append(
                    np.zeros((self._ph, self._pw, 3), np.float32)
                )
                slot_idx.append(scratch)
                cold.append(1.0)
        self.stats.note("batches")
        self.stats.note("padded_rows", pad_rows)
        with self._reg_lock:
            self._occupancy_sum += self.registry.occupancy
            self._tel.gauge_set(
                "stream_slot_occupancy", self.registry.occupancy
            )

        from raft_ncup_tpu.utils.profiling import stage_annotation

        t_dispatch = self._clock()
        step = self._step(n_rows)
        trace_ids = [r.trace_id for r in batch if r.trace_id is not None]
        with self._tel.span(
            "stream_dispatch",
            batch_id=token,
            request_ids=[r.request_id for r in batch],
            stream_ids=[r.stream_id for r in batch],
            mesh=self._fwd.mesh_fp,
            policy=self._policy.name,
            **({"trace_ids": trace_ids} if trace_ids else {}),
        ), stage_annotation("stream.dispatch"):
            with self._table_lock:
                self._table, flow_up, bad = step(
                    self._fwd.variables,
                    self._table,
                    jnp.asarray(np.stack(rows1)),
                    jnp.asarray(np.stack(rows2)),
                    jnp.asarray(np.asarray(slot_idx, np.int32)),
                    jnp.asarray(np.asarray(cold, np.float32)),
                )
            self._throttle.push(flow_up)
        with self._inflight_lock:
            self._inflight[token] = batch

        def deliver(host, batch=batch, token=token):
            with self._inflight_lock:
                self._inflight.pop(token, None)
            host_flow, host_bad = host
            done = self._clock()
            # One sanctioned pull per batch (flow + anomaly flags): the
            # independent count flip_recommendations checks against the
            # recorded stream_batches for snapshot consistency.
            self._tel.inc("stream_drain_pulls_total")
            tids = [r.trace_id for r in batch if r.trace_id is not None]
            self._tel.observe_ms(
                "stream_drain", (done - t_dispatch) * 1e3,
                batch_id=token,
                request_ids=[r.request_id for r in batch],
                **({"trace_ids": tids} if tids else {}),
            )
            for k, req in enumerate(batch):
                bad = bool(host_bad[k])
                if bad:
                    resp = FlowResponse(
                        req.request_id, STATUS_REJECTED,
                        latency_s=done - req.submit_time,
                        detail=(
                            "in-graph anomaly: stream reset to cold "
                            "start"
                        ),
                    )
                else:
                    (t, b), (le, r) = req.pad_spec
                    hh, ww = host_flow.shape[1], host_flow.shape[2]
                    resp = FlowResponse(
                        req.request_id, STATUS_OK,
                        flow=host_flow[k, t: hh - b, le: ww - r, :],
                        iters=self.cfg.iters,
                        latency_s=done - req.submit_time,
                    )
                # Gate ALL per-frame bookkeeping on the completion
                # actually happening: if a server-side failure already
                # flushed this frame (_fail_inflight answered it with
                # `error`), finishing it again here would double-
                # decrement the stream's pending count — and a
                # pending==0 misread frees a slot whose stream still
                # has queued frames.
                if not self._complete(req.request_id, resp):
                    continue
                self._finish_frame(req, reset=bad)
                self.stats.note("resets" if bad else "completed")
                if bad:
                    self._tel.event(
                        "stream_anomaly_reset",
                        stream_id=req.stream_id, slot=req.slot,
                        frame_index=req.frame_index, batch_id=token,
                    )
                    # Fault trigger: the reset decision + the recent
                    # timeline (the corrupted frame's whole journey is
                    # still in the ring at delivery time).
                    self._tel.flight_dump(
                        "stream_anomaly_reset",
                        stream_id=req.stream_id, slot=req.slot,
                        frame_index=req.frame_index, batch_id=token,
                    )
                else:
                    # Per-frame end-to-end latency: the SLI behind the
                    # stream_p99_latency SLO (histogram only, no ring
                    # record).
                    self._tel.hist_observe(
                        "stream_e2e_ms",
                        (done - req.submit_time) * 1e3,
                    )
            self._note_service(
                (done - t_dispatch) / max(1, len(batch))
            )

        # The batch's ONE sanctioned pull: full flow + B anomaly flags.
        self._drainer.submit((flow_up, bad), deliver)

    def _finish_frame(self, req: FrameRequest, reset: bool = False) -> None:
        """Per-frame terminal bookkeeping: pending counts, deferred
        close-release, activity refresh, reset accounting."""
        with self._reg_lock:
            state = self.registry.get(req.stream_id)
            if state is None:
                return
            state.pending = max(0, state.pending - 1)
            state.frames_completed += 1
            if reset:
                state.resets += 1
            if state.closing and state.pending == 0:
                slot = self.registry.release(req.stream_id)
                self.stats.note("streams_closed")
                self._tel.event(
                    "stream_slot_released",
                    stream_id=req.stream_id, slot=slot,
                )

    def _fail_inflight(self, exc: BaseException) -> None:
        with self._inflight_lock:
            stranded = list(self._inflight.values())
            self._inflight.clear()
        for batch in stranded:
            for req in batch:
                if self._complete(req.request_id, FlowResponse(
                    req.request_id, STATUS_ERROR,
                    detail=f"result drain failed: {exc!r}",
                )):
                    self._finish_frame(req)
                    self.stats.note("errors")

    def _stage(self, image, pad_spec) -> np.ndarray:
        (t, b), (le, r) = pad_spec
        arr = np.asarray(image, np.float32)
        if t or b or le or r:
            arr = np.pad(arr, ((t, b), (le, r), (0, 0)), mode="edge")
        return arr

    def _complete(self, rid: int, response: FlowResponse) -> bool:
        handle = self._handles.pop(rid, None)
        if handle is None:
            return False
        handle.complete(response)
        return True

    def _note_service(self, per_frame_s: float) -> None:
        with self._ema_lock:
            prev = self._service_ema
            self._service_ema = (
                per_frame_s if prev is None
                else 0.8 * prev + 0.2 * per_frame_s
            )
            ema = self._service_ema
        self._tel.gauge_set("stream_service_time_ema_ms", ema * 1e3)

    # ------------------------------------------------------------ lifecycle

    def warmup(self) -> int:
        """Compile the whole executable set (one step program per batch
        size) against the scratch slot. Returns programs compiled.
        Pausing the queue keeps NEW batches from assembling; the table
        lock is what makes warmup safe against a batch the dispatcher
        had already popped before the pause landed — both donate the
        slot table, and two concurrent donors of one buffer is a
        use-after-donate."""
        import jax

        self.health.warming()
        before = self._fwd.stats["compiles"]
        self._queue.set_paused(True)
        warmed = []
        try:
            import jax.numpy as jnp

            scratch = self.cfg.capacity
            for n in self.cfg.batch_sizes:
                warmed.append((self._ph, self._pw, n, self.cfg.iters))
                zeros = np.zeros(
                    (n, self._ph, self._pw, 3), np.float32
                )
                step = self._step(n)
                with self._table_lock:
                    self._table, flow_up, bad = step(
                        self._fwd.variables,
                        self._table,
                        jnp.asarray(zeros),
                        jnp.asarray(zeros),
                        jnp.asarray(
                            np.full((n,), scratch, np.int32)
                        ),
                        jnp.asarray(np.ones((n,), np.float32)),
                    )
                jax.block_until_ready((self._table, flow_up, bad))
        finally:
            self._queue.set_paused(False)
        # The warmed (padded_h, padded_w, batch, iters) step set — the
        # streaming half of the replica identity serve.py threads into
        # healthz (docs/FLEET.md).
        self.warmed = warmed
        compiled = self._fwd.stats["compiles"] - before
        self.health.ready(f"warmup compiled {compiled} programs")
        return compiled

    def pause(self) -> None:
        """Test/ops hook: stop assembling new batches (queued and new
        frames wait). Deterministic, see AdmissionQueue.set_paused."""
        self._queue.set_paused(True)

    def resume(self) -> None:
        self._queue.set_paused(False)

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, timeout: Optional[float] = None) -> StreamStats:
        """Graceful drain: stop admitting, flush every admitted frame,
        tear down, return final stats. Idempotent. Health goes DRAINING
        immediately (the SIGTERM → exit-75 contract: a healthz poller
        stops routing streams here before the flush completes)."""
        self.health.draining()
        self._draining.set()
        self._queue.close()  # clears any pause: drain must finish
        if self._thread.is_alive():
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"stream dispatcher did not drain within {timeout}s "
                    f"({len(self._queue)} frames still queued)"
                )
        if not self._drained:
            self._drained = True
            self._throttle.drain()
            try:
                self._drainer.close()
            except Exception as e:
                import sys

                print(
                    f"stream drain worker failed: {e!r}", file=sys.stderr
                )
                self._fail_inflight(e)
        return self.stats

    def report(self) -> dict:
        """One JSON-able summary: stats + slot-table occupancy +
        executable accounting."""
        with self._reg_lock:
            occupancy = self.registry.occupancy
            peak = self.registry.peak_occupancy
            evicted = self.registry.evicted_total
        batches = max(1, self.stats.batches)
        # Every pre-telemetry key survives verbatim (back-compat pinned
        # in tests/test_observability.py); `stages` adds the per-stage
        # p50/p99 breakdown from the span tracer alongside.
        stages = {
            k: v
            for k, v in self._tel.tracer.stage_summary().items()
            if k.startswith("stream_")
        }
        return {
            "stats": self.stats.summary(),
            "capacity": self.cfg.capacity,
            "occupancy": occupancy,
            "peak_occupancy": peak,
            "mean_occupancy": round(self._occupancy_sum / batches, 2),
            "evicted": evicted,
            "executables": dict(self._fwd.stats),
            "precision": self._policy.name,  # RESOLVED (None inherits)
            "mesh": self._fwd.mesh_fp,
            "stages": stages,
            "health": self.health.snapshot(),
        }

    def __enter__(self) -> "StreamEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.drain()
