"""Optical-flow and image file I/O.

Covers the full format surface of the reference loader (reference:
core/utils/frame_utils.py): Middlebury ``.flo`` (magic 202021.25),
``.pfm`` (FlyingThings3D), KITTI 16-bit png flow with validity channel,
compressed ``.npz`` FlyingThings flow, and a ``read_gen`` extension
dispatcher. All functions are host-side numpy; arrays are channel-last
``(H, W, 2)`` float32 flow, matching the framework-wide NHWC layout.

Everything here is deliberately vectorized and endian-explicit rather than
a transliteration of the reference's struct-poking.
"""

from __future__ import annotations

import os
import re
import struct
from typing import Union

import numpy as np

# Keep OpenCV single-threaded inside data-loader workers (reference:
# core/utils/frame_utils.py:8-9).
try:
    import cv2

    cv2.setNumThreads(0)
    cv2.ocl.setUseOpenCL(False)
except ImportError:  # pragma: no cover - cv2 is baked into the image
    cv2 = None

_FLO_MAGIC = 202021.25


# --------------------------------------------------------------------- .flo


def read_flo(path: Union[str, os.PathLike]) -> np.ndarray:
    """Read a Middlebury ``.flo`` file -> (H, W, 2) float32.

    Format: float32 magic 202021.25, int32 width, int32 height, then
    row-major interleaved (u, v) float32 pairs — all little-endian
    (reference: core/utils/frame_utils.py:11-30).
    """
    with open(path, "rb") as f:
        magic = struct.unpack("<f", f.read(4))[0]
        if abs(magic - _FLO_MAGIC) > 1e-3:
            raise ValueError(f"{path}: bad .flo magic {magic!r}")
        w, h = struct.unpack("<ii", f.read(8))
        data = np.frombuffer(f.read(8 * w * h), dtype="<f4")
    if data.size != 2 * w * h:
        raise ValueError(f"{path}: truncated .flo ({data.size} of {2*w*h})")
    return data.reshape(h, w, 2).astype(np.float32)


def write_flo(path: Union[str, os.PathLike], flow: np.ndarray) -> None:
    """Write (H, W, 2) float32 flow as Middlebury ``.flo``."""
    flow = np.asarray(flow, dtype=np.float32)
    if flow.ndim != 3 or flow.shape[2] != 2:
        raise ValueError(f"flow must be (H, W, 2), got {flow.shape}")
    h, w = flow.shape[:2]
    with open(path, "wb") as f:
        f.write(struct.pack("<f", _FLO_MAGIC))
        f.write(struct.pack("<ii", w, h))
        f.write(flow.astype("<f4").tobytes())


# --------------------------------------------------------------------- .pfm


def read_pfm(path: Union[str, os.PathLike]) -> np.ndarray:
    """Read a ``.pfm`` file -> (H, W) or (H, W, 3) float32, top-down rows.

    PFM stores rows bottom-up; a negative scale marks little-endian
    (reference: core/utils/frame_utils.py:32-67).
    """
    with open(path, "rb") as f:
        header = f.readline().rstrip()
        if header == b"PF":
            channels = 3
        elif header == b"Pf":
            channels = 1
        else:
            raise ValueError(f"{path}: not a PFM file (header {header!r})")
        dims = f.readline()
        m = re.match(rb"^(\d+)\s+(\d+)\s*$", dims)
        if not m:
            raise ValueError(f"{path}: malformed PFM dims {dims!r}")
        w, h = int(m.group(1)), int(m.group(2))
        scale = float(f.readline().rstrip())
        endian = "<" if scale < 0 else ">"
        data = np.frombuffer(f.read(4 * w * h * channels), dtype=endian + "f4")
    shape = (h, w, 3) if channels == 3 else (h, w)
    return np.flipud(data.reshape(shape)).astype(np.float32)


def write_pfm(
    path: Union[str, os.PathLike], data: np.ndarray, scale: float = 1.0
) -> None:
    """Write (H, W) or (H, W, 3) float32 as little-endian ``.pfm``."""
    data = np.asarray(data, dtype=np.float32)
    if data.ndim == 3 and data.shape[2] == 3:
        header = b"PF"
    elif data.ndim == 2:
        header = b"Pf"
    else:
        raise ValueError(f"pfm data must be (H,W) or (H,W,3), got {data.shape}")
    h, w = data.shape[:2]
    with open(path, "wb") as f:
        f.write(header + b"\n")
        f.write(f"{w} {h}\n".encode())
        f.write(f"{-abs(scale)}\n".encode())
        f.write(np.flipud(data).astype("<f4").tobytes())


# --------------------------------------------------------- KITTI 16-bit png


def read_flow_kitti(
    path: Union[str, os.PathLike]
) -> tuple[np.ndarray, np.ndarray]:
    """Read KITTI 16-bit png flow -> ((H, W, 2) float32, (H, W) valid).

    Encoding: ``u = (png[..., 0] - 2^15) / 64`` with channel 2 the validity
    mask (reference: core/utils/frame_utils.py:102-107).
    """
    raw = cv2.imread(str(path), cv2.IMREAD_ANYDEPTH | cv2.IMREAD_COLOR)
    if raw is None:
        raise FileNotFoundError(f"cannot read {path}")
    raw = raw[:, :, ::-1].astype(np.float32)  # BGR -> RGB channel order
    flow = (raw[:, :, :2] - 2.0**15) / 64.0
    valid = raw[:, :, 2]
    return flow, valid


def read_disp_kitti(
    path: Union[str, os.PathLike]
) -> tuple[np.ndarray, np.ndarray]:
    """Read a KITTI 16-bit disparity png as pseudo-flow
    ((H, W, 2) with u = -disparity, v = 0) plus validity
    (reference: core/utils/frame_utils.py:109-113)."""
    raw = cv2.imread(str(path), cv2.IMREAD_ANYDEPTH)
    if raw is None:
        raise FileNotFoundError(f"cannot read {path}")
    disp = raw.astype(np.float32) / 256.0
    valid = disp > 0.0
    flow = np.stack([-disp, np.zeros_like(disp)], axis=-1)
    return flow, valid


def write_flow_kitti(path: Union[str, os.PathLike], flow: np.ndarray) -> None:
    """Write (H, W, 2) flow as KITTI 16-bit png (all pixels marked valid)."""
    flow = np.asarray(flow, dtype=np.float64)
    enc = 64.0 * flow + 2.0**15
    valid = np.ones(flow.shape[:2] + (1,), np.float64)
    png = np.concatenate([enc, valid], axis=-1).astype(np.uint16)
    cv2.imwrite(str(path), png[:, :, ::-1])


# ------------------------------------------------------------------ images


def read_image(path: Union[str, os.PathLike]) -> np.ndarray:
    """Read an image file -> (H, W, 3) uint8 RGB (grayscale broadcast)."""
    from PIL import Image

    img = np.asarray(Image.open(path)).astype(np.uint8)
    if img.ndim == 2:
        img = np.tile(img[..., None], (1, 1, 3))
    return img[..., :3]


# ---------------------------------------------------------------- dispatch


def read_gen(path: Union[str, os.PathLike]):
    """Read a file by extension (reference: core/utils/frame_utils.py:123-140).

    Images -> (H, W, 3) uint8; ``.flo`` -> (H, W, 2); ``.pfm`` flow ->
    (H, W, 2) (third channel dropped); ``.npz`` compressed FlyingThings ->
    (H, W, 2).
    """
    ext = os.path.splitext(str(path))[-1].lower()
    if ext in (".png", ".jpeg", ".jpg", ".ppm", ".webp"):
        return read_image(path)
    if ext == ".flo":
        return read_flo(path)
    if ext == ".pfm":
        data = read_pfm(path)
        return data if data.ndim == 2 else data[:, :, :2]
    if ext == ".npz":
        return (
            np.load(path)["optical_flow"]
            .astype(np.float32)
            .transpose(1, 2, 0)
        )
    if ext in (".bin", ".raw"):
        return np.load(path)
    raise ValueError(f"unsupported extension: {path}")
