from raft_ncup_tpu.io.flow_io import (
    read_disp_kitti,
    read_flo,
    read_flow_kitti,
    read_gen,
    read_image,
    read_pfm,
    write_flo,
    write_flow_kitti,
    write_pfm,
)

__all__ = [
    "read_flo",
    "write_flo",
    "read_pfm",
    "write_pfm",
    "read_flow_kitti",
    "read_disp_kitti",
    "write_flow_kitti",
    "read_image",
    "read_gen",
]
