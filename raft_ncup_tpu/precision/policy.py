"""The precision policy: the single authority for dtypes on the hot path.

Every compute dtype the model, the inference pipeline, the serving/
streaming tiers, and the bench touch is decided HERE, by one frozen
``PrecisionPolicy`` — flax-style ``param_dtype`` / ``compute_dtype`` /
``output_dtype`` plus the derived dtypes the policy deliberately PINS
regardless of preset (see the property docstrings). Hot-path modules
never spell a raw ``jnp.float32``/``jnp.bfloat16`` inline: graftlint
JGL009 enforces that they route through a policy (or a named, commented
module/class-level constant the policy asserts against).

Why bf16 is safe here (docs/PRECISION.md has the full argument): RAFT's
iterative refinement re-reads full-precision query COORDINATES from the
correlation pyramid every GRU iteration (arXiv:2003.12039), so bf16
compute error in one iteration perturbs the next iteration's *inputs*
but does not accumulate in a carried high-precision state — the error
is bounded per-iteration, which is what makes a measured EPE budget
(tests/test_precision.py) meaningful rather than hopeful. What must NOT
be bf16 is pinned by the policy itself:

- ``coord_dtype`` (f32): the query coordinates / low-res flow carry.
  This is the numerical backbone of the refinement; bf16's 8 mantissa
  bits cannot even represent integer pixel positions above 256.
- ``acc_dtype`` (f32): metric accumulators sum millions of per-pixel
  terms; bf16 sums stall at ~256 (JGL005's dtype-hygiene discipline).
- ``norm_dtype`` (f32): normalization statistics (variance of many
  terms) — the standard mixed-precision exception.
- ``upsampler_dtype`` (f32): the NCUP upsampler sits outside the
  reference's autocast region (core/raft_nc_dbl.py:161) and its
  normalized-conv confidences are ratio-of-sums arithmetic.
- ``param_dtype`` (f32 in every shipped preset): master weights. The
  bf16 *training* preset is bf16-compute-with-f32-master-weights; the
  optimizer, loss, grad-norm and anomaly-sentinel arithmetic all run on
  f32 leaves exactly as before (pinned by tests/test_precision.py).

Presets:

- ``f32``        — everything float32 (the historical behavior).
- ``bf16_infer`` — bf16 activations + bf16 correlation features/volume
  on the test-mode forward; f32 params/coords/outputs/metrics.
- ``bf16_train`` — the same compute dtypes selected for training
  (f32 master weights; f32 loss/grad/sentinel arithmetic falls out of
  the f32 param leaves). Kept as a distinct named preset so a config
  or a bench row says which *phase* opted in, and so the two knobs can
  diverge later without a config migration.

The correlation volume is the dominant memory term (Efficient All-Pairs
Correlation Volume Sampling, arXiv:2505.16942); ``compute_dtype``
halving its element size is also what raises the Pallas VMEM dispatch
thresholds in ``ops/corr_pallas.py::fits_vmem`` (itemsize-aware since
this subsystem landed) so higher pyramid levels stay on-chip at 1080p.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Union

import jax.numpy as jnp

# The dtypes a policy may name. Strings (not jnp dtypes) are stored so
# the frozen dataclass stays hashable, JSON-able, and importable without
# touching a backend.
_ALLOWED = ("float32", "bfloat16")

# Error budgets the bf16 presets are HELD to, vs the f32 preset on the
# synthetic set (mean end-point-error between the two predictions, in
# pixels, at eval shapes). These are the test-pinned contract
# (tests/test_precision.py measures the real deltas and asserts them
# under these bounds) and the thresholds flip_recommendations applies
# to a bench record's parity fields before recommending a default flip.
# Measured on CPU (bf16 emulated, worst-case rounding): forward deltas
# land around 0.05-0.15 px at 96x128/12it; budgets sit ~2-3x above the
# observed ceiling so they catch regressions, not noise.
FORWARD_EPE_BUDGET = 0.5  # px: test-mode forward / serving / streaming
TRAIN_LOSS_RTOL = 0.15  # relative per-step loss-trajectory tolerance

# Early exit rides the same error-budget discipline (docs/PERF.md
# "Early exit"): the adaptive-compute path is HELD to this mean-EPE
# delta vs its own full-budget twin (same inputs, same weights, no
# detection) before a speedup may be recommended. The detection norm
# bounds remaining full-res drift by ~8*tol px per skipped iteration
# (the 8x upsample scales displacements), so a tolerance in the
# recommended range keeps the delta far inside this budget; the pinned
# value sits above detection-boundary noise, not above real quality
# loss (tests/test_earlyexit.py measures the actual deltas under it).
EARLYEXIT_EPE_BUDGET = 0.5  # px: early-exit vs full-budget twin


@dataclass(frozen=True)
class PrecisionPolicy:
    """Immutable dtype policy (flax-style param/compute/output triple).

    ``name`` doubles as the cache fingerprint: ``ShapeCachedForward``
    keys compiled executables on it, serving/streaming configs select
    presets by it, and bench rows are suffixed with it — two policies
    with different dtypes MUST have different names.
    """

    name: str
    param_dtype: str = "float32"
    compute_dtype: str = "float32"
    output_dtype: str = "float32"

    def __post_init__(self) -> None:
        for field in ("param_dtype", "compute_dtype", "output_dtype"):
            v = getattr(self, field)
            if v not in _ALLOWED:
                raise ValueError(
                    f"{field}={v!r} not in {_ALLOWED} (policy {self.name!r})"
                )
        if self.param_dtype != "float32":
            # Master weights are f32 in every supported preset: optimizer
            # moments, loss and sentinel arithmetic all key off the param
            # leaves' dtype, and bf16 master weights would silently halve
            # their precision too.
            raise ValueError(
                f"param_dtype must be 'float32' (master weights); "
                f"policy {self.name!r} asked for {self.param_dtype!r}"
            )
        if self.output_dtype != "float32":
            # Outputs feed metric accumulators, submission writers and
            # the serving response contract — all of which are defined
            # in f32.
            raise ValueError(
                f"output_dtype must be 'float32' (metrics/serving "
                f"contract); policy {self.name!r} asked for "
                f"{self.output_dtype!r}"
            )

    # ------------------------------------------------------- jnp dtypes

    @property
    def param_jnp(self):
        """Master-weight storage dtype (f32 in every shipped preset)."""
        return jnp.dtype(self.param_dtype)

    @property
    def compute_jnp(self):
        """Activation / conv / correlation compute dtype."""
        return jnp.dtype(self.compute_dtype)

    @property
    def output_jnp(self):
        """Final flow-field dtype (metrics/serving contract: f32)."""
        return jnp.dtype(self.output_dtype)

    @property
    def corr_jnp(self):
        """Correlation feature/volume dtype — the dominant memory term,
        deliberately the compute dtype so bf16 halves the volume and
        doubles the Pallas VMEM dispatch thresholds."""
        return self.compute_jnp

    @property
    def state_jnp(self):
        """Streaming slot-table recurrent-state dtype (prev low-res
        flow, optional GRU net): compute dtype, so the bf16 presets
        halve per-stream HBM. The warm-start chain upcasts to
        ``coord_dtype`` before the splat — storage is narrow, coordinate
        arithmetic is not."""
        return self.compute_jnp

    # ------------------------------------------------ pinned (non-knob)

    @property
    def coord_jnp(self):
        """Query-coordinate / low-res-flow-carry dtype: ALWAYS f32.
        The refinement's correctness argument rests on re-reading
        full-precision coordinates each iteration; bf16 cannot represent
        integer pixel positions above 256."""
        return jnp.dtype("float32")

    @property
    def acc_jnp(self):
        """Metric-accumulator dtype: ALWAYS f32 (JGL005 discipline —
        bf16 sums saturate at ~256 summands)."""
        return jnp.dtype("float32")

    @property
    def norm_jnp(self):
        """Normalization-statistics dtype: ALWAYS f32 (the standard
        mixed-precision exception; ``nn/layers.py::Norm`` asserts its
        module constant equals this)."""
        return jnp.dtype("float32")

    @property
    def upsampler_jnp(self):
        """NCUP/convex upsampler dtype: ALWAYS f32 (outside the
        reference's autocast region; normalized-conv confidence
        arithmetic is ratio-of-sums)."""
        return jnp.dtype("float32")

    # ------------------------------------------------------ conveniences

    @property
    def module_dtype(self) -> Optional[Any]:
        """What ``nn/`` modules receive as their ``dtype`` attribute:
        ``None`` for pure-f32 policies (modules follow the input dtype,
        the historical behavior — avoids gratuitous casts in the f32
        program) and the compute dtype otherwise."""
        if self.compute_dtype == "float32":
            return None
        return self.compute_jnp

    @property
    def corr_itemsize(self) -> int:
        """Bytes per correlation element — what
        ``ops/corr_pallas.py::fits_vmem`` budgets VMEM with."""
        return int(self.corr_jnp.itemsize)

    @property
    def is_f32(self) -> bool:
        return self.compute_dtype == "float32"

    def fingerprint(self) -> str:
        """Stable executable-cache key component (``ShapeCachedForward``,
        bench row suffixes)."""
        return self.name


F32 = PrecisionPolicy(name="f32")
BF16_INFER = PrecisionPolicy(name="bf16_infer", compute_dtype="bfloat16")
BF16_TRAIN = PrecisionPolicy(name="bf16_train", compute_dtype="bfloat16")

PRESETS: dict[str, PrecisionPolicy] = {
    p.name: p for p in (F32, BF16_INFER, BF16_TRAIN)
}

PRESET_NAMES = tuple(PRESETS)


def resolve_policy(
    spec: Union[str, PrecisionPolicy, None]
) -> PrecisionPolicy:
    """Resolve a preset name / policy / None (→ ``f32``) to a policy."""
    if spec is None:
        return F32
    if isinstance(spec, PrecisionPolicy):
        return spec
    try:
        return PRESETS[spec]
    except KeyError:
        raise ValueError(
            f"unknown precision preset {spec!r}; known: {PRESET_NAMES}"
        ) from None
