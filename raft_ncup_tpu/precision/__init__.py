"""Precision-policy subsystem (docs/PRECISION.md).

One frozen ``PrecisionPolicy`` is the single authority for every dtype
on the hot path — model compute, correlation volume, Pallas VMEM
budgeting, streaming slot-table state — with named presets selected by
``ModelConfig.precision`` / ``ServeConfig.precision`` /
``StreamConfig.precision`` / ``TrainConfig.precision`` and enforced by
graftlint JGL009 (no raw dtype literals in hot-path modules).
"""

from raft_ncup_tpu.precision.policy import (
    BF16_INFER,
    BF16_TRAIN,
    EARLYEXIT_EPE_BUDGET,
    F32,
    FORWARD_EPE_BUDGET,
    PRESET_NAMES,
    PRESETS,
    TRAIN_LOSS_RTOL,
    PrecisionPolicy,
    resolve_policy,
)

__all__ = [
    "BF16_INFER",
    "BF16_TRAIN",
    "EARLYEXIT_EPE_BUDGET",
    "F32",
    "FORWARD_EPE_BUDGET",
    "PRESETS",
    "PRESET_NAMES",
    "TRAIN_LOSS_RTOL",
    "PrecisionPolicy",
    "resolve_policy",
]
