"""Validation and leaderboard-submission drivers.

Mirrors the reference eval surface (reference: evaluate.py:22-182):
``validate_chairs`` (EPE @ 24 iters), ``validate_sintel`` (clean+final
EPE and 1/3/5px @ 32 iters), ``validate_kitti`` (EPE + F1 @ 24 iters),
and the Sintel/KITTI submission writers (warm-start supported for
Sintel).

TPU shape discipline: frames stream one at a time with dataset-dependent
sizes, so the jitted test-mode forward is cached per padded input shape
(Sintel is one shape; KITTI has a handful) — each unique shape compiles
once instead of every frame.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_ncup_tpu.config import DataConfig
from raft_ncup_tpu.data import datasets as ds_mod
from raft_ncup_tpu.io import write_flo, write_flow_kitti
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.ops import InputPadder, forward_interpolate
from raft_ncup_tpu.viz import flow_to_image


class _ShapeCachedForward:
    """jit cache keyed by (padded shape, iters, warm-start presence).

    With ``mesh`` set (a (data, spatial) ``jax.sharding.Mesh``), every
    forward is one SPMD program: images/flow_init sharded over
    (batch, height), variables and outputs replicated — the driver-level
    entry to spatially-sharded high-res eval (the corr lookup takes the
    shard_map path inside the model, models/raft.py)."""

    def __init__(self, model: RAFT, variables: dict, mesh=None):
        self.model = model
        self.variables = variables
        self.mesh = mesh
        self._fns: dict = {}

    def _jit(self, fn, n_img_args: int):
        if self.mesh is None:
            return jax.jit(fn)
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        img = NamedSharding(self.mesh, P("data", "spatial"))
        return jax.jit(
            fn,
            in_shardings=(repl,) + (img,) * n_img_args,
            out_shardings=(repl, repl),
        )

    def __call__(
        self,
        image1: np.ndarray,
        image2: np.ndarray,
        iters: int,
        flow_init: Optional[np.ndarray] = None,
    ):
        key = (image1.shape, iters, flow_init is not None)
        if key not in self._fns:
            mesh = self.mesh
            if flow_init is None:

                def fn(v, i1, i2):
                    return self.model.apply(
                        v, i1, i2, iters=iters, test_mode=True, mesh=mesh
                    )

            else:

                def fn(v, i1, i2, finit):
                    return self.model.apply(
                        v, i1, i2, iters=iters, flow_init=finit,
                        test_mode=True, mesh=mesh,
                    )

            self._fns[key] = self._jit(fn, 2 if flow_init is None else 3)
        args = (jnp.asarray(image1), jnp.asarray(image2))
        if flow_init is not None:
            args += (jnp.asarray(flow_init),)
        flow_lr, flow_up = self._fns[key](self.variables, *args)
        return np.asarray(flow_lr), np.asarray(flow_up)


def _pad_divisor(mesh) -> int:
    """Images must pad so the 1/8-res feature height divides the mesh's
    spatial axis, else the model's corr lookup cannot take the shard_map
    path (models/raft.py) and GSPMD partitions it pathologically."""
    if mesh is None:
        return 8
    return 8 * int(mesh.shape.get("spatial", 1))


def _pair_arrays(sample: dict) -> tuple[np.ndarray, np.ndarray]:
    img1 = np.asarray(sample["image1"], np.float32)[None]
    img2 = np.asarray(sample["image2"], np.float32)[None]
    return img1, img2


def _prefetch_samples(dataset, num_workers: int = 4, lookahead: int = 8):
    """Decode samples ahead of consumption with a thread pool, preserving
    order. Host-side image decode overlaps the device compute of the
    previous frame/batch — a full 1,041-frame Sintel submission pass at
    32 iters would otherwise be dominated by single-threaded cv2/PNG
    decode (VERDICT r1 weak #6)."""
    from concurrent.futures import ThreadPoolExecutor

    n = len(dataset)
    with ThreadPoolExecutor(num_workers) as pool:
        futures: deque = deque(
            pool.submit(dataset.sample, i) for i in range(min(lookahead, n))
        )
        submitted = len(futures)
        while futures:
            s = futures.popleft().result()
            if submitted < n:
                futures.append(pool.submit(dataset.sample, submitted))
                submitted += 1
            yield s


def _uniform_batches(dataset, batch_size: int, num_workers: int = 4):
    """Yield lists of samples grouped into fixed-size batches when every
    frame shares one shape (Sintel/Chairs); falls back to singletons on
    mixed shapes. Batching amortizes dispatch and fills the MXU — the
    reference evaluates strictly frame-by-frame (evaluate.py:98-104)."""
    pending: list[dict] = []
    shape = None
    for s in _prefetch_samples(
        dataset, num_workers, lookahead=max(2 * batch_size, num_workers)
    ):
        if shape is not None and s["image1"].shape != shape:
            if pending:
                yield pending
            pending = []
        shape = s["image1"].shape
        pending.append(s)
        if len(pending) == batch_size:
            yield pending
            pending = []
    if pending:
        yield pending


def validate_chairs(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 24, batch_size: int = 4, mesh=None,
) -> dict:
    """FlyingChairs validation-split EPE (reference: evaluate.py:90-108)."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.FlyingChairs(
        None, split="validation", root=cfg.root_chairs,
        split_file=cfg.chairs_split_file,
    )
    if len(dataset) == 0:
        print(f"validate_chairs: no data under {cfg.root_chairs}, skipping")
        return {}
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    epe_list = []
    for group in _uniform_batches(dataset, batch_size):
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        _, flow_up = fwd(img1, img2, iters)
        for k, s in enumerate(group):
            epe = np.sqrt(((flow_up[k] - s["flow"]) ** 2).sum(-1))
            epe_list.append(epe.ravel())
    epe = float(np.concatenate(epe_list).mean())
    print(f"Validation Chairs EPE: {epe:f}")
    return {"chairs": epe}


def validate_sintel(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 32, batch_size: int = 2, mesh=None,
) -> dict:
    """Sintel train-split clean+final EPE / 1px / 3px / 5px
    (reference: evaluate.py:111-143)."""
    cfg = data_cfg or DataConfig()
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    results = {}
    for dstype in ("clean", "final"):
        dataset = ds_mod.MpiSintel(
            None, split="training", root=cfg.root_sintel, dstype=dstype
        )
        if len(dataset) == 0:
            print(
                f"validate_sintel: no {dstype} data under "
                f"{cfg.root_sintel}, skipping"
            )
            continue
        epe_list = []
        for group in _uniform_batches(dataset, batch_size):
            img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
            img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
            padder = InputPadder(img1.shape, divisor=_pad_divisor(mesh))
            img1, img2 = padder.pad(img1, img2)
            _, flow_up = fwd(np.asarray(img1), np.asarray(img2), iters)
            flow_b = np.asarray(padder.unpad(jnp.asarray(flow_up)))
            for k, s in enumerate(group):
                epe = np.sqrt(((flow_b[k] - s["flow"]) ** 2).sum(-1))
                epe_list.append(epe.ravel())
        epe_all = np.concatenate(epe_list)
        epe = float(epe_all.mean())
        px1, px3, px5 = (float((epe_all < t).mean()) for t in (1, 3, 5))
        print(
            f"Validation ({dstype}) EPE: {epe:f}, 1px: {px1:f}, "
            f"3px: {px3:f}, 5px: {px5:f}"
        )
        results[dstype] = epe
        results.update(
            {f"{dstype}_1px": px1, f"{dstype}_3px": px3, f"{dstype}_5px": px5}
        )
    return results


def validate_kitti(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 24, mesh=None,
) -> dict:
    """KITTI-2015 train-split EPE + F1 (reference: evaluate.py:146-182).
    F1 = % of valid pixels with epe > 3 and epe/mag > 0.05."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.KITTI(None, split="training", root=cfg.root_kitti)
    if len(dataset) == 0:
        print(f"validate_kitti: no data under {cfg.root_kitti}, skipping")
        return {}
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    epe_list, out_list = [], []
    for s in _prefetch_samples(dataset):
        img1, img2 = _pair_arrays(s)
        padder = InputPadder(img1.shape, mode="kitti", divisor=_pad_divisor(mesh))
        img1, img2 = padder.pad(img1, img2)
        _, flow_up = fwd(np.asarray(img1), np.asarray(img2), iters)
        flow = np.asarray(padder.unpad(jnp.asarray(flow_up))[0])

        epe = np.sqrt(((flow - s["flow"]) ** 2).sum(-1)).ravel()
        mag = np.sqrt((s["flow"] ** 2).sum(-1)).ravel()
        val = s["valid"].ravel() >= 0.5
        out = (epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
        epe_list.append(epe[val].mean())
        out_list.append(out[val])
    epe = float(np.mean(epe_list))
    f1 = 100.0 * float(np.concatenate(out_list).mean())
    print(f"Validation KITTI: {epe:f}, {f1:f}")
    return {"kitti-epe": epe, "kitti-f1": f1}


def create_sintel_submission(
    model: RAFT,
    variables: dict,
    data_cfg: Optional[DataConfig] = None,
    iters: int = 32,
    warm_start: bool = False,
    output_path: str = "sintel_submission",
    write_png: bool = False,
    mesh=None,
) -> None:
    """Write Sintel leaderboard .flo files (reference: evaluate.py:22-57),
    optionally warm-starting each sequence from the previous frame's
    forward-interpolated low-res flow."""
    cfg = data_cfg or DataConfig()
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    for dstype in ("clean", "final"):
        dataset = ds_mod.MpiSintel(
            None, split="test", root=cfg.root_sintel, dstype=dstype
        )
        flow_prev, sequence_prev = None, None
        for s in _prefetch_samples(dataset):
            sequence, frame = s["extra_info"]
            if sequence != sequence_prev:
                flow_prev = None
            img1 = np.asarray(s["image1"], np.float32)[None]
            img2 = np.asarray(s["image2"], np.float32)[None]
            padder = InputPadder(img1.shape, divisor=_pad_divisor(mesh))
            img1, img2 = padder.pad(img1, img2)
            flow_lr, flow_up = fwd(
                np.asarray(img1), np.asarray(img2), iters, flow_init=flow_prev
            )
            flow = np.asarray(padder.unpad(jnp.asarray(flow_up))[0])
            if warm_start:
                flow_prev = forward_interpolate(flow_lr[0])[None]

            out_dir = os.path.join(output_path, dstype, sequence)
            os.makedirs(out_dir, exist_ok=True)
            write_flo(
                os.path.join(out_dir, f"frame{frame + 1:04d}.flo"), flow
            )
            if write_png:
                import cv2

                png_dir = os.path.join(output_path + "_png", dstype, sequence)
                os.makedirs(png_dir, exist_ok=True)
                cv2.imwrite(
                    os.path.join(png_dir, f"frame{frame + 1:04d}.png"),
                    flow_to_image(flow, convert_to_bgr=True),
                )
            sequence_prev = sequence


def create_kitti_submission(
    model: RAFT,
    variables: dict,
    data_cfg: Optional[DataConfig] = None,
    iters: int = 24,
    output_path: str = "kitti_submission",
    write_png: bool = False,
    mesh=None,
) -> None:
    """Write KITTI leaderboard 16-bit pngs (reference: evaluate.py:60-87)."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.KITTI(None, split="testing", root=cfg.root_kitti)
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    os.makedirs(output_path, exist_ok=True)
    if write_png:
        os.makedirs(output_path + "_png", exist_ok=True)
    for s in _prefetch_samples(dataset):
        (frame_id,) = s["extra_info"]
        img1 = np.asarray(s["image1"], np.float32)[None]
        img2 = np.asarray(s["image2"], np.float32)[None]
        padder = InputPadder(img1.shape, mode="kitti", divisor=_pad_divisor(mesh))
        img1, img2 = padder.pad(img1, img2)
        _, flow_up = fwd(np.asarray(img1), np.asarray(img2), iters)
        flow = np.asarray(padder.unpad(jnp.asarray(flow_up))[0])
        write_flow_kitti(os.path.join(output_path, frame_id), flow)
        if write_png:
            import cv2

            cv2.imwrite(
                os.path.join(output_path + "_png", frame_id),
                flow_to_image(flow, convert_to_bgr=True),
            )


def validate_synthetic(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 12, batch_size: int = 4, size_hw: tuple[int, int] = (96, 128),
    length: int = 32, mesh=None, style: Optional[str] = None,
) -> dict:
    """EPE on a HELD-OUT procedural split (seed distinct from the
    training fallback's seed=0) so data-free runs (`--synthetic_ok`,
    `--validation synthetic`) get a genuine generalization signal, not a
    training-set echo. No reference analogue — the reference always
    validates on real datasets (evaluate.py:90-182).

    ``style`` defaults to the training distribution
    (``data_cfg.synthetic_style``) so `--validation synthetic` measures
    generalization on the data the run trained on. ``style="rigid"``
    additionally reports a boundary-band EPE (pixels within 3 px of a
    flow discontinuity) and its complement — the metric pair on which
    guided (NCUP) upsampling is expected to beat bilinear (reference
    claim: core/upsampler.py:75-210)."""
    from raft_ncup_tpu.data.synthetic import (
        SyntheticFlowDataset,
        flow_boundary_mask,
    )

    if style is None:
        style = data_cfg.synthetic_style if data_cfg else "smooth"
    prefix = "synthetic" if style == "smooth" else f"synthetic_{style}"
    dataset = SyntheticFlowDataset(size_hw, length=length, seed=999,
                                   style=style)
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    epe_list, bnd_list, interior_list = [], [], []
    for group in _uniform_batches(dataset, batch_size):
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        _, flow_up = fwd(img1, img2, iters)
        for k, s in enumerate(group):
            epe = np.sqrt(((np.asarray(flow_up[k]) - s["flow"]) ** 2).sum(-1))
            epe_list.append(epe.ravel())
            if style == "rigid":
                band = flow_boundary_mask(s["flow"])
                bnd_list.append(epe[band])
                interior_list.append(epe[~band])
    epe = float(np.concatenate(epe_list).mean())
    out = {prefix: epe}
    if bnd_list:
        out[f"{prefix}_bnd"] = float(np.concatenate(bnd_list).mean())
        out[f"{prefix}_interior"] = float(
            np.concatenate(interior_list).mean()
        )
        print(
            f"Validation Synthetic[{style}] EPE: {epe:f}, "
            f"boundary: {out[f'{prefix}_bnd']:f}, "
            f"interior: {out[f'{prefix}_interior']:f}"
        )
    else:
        print(f"Validation Synthetic EPE: {epe:f}")
    return out


def validate_synthetic_rigid(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    **kwargs,
) -> dict:
    """Held-out piecewise-rigid split with boundary-band EPE (see
    :func:`validate_synthetic`)."""
    return validate_synthetic(
        model, variables, data_cfg, style="rigid", **kwargs
    )


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "kitti": validate_kitti,
    "synthetic": validate_synthetic,
    "synthetic_rigid": validate_synthetic_rigid,
}
