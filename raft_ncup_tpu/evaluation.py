"""Validation and leaderboard-submission drivers.

Mirrors the reference eval surface (reference: evaluate.py:22-182):
``validate_chairs`` (EPE @ 24 iters), ``validate_sintel`` (clean+final
EPE and 1/3/5px @ 32 iters), ``validate_kitti`` (EPE + F1 @ 24 iters),
and the Sintel/KITTI submission writers (warm-start supported for
Sintel).

Built on the async inference subsystem (``raft_ncup_tpu/inference/``;
docs/PERF.md "Eval pipeline"):

- Validators stream batches through :class:`EvalPipeline` (decode →
  host stage/pad → device transfer, all off the dispatch thread) and
  fold EPE/F1 **on device** inside the jitted forward
  (``inference/metrics.py`` via ``RAFT.apply(metric_head=...)``). The
  host pulls a handful of accumulator scalars ONCE per dataset window —
  never a flow field — so the steady-state loop runs clean under
  ``analysis/guards.forbid_host_transfers``.
- Submissions still need full-field pulls; they go through
  :class:`AsyncDrain`, which performs the sanctioned ``jax.device_get``
  on a worker thread behind dispatch.
- Compiled test-mode executables are cached per padded shape in a
  bounded LRU (:class:`ShapeCachedForward`, knob
  ``DataConfig.eval_cache_size``); KITTI's native-shape diversity can
  additionally be collapsed with pad bucketing
  (``DataConfig.eval_pad_bucket``).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from raft_ncup_tpu.config import DataConfig
from raft_ncup_tpu.data import datasets as ds_mod
from raft_ncup_tpu.inference import metrics as metrics_mod
from raft_ncup_tpu.inference.pipeline import (
    AsyncDrain,
    DispatchThrottle,
    EvalPipeline,
    SamplePrefetcher,
    ShapeCachedForward,
)
from raft_ncup_tpu.io import write_flo, write_flow_kitti
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.ops import InputPadder
from raft_ncup_tpu.ops.warmstart import forward_interpolate_batch
from raft_ncup_tpu.parallel.multihost import (
    allreduce_sum_across_hosts,
    is_main_process,
    is_multihost,
)
from raft_ncup_tpu.viz import flow_to_image


def _pad_divisor(mesh) -> int:
    """Images must pad so the 1/8-res feature height divides the mesh's
    spatial axis, else the model's corr lookup cannot take the shard_map
    path (models/raft.py) and GSPMD partitions it pathologically."""
    if mesh is None:
        return 8
    return 8 * int(mesh.shape.get("spatial", 1))


class _HostShard:
    """Round-robin view of a dataset restricted to this process's frames
    (indices ``process_index::process_count``), so a multi-host job
    validates each frame exactly once instead of every host duplicating
    the full pass (VERDICT r4 weak #4). ``n_global`` bounds indexing to
    the cross-host AGREED length (hosts with divergent disks must not
    index frames others lack)."""

    def __init__(self, dataset, n_global: int):
        self._ds = dataset
        self._n = n_global
        self._pi = jax.process_index()
        self._pc = jax.process_count()

    def __len__(self) -> int:
        return (self._n - self._pi + self._pc - 1) // self._pc

    def sample(self, index: int, *a, **kw):
        return self._ds.sample(self._pi + index * self._pc, *a, **kw)


def _shard_for_validation(dataset, mesh):
    """Decide the multi-host validation plan for one dataset.

    Returns ``(dataset_view, n_agreed, do_reduce)``:

    - Host-local forward (``mesh is None``): frames are host-sharded and
      the metric sums all-reduce afterwards — each frame computed once.
    - Global SPMD mesh: every process MUST execute every jitted forward
      in lockstep (the program contains cross-host collectives), so the
      dataset is left whole, all hosts compute identical global metrics,
      and reduction is the identity. Sharding here would desynchronize
      the collectives and hang the pod.

    ``n_agreed`` is the cross-host minimum length, so a host whose disk
    is missing the dataset makes EVERY host skip consistently — a
    host-local skip with a global collective pending deadlocks the rest.
    """
    n = len(dataset)
    if jax.process_count() == 1:
        return dataset, n, False
    from jax.experimental import multihost_utils

    lens = np.asarray(multihost_utils.process_allgather(np.asarray([n])))
    n = int(lens.min())
    if mesh is not None:
        if n != len(dataset):
            return _Truncated(dataset, n), n, False
        return dataset, n, False
    return _HostShard(dataset, n), n, True


class _Truncated:
    """Identity view capped at the cross-host agreed length (lockstep
    SPMD iteration requires every host to run the same batch count)."""

    def __init__(self, dataset, n: int):
        self._ds = dataset
        self._n = n

    def __len__(self) -> int:
        return self._n

    def sample(self, index: int, *a, **kw):
        return self._ds.sample(index, *a, **kw)


def _print_main(msg: str) -> None:
    """Validator console lines only from one process on a pod."""
    if is_main_process():
        print(msg)


def _pad_host(pad_spec, *arrays: np.ndarray) -> list[np.ndarray]:
    """Apply an InputPadder spec with host-side np.pad (replicate edges).

    Staging runs on the EvalPipeline's worker thread; padding there with
    ``jnp.pad`` (InputPadder.pad) would put device work — and a device
    array round-trip — on the staging thread. The spec is identical, the
    backend is not.
    """
    (t, b), (l, r) = pad_spec
    spec = ((0, 0), (t, b), (l, r), (0, 0))
    return [np.pad(x, spec, mode="edge") for x in arrays]


def _run_metric_pass(
    fwd: ShapeCachedForward,
    dataset,
    *,
    kind: str,
    iters: int,
    batch_size: int,
    mesh=None,
    pad_mode: Optional[str] = None,
    bucket: int = 0,
    with_valid: bool = False,
    band_fn=None,
    num_workers: int = 4,
    depth: int = 2,
) -> np.ndarray:
    """One validation pass: stream ``dataset`` through the
    double-buffered :class:`EvalPipeline`, folding every batch into an
    on-device ``kind`` accumulator inside the jitted forward, and pull
    the handful of sums to host with ONE sanctioned ``jax.device_get``
    at the window end. No flow field crosses to host.

    ``pad_mode`` None skips padding (chairs/synthetic shapes are already
    stride-aligned); otherwise images pad host-side on the staging
    thread and the static pad spec rides the batch meta so the jitted
    program crops predictions in-graph (metrics.unpad_in_graph).
    ``band_fn`` (epe_band only) computes the host-side boundary mask
    during staging. Returns the host accumulator (float32 sums, ready
    for ``allreduce_sum_across_hosts`` + ``metrics.finalize``).
    """
    divisor = _pad_divisor(mesh)

    def stage(group: list) -> tuple:
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        arrays = {
            "flow": np.stack([s["flow"] for s in group]).astype(np.float32)
        }
        if with_valid:
            arrays["valid"] = np.stack(
                [s["valid"] for s in group]
            ).astype(np.float32)
        if band_fn is not None:
            arrays["band"] = np.stack(
                [band_fn(s["flow"]) for s in group]
            ).astype(np.float32)
        pad = None
        if pad_mode is not None:
            padder = InputPadder(
                img1.shape, mode=pad_mode, divisor=divisor, bucket=bucket
            )
            pad = padder.pad_spec
            img1, img2 = _pad_host(pad, img1, img2)
        arrays["image1"], arrays["image2"] = img1, img2
        return arrays, {"pad": pad}

    shardings = None
    if mesh is not None and not is_multihost():
        # Transfer each batch straight into the compiled program's input
        # layout (images sharded over (batch, height), metric operands
        # replicated — ShapeCachedForward._jit) so the worker thread owns
        # the distribution and jit dispatch does no re-layout. Multihost
        # global-mesh eval stages the FULL batch on every host
        # (_shard_for_validation's lockstep plan), which is not the
        # per-host-local-shard contract device_put_batch's global_batch
        # path expects — there, placement stays with jit dispatch.
        from jax.sharding import NamedSharding, PartitionSpec as P

        img = NamedSharding(mesh, P("data", "spatial"))
        repl = NamedSharding(mesh, P())
        shardings = {
            "image1": img, "image2": img,
            "flow": repl, "valid": repl, "band": repl,
        }

    acc = metrics_mod.init_acc(kind)
    throttle = DispatchThrottle()  # backend-tuned in-flight bound
    with EvalPipeline(
        dataset,
        stage,
        batch_size=batch_size,
        depth=depth,
        num_workers=num_workers,
        mesh=mesh,
        shardings=shardings,
    ) as pipe:
        for batch, meta in pipe:
            acc = fwd.metrics(
                batch, iters=iters, acc=acc, kind=kind, pad=meta["pad"]
            )
            throttle.push(acc)
    # The window's single sanctioned pull: a few float32 sums, not fields.
    return np.asarray(jax.device_get(acc), np.float64)


# The device-side warm-start splat: jit caches one tiny executable per
# low-res shape; the result stays on device and feeds the next frame's
# flow_init (submissions) or metric program (warm-start validation).
_device_splat = jax.jit(lambda f: forward_interpolate_batch(f))


def _run_warmstart_metric_pass(
    fwd: ShapeCachedForward,
    dataset,
    *,
    kind: str,
    iters: int,
    pad_mode: str = "sintel",
    num_workers: int = 4,
    sequence_of=None,
) -> np.ndarray:
    """Warm-start validation pass: frames stream IN ORDER (batch size 1
    — warm start is a serial per-sequence dependence), each frame's
    metric folds on device inside the jitted forward, and the next
    frame's ``flow_init`` is the device forward-splat of this frame's
    low-res flow. The chain ``flow_lr → splat → flow_init`` never
    touches the host; the window ends with ONE sanctioned
    ``jax.device_get`` of the accumulator sums.

    ``sequence_of(sample)`` names the sample's sequence (default: first
    element of ``extra_info``); a sequence change resets the warm chain
    to cold (zeros ``flow_init`` — bitwise identical to a cold start,
    and the SAME executable, so sequence boundaries cannot recompile).

    Single-host only: warm start needs sequence-adjacent frames, which
    is exactly what ``_HostShard``'s round-robin would destroy.
    """
    import jax.numpy as jnp

    if sequence_of is None:
        def sequence_of(s):
            info = s.get("extra_info")
            return info[0] if info else None

    acc = metrics_mod.init_acc(kind)
    throttle = DispatchThrottle()
    flow_prev = None
    seq_prev = object()  # never equal to a real sequence name
    with SamplePrefetcher(dataset, num_workers=num_workers) as samples:
        for s in samples:
            sequence = sequence_of(s)
            if sequence != seq_prev:
                flow_prev = None
            img1 = np.asarray(s["image1"], np.float32)[None]
            img2 = np.asarray(s["image2"], np.float32)[None]
            gt = np.asarray(s["flow"], np.float32)[None]
            padder = InputPadder(img1.shape, mode=pad_mode)
            pad = padder.pad_spec
            img1, img2 = _pad_host(pad, img1, img2)
            if flow_prev is None:
                # Cold frames reuse the warm executable with a zero
                # init (coords + 0 is bitwise the cold start), so the
                # whole pass is ONE program per shape.
                h8, w8 = img1.shape[1] // 8, img1.shape[2] // 8
                flow_prev = jnp.zeros((1, h8, w8, 2), jnp.float32)
            batch = {"image1": img1, "image2": img2, "flow": gt}
            acc, flow_lr = fwd.metrics(
                batch, iters=iters, acc=acc, kind=kind, pad=pad,
                flow_init=flow_prev,
            )
            flow_prev = _device_splat(flow_lr)
            throttle.push(acc)
            seq_prev = sequence
    return np.asarray(jax.device_get(acc), np.float64)


def validate_chairs(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 24, batch_size: int = 4, mesh=None,
    precision: Optional[str] = None,
) -> dict:
    """FlyingChairs validation-split EPE (reference: evaluate.py:90-108)."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.FlyingChairs(
        None, split="validation", root=cfg.root_chairs,
        split_file=cfg.chairs_split_file,
    )
    dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
    if n == 0:
        _print_main(f"validate_chairs: no data under {cfg.root_chairs}, skipping")
        return {}
    fwd = ShapeCachedForward(
        model, variables, mesh=mesh, cache_size=cfg.eval_cache_size,
        policy=precision,
    )
    acc = _run_metric_pass(
        fwd, dataset, kind="epe", iters=iters, batch_size=batch_size,
        mesh=mesh, num_workers=cfg.num_workers, depth=cfg.device_prefetch,
    )
    if do_reduce:
        acc = allreduce_sum_across_hosts(acc)
    epe = metrics_mod.finalize("epe", acc)["epe"]
    _print_main(f"Validation Chairs EPE: {epe:f}")
    return {"chairs": epe}


def validate_sintel(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 32, batch_size: int = 2, mesh=None,
    warm_start: bool = False, precision: Optional[str] = None,
) -> dict:
    """Sintel train-split clean+final EPE / 1px / 3px / 5px
    (reference: evaluate.py:111-143).

    ``warm_start=True`` evaluates the video scenario the reference's
    ``--warm_start`` submission uses: frames stream sequentially (batch
    size 1), each frame's ``flow_init`` is the device forward-splat of
    the previous frame's low-res flow, and sequence changes reset to
    cold. Single-host only (the warm chain needs sequence-adjacent
    frames; host-sharding would break it) and incompatible with a
    spatial mesh."""
    cfg = data_cfg or DataConfig()
    if warm_start and (mesh is not None or is_multihost()):
        raise ValueError(
            "warm-start validation is a serial per-sequence chain: "
            "single host, no mesh (see _run_warmstart_metric_pass)"
        )
    fwd = ShapeCachedForward(
        model, variables, mesh=mesh, cache_size=cfg.eval_cache_size,
        policy=precision,
    )
    results = {}
    prefix = "warm_" if warm_start else ""
    for dstype in ("clean", "final"):
        dataset = ds_mod.MpiSintel(
            None, split="training", root=cfg.root_sintel, dstype=dstype
        )
        if warm_start:
            if len(dataset) == 0:
                _print_main(
                    f"validate_sintel: no {dstype} data under "
                    f"{cfg.root_sintel}, skipping"
                )
                continue
            acc = _run_warmstart_metric_pass(
                fwd, dataset, kind="px", iters=iters,
                num_workers=cfg.num_workers,
            )
        else:
            dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
            if n == 0:
                _print_main(
                    f"validate_sintel: no {dstype} data under "
                    f"{cfg.root_sintel}, skipping"
                )
                continue
            acc = _run_metric_pass(
                fwd, dataset, kind="px", iters=iters,
                batch_size=batch_size, mesh=mesh, pad_mode="sintel",
                num_workers=cfg.num_workers, depth=cfg.device_prefetch,
            )
            if do_reduce:
                acc = allreduce_sum_across_hosts(acc)
        m = metrics_mod.finalize("px", acc)
        _print_main(
            f"Validation ({prefix}{dstype}) EPE: {m['epe']:f}, "
            f"1px: {m['1px']:f}, 3px: {m['3px']:f}, 5px: {m['5px']:f}"
        )
        results[f"{prefix}{dstype}"] = m["epe"]
        results.update(
            {
                f"{prefix}{dstype}_1px": m["1px"],
                f"{prefix}{dstype}_3px": m["3px"],
                f"{prefix}{dstype}_5px": m["5px"],
            }
        )
    return results


def validate_sintel_warm(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    **kwargs,
) -> dict:
    """Sintel warm-start (video) validation — see :func:`validate_sintel`."""
    return validate_sintel(
        model, variables, data_cfg, warm_start=True, **kwargs
    )


def validate_kitti(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 24, batch_size: int = 2, mesh=None,
    precision: Optional[str] = None,
) -> dict:
    """KITTI-2015 train-split EPE + F1 (reference: evaluate.py:146-182).
    F1 = % of valid pixels with epe > 3 and epe/mag > 0.05.

    Frames group per native shape (``uniform_batches``; KITTI has a
    handful of resolutions — ``DataConfig.eval_pad_bucket`` collapses
    the *padded* shape set so the executable count stays small). The
    reference streams singletons; per-frame metric semantics are
    unchanged: EPE averages per frame, F1 pools valid pixels."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.KITTI(None, split="training", root=cfg.root_kitti)
    dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
    if n == 0:
        _print_main(f"validate_kitti: no data under {cfg.root_kitti}, skipping")
        return {}
    fwd = ShapeCachedForward(
        model, variables, mesh=mesh, cache_size=cfg.eval_cache_size,
        policy=precision,
    )
    acc = _run_metric_pass(
        fwd, dataset, kind="kitti", iters=iters, batch_size=batch_size,
        mesh=mesh, pad_mode="kitti", bucket=cfg.eval_pad_bucket,
        with_valid=True, num_workers=cfg.num_workers,
        depth=cfg.device_prefetch,
    )
    if do_reduce:
        acc = allreduce_sum_across_hosts(acc)
    m = metrics_mod.finalize("kitti", acc)
    _print_main(f"Validation KITTI: {m['epe']:f}, {m['f1']:f}")
    return {"kitti-epe": m["epe"], "kitti-f1": m["f1"]}


def create_sintel_submission(
    model: RAFT,
    variables: dict,
    data_cfg: Optional[DataConfig] = None,
    iters: int = 32,
    warm_start: bool = False,
    output_path: str = "sintel_submission",
    write_png: bool = False,
    mesh=None,
    precision: Optional[str] = None,
) -> None:
    """Write Sintel leaderboard .flo files (reference: evaluate.py:22-57),
    optionally warm-starting each sequence from the previous frame's
    forward-interpolated low-res flow.

    Full-field pulls are unavoidable here — the deliverable IS the flow
    field — but they ride the :class:`AsyncDrain` worker: dispatch of
    frame N+1 overlaps the device→host pull and file write of frame N.
    The warm-start splat runs ON DEVICE
    (``ops/warmstart.forward_interpolate_jax``): the next frame's
    ``flow_init`` is the jitted forward-splat of this frame's device
    ``flow_lr``, so the serial per-frame device→host pull the host
    cKDTree splat used to force (the last JGL008 allowlist entry) is
    gone — the warm-start chain never leaves the device.

    On a pod EVERY process runs the forwards (with a global mesh the
    SPMD program requires all participants — an early return on non-main
    processes would deadlock process 0's first sharded forward), but
    only the main process touches the filesystem: N hosts writing the
    same files into shared storage interleave. Without a mesh the
    forwards are host-local (no collectives), so non-main processes
    skip the pass entirely instead of computing results nobody keeps."""
    write = is_main_process()
    if mesh is None and not write:
        return
    cfg = data_cfg or DataConfig()
    fwd = ShapeCachedForward(
        model, variables, mesh=mesh, cache_size=cfg.eval_cache_size,
        policy=precision,
    )
    for dstype in ("clean", "final"):
        dataset = ds_mod.MpiSintel(
            None, split="test", root=cfg.root_sintel, dstype=dstype
        )
        flow_prev, sequence_prev = None, None
        with SamplePrefetcher(
            dataset, num_workers=cfg.num_workers
        ) as samples, AsyncDrain(depth=cfg.device_prefetch) as drain:
            for s in samples:
                sequence, frame = s["extra_info"]
                if sequence != sequence_prev:
                    flow_prev = None
                img1 = np.asarray(s["image1"], np.float32)[None]
                img2 = np.asarray(s["image2"], np.float32)[None]
                padder = InputPadder(img1.shape, divisor=_pad_divisor(mesh))
                img1, img2 = _pad_host(padder.pad_spec, img1, img2)
                flow_lr, flow_up = fwd.forward_device(
                    img1, img2, iters, flow_init=flow_prev
                )
                if warm_start:
                    # The next frame's flow_init is this frame's
                    # forward-splatted low-res flow — computed on
                    # device, handed straight back to the next
                    # forward_device call as a device array. No host
                    # round-trip in the warm-start chain.
                    flow_prev = _device_splat(flow_lr)
                if write:
                    drain.submit(
                        flow_up,
                        _sintel_writer(
                            padder, output_path, dstype, sequence, frame,
                            write_png,
                        ),
                    )
                sequence_prev = sequence


def _sintel_writer(
    padder: InputPadder, output_path: str, dstype: str, sequence: str,
    frame: int, write_png: bool,
):
    """Drain callback: unpad on host (pure slicing) and write the frame's
    .flo (and optional viz png). Runs on the AsyncDrain worker thread,
    overlapped with the next frame's device compute."""

    def write_cb(flow_up: np.ndarray) -> None:
        flow = padder.unpad(flow_up)[0]
        out_dir = os.path.join(output_path, dstype, sequence)
        os.makedirs(out_dir, exist_ok=True)
        write_flo(os.path.join(out_dir, f"frame{frame + 1:04d}.flo"), flow)
        if write_png:
            import cv2

            png_dir = os.path.join(output_path + "_png", dstype, sequence)
            os.makedirs(png_dir, exist_ok=True)
            cv2.imwrite(
                os.path.join(png_dir, f"frame{frame + 1:04d}.png"),
                flow_to_image(flow, convert_to_bgr=True),
            )

    return write_cb


def create_kitti_submission(
    model: RAFT,
    variables: dict,
    data_cfg: Optional[DataConfig] = None,
    iters: int = 24,
    output_path: str = "kitti_submission",
    write_png: bool = False,
    mesh=None,
    precision: Optional[str] = None,
) -> None:
    """Write KITTI leaderboard 16-bit pngs (reference: evaluate.py:60-87).
    All processes compute when a global mesh forces lockstep, only main
    writes (see create_sintel_submission). Full-field pulls ride the
    AsyncDrain worker behind dispatch."""
    write = is_main_process()
    if mesh is None and not write:
        return
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.KITTI(None, split="testing", root=cfg.root_kitti)
    fwd = ShapeCachedForward(
        model, variables, mesh=mesh, cache_size=cfg.eval_cache_size,
        policy=precision,
    )
    if write:
        os.makedirs(output_path, exist_ok=True)
        if write_png:
            os.makedirs(output_path + "_png", exist_ok=True)
    with SamplePrefetcher(
        dataset, num_workers=cfg.num_workers
    ) as samples, AsyncDrain(depth=cfg.device_prefetch) as drain:
        for s in samples:
            (frame_id,) = s["extra_info"]
            img1 = np.asarray(s["image1"], np.float32)[None]
            img2 = np.asarray(s["image2"], np.float32)[None]
            padder = InputPadder(
                img1.shape, mode="kitti", divisor=_pad_divisor(mesh),
                bucket=cfg.eval_pad_bucket,
            )
            img1, img2 = _pad_host(padder.pad_spec, img1, img2)
            _, flow_up = fwd.forward_device(img1, img2, iters)
            if write:
                drain.submit(
                    flow_up,
                    _kitti_writer(padder, output_path, frame_id, write_png),
                )


def _kitti_writer(
    padder: InputPadder, output_path: str, frame_id: str, write_png: bool
):
    """Drain callback: unpad + write one KITTI 16-bit submission png."""

    def write_cb(flow_up: np.ndarray) -> None:
        flow = padder.unpad(flow_up)[0]
        write_flow_kitti(os.path.join(output_path, frame_id), flow)
        if write_png:
            import cv2

            cv2.imwrite(
                os.path.join(output_path + "_png", frame_id),
                flow_to_image(flow, convert_to_bgr=True),
            )

    return write_cb


def validate_synthetic(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 12, batch_size: int = 4, size_hw: tuple[int, int] = (96, 128),
    length: int = 32, mesh=None, style: Optional[str] = None,
    seed: int = 999, precision: Optional[str] = None,
) -> dict:
    """EPE on a HELD-OUT procedural split (seed distinct from the
    training fallback's seed=0) so data-free runs (`--synthetic_ok`,
    `--validation synthetic`) get a genuine generalization signal, not a
    training-set echo. No reference analogue — the reference always
    validates on real datasets (evaluate.py:90-182).

    ``style`` defaults to the training distribution
    (``data_cfg.synthetic_style``) so `--validation synthetic` measures
    generalization on the data the run trained on. ``style="rigid"``
    additionally reports a boundary-band EPE (pixels within 3 px of a
    flow discontinuity) and its complement — the metric pair on which
    guided (NCUP) upsampling is expected to beat bilinear (reference
    claim: core/upsampler.py:75-210). The band mask is computed on the
    staging thread (cv2.dilate) and shipped to device with the batch.

    ``seed`` keys the held-out split's content. The default (999) is the
    historical held-out split; multi-seed callers
    (scripts/ncup_vs_bilinear.py's bootstrap CI) evaluate the same
    checkpoint over several disjoint splits to put error bars on the
    quality claim. Keep any explicit seed away from the training
    fallback's seed=0."""
    from raft_ncup_tpu.data.synthetic import (
        SyntheticFlowDataset,
        flow_boundary_mask,
    )

    if style is None:
        style = data_cfg.synthetic_style if data_cfg else "smooth"
    prefix = "synthetic" if style == "smooth" else f"synthetic_{style}"
    dataset = SyntheticFlowDataset(size_hw, length=length, seed=seed,
                                   style=style)
    dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
    if n == 0:
        # Mirror the real-data validators: an empty agreed length (e.g.
        # length=0, or more hosts than frames) must skip, not divide by
        # zero below (ADVICE r5).
        _print_main("validate_synthetic: no frames after sharding, skipping")
        return {}
    cfg = data_cfg or DataConfig()
    fwd = ShapeCachedForward(
        model, variables, mesh=mesh, cache_size=cfg.eval_cache_size,
        policy=precision,
    )
    kind = "epe_band" if style == "rigid" else "epe"
    acc = _run_metric_pass(
        fwd, dataset, kind=kind, iters=iters, batch_size=batch_size,
        mesh=mesh,
        band_fn=flow_boundary_mask if style == "rigid" else None,
        num_workers=cfg.num_workers, depth=cfg.device_prefetch,
    )
    if do_reduce:
        acc = allreduce_sum_across_hosts(acc)
    m = metrics_mod.finalize(kind, acc)
    out = {prefix: m["epe"]}
    if style == "rigid":
        out[f"{prefix}_bnd"] = m["bnd"]
        out[f"{prefix}_interior"] = m["interior"]
        _print_main(
            f"Validation Synthetic[{style}] EPE: {m['epe']:f}, "
            f"boundary: {m['bnd']:f}, "
            f"interior: {m['interior']:f}"
        )
    else:
        _print_main(f"Validation Synthetic EPE: {m['epe']:f}")
    return out


def validate_synthetic_rigid(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    **kwargs,
) -> dict:
    """Held-out piecewise-rigid split with boundary-band EPE (see
    :func:`validate_synthetic`)."""
    return validate_synthetic(
        model, variables, data_cfg, style="rigid", **kwargs
    )


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "sintel_warm": validate_sintel_warm,
    "kitti": validate_kitti,
    "synthetic": validate_synthetic,
    "synthetic_rigid": validate_synthetic_rigid,
}
