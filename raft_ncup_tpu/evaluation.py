"""Validation and leaderboard-submission drivers.

Mirrors the reference eval surface (reference: evaluate.py:22-182):
``validate_chairs`` (EPE @ 24 iters), ``validate_sintel`` (clean+final
EPE and 1/3/5px @ 32 iters), ``validate_kitti`` (EPE + F1 @ 24 iters),
and the Sintel/KITTI submission writers (warm-start supported for
Sintel).

TPU shape discipline: frames stream one at a time with dataset-dependent
sizes, so the jitted test-mode forward is cached per padded input shape
(Sintel is one shape; KITTI has a handful) — each unique shape compiles
once instead of every frame.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_ncup_tpu.config import DataConfig
from raft_ncup_tpu.data import datasets as ds_mod
from raft_ncup_tpu.io import write_flo, write_flow_kitti
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.ops import InputPadder, forward_interpolate
from raft_ncup_tpu.parallel.multihost import (
    allreduce_sum_across_hosts,
    is_main_process,
)
from raft_ncup_tpu.viz import flow_to_image


class _ShapeCachedForward:
    """jit cache keyed by (padded shape, iters, warm-start presence).

    With ``mesh`` set (a (data, spatial) ``jax.sharding.Mesh``), every
    forward is one SPMD program: images/flow_init sharded over
    (batch, height), variables and outputs replicated — the driver-level
    entry to spatially-sharded high-res eval (the corr lookup takes the
    shard_map path inside the model, models/raft.py)."""

    def __init__(self, model: RAFT, variables: dict, mesh=None):
        self.model = model
        self.variables = variables
        self.mesh = mesh
        self._fns: dict = {}

    def _jit(self, fn, n_img_args: int):
        if self.mesh is None:
            return jax.jit(fn)
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        img = NamedSharding(self.mesh, P("data", "spatial"))
        return jax.jit(
            fn,
            in_shardings=(repl,) + (img,) * n_img_args,
            out_shardings=(repl, repl),
        )

    def __call__(
        self,
        image1: np.ndarray,
        image2: np.ndarray,
        iters: int,
        flow_init: Optional[np.ndarray] = None,
    ):
        key = (image1.shape, iters, flow_init is not None)
        if key not in self._fns:
            mesh = self.mesh
            if flow_init is None:

                def fn(v, i1, i2):
                    return self.model.apply(
                        v, i1, i2, iters=iters, test_mode=True, mesh=mesh
                    )

            else:

                def fn(v, i1, i2, finit):
                    return self.model.apply(
                        v, i1, i2, iters=iters, flow_init=finit,
                        test_mode=True, mesh=mesh,
                    )

            self._fns[key] = self._jit(fn, 2 if flow_init is None else 3)
        args = (jnp.asarray(image1), jnp.asarray(image2))
        if flow_init is not None:
            args += (jnp.asarray(flow_init),)
        flow_lr, flow_up = self._fns[key](self.variables, *args)
        # ONE explicit batched pull for both outputs (the eval-side
        # analogue of the Logger's one-get-per-window): the previous
        # per-output np.asarray was two implicit device→host syncs per
        # frame/batch — the JGL001 bug class, flagged live by
        # analysis/guards.forbid_host_transfers.
        return jax.device_get((flow_lr, flow_up))


def _pad_divisor(mesh) -> int:
    """Images must pad so the 1/8-res feature height divides the mesh's
    spatial axis, else the model's corr lookup cannot take the shard_map
    path (models/raft.py) and GSPMD partitions it pathologically."""
    if mesh is None:
        return 8
    return 8 * int(mesh.shape.get("spatial", 1))


class _HostShard:
    """Round-robin view of a dataset restricted to this process's frames
    (indices ``process_index::process_count``), so a multi-host job
    validates each frame exactly once instead of every host duplicating
    the full pass (VERDICT r4 weak #4). ``n_global`` bounds indexing to
    the cross-host AGREED length (hosts with divergent disks must not
    index frames others lack)."""

    def __init__(self, dataset, n_global: int):
        self._ds = dataset
        self._n = n_global
        self._pi = jax.process_index()
        self._pc = jax.process_count()

    def __len__(self) -> int:
        return (self._n - self._pi + self._pc - 1) // self._pc

    def sample(self, index: int, *a, **kw):
        return self._ds.sample(self._pi + index * self._pc, *a, **kw)


def _shard_for_validation(dataset, mesh):
    """Decide the multi-host validation plan for one dataset.

    Returns ``(dataset_view, n_agreed, do_reduce)``:

    - Host-local forward (``mesh is None``): frames are host-sharded and
      the metric sums all-reduce afterwards — each frame computed once.
    - Global SPMD mesh: every process MUST execute every jitted forward
      in lockstep (the program contains cross-host collectives), so the
      dataset is left whole, all hosts compute identical global metrics,
      and reduction is the identity. Sharding here would desynchronize
      the collectives and hang the pod.

    ``n_agreed`` is the cross-host minimum length, so a host whose disk
    is missing the dataset makes EVERY host skip consistently — a
    host-local skip with a global collective pending deadlocks the rest.
    """
    n = len(dataset)
    if jax.process_count() == 1:
        return dataset, n, False
    from jax.experimental import multihost_utils

    lens = np.asarray(multihost_utils.process_allgather(np.asarray([n])))
    n = int(lens.min())
    if mesh is not None:
        if n != len(dataset):
            return _Truncated(dataset, n), n, False
        return dataset, n, False
    return _HostShard(dataset, n), n, True


class _Truncated:
    """Identity view capped at the cross-host agreed length (lockstep
    SPMD iteration requires every host to run the same batch count)."""

    def __init__(self, dataset, n: int):
        self._ds = dataset
        self._n = n

    def __len__(self) -> int:
        return self._n

    def sample(self, index: int, *a, **kw):
        return self._ds.sample(index, *a, **kw)


def _print_main(msg: str) -> None:
    """Validator console lines only from one process on a pod."""
    if is_main_process():
        print(msg)


def _prefetch_samples(dataset, num_workers: int = 4, lookahead: int = 8):
    """Decode samples ahead of consumption with a thread pool, preserving
    order. Host-side image decode overlaps the device compute of the
    previous frame/batch — a full 1,041-frame Sintel submission pass at
    32 iters would otherwise be dominated by single-threaded cv2/PNG
    decode (VERDICT r1 weak #6)."""
    from concurrent.futures import ThreadPoolExecutor

    n = len(dataset)
    with ThreadPoolExecutor(num_workers) as pool:
        futures: deque = deque(
            pool.submit(dataset.sample, i) for i in range(min(lookahead, n))
        )
        submitted = len(futures)
        while futures:
            s = futures.popleft().result()
            if submitted < n:
                futures.append(pool.submit(dataset.sample, submitted))
                submitted += 1
            yield s


def _uniform_batches(dataset, batch_size: int, num_workers: int = 4):
    """Yield lists of samples grouped into fixed-size batches when every
    frame shares one shape (Sintel/Chairs); falls back to singletons on
    mixed shapes. Batching amortizes dispatch and fills the MXU — the
    reference evaluates strictly frame-by-frame (evaluate.py:98-104)."""
    pending: list[dict] = []
    shape = None
    for s in _prefetch_samples(
        dataset, num_workers, lookahead=max(2 * batch_size, num_workers)
    ):
        if shape is not None and s["image1"].shape != shape:
            if pending:
                yield pending
            pending = []
        shape = s["image1"].shape
        pending.append(s)
        if len(pending) == batch_size:
            yield pending
            pending = []
    if pending:
        yield pending


def validate_chairs(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 24, batch_size: int = 4, mesh=None,
) -> dict:
    """FlyingChairs validation-split EPE (reference: evaluate.py:90-108)."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.FlyingChairs(
        None, split="validation", root=cfg.root_chairs,
        split_file=cfg.chairs_split_file,
    )
    dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
    if n == 0:
        _print_main(f"validate_chairs: no data under {cfg.root_chairs}, skipping")
        return {}
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    acc = np.zeros(2)  # [epe_sum, n_pixels] — sums so hosts can reduce
    for group in _uniform_batches(dataset, batch_size):
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        _, flow_up = fwd(img1, img2, iters)
        for k, s in enumerate(group):
            epe = np.sqrt(((flow_up[k] - s["flow"]) ** 2).sum(-1))
            acc += (float(epe.sum()), epe.size)
    if do_reduce:
        acc = allreduce_sum_across_hosts(acc)
    epe = float(acc[0] / acc[1])
    _print_main(f"Validation Chairs EPE: {epe:f}")
    return {"chairs": epe}


def validate_sintel(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 32, batch_size: int = 2, mesh=None,
) -> dict:
    """Sintel train-split clean+final EPE / 1px / 3px / 5px
    (reference: evaluate.py:111-143)."""
    cfg = data_cfg or DataConfig()
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    results = {}
    for dstype in ("clean", "final"):
        dataset = ds_mod.MpiSintel(
            None, split="training", root=cfg.root_sintel, dstype=dstype
        )
        dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
        if n == 0:
            _print_main(
                f"validate_sintel: no {dstype} data under "
                f"{cfg.root_sintel}, skipping"
            )
            continue
        # [epe_sum, n, n<1px, n<3px, n<5px] — reducible across hosts.
        acc = np.zeros(5)
        for group in _uniform_batches(dataset, batch_size):
            img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
            img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
            padder = InputPadder(img1.shape, divisor=_pad_divisor(mesh))
            img1, img2 = padder.pad(img1, img2)
            # padded images are already device arrays; round-tripping them
            # through np.asarray would add a d2h pull per batch. unpad is
            # pure slicing and runs host-side on fwd's numpy outputs.
            _, flow_up = fwd(img1, img2, iters)
            flow_b = padder.unpad(flow_up)
            for k, s in enumerate(group):
                epe = np.sqrt(((flow_b[k] - s["flow"]) ** 2).sum(-1))
                acc += (
                    float(epe.sum()), epe.size,
                    int((epe < 1).sum()), int((epe < 3).sum()),
                    int((epe < 5).sum()),
                )
        if do_reduce:
            acc = allreduce_sum_across_hosts(acc)
        epe = float(acc[0] / acc[1])
        px1, px3, px5 = (float(acc[i] / acc[1]) for i in (2, 3, 4))
        _print_main(
            f"Validation ({dstype}) EPE: {epe:f}, 1px: {px1:f}, "
            f"3px: {px3:f}, 5px: {px5:f}"
        )
        results[dstype] = epe
        results.update(
            {f"{dstype}_1px": px1, f"{dstype}_3px": px3, f"{dstype}_5px": px5}
        )
    return results


def validate_kitti(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 24, batch_size: int = 2, mesh=None,
) -> dict:
    """KITTI-2015 train-split EPE + F1 (reference: evaluate.py:146-182).
    F1 = % of valid pixels with epe > 3 and epe/mag > 0.05.

    Frames are batched per shape group via ``_uniform_batches`` like
    chairs/sintel (KITTI has a handful of native resolutions; mixed runs
    fall back to smaller groups) — the reference streams singletons.
    Per-frame metric semantics are unchanged: EPE averages per frame,
    F1 pools valid pixels."""
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.KITTI(None, split="training", root=cfg.root_kitti)
    dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
    if n == 0:
        _print_main(f"validate_kitti: no data under {cfg.root_kitti}, skipping")
        return {}
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    # [frame_epe_sum, n_frames, outlier_count, n_valid_px] — the
    # reference's metric shape (per-frame EPE mean, pixel-pooled F1)
    # expressed as host-reducible sums.
    acc = np.zeros(4)
    for group in _uniform_batches(dataset, batch_size):
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        padder = InputPadder(img1.shape, mode="kitti", divisor=_pad_divisor(mesh))
        img1, img2 = padder.pad(img1, img2)
        _, flow_up = fwd(img1, img2, iters)  # device in, numpy out
        flow_b = padder.unpad(flow_up)  # host-side slicing
        for k, s in enumerate(group):
            epe = np.sqrt(((flow_b[k] - s["flow"]) ** 2).sum(-1)).ravel()
            mag = np.sqrt((s["flow"] ** 2).sum(-1)).ravel()
            val = s["valid"].ravel() >= 0.5
            out = (epe > 3.0) & ((epe / np.maximum(mag, 1e-12)) > 0.05)
            acc += (
                float(epe[val].mean()), 1,
                int(out[val].sum()), int(val.sum()),
            )
    if do_reduce:
        acc = allreduce_sum_across_hosts(acc)
    epe = float(acc[0] / acc[1])
    f1 = 100.0 * float(acc[2] / acc[3])
    _print_main(f"Validation KITTI: {epe:f}, {f1:f}")
    return {"kitti-epe": epe, "kitti-f1": f1}


def create_sintel_submission(
    model: RAFT,
    variables: dict,
    data_cfg: Optional[DataConfig] = None,
    iters: int = 32,
    warm_start: bool = False,
    output_path: str = "sintel_submission",
    write_png: bool = False,
    mesh=None,
) -> None:
    """Write Sintel leaderboard .flo files (reference: evaluate.py:22-57),
    optionally warm-starting each sequence from the previous frame's
    forward-interpolated low-res flow.

    On a pod EVERY process runs the forwards (with a global mesh the
    SPMD program requires all participants — an early return on non-main
    processes would deadlock process 0's first sharded forward), but
    only the main process touches the filesystem: N hosts writing the
    same files into shared storage interleave. Without a mesh the
    forwards are host-local (no collectives), so non-main processes
    skip the pass entirely instead of computing results nobody keeps."""
    write = is_main_process()
    if mesh is None and not write:
        return
    cfg = data_cfg or DataConfig()
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    for dstype in ("clean", "final"):
        dataset = ds_mod.MpiSintel(
            None, split="test", root=cfg.root_sintel, dstype=dstype
        )
        flow_prev, sequence_prev = None, None
        for s in _prefetch_samples(dataset):
            sequence, frame = s["extra_info"]
            if sequence != sequence_prev:
                flow_prev = None
            img1 = np.asarray(s["image1"], np.float32)[None]
            img2 = np.asarray(s["image2"], np.float32)[None]
            padder = InputPadder(img1.shape, divisor=_pad_divisor(mesh))
            img1, img2 = padder.pad(img1, img2)
            flow_lr, flow_up = fwd(img1, img2, iters, flow_init=flow_prev)
            flow = padder.unpad(flow_up)[0]  # numpy already; pure slicing
            if warm_start:
                flow_prev = forward_interpolate(flow_lr[0])[None]

            if write:
                out_dir = os.path.join(output_path, dstype, sequence)
                os.makedirs(out_dir, exist_ok=True)
                write_flo(
                    os.path.join(out_dir, f"frame{frame + 1:04d}.flo"), flow
                )
            if write and write_png:
                import cv2

                png_dir = os.path.join(output_path + "_png", dstype, sequence)
                os.makedirs(png_dir, exist_ok=True)
                cv2.imwrite(
                    os.path.join(png_dir, f"frame{frame + 1:04d}.png"),
                    flow_to_image(flow, convert_to_bgr=True),
                )
            sequence_prev = sequence


def create_kitti_submission(
    model: RAFT,
    variables: dict,
    data_cfg: Optional[DataConfig] = None,
    iters: int = 24,
    output_path: str = "kitti_submission",
    write_png: bool = False,
    mesh=None,
) -> None:
    """Write KITTI leaderboard 16-bit pngs (reference: evaluate.py:60-87).
    All processes compute when a global mesh forces lockstep, only main
    writes (see create_sintel_submission)."""
    write = is_main_process()
    if mesh is None and not write:
        return
    cfg = data_cfg or DataConfig()
    dataset = ds_mod.KITTI(None, split="testing", root=cfg.root_kitti)
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    if write:
        os.makedirs(output_path, exist_ok=True)
        if write_png:
            os.makedirs(output_path + "_png", exist_ok=True)
    for s in _prefetch_samples(dataset):
        (frame_id,) = s["extra_info"]
        img1 = np.asarray(s["image1"], np.float32)[None]
        img2 = np.asarray(s["image2"], np.float32)[None]
        padder = InputPadder(img1.shape, mode="kitti", divisor=_pad_divisor(mesh))
        img1, img2 = padder.pad(img1, img2)
        _, flow_up = fwd(img1, img2, iters)
        flow = padder.unpad(flow_up)[0]
        if write:
            write_flow_kitti(os.path.join(output_path, frame_id), flow)
        if write and write_png:
            import cv2

            cv2.imwrite(
                os.path.join(output_path + "_png", frame_id),
                flow_to_image(flow, convert_to_bgr=True),
            )


def validate_synthetic(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    iters: int = 12, batch_size: int = 4, size_hw: tuple[int, int] = (96, 128),
    length: int = 32, mesh=None, style: Optional[str] = None,
) -> dict:
    """EPE on a HELD-OUT procedural split (seed distinct from the
    training fallback's seed=0) so data-free runs (`--synthetic_ok`,
    `--validation synthetic`) get a genuine generalization signal, not a
    training-set echo. No reference analogue — the reference always
    validates on real datasets (evaluate.py:90-182).

    ``style`` defaults to the training distribution
    (``data_cfg.synthetic_style``) so `--validation synthetic` measures
    generalization on the data the run trained on. ``style="rigid"``
    additionally reports a boundary-band EPE (pixels within 3 px of a
    flow discontinuity) and its complement — the metric pair on which
    guided (NCUP) upsampling is expected to beat bilinear (reference
    claim: core/upsampler.py:75-210)."""
    from raft_ncup_tpu.data.synthetic import (
        SyntheticFlowDataset,
        flow_boundary_mask,
    )

    if style is None:
        style = data_cfg.synthetic_style if data_cfg else "smooth"
    prefix = "synthetic" if style == "smooth" else f"synthetic_{style}"
    dataset = SyntheticFlowDataset(size_hw, length=length, seed=999,
                                   style=style)
    dataset, n, do_reduce = _shard_for_validation(dataset, mesh)
    if n == 0:
        # Mirror the real-data validators: an empty agreed length (e.g.
        # length=0, or more hosts than frames) must skip, not divide by
        # zero below (ADVICE r5).
        _print_main("validate_synthetic: no frames after sharding, skipping")
        return {}
    fwd = _ShapeCachedForward(model, variables, mesh=mesh)
    # [epe_sum, n, bnd_sum, n_bnd, interior_sum, n_interior]
    acc = np.zeros(6)
    for group in _uniform_batches(dataset, batch_size):
        img1 = np.stack([s["image1"] for s in group]).astype(np.float32)
        img2 = np.stack([s["image2"] for s in group]).astype(np.float32)
        _, flow_up = fwd(img1, img2, iters)
        for k, s in enumerate(group):
            epe = np.sqrt(((np.asarray(flow_up[k]) - s["flow"]) ** 2).sum(-1))
            acc[:2] += (float(epe.sum()), epe.size)
            if style == "rigid":
                band = flow_boundary_mask(s["flow"])
                acc[2:] += (
                    float(epe[band].sum()), int(band.sum()),
                    float(epe[~band].sum()), int((~band).sum()),
                )
    if do_reduce:
        acc = allreduce_sum_across_hosts(acc)
    epe = float(acc[0] / acc[1])
    out = {prefix: epe}
    if style == "rigid":
        out[f"{prefix}_bnd"] = float(acc[2] / acc[3])
        out[f"{prefix}_interior"] = float(acc[4] / acc[5])
        _print_main(
            f"Validation Synthetic[{style}] EPE: {epe:f}, "
            f"boundary: {out[f'{prefix}_bnd']:f}, "
            f"interior: {out[f'{prefix}_interior']:f}"
        )
    else:
        _print_main(f"Validation Synthetic EPE: {epe:f}")
    return out


def validate_synthetic_rigid(
    model: RAFT, variables: dict, data_cfg: Optional[DataConfig] = None,
    **kwargs,
) -> dict:
    """Held-out piecewise-rigid split with boundary-band EPE (see
    :func:`validate_synthetic`)."""
    return validate_synthetic(
        model, variables, data_cfg, style="rigid", **kwargs
    )


VALIDATORS = {
    "chairs": validate_chairs,
    "sintel": validate_sintel,
    "kitti": validate_kitti,
    "synthetic": validate_synthetic,
    "synthetic_rigid": validate_synthetic_rigid,
}
