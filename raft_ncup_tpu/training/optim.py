"""Optimizers and LR schedules (reference: train.py:83-99).

AdamW/Adam with global-norm gradient clipping (clip 1.0, reference:
train.py:221) and either the OneCycle-linear schedule or StepLR. optax has
no exact torch OneCycleLR, so the ``anneal_strategy='linear'`` schedule is
implemented directly: warmup from max_lr/div_factor to max_lr over
pct_start of total steps, then linear anneal to
max_lr/div_factor/final_div_factor — over ``num_steps + 100`` total steps
with pct_start 0.05 as the reference configures it.

``freeze_raft`` (reference: core/raft_nc_dbl.py:70-72) is realized with an
optax mask that zeroes updates for every trunk parameter, training only
the upsampler.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from raft_ncup_tpu.config import TrainConfig


def onecycle_linear(
    max_lr: float,
    total_steps: int,
    pct_start: float = 0.05,
    div_factor: float = 25.0,
    final_div_factor: float = 1e4,
) -> Callable[[jax.Array], jax.Array]:
    """torch OneCycleLR(anneal_strategy='linear', cycle_momentum=False).

    Phase boundaries match torch's ``_schedule_phases``: warmup ends at
    ``pct_start * total_steps - 1``; anneal ends at ``total_steps - 1``.
    """
    initial = max_lr / div_factor
    final = initial / final_div_factor
    warm_end = float(pct_start * total_steps) - 1.0
    ann_end = float(total_steps - 1)

    def schedule(count):
        step = jnp.asarray(count, jnp.float32)
        warm_pct = jnp.clip(step / jnp.maximum(warm_end, 1e-8), 0.0, 1.0)
        up = initial + warm_pct * (max_lr - initial)
        ann_pct = jnp.clip(
            (step - warm_end) / jnp.maximum(ann_end - warm_end, 1e-8), 0.0, 1.0
        )
        down = max_lr + ann_pct * (final - max_lr)
        return jnp.where(step <= warm_end, up, down)

    return schedule


def step_lr(base_lr: float, step_size: int, gamma: float = 0.5):
    """torch StepLR (reference: train.py:95-96)."""

    def schedule(count):
        return base_lr * gamma ** (jnp.asarray(count) // step_size)

    return schedule


def build_schedule(cfg: TrainConfig):
    if cfg.scheduler.lower() == "cyclic":
        return onecycle_linear(cfg.lr, cfg.total_schedule_steps, pct_start=0.05)
    if cfg.scheduler.lower() == "step":
        return step_lr(cfg.lr, cfg.scheduler_step, 0.5)
    raise NotImplementedError(f"{cfg.scheduler} scheduler is not implemented!")


# Transform reuse across trainer invocations in one process. optax
# transforms are stateless function bundles, so sharing one instance
# between TrainStates is sound — and necessary for executable reuse:
# ``tx`` rides the TrainState treedef as static metadata
# (pytree_node=False), so a fresh ``tx`` per run means a fresh treedef
# and a full XLA recompile of an otherwise identical train step. The
# kill/resume path (resilience tests, notebook restarts) invokes the
# trainer repeatedly in one process and would otherwise pay that
# compile every time. Keyed on exactly the config fields the transform
# reads; the freeze_raft mask path is excluded (pytree masks are not
# hashable and the flagship path never uses it repeatedly). Bounded FIFO
# so config sweeps cannot grow it without limit.
_TX_CACHE: dict = {}
_TX_CACHE_MAX = 16


def _tx_cache_key(cfg: TrainConfig) -> tuple:
    return (
        cfg.optimizer.lower(), cfg.lr, cfg.wdecay, cfg.epsilon, cfg.clip,
        cfg.scheduler.lower(), cfg.scheduler_step, cfg.total_schedule_steps,
    )


def build_optimizer(
    cfg: TrainConfig,
    trainable_mask: Optional[dict] = None,
) -> optax.GradientTransformation:
    """clip-by-global-norm -> Adam(W) with the configured schedule.

    Args:
      trainable_mask: params-shaped pytree of bools; False freezes the
        parameter (used for freeze_raft).
    """
    if trainable_mask is None:
        key = _tx_cache_key(cfg)
        cached = _TX_CACHE.get(key)
        if cached is not None:
            return cached
    schedule = build_schedule(cfg)
    if cfg.optimizer.lower() == "adamw":
        opt = optax.adamw(
            learning_rate=schedule,
            b1=0.9,
            b2=0.999,
            eps=cfg.epsilon,
            weight_decay=cfg.wdecay,
        )
    elif cfg.optimizer.lower() == "adam":
        opt = optax.adam(
            learning_rate=schedule, b1=0.9, b2=0.999, eps=cfg.epsilon
        )
    else:
        raise NotImplementedError(f"{cfg.optimizer} optimizer is not implemented!")

    tx = optax.chain(optax.clip_by_global_norm(cfg.clip), opt)
    if trainable_mask is not None:
        # multi_transform so the gradient-norm clip sees only trainable
        # parameters — matching torch, where frozen params have no grads at
        # all and so don't contribute to the clipped norm.
        labels = jax.tree.map(
            lambda m: "train" if m else "frozen", trainable_mask
        )
        return optax.multi_transform(
            {"train": tx, "frozen": optax.set_to_zero()}, labels
        )
    while len(_TX_CACHE) >= _TX_CACHE_MAX:
        _TX_CACHE.pop(next(iter(_TX_CACHE)))
    _TX_CACHE[key] = tx
    return tx


def freeze_raft_mask(params: dict) -> dict:
    """Trainable-mask marking only the upsampler as trainable (reference:
    core/raft_nc_dbl.py:70-75: the trunk is frozen *before* the upsampler
    is attached, so only upsampler params receive gradients)."""
    return {
        top: jax.tree.map(lambda _: top == "upsampler", sub)
        for top, sub in params.items()
    }
