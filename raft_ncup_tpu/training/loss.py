"""Sequence loss + training metrics (reference: train.py:42-71).

Gamma-weighted L1 over the per-iteration flow predictions. Faithfulness
notes:

- the per-iteration term is ``mean(valid * |pred - gt|)`` over *all*
  elements — invalid pixels contribute zeros to the numerator but still
  count in the denominator, exactly as the reference's
  ``(valid[:, None] * i_loss).mean()``;
- validity = (valid >= 0.5) AND (|flow_gt| < max_flow), max_flow 400;
- metrics (epe / 1px / 3px / 5px) are computed on the *final* prediction
  over valid pixels only.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sequence_loss(
    flow_preds: jax.Array,
    flow_gt: jax.Array,
    valid: jax.Array,
    gamma: float = 0.8,
    max_flow: float = 400.0,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Args:
      flow_preds: (T, B, H, W, 2) per-iteration predictions.
      flow_gt: (B, H, W, 2).
      valid: (B, H, W) float or bool.
    Returns:
      (scalar loss, metrics dict).
    """
    n = flow_preds.shape[0]
    mag = jnp.sqrt(jnp.sum(flow_gt**2, axis=-1))
    valid = (valid >= 0.5) & (mag < max_flow)
    vmask = valid[None, ..., None].astype(flow_preds.dtype)  # (1, B, H, W, 1)

    weights = gamma ** jnp.arange(n - 1, -1, -1, dtype=flow_preds.dtype)
    abs_err = jnp.abs(flow_preds - flow_gt[None])
    per_iter = jnp.mean(vmask * abs_err, axis=(1, 2, 3, 4))  # (T,)
    loss = jnp.sum(weights * per_iter)

    epe = jnp.sqrt(jnp.sum((flow_preds[-1] - flow_gt) ** 2, axis=-1))
    v = valid.astype(epe.dtype)
    denom = jnp.maximum(v.sum(), 1.0)

    def vmean(x):
        return (x * v).sum() / denom

    metrics = {
        "epe": vmean(epe),
        "1px": vmean((epe < 1).astype(epe.dtype)),
        "3px": vmean((epe < 3).astype(epe.dtype)),
        "5px": vmean((epe < 5).astype(epe.dtype)),
    }
    return loss, metrics
