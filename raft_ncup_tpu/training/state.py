"""Train state: params + batch_stats + optimizer state + step counter.

Unlike the reference — which checkpoints only model weights and silently
restarts the LR schedule on resume (SURVEY.md §5 checkpoint/resume) — the
full state (including optimizer moments and step) is a single pytree,
checkpointed with orbax in ``raft_ncup_tpu.training.checkpoint``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import optax
from flax import struct

from raft_ncup_tpu.config import ModelConfig, TrainConfig
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.training.optim import build_optimizer, freeze_raft_mask


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    # Divergence-sentinel accumulators (resilience/anomaly.py), carried in
    # the state pytree so they live on device and ride the same donated
    # buffers as the optimizer state. None when the sentinel is disabled
    # (an empty pytree subtree — invisible to tree ops and shardings).
    sentinel: Any = None

    def apply_gradients(self, grads, new_batch_stats=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
        )


def create_train_state(
    rng: jax.Array,
    model_cfg: ModelConfig,
    train_cfg: TrainConfig,
    image_shape: Optional[tuple[int, ...]] = None,
) -> tuple[RAFT, TrainState]:
    """Build the model, initialize variables, and assemble the optimizer
    (with the freeze_raft mask when configured)."""
    import jax.numpy as jnp

    model = RAFT(model_cfg)
    if image_shape is None:
        h, w = train_cfg.image_size
        image_shape = (1, h, w, 3)
    variables = model.init(rng, image_shape)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    mask = freeze_raft_mask(params) if model_cfg.freeze_raft else None
    tx = build_optimizer(train_cfg, trainable_mask=mask)
    opt_state = tx.init(params)

    sentinel = None
    if getattr(train_cfg, "anomaly_sentinel", False):
        from raft_ncup_tpu.resilience.anomaly import init_sentinel

        sentinel = init_sentinel()

    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        tx=tx,
        sentinel=sentinel,
    )
    return model, state
