"""Training metrics logging: text file + console + TensorBoard.

Covers the reference ``Logger`` (reference: train.py:102-164): running
means printed every ``sum_freq`` steps, args dumped once at startup,
train scalars and validation dicts to TensorBoard — without the
reference's reliance on a global ``args`` and its lazily-created default
writer (quirks noted in SURVEY.md §5).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Mapping, Optional

import jax


class Logger:
    def __init__(
        self,
        run_dir: str,
        config: Any = None,
        sum_freq: int = 100,
        use_tensorboard: bool = True,
        active: bool = True,
    ):
        """``active=False`` makes every output a no-op — the non-main
        processes of a pod, which would otherwise interleave N copies of
        log.txt/TensorBoard into the same shared run_dir (the reference
        is single-process and never faces this — train.py:102-164)."""
        self.run_dir = run_dir
        self.sum_freq = sum_freq
        self.active = active
        self._txt = None
        self._writer = None
        # Metrics accumulate as running sums ON DEVICE (device scalars stay
        # device scalars; `+` dispatches asynchronously) and are pulled to
        # host with ONE jax.device_get only when a summary fires. A per-push
        # float(v) would be a per-step block_until_ready — it collapses
        # JAX's async dispatch and puts a host round-trip on the critical
        # path of every training step.
        self._acc: dict[str, Any] = {}
        self._acc_n = 0
        if not active:
            return
        os.makedirs(run_dir, exist_ok=True)
        self._txt = open(os.path.join(run_dir, "log.txt"), "a")
        if use_tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._writer = SummaryWriter(
                    log_dir=os.path.join(run_dir, "tb")
                )
            except ImportError:
                pass
        self._t_last = time.perf_counter()
        self._steps_last: Optional[int] = None
        if config is not None:
            self.write_text(self._config_str(config))

    @staticmethod
    def _config_str(config: Any) -> str:
        try:
            from raft_ncup_tpu.config import config_to_json

            return config_to_json(config)
        except Exception:
            return repr(config)

    def write_text(self, text: str) -> None:
        if not self.active:
            return
        self._txt.write(text + "\n")
        self._txt.flush()

    def push(self, step: int, metrics: Mapping[str, Any], lr: Optional[float] = None) -> None:
        """Accumulate one step's metrics; emit a summary every sum_freq
        steps (reference: train.py:124-139).

        Between summaries this performs ZERO host transfers: device
        scalars are summed on device (async dispatch), and the single
        ``jax.device_get`` at the boundary is the only synchronization
        point the logger ever introduces."""
        if not self.active:
            return
        for k, v in metrics.items():
            prev = self._acc.get(k)
            self._acc[k] = v if prev is None else prev + v
        self._acc_n += 1
        if self._steps_last is None:
            self._steps_last = step  # first push after start/resume
        if (step + 1) % self.sum_freq == 0 and self._acc_n:
            # ONE transfer for the whole window, lr riding along as its
            # own tree leaf (a dict key would collide with a metric of the
            # same name): float(lr) on a schedule that returns a device
            # scalar would be an implicit pull (JGL001's runtime analogue
            # — guards.py flags it under --strict_guards).
            sums, lr = jax.device_get((self._acc, lr))
            lr = None if lr is None else float(lr)
            means = {k: float(v) / self._acc_n for k, v in sums.items()}
            self._acc, self._acc_n = {}, 0
            now = time.perf_counter()
            sps = (step + 1 - self._steps_last) / max(now - self._t_last, 1e-9)
            # Telemetry mirror (observability/): the window means ride
            # the SAME boundary pull as host floats into gauges — the
            # training loop's scalars join the one registry every other
            # subsystem reports to, at zero additional syncs.
            from raft_ncup_tpu.observability import get_telemetry

            tel = get_telemetry()
            for k, v in means.items():
                tel.gauge_set(f"train_{k}", v)
            tel.gauge_set("train_steps_per_sec", sps)
            if lr is not None:
                tel.gauge_set("train_lr", lr)
            self._t_last, self._steps_last = now, step + 1
            parts = [f"[{step + 1:6d}"]
            if lr is not None:
                parts.append(f"lr {lr:.2e}")
            parts.append(f"{sps:5.2f} it/s]")
            parts += [f"{k} {v:.4f}" for k, v in sorted(means.items())]
            line = " ".join(parts)
            print(line, flush=True)
            self.write_text(line)
            if self._writer is not None:
                for k, v in means.items():
                    self._writer.add_scalar(f"train/{k}", v, step + 1)
                if lr is not None:
                    self._writer.add_scalar("train/lr", lr, step + 1)
                self._writer.add_scalar("train/steps_per_sec", sps, step + 1)

    def write_dict(self, step: int, results: Mapping[str, float]) -> None:
        """Log a validation-results dict (reference: train.py:151-161)."""
        if not self.active:
            return
        line = f"[val @ {step}] " + json.dumps(
            {k: round(float(v), 5) for k, v in results.items()}
        )
        print(line, flush=True)
        self.write_text(line)
        if self._writer is not None:
            for k, v in results.items():
                self._writer.add_scalar(f"val/{k}", float(v), step)

    def close(self) -> None:
        if self._txt is not None:
            self._txt.close()
        if self._writer is not None:
            self._writer.close()
