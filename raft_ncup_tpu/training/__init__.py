from raft_ncup_tpu.training.loss import sequence_loss  # noqa: F401
from raft_ncup_tpu.training.optim import build_optimizer, onecycle_linear  # noqa: F401
from raft_ncup_tpu.training.state import TrainState, create_train_state  # noqa: F401
