"""Full-train-state checkpointing with orbax.

The reference saves only ``model.state_dict()`` every 5,000 steps and
restarts the LR schedule on resume (reference: train.py:229-231; optimizer/
scheduler state never saved — SURVEY.md §5). Here the whole
``TrainState`` pytree — params, batch_stats, optimizer moments, step —
round-trips through orbax, so resume is exact.

Three load paths mirror the reference's semantics:

- :func:`restore` — resume a run from this framework's own checkpoints
  (the ``--restore_ckpt`` analogue; reference: train.py:179-180);
- :func:`load_torch` — import a PyTorch reference ``.pth`` into the model
  variables (strict, the eval path; reference: evaluate.py:257);
- :func:`load_pretrained_trunk` — warm-start the RAFT trunk of a
  raft_nc_dbl model from a RAFT checkpoint, ignoring the missing
  upsampler (reference: core/raft_nc_dbl.py:57-66).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from raft_ncup_tpu.training.state import TrainState
from raft_ncup_tpu.utils.torch_import import load_torch_checkpoint


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper bound to a run directory."""

    def __init__(self, directory: str, max_to_keep: int = 5):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, state: TrainState, step: Optional[int] = None) -> None:
        step = int(state.step) if step is None else int(step)
        payload = {
            "step": np.asarray(state.step),
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        self._mgr.save(step, args=ocp.args.StandardSave(payload))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(
        self, state: TrainState, step: Optional[int] = None
    ) -> TrainState:
        """Restore into the structure of ``state`` (which supplies the
        optimizer transform and pytree shapes)."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        target = {
            "step": np.asarray(state.step),
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        return state.replace(
            step=jax.numpy.asarray(restored["step"]),
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
        )

    def close(self) -> None:
        self._mgr.close()


def load_torch(path: str, variables: dict, strict: bool = True) -> dict:
    """Import a PyTorch ``.pth`` state dict into model variables."""
    return load_torch_checkpoint(path, variables, strict=strict)


def load_pretrained_trunk(path: str, variables: dict) -> dict:
    """Warm-start the RAFT trunk from a RAFT checkpoint (torch ``.pth`` or
    an orbax run dir), leaving upsampler params at init.

    Mirrors ``--load_pretrained`` (reference: core/raft_nc_dbl.py:57-66):
    the source has no upsampler keys, which is fine; source keys that match
    nothing raise.
    """
    if os.path.isdir(path):
        restored = restore_variables(path)
        return _merge_trunk(restored, variables)
    # Stock RAFT checkpoints carry the convex-mask head; a raft_nc_dbl
    # destination deletes it (reference loads *then* deletes,
    # core/raft_nc_dbl.py:57-68), so those source keys are expected to be
    # unmatched — but only when the destination really has no mask head.
    allow: tuple[str, ...] = ()
    update_params = variables.get("params", {}).get("update_block", {})
    if "mask_conv1" not in update_params:
        allow = (r"^update_block\.mask\.",)
    return load_torch_checkpoint(
        path, variables, strict=True, allow_unmatched=allow
    )


def restore_variables(directory: str) -> dict:
    """Load just the model variables ({params[, batch_stats]}) from an
    orbax run directory's latest step — the eval-side restore (no
    optimizer state, no TrainState structure needed)."""
    mgr = ocp.CheckpointManager(os.path.abspath(directory))
    step = mgr.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoint in {directory}")
    restored = mgr.restore(step)
    mgr.close()
    out = {"params": restored["params"]}
    if restored.get("batch_stats"):
        out["batch_stats"] = restored["batch_stats"]
    return out


def _merge_trunk(source: dict, dest: dict) -> dict:
    """Leaf-level merge of ``source`` variables into ``dest`` variables.

    Source leaves with no destination are allowed (the RAFT mask head is
    deleted in raft_nc_dbl — reference: core/raft_nc_dbl.py:68); dest
    leaves absent from the source stay at init (the NCUP upsampler).
    But if an entire source component (fnet/cnet/...) matches nothing, or
    a matching leaf has the wrong shape, raise — a silently unmatched
    trunk would leave the model at random init while the driver reports a
    successful warm start."""
    from flax import traverse_util

    out = {"params": dict(dest["params"])}
    if "batch_stats" in dest:
        out["batch_stats"] = dict(dest["batch_stats"])
    for group in ("params", "batch_stats"):
        if group not in source or group not in out:
            continue
        src_flat = traverse_util.flatten_dict(source[group])
        dst_flat = dict(traverse_util.flatten_dict(out[group]))
        matched_components: set = set()
        for key, val in src_flat.items():
            if key in dst_flat:
                if np.shape(dst_flat[key]) != np.shape(val):
                    raise ValueError(
                        f"shape mismatch for {group}/{'/'.join(key)}: "
                        f"{np.shape(val)} vs {np.shape(dst_flat[key])}"
                    )
                dst_flat[key] = val
                matched_components.add(key[0])
        unmatched = {k[0] for k in src_flat} - matched_components
        if unmatched:
            raise ValueError(
                f"pretrained {group} components matched nothing in the "
                f"destination model: {sorted(unmatched)}"
            )
        out[group] = traverse_util.unflatten_dict(dst_flat)
    return out
