"""Full-train-state checkpointing with orbax.

The reference saves only ``model.state_dict()`` every 5,000 steps and
restarts the LR schedule on resume (reference: train.py:229-231; optimizer/
scheduler state never saved — SURVEY.md §5). Here the whole
``TrainState`` pytree — params, batch_stats, optimizer moments, step —
round-trips through orbax, so resume is exact.

Three load paths mirror the reference's semantics:

- :func:`restore` — resume a run from this framework's own checkpoints
  (the ``--restore_ckpt`` analogue; reference: train.py:179-180);
- :func:`load_torch` — import a PyTorch reference ``.pth`` into the model
  variables (strict, the eval path; reference: evaluate.py:257);
- :func:`load_pretrained_trunk` — warm-start the RAFT trunk of a
  raft_nc_dbl model from a RAFT checkpoint, ignoring the missing
  upsampler (reference: core/raft_nc_dbl.py:57-66).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

try:
    from orbax.checkpoint.checkpoint_manager import StepAlreadyExistsError
except ImportError:  # pragma: no cover - orbax layout drift
    class StepAlreadyExistsError(ValueError):
        """Stand-in for orbax builds that don't export the type; never
        raised, so the idempotent-save catch simply never fires."""

from raft_ncup_tpu.resilience.anomaly import init_sentinel
from raft_ncup_tpu.resilience.retry import RetryStats, retry_io
from raft_ncup_tpu.training.state import TrainState
from raft_ncup_tpu.utils.torch_import import load_torch_checkpoint

METADATA_FILE = "resume_meta.json"


class CheckpointManager:
    """Thin orbax CheckpointManager wrapper bound to a run directory.

    ``metadata`` (resilience/preemption.py's ``resume_metadata`` blob:
    model variant, config fingerprint, seed) is written next to the
    orbax payloads on every save and VERIFIED before every restore — a
    wrong-architecture resume fails with a clear message instead of an
    opaque orbax pytree-structure error. ``save`` is synchronous
    (staging AND commit-wait) and idempotent per step, so the whole
    write retries on transient ``OSError`` with bounded backoff
    (``retry_stats`` accounts; the train driver writes it to log.txt).
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 5,
        metadata: Optional[dict] = None,
        save_retries: int = 2,
    ):
        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )
        self._metadata = dict(metadata) if metadata else None
        self._save_retries = save_retries
        self.retry_stats = RetryStats()

    def save(self, state: TrainState, step: Optional[int] = None) -> None:
        step = int(state.step) if step is None else int(step)
        payload = {
            "step": np.asarray(state.step),
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            # Always present so the payload structure is uniform whether
            # or not the sentinel is enabled (zeros when it is off).
            "sentinel": (
                state.sentinel if state.sentinel is not None
                else init_sentinel()
            ),
        }
        def _save_and_commit() -> None:
            # orbax defaults to ASYNC checkpointing: save() returns after
            # staging and the disk write fails (if it fails) inside
            # wait_until_finished. Retrying the staging call alone would
            # never cover the actual write, so the retried unit is
            # save + commit-wait. A retry after an attempt that actually
            # committed (the error raced the commit) surfaces as
            # step-already-exists — that is success, not a failure, which
            # makes save() idempotent per step.
            try:
                self._mgr.save(step, args=ocp.args.StandardSave(payload))
            except StepAlreadyExistsError:
                return
            self._mgr.wait_until_finished()

        retry_io(
            _save_and_commit,
            attempts=self._save_retries,
            base_delay_s=0.2,
            stats=self.retry_stats,
            desc=f"checkpoint save @{step}",
            log=self._log_retry,
        )
        self._write_metadata()

    @staticmethod
    def _log_retry(msg: str) -> None:
        # stderr: child stdout is a parsed protocol stream in the bench
        # and distributed-test harnesses around the trainer.
        print(f"CheckpointManager {msg}", file=sys.stderr)

    def _write_metadata(self) -> None:
        if self._metadata is None or jax.process_index() != 0:
            return
        path = os.path.join(self._dir, METADATA_FILE)

        def _write() -> None:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self._metadata, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, path)  # atomic publish

        retry_io(
            _write,
            attempts=self._save_retries,
            base_delay_s=0.2,
            stats=self.retry_stats,
            desc="resume-metadata write",
            log=self._log_retry,
        )

    def saved_metadata(self) -> Optional[dict]:
        """The resume-metadata blob recorded in the run directory, or
        None for pre-metadata checkpoints."""
        path = os.path.join(self._dir, METADATA_FILE)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    def verify_metadata(self) -> None:
        """Fail fast — and legibly — on a mismatched resume."""
        if self._metadata is None:
            return
        saved = self.saved_metadata()
        if saved is None:
            return  # nothing recorded: nothing to verify against
        mismatch = {
            k: (saved[k], v)
            for k, v in self._metadata.items()
            if k in saved and saved[k] != v
        }
        if mismatch:
            detail = "; ".join(
                f"{k}: checkpoint has {a!r}, this run expects {b!r}"
                for k, (a, b) in sorted(mismatch.items())
            )
            raise ValueError(
                f"refusing to restore from {self._dir}: resume metadata "
                f"mismatch ({detail}). A mismatched architecture/config "
                "would otherwise die deep inside orbax with an opaque "
                "pytree-structure error — fix --model / --restore_ckpt "
                "(or the seed) to match the checkpointed run."
            )

    def wait(self) -> None:
        """Compatibility barrier: ``save`` already commits synchronously
        (the retried unit is staging + wait), so this is a no-op unless
        a future orbax path re-introduces background work."""
        self._mgr.wait_until_finished()

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def _payload_has_sentinel(self, step: int) -> bool:
        """Whether the saved payload carries the 'sentinel' subtree.
        Pre-resilience checkpoints don't; restoring them with a sentinel
        in the target would die on the orbax structure mismatch this
        class otherwise exists to make legible. Read from the step's
        on-disk tree metadata; unknown layouts assume current-format."""
        path = os.path.join(self._dir, str(step), "default", "_METADATA")
        try:
            with open(path, encoding="utf-8") as f:
                tree = json.load(f).get("tree_metadata", {})
        except (OSError, ValueError):
            return True
        return any(k.startswith("('sentinel'") for k in tree)

    def restore(
        self, state: TrainState, step: Optional[int] = None
    ) -> TrainState:
        """Restore into the structure of ``state`` (which supplies the
        optimizer transform and pytree shapes)."""
        self.verify_metadata()
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        target = {
            "step": np.asarray(state.step),
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        has_sentinel = self._payload_has_sentinel(step)
        if has_sentinel:
            target["sentinel"] = (
                state.sentinel if state.sentinel is not None
                else init_sentinel()
            )
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        return state.replace(
            step=jax.numpy.asarray(restored["step"]),
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=restored["opt_state"],
            # A pre-sentinel payload restores with the run's fresh
            # (zeroed) counters; disabled-sentinel runs stay None.
            sentinel=(
                restored["sentinel"]
                if has_sentinel and state.sentinel is not None
                else state.sentinel
            ),
        )

    def close(self) -> None:
        self._mgr.close()


def load_torch(path: str, variables: dict, strict: bool = True) -> dict:
    """Import a PyTorch ``.pth`` state dict into model variables."""
    return load_torch_checkpoint(path, variables, strict=strict)


def load_pretrained_trunk(path: str, variables: dict) -> dict:
    """Warm-start the RAFT trunk from a RAFT checkpoint (torch ``.pth`` or
    an orbax run dir), leaving upsampler params at init.

    Mirrors ``--load_pretrained`` (reference: core/raft_nc_dbl.py:57-66):
    the source has no upsampler keys, which is fine; source keys that match
    nothing raise.
    """
    if os.path.isdir(path):
        restored = restore_variables(path)
        return _merge_trunk(restored, variables)
    # Stock RAFT checkpoints carry the convex-mask head; a raft_nc_dbl
    # destination deletes it (reference loads *then* deletes,
    # core/raft_nc_dbl.py:57-68), so those source keys are expected to be
    # unmatched — but only when the destination really has no mask head.
    allow: tuple[str, ...] = ()
    update_params = variables.get("params", {}).get("update_block", {})
    if "mask_conv1" not in update_params:
        allow = (r"^update_block\.mask\.",)
    return load_torch_checkpoint(
        path, variables, strict=True, allow_unmatched=allow
    )


def restore_variables(directory: str) -> dict:
    """Load just the model variables ({params[, batch_stats]}) from an
    orbax run directory's latest step — the eval-side restore (no
    optimizer state, no TrainState structure needed)."""
    mgr = ocp.CheckpointManager(os.path.abspath(directory))
    try:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {directory}")
        # Explicit StandardRestore: this orbax build cannot infer the
        # handler for a bare restore(step) and raises an opaque
        # 'Item "default" ... could not be restored' KeyError.
        restored = mgr.restore(step, args=ocp.args.StandardRestore())
    finally:
        # The orbax manager owns background threads and an async-save
        # barrier; leaking it on a failed restore (missing/corrupt
        # checkpoint) kept those alive for the life of the process.
        mgr.close()
    out = {"params": restored["params"]}
    if restored.get("batch_stats"):
        out["batch_stats"] = restored["batch_stats"]
    return out


def _merge_trunk(source: dict, dest: dict) -> dict:
    """Leaf-level merge of ``source`` variables into ``dest`` variables.

    Source leaves with no destination are allowed (the RAFT mask head is
    deleted in raft_nc_dbl — reference: core/raft_nc_dbl.py:68); dest
    leaves absent from the source stay at init (the NCUP upsampler).
    But if an entire source component (fnet/cnet/...) matches nothing, or
    a matching leaf has the wrong shape, raise — a silently unmatched
    trunk would leave the model at random init while the driver reports a
    successful warm start."""
    from flax import traverse_util

    out = {"params": dict(dest["params"])}
    if "batch_stats" in dest:
        out["batch_stats"] = dict(dest["batch_stats"])
    for group in ("params", "batch_stats"):
        if group not in source or group not in out:
            continue
        src_flat = traverse_util.flatten_dict(source[group])
        dst_flat = dict(traverse_util.flatten_dict(out[group]))
        matched_components: set = set()
        for key, val in src_flat.items():
            if key in dst_flat:
                if np.shape(dst_flat[key]) != np.shape(val):
                    raise ValueError(
                        f"shape mismatch for {group}/{'/'.join(key)}: "
                        f"{np.shape(val)} vs {np.shape(dst_flat[key])}"
                    )
                dst_flat[key] = val
                matched_components.add(key[0])
        unmatched = {k[0] for k in src_flat} - matched_components
        if unmatched:
            raise ValueError(
                f"pretrained {group} components matched nothing in the "
                f"destination model: {sorted(unmatched)}"
            )
        out[group] = traverse_util.unflatten_dict(dst_flat)
    return out
