"""Deterministic multi-phase traffic: the scenario layer over the
single-rate streams (first slice of ROADMAP item 4's scenario suite;
docs/FLEET.md "Elasticity bench").

``serving/traffic.SyntheticTraffic`` is one arrival rate for the whole
run — right for chaos coordinates, wrong for the questions elasticity
asks, which are all about rate CHANGES: how long after a load step does
new capacity take traffic, does a scale-down under load lose anything,
does p99 stay flat through both. :class:`StepTraffic` strings
:class:`TrafficPhase` segments (each its own inter-arrival interval)
into one schedule whose due times, frame content, and phase attribution
are all pure functions of ``(seed, phases)`` — the same step replays
bitwise-identically into the serve bench, the fleet bench, and the
autoscaler acceptance tests.

Three consumption shapes, one schedule:

- ``iter(traffic)`` yields ``(due_s, image1, image2)`` — drop-in for
  ``serving/traffic.replay`` (the serve.py driver);
- :meth:`items` yields ``fleet/router.replay_fleet`` dicts
  (``image1``/``image2`` + ``due_s``/``phase`` riders);
- :meth:`schedule` yields the rich records (global index, phase name,
  due time, frames) the elasticity bench attributes latencies with.

Chaos composes exactly as it does for the single-rate stream:
``burst@N`` expands request ``N`` into ``burst_size`` simultaneous
arrivals, ``poison@N`` NaNs request ``N``'s first frame — ``N`` is the
global request index across phases, so fault coordinates stay
deterministic through a rate step.

Generation is pure numpy on the submitting thread (frames come from
``data/synthetic``, same as the single-rate stream — bench drivers
already hold that import; the jax-free router PROCESS never generates
traffic, it only receives it).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.resilience.chaos import ChaosSpec

__all__ = ["TrafficPhase", "StepTraffic", "TrafficItem"]


@dataclass(frozen=True)
class TrafficPhase:
    """One constant-rate segment of a schedule. ``interval_s`` is the
    inter-arrival gap inside the phase (0 = as fast as the driver
    submits)."""

    name: str
    n_requests: int
    interval_s: float

    def __post_init__(self) -> None:
        if self.n_requests < 0:
            raise ValueError(f"n_requests must be >= 0: {self.n_requests}")
        if self.interval_s < 0:
            raise ValueError(f"interval_s must be >= 0: {self.interval_s}")


@dataclass(frozen=True)
class TrafficItem:
    """One scheduled arrival, fully attributed."""

    index: int          # global request index (the chaos coordinate)
    phase: str
    due_s: float        # seconds from schedule start
    image1: np.ndarray
    image2: np.ndarray


class StepTraffic:
    """A deterministic multi-phase arrival schedule.

    Due times accumulate across phases: phase k+1's first request is
    due one of ITS intervals after phase k's last — a step is a rate
    change at an instant, not a gap. Frame content is keyed on the
    global emission index through ``SyntheticFlowDataset`` exactly like
    the single-rate stream, so two runs (or two benches) replaying the
    same ``(seed, phases)`` submit identical bytes.
    """

    def __init__(
        self,
        size_hw: Tuple[int, int],
        phases: List[TrafficPhase],
        *,
        seed: int = 0,
        burst_size: int = 8,
        chaos: Optional[ChaosSpec] = None,
        style: str = "smooth",
    ):
        if not phases:
            raise ValueError("a schedule needs at least one phase")
        names = [p.name for p in phases]
        if len(set(names)) != len(names):
            raise ValueError(f"phase names must be unique: {names}")
        self.size_hw = tuple(size_hw)
        self.phases = list(phases)
        self.burst_size = max(1, int(burst_size))
        self.chaos = chaos or ChaosSpec()
        self.n_requests = sum(p.n_requests for p in phases)
        live_bursts = sum(
            1 for i in self.chaos.burst_requests if i < self.n_requests
        )
        self._total = self.n_requests + live_bursts * (self.burst_size - 1)
        self._ds = SyntheticFlowDataset(
            self.size_hw, length=max(1, self._total), seed=seed,
            style=style,
        )

    @classmethod
    def step(
        cls,
        size_hw: Tuple[int, int],
        *,
        low_n: int = 8,
        high_n: int = 24,
        low_interval_s: float = 0.25,
        high_interval_s: float = 0.02,
        seed: int = 0,
        **kw,
    ) -> "StepTraffic":
        """The canonical elasticity scenario: low → high → low. The
        high phase is what must force a scale-up; the trailing low
        phase is what must let the scale-down drain with zero loss."""
        return cls(size_hw, [
            TrafficPhase("low", low_n, low_interval_s),
            TrafficPhase("high", high_n, high_interval_s),
            TrafficPhase("cooldown", low_n, low_interval_s),
        ], seed=seed, **kw)

    def __len__(self) -> int:
        return self._total

    def phase_bounds(self) -> Dict[str, Tuple[int, int]]:
        """``{phase name: (first, past-last)}`` in GLOBAL request
        indices — what turns a per-request latency list into per-phase
        percentiles, and what aims chaos coordinates at a phase."""
        bounds: Dict[str, Tuple[int, int]] = {}
        start = 0
        for p in self.phases:
            bounds[p.name] = (start, start + p.n_requests)
            start += p.n_requests
        return bounds

    def schedule(self) -> Iterator[TrafficItem]:
        """The rich schedule: every arrival with its phase attribution.
        Burst copies share their trigger's index, phase, and due time
        (they ARE request N, multiplied)."""
        emitted = 0
        index = 0
        due = 0.0
        for p in self.phases:
            for _ in range(p.n_requests):
                due += p.interval_s
                copies = (
                    self.burst_size
                    if index in self.chaos.burst_requests else 1
                )
                for _ in range(copies):
                    sample = self._ds.sample(emitted)
                    img1, img2 = sample["image1"], sample["image2"]
                    if index in self.chaos.poison_requests:
                        img1 = np.full(img1.shape, np.nan, np.float32)
                    emitted += 1
                    yield TrafficItem(
                        index=index, phase=p.name, due_s=due,
                        image1=img1, image2=img2,
                    )
                index += 1

    def __iter__(self) -> Iterator[Tuple[float, np.ndarray, np.ndarray]]:
        """``serving/traffic.replay`` compatibility: bare
        ``(due_s, image1, image2)`` triples."""
        for item in self.schedule():
            yield item.due_s, item.image1, item.image2

    def items(self) -> Iterator[dict]:
        """``fleet/router.replay_fleet`` compatibility: one dict per
        arrival (extra keys ride along for the bench's attribution)."""
        for item in self.schedule():
            yield {
                "image1": item.image1,
                "image2": item.image2,
                "due_s": item.due_s,
                "phase": item.phase,
                "index": item.index,
            }
