"""Deterministic multi-phase traffic: the scenario layer over the
single-rate streams (first slice of ROADMAP item 4's scenario suite;
docs/FLEET.md "Elasticity bench").

``serving/traffic.SyntheticTraffic`` is one arrival rate for the whole
run — right for chaos coordinates, wrong for the questions elasticity
asks, which are all about rate CHANGES: how long after a load step does
new capacity take traffic, does a scale-down under load lose anything,
does p99 stay flat through both. :class:`StepTraffic` strings
:class:`TrafficPhase` segments (each its own inter-arrival interval)
into one schedule whose due times, frame content, and phase attribution
are all pure functions of ``(seed, phases)`` — the same step replays
bitwise-identically into the serve bench, the fleet bench, and the
autoscaler acceptance tests.

Three consumption shapes, one schedule:

- ``iter(traffic)`` yields ``(due_s, image1, image2)`` — drop-in for
  ``serving/traffic.replay`` (the serve.py driver);
- :meth:`items` yields ``fleet/router.replay_fleet`` dicts
  (``image1``/``image2`` + ``due_s``/``phase`` riders);
- :meth:`schedule` yields the rich records (global index, phase name,
  due time, frames) the elasticity bench attributes latencies with.

Chaos composes exactly as it does for the single-rate stream:
``burst@N`` expands request ``N`` into ``burst_size`` simultaneous
arrivals, ``poison@N`` NaNs request ``N``'s first frame — ``N`` is the
global request index across phases, so fault coordinates stay
deterministic through a rate step.

Generation is pure numpy on the submitting thread (frames come from
``data/synthetic``, same as the single-rate stream — bench drivers
already hold that import; the jax-free router PROCESS never generates
traffic, it only receives it).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from raft_ncup_tpu.data.synthetic import SyntheticFlowDataset
from raft_ncup_tpu.resilience.chaos import ChaosSpec

__all__ = [
    "TrafficPhase", "StepTraffic", "TrafficItem",
    "MixedResolutionTraffic",
]


@dataclass(frozen=True)
class TrafficPhase:
    """One constant-rate segment of a schedule. ``interval_s`` is the
    inter-arrival gap inside the phase (0 = as fast as the driver
    submits)."""

    name: str
    n_requests: int
    interval_s: float

    def __post_init__(self) -> None:
        if self.n_requests < 0:
            raise ValueError(f"n_requests must be >= 0: {self.n_requests}")
        if self.interval_s < 0:
            raise ValueError(f"interval_s must be >= 0: {self.interval_s}")


@dataclass(frozen=True)
class TrafficItem:
    """One scheduled arrival, fully attributed."""

    index: int          # global request index (the chaos coordinate)
    phase: str
    due_s: float        # seconds from schedule start
    image1: np.ndarray
    image2: np.ndarray


class StepTraffic:
    """A deterministic multi-phase arrival schedule.

    Due times accumulate across phases: phase k+1's first request is
    due one of ITS intervals after phase k's last — a step is a rate
    change at an instant, not a gap. Frame content is keyed on the
    global emission index through ``SyntheticFlowDataset`` exactly like
    the single-rate stream, so two runs (or two benches) replaying the
    same ``(seed, phases)`` submit identical bytes.
    """

    def __init__(
        self,
        size_hw: Tuple[int, int],
        phases: List[TrafficPhase],
        *,
        seed: int = 0,
        burst_size: int = 8,
        chaos: Optional[ChaosSpec] = None,
        style: str = "smooth",
    ):
        if not phases:
            raise ValueError("a schedule needs at least one phase")
        names = [p.name for p in phases]
        if len(set(names)) != len(names):
            raise ValueError(f"phase names must be unique: {names}")
        self.size_hw = tuple(size_hw)
        self.phases = list(phases)
        self.burst_size = max(1, int(burst_size))
        self.chaos = chaos or ChaosSpec()
        self.n_requests = sum(p.n_requests for p in phases)
        live_bursts = sum(
            1 for i in self.chaos.burst_requests if i < self.n_requests
        )
        self._total = self.n_requests + live_bursts * (self.burst_size - 1)
        self._ds = SyntheticFlowDataset(
            self.size_hw, length=max(1, self._total), seed=seed,
            style=style,
        )

    @classmethod
    def step(
        cls,
        size_hw: Tuple[int, int],
        *,
        low_n: int = 8,
        high_n: int = 24,
        low_interval_s: float = 0.25,
        high_interval_s: float = 0.02,
        seed: int = 0,
        **kw,
    ) -> "StepTraffic":
        """The canonical elasticity scenario: low → high → low. The
        high phase is what must force a scale-up; the trailing low
        phase is what must let the scale-down drain with zero loss."""
        return cls(size_hw, [
            TrafficPhase("low", low_n, low_interval_s),
            TrafficPhase("high", high_n, high_interval_s),
            TrafficPhase("cooldown", low_n, low_interval_s),
        ], seed=seed, **kw)

    def __len__(self) -> int:
        return self._total

    def phase_bounds(self) -> Dict[str, Tuple[int, int]]:
        """``{phase name: (first, past-last)}`` in GLOBAL request
        indices — what turns a per-request latency list into per-phase
        percentiles, and what aims chaos coordinates at a phase."""
        bounds: Dict[str, Tuple[int, int]] = {}
        start = 0
        for p in self.phases:
            bounds[p.name] = (start, start + p.n_requests)
            start += p.n_requests
        return bounds

    def schedule(self) -> Iterator[TrafficItem]:
        """The rich schedule: every arrival with its phase attribution.
        Burst copies share their trigger's index, phase, and due time
        (they ARE request N, multiplied)."""
        emitted = 0
        index = 0
        due = 0.0
        for p in self.phases:
            for _ in range(p.n_requests):
                due += p.interval_s
                copies = (
                    self.burst_size
                    if index in self.chaos.burst_requests else 1
                )
                for _ in range(copies):
                    sample = self._ds.sample(emitted)
                    img1, img2 = sample["image1"], sample["image2"]
                    if index in self.chaos.poison_requests:
                        img1 = np.full(img1.shape, np.nan, np.float32)
                    emitted += 1
                    yield TrafficItem(
                        index=index, phase=p.name, due_s=due,
                        image1=img1, image2=img2,
                    )
                index += 1

    def __iter__(self) -> Iterator[Tuple[float, np.ndarray, np.ndarray]]:
        """``serving/traffic.replay`` compatibility: bare
        ``(due_s, image1, image2)`` triples."""
        for item in self.schedule():
            yield item.due_s, item.image1, item.image2

    def items(self) -> Iterator[dict]:
        """``fleet/router.replay_fleet`` compatibility: one dict per
        arrival (extra keys ride along for the bench's attribution)."""
        for item in self.schedule():
            yield {
                "image1": item.image1,
                "image2": item.image2,
                "due_s": item.due_s,
                "phase": item.phase,
                "index": item.index,
            }


class MixedResolutionTraffic:
    """Deterministic mixed-RESOLUTION arrival schedule with a zipf
    popularity law over frame sizes (second slice of ROADMAP item 4's
    scenario suite; the first was the rate-step schedule above).

    Production flow traffic is not one synthetic shape: a few sizes
    dominate (the product's default capture resolutions) with a long
    tail of odd ones — the classic zipf shape. This scenario draws each
    request's size from ``P(rank r) ∝ (r+1)^-exponent`` over ``sizes``
    (listed most-popular first), with one ``SyntheticFlowDataset`` per
    size so frame content stays a pure function of ``(seed, sizes)`` —
    the same schedule replays bitwise-identically into any consumer.
    The early-exit bench row (docs/PERF.md "Early exit") drives its
    measurement with this scenario, so the recorded speedup reflects
    HETEROGENEOUS per-sample convergence across a realistic size mix
    rather than one shape's behavior.

    Attribution reuses :class:`TrafficItem` with the size name (e.g.
    ``"96x128"``) as the phase, so per-size latency/exec-iters breakouts
    fall out of the same phase bucketing the step schedule uses. Chaos
    composes identically: coordinates are global request indices
    (``burst@N`` multiplies request N at its size; ``poison@N`` NaNs its
    first frame).
    """

    def __init__(
        self,
        sizes,
        n_requests: int,
        *,
        exponent: float = 1.1,
        interval_s: float = 0.0,
        seed: int = 0,
        burst_size: int = 8,
        chaos: Optional[ChaosSpec] = None,
        style: str = "smooth",
    ):
        self.sizes = [tuple(int(x) for x in s) for s in sizes]
        if not self.sizes:
            raise ValueError("a mixed-resolution schedule needs sizes")
        if len(set(self.sizes)) != len(self.sizes):
            raise ValueError(f"sizes must be unique: {self.sizes}")
        if exponent <= 0:
            raise ValueError(f"zipf exponent must be > 0: {exponent}")
        self.n_requests = int(n_requests)
        if self.n_requests < 0:
            raise ValueError(f"n_requests must be >= 0: {n_requests}")
        self.exponent = float(exponent)
        self.interval_s = float(interval_s)
        self.burst_size = max(1, int(burst_size))
        self.chaos = chaos or ChaosSpec()
        # The zipf popularity law over size RANKS (list order = rank).
        weights = np.array(
            [(r + 1.0) ** -self.exponent for r in range(len(self.sizes))]
        )
        probs = weights / weights.sum()
        # default_rng(seed): the assignment is a pure function of
        # (seed, sizes, exponent, n) — replays are bitwise-identical.
        rng = np.random.default_rng(seed)
        self._assign = rng.choice(
            len(self.sizes), size=self.n_requests, p=probs
        )
        live_bursts = sum(
            1 for i in self.chaos.burst_requests if i < self.n_requests
        )
        self._total = self.n_requests + live_bursts * (self.burst_size - 1)
        # Per-size emission totals (burst copies included) size each
        # size's dataset exactly once, up front.
        totals = [0] * len(self.sizes)
        for index, s in enumerate(self._assign):
            copies = (
                self.burst_size
                if index in self.chaos.burst_requests else 1
            )
            totals[s] += copies
        self._ds = [
            SyntheticFlowDataset(
                size, length=max(1, totals[k]), seed=seed, style=style
            )
            for k, size in enumerate(self.sizes)
        ]

    @staticmethod
    def size_name(size_hw: Tuple[int, int]) -> str:
        return f"{size_hw[0]}x{size_hw[1]}"

    def __len__(self) -> int:
        return self._total

    def size_counts(self) -> Dict[str, int]:
        """``{size name: request count}`` (burst copies counted with
        their trigger, matching ``phase_bounds``'s request-not-emission
        accounting) — what a bench row reports as the measured mix."""
        counts = {self.size_name(s): 0 for s in self.sizes}
        for s in self._assign:
            counts[self.size_name(self.sizes[s])] += 1
        return counts

    def schedule(self) -> Iterator[TrafficItem]:
        """Every arrival with its size attribution in the phase field.
        Burst copies share their trigger's index, phase, and due time."""
        emitted = [0] * len(self.sizes)
        due = 0.0
        for index, s in enumerate(self._assign):
            s = int(s)
            due += self.interval_s
            copies = (
                self.burst_size
                if index in self.chaos.burst_requests else 1
            )
            for _ in range(copies):
                sample = self._ds[s].sample(emitted[s])
                img1, img2 = sample["image1"], sample["image2"]
                if index in self.chaos.poison_requests:
                    img1 = np.full(img1.shape, np.nan, np.float32)
                emitted[s] += 1
                yield TrafficItem(
                    index=index, phase=self.size_name(self.sizes[s]),
                    due_s=due, image1=img1, image2=img2,
                )

    def __iter__(self) -> Iterator[Tuple[float, np.ndarray, np.ndarray]]:
        """``serving/traffic.replay`` compatibility: bare
        ``(due_s, image1, image2)`` triples."""
        for item in self.schedule():
            yield item.due_s, item.image1, item.image2

    def items(self) -> Iterator[dict]:
        """``fleet/router.replay_fleet`` compatibility: one dict per
        arrival (extra keys ride along for the bench's attribution)."""
        for item in self.schedule():
            yield {
                "image1": item.image1,
                "image2": item.image2,
                "due_s": item.due_s,
                "phase": item.phase,
                "index": item.index,
            }
