"""Device mesh construction.

The parallelism model (TPU-native replacement for the reference's
single-process ``nn.DataParallel`` over 2 GPUs, reference: train.py:169-175):

- axis ``data``: batch-sharded data parallelism. Gradients are averaged by
  XLA-inserted psums over ICI — the jit partitioner sees replicated params
  and a sharded batch and does the rest.
- axis ``spatial``: the image-height dimension is sharded — the convnet
  analogue of sequence/context parallelism. XLA inserts halo exchanges
  for spatially-sharded convolutions automatically. This is what lets
  1080p 32-iteration inference (whose correlation volume would otherwise
  be several GB) scale across chips.

Multi-host: ``jax.distributed.initialize`` + the same mesh spanning all
processes; each host feeds its local shard of the batch
(``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    data: Optional[int] = None,
    spatial: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, spatial) mesh. ``data=None`` uses all remaining
    devices after spatial partitioning."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % spatial:
            raise ValueError(f"{n} devices not divisible by spatial={spatial}")
        data = n // spatial
    use = data * spatial
    if use > n:
        raise ValueError(f"mesh {data}x{spatial} needs {use} devices, have {n}")
    arr = np.asarray(devices[:use]).reshape(data, spatial)
    return Mesh(arr, ("data", "spatial"))


def batch_sharding(mesh: Mesh) -> dict:
    """Shardings for a training batch dict: batch over 'data', image height
    over 'spatial'."""
    img = NamedSharding(mesh, P("data", "spatial", None, None))
    return {
        "image1": img,
        "image2": img,
        "flow": img,
        "valid": NamedSharding(mesh, P("data", "spatial", None)),
    }


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
