"""Device mesh construction.

The parallelism model (TPU-native replacement for the reference's
single-process ``nn.DataParallel`` over 2 GPUs, reference: train.py:169-175):

- axis ``data``: batch-sharded data parallelism. Gradients are averaged by
  XLA-inserted psums over ICI — the jit partitioner sees replicated params
  and a sharded batch and does the rest.
- axis ``spatial``: the image-height dimension is sharded — the convnet
  analogue of sequence/context parallelism. XLA inserts halo exchanges
  for spatially-sharded convolutions automatically. This is what lets
  1080p 32-iteration inference (whose correlation volume would otherwise
  be several GB) scale across chips.
- axis ``pipe``: iteration pipelining (docs/SHARDING.md "Pipeline
  axis"; inference/pipe_schedule.py). RAFT's N identical GRU refinement
  iterations split into S contiguous segments placed on S device
  groups; micro-batches stream through the stages, carries handed
  between groups by ``collective_permute``. ``pipe=1`` (the default)
  produces the exact 2-axis ``(data, spatial)`` mesh every existing
  fingerprint/cache key was minted against — the third axis only exists
  when a pipeline asked for it.

Multi-host: ``jax.distributed.initialize`` + the same mesh spanning all
processes; each host feeds its local shard of the batch
(``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

import warnings
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    data: Optional[int] = None,
    spatial: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    pipe: int = 1,
) -> Mesh:
    """Build a (data, spatial[, pipe]) mesh. ``data=None`` uses all
    remaining devices after spatial (and pipe) partitioning.

    ``pipe`` (default 1) is the iteration-pipelining axis
    (inference/pipe_schedule.py): S pipeline stages on S device groups.
    ``pipe=1`` deliberately yields the identical 2-axis
    ``("data", "spatial")`` mesh this function always built — same axis
    names, same fingerprint, so no existing cache key or bench
    provenance string changes under the default.

    An explicit ``data`` x ``spatial`` x ``pipe`` smaller than the
    device set warns loudly: the stripped devices sit idle for the
    whole program, which is a legitimate ops choice (e.g.
    ``--spatial_parallel 2`` on an 8-chip host while debugging) but
    must never happen silently — a mis-sized mesh that quietly drops 6
    of 8 chips looks exactly like a 4x perf regression.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    pipe = int(pipe)
    if pipe < 1:
        raise ValueError(f"pipe must be >= 1, got {pipe}")
    if data is None:
        if n % (spatial * pipe):
            raise ValueError(
                f"{n} devices not divisible by spatial={spatial}"
                + (f" * pipe={pipe}" if pipe > 1 else "")
            )
        data = n // (spatial * pipe)
    use = data * spatial * pipe
    shape_str = f"{data}x{spatial}" + (f"x{pipe}" if pipe > 1 else "")
    if use > n:
        raise ValueError(
            f"mesh {shape_str} needs {use} devices, have {n}"
        )
    if use < n:
        warnings.warn(
            f"mesh {shape_str} uses only {use} of {n} visible "
            f"devices; {n - use} device(s) will sit idle. Pass data=None "
            "to span all devices, or restrict `devices=` explicitly if "
            "the subset is intentional.",
            stacklevel=2,
        )
    # One Mesh(...) call declares both shapes: the axis-name tuple is a
    # conditional literal so lint JGL006's declared-axes discovery (which
    # parses this file) sees 'pipe' exactly when the code can build it.
    arr = np.asarray(devices[:use]).reshape(
        (data, spatial, pipe) if pipe > 1 else (data, spatial)
    )
    return Mesh(
        arr,
        ("data", "spatial", "pipe") if pipe > 1 else ("data", "spatial"),
    )


def resolve_config_mesh(mesh, cfg_mesh) -> tuple:
    """The serving/streaming mesh-resolution rule, in one place: an
    explicit ``mesh`` wins, else a config's ``(data, spatial)`` or
    ``(data, spatial, pipe)`` sizes build one, else unsharded. Returns
    ``(mesh_or_None, pad_divisor)`` where the divisor is 8*spatial —
    every image padded for this mesh must round to it so the 1/8-res
    feature height divides the spatial axis (evaluation._pad_divisor's
    rule; the pipe axis never shards image dims, so it adds nothing to
    the divisor)."""
    if mesh is None and cfg_mesh is not None:
        mesh = make_mesh(
            data=int(cfg_mesh[0]),
            spatial=int(cfg_mesh[1]),
            pipe=int(cfg_mesh[2]) if len(cfg_mesh) > 2 else 1,
        )
    spatial = int(mesh.shape.get("spatial", 1)) if mesh is not None else 1
    return mesh, 8 * spatial


def mesh_fingerprint(mesh: Optional[Mesh]) -> str:
    """Stable, hashable identity of a mesh configuration — part of every
    compiled-executable cache key on the inference/serving/streaming
    path (inference/pipeline.ShapeCachedForward) and of the bench rows'
    sharding provenance. Two programs compiled for different meshes (or
    sharded vs unsharded) must never collide in a cache, and a recorded
    number must say which mesh produced it."""
    if mesh is None:
        return "nomesh"
    axes = ",".join(f"{k}={v}" for k, v in mesh.shape.items())
    platform = next(iter(mesh.devices.flat)).platform
    return f"mesh({axes}:{platform})"


_COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)
_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4,
    "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}


def collective_stats(hlo_text: str) -> dict:
    """Sharding fingerprint of a compiled executable: how many
    cross-device collective ops the partitioner inserted and the total
    bytes they produce, parsed from the optimized HLO text
    (``compiled.as_text()``), plus the same pair broken out per op kind
    under ``by_op`` — ``{"all-gather": {"count": n, "bytes": b}, ...}``
    with every kind in ``_COLLECTIVE_OPS`` present (zeros included, so
    consumers index without guards). The breakout is what lets pipeline
    carry-handoff traffic (``collective-permute`` over the ``pipe``
    axis) be attributed separately from spatial halo exchanges and
    fmap2 all-gathers in one mixed-mesh program.

    An unsharded program has zero of everything; a spatially-sharded
    forward shows the halo exchanges and the replicated-fmap2
    all-gathers the mesh costs. The byte count is approximate (result
    shapes only, async start/done pairs counted once via the ``-start``
    form) — it is a fingerprint for bench rows
    (``highres_collective_bytes``), not an interconnect-traffic model.
    """
    import re

    shape_re = re.compile(r"(\w+)\[([0-9,]*)\]")
    by_op = {op: {"count": 0, "bytes": 0} for op in _COLLECTIVE_OPS}
    for line in hlo_text.splitlines():
        # `%x = TYPE op-name(...)`: match the op between the result type
        # and its operand list; skip `-done` halves of async pairs.
        hit = None
        hit_op = None
        for op in _COLLECTIVE_OPS:
            for form in (f" {op}(", f" {op}-start("):
                idx = line.find(form)
                if idx != -1:
                    hit = idx
                    hit_op = op
                    break
            if hit is not None:
                break
        if hit is None or "=" not in line[:hit]:
            continue
        by_op[hit_op]["count"] += 1
        result = line[line.index("=") + 1: hit]
        for dtype, dims in shape_re.findall(result):
            nbytes = _DTYPE_BYTES.get(dtype)
            if nbytes is None:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            by_op[hit_op]["bytes"] += n * nbytes
    return {
        "collectives": sum(v["count"] for v in by_op.values()),
        "collective_bytes": sum(v["bytes"] for v in by_op.values()),
        "by_op": by_op,
    }


def batch_sharding(mesh: Mesh) -> dict:
    """Shardings for a training batch dict: batch over 'data', image height
    over 'spatial'."""
    img = NamedSharding(mesh, P("data", "spatial", None, None))
    return {
        "image1": img,
        "image2": img,
        "flow": img,
        "valid": NamedSharding(mesh, P("data", "spatial", None)),
    }


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
