"""Multi-host (pod) support: process initialization + global batch
assembly.

The reference's entire distributed story is single-process
``nn.DataParallel`` (reference: train.py:169-175; SURVEY.md §2 C21). Here
the same jitted SPMD step runs unchanged on a pod: every host runs the
same program, ``jax.distributed.initialize`` wires the processes into one
runtime, the mesh spans all chips, gradient psums ride ICI within a slice
and DCN between them (XLA routes collectives by mesh topology), and each
host feeds its disjoint input shard (FlowLoader already shards by
``jax.process_index()``).
"""

from __future__ import annotations

import sys
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-process JAX runtime (no-op when single-process
    or when the TPU pod environment provides the coordination config).

    On Cloud TPU pods, ``jax.distributed.initialize()`` reads everything
    from the environment; explicit args support other clusters.
    """
    if num_processes == 1:
        return
    explicit = coordinator_address is not None or process_id is not None
    if explicit and (num_processes or 0) > 1:
        # Cross-process computations on the CPU backend need an actual
        # collectives transport; without one XLA refuses to compile any
        # multiprocess program ("Multiprocess computations aren't
        # implemented on the CPU backend"). Gloo ships with jaxlib and
        # only affects the CPU backend, so enable it when we are about
        # to join a multi-process runtime — this is what lets the
        # distributed tests (tests/test_multihost.py) run real
        # multi-host SPMD on virtual CPU devices. Guarded to the
        # explicit-args path: touching this config on the no-op
        # single-process path would re-initialize an already-live
        # backend (and on the axon tunnel, re-resolve a platform that
        # must only be initialized once).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception as e:  # option absent / backend already up
            print(f"cpu collectives not configured: {e}")
    try:
        jax.distributed.initialize(
            coordinator_address, num_processes, process_id
        )
    except (RuntimeError, ValueError) as e:
        # With explicit coordination args, a failed init must not fall
        # back to independent single-process runs silently (every host
        # would train its own full copy into the same run dir).
        if explicit:
            raise
        # No coordination config: single-process run. Log loudly rather
        # than swallowing, so a misconfigured pod is visible in the logs.
        if "already initialized" not in str(e).lower():
            print(f"jax.distributed.initialize skipped: {e}")


def global_batch(batch: dict, mesh: Mesh, shardings: dict) -> dict:
    """Assemble per-host local batches into global sharded arrays.

    Each host passes its local slice (the FlowLoader shard); the result is
    a dict of global ``jax.Array`` whose shards live where the mesh puts
    them — the multi-host replacement for passing host-local numpy straight
    into jit (which only works single-process).
    """
    out = {}
    for key, value in batch.items():
        sharding = shardings.get(key)
        if sharding is None:
            out[key] = value
            continue
        out[key] = jax.make_array_from_process_local_data(
            sharding, np.asarray(value)
        )
    return out


def device_put_batch(
    batch: dict, mesh: Optional[Mesh], shardings: Optional[dict]
) -> dict:
    """Move one host-local batch dict onto device — the single transfer
    policy shared by the train loop's async prefetcher and the bench's
    pipelined-loop row.

    Multi-host with a mesh: each host contributes its local shard and the
    result is a dict of global ``jax.Array`` (:func:`global_batch`).
    Single-process with shardings: ``jax.device_put`` straight into the
    batch sharding's layout, so the jitted step's dispatch does no
    re-layout. No shardings: default device placement.
    """
    if mesh is not None and shardings is not None and is_multihost():
        return global_batch(batch, mesh, shardings)
    shardings = shardings or {}
    return {
        key: jax.device_put(np.asarray(value), shardings.get(key))
        for key, value in batch.items()
    }


def is_multihost() -> bool:
    return jax.process_count() > 1


def is_main_process() -> bool:
    """True on exactly one process per job — the only one that should
    write human-facing output (log files, TensorBoard, submissions).
    Orbax checkpoint saves stay all-process (orbax coordinates its own
    per-host shard writes)."""
    return jax.process_index() == 0


def allreduce_sum_across_hosts(x) -> np.ndarray:
    """Sum a host-local numpy accumulator over all processes.

    The multi-host reduction for host-sharded validation: each process
    validates its slice of the frames and the fixed-size metric
    accumulator (sums and counts, NOT means) is summed across hosts so
    every process returns identical global metrics. Single-process: a
    cheap pass-through. Requires the same accumulator shape on every
    process (``process_allgather`` stages one collective)."""
    x = np.asarray(x)
    if not is_multihost():
        return x
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x)).sum(axis=0)


def barrier(name: str, timeout_s: float = 480.0) -> bool:
    """Block until every process reaches this barrier (coordination
    service — no device collectives involved, so it tolerates arbitrary
    cross-process skew, unlike Gloo/ICI ops whose context init has a
    hard ~30s deadline). Use it to align processes before the first
    collective execution when their compile times can drift apart.

    Returns False (after logging) instead of raising when this jax
    build's distributed client doesn't expose the barrier API — the
    jax._src access is isolated HERE so a jax upgrade breaks one
    maintained helper, not every caller.
    """
    if not is_multihost():
        return True
    try:
        from jax._src import distributed

        distributed.global_state.client.wait_at_barrier(
            name, timeout_in_ms=int(timeout_s * 1000)
        )
        return True
    except (ImportError, AttributeError, TypeError) as e:
        # TypeError included: the unstable jax._src signature changing
        # (e.g. the timeout keyword renamed) must degrade like the API
        # being absent, per this helper's contract.
        # stderr: child stdout is a parsed protocol stream in the tooling
        # around this helper (tests/_distributed_child.py's LOSS= lines,
        # bench.py's JSON-tail harvest) — diagnostics must not mix in.
        print(
            f"multihost barrier unavailable ({e}); proceeding unaligned",
            file=sys.stderr,
        )
        return False


def replicated_hosts_sharding(mesh: Mesh) -> NamedSharding:
    from jax.sharding import PartitionSpec as P

    return NamedSharding(mesh, P())
