"""Jitted (and optionally mesh-sharded) train/eval steps.

One step function serves single-chip and multi-chip runs: with a mesh, the
batch is sharded over (data, spatial) and parameters are replicated; XLA's
SPMD partitioner inserts the gradient psums and conv halo exchanges. This
replaces the reference's DataParallel scatter/gather (train.py:169-215)
with compiler-inserted collectives over ICI.

BatchNorm under data parallelism computes statistics over the *global*
batch (sync-BN): the batch reduction crosses the sharded axis, so XLA
emits the cross-replica reduction — strictly better-behaved than the
reference's DataParallel per-replica stats.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from raft_ncup_tpu.config import TrainConfig
from raft_ncup_tpu.models.raft import RAFT
from raft_ncup_tpu.parallel.mesh import batch_sharding, replicated
from raft_ncup_tpu.resilience.anomaly import guard_update
from raft_ncup_tpu.training.loss import sequence_loss
from raft_ncup_tpu.training.state import TrainState


# Step-function reuse across trainer invocations in one process: two
# models with equal ModelConfig compute identically (flax modules carry
# only their config), so the jitted step — and, with the shared
# optimizer transform from training/optim.py, its compiled executable —
# can be reused instead of re-traced. This is what makes an in-process
# kill/resume cycle (resilience tests, notebook restarts) pay restore
# latency rather than a full recompile. Keyed on every config field the
# traced step reads; bounded FIFO so a config-sweeping process cannot
# pin unboundedly many executables (callers keep their own references —
# eviction only means a later identical request re-traces).
_STEP_CACHE: dict = {}
_STEP_CACHE_MAX = 8


def _step_cache_key(model_cfg, cfg: TrainConfig, mesh) -> tuple:
    return (
        model_cfg, mesh,
        cfg.stage != "chairs",  # freeze_bn (reference: train.py:185-186)
        cfg.add_noise, cfg.iters, cfg.gamma, cfg.max_flow,
        cfg.anomaly_sentinel, cfg.sentinel_spike_factor,
        cfg.sentinel_ema_decay, cfg.sentinel_warmup,
    )


def make_train_step(
    model: RAFT,
    cfg: TrainConfig,
    mesh: Optional[Mesh] = None,
):
    """Returns ``step(state, batch, rng) -> (state, metrics)``.

    ``batch``: dict with image1/image2 (B, H, W, 3) uint8 or float32 in
    [0, 255] (the loader ships uint8; the cast happens on device), flow
    (B, H, W, 2), valid (B, H, W).
    """
    cache_key = _step_cache_key(model.cfg, cfg, mesh)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    freeze_bn = cfg.stage != "chairs"  # reference: train.py:185-186

    def loss_fn(params, batch_stats, batch, rng):
        img1 = batch["image1"].astype(jnp.float32)
        img2 = batch["image2"].astype(jnp.float32)
        if cfg.add_noise:
            # Gaussian noise with per-step uniform stddev in [0, 5]
            # (reference: train.py:210-213).
            kstd, k1, k2 = jax.random.split(rng, 3)
            stdv = jax.random.uniform(kstd, (), maxval=5.0)
            img1 = jnp.clip(
                img1 + stdv * jax.random.normal(k1, img1.shape), 0.0, 255.0
            )
            img2 = jnp.clip(
                img2 + stdv * jax.random.normal(k2, img2.shape), 0.0, 255.0
            )

        variables = {"params": params, "batch_stats": batch_stats}
        preds, new_stats = model.apply(
            variables,
            img1,
            img2,
            iters=cfg.iters,
            train=True,
            freeze_bn=freeze_bn,
            rngs={"dropout": rng} if model.cfg.dropout > 0 else None,
            mutable=True,
            mesh=mesh,
        )
        loss, metrics = sequence_loss(
            preds, batch["flow"], batch["valid"], cfg.gamma, cfg.max_flow
        )
        return loss, (metrics, new_stats)

    def step(state: TrainState, batch: dict, rng: jax.Array):
        # jax.named_scope: stage labels in the compiled step's HLO so an
        # xprof capture splits fwd+bwd / optimizer / sentinel wall time
        # (docs/OBSERVABILITY.md; staged for the hardware window).
        with jax.named_scope("train.forward_backward"):
            (loss, (metrics, new_stats)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state.batch_stats, batch, rng)
        with jax.named_scope("train.optimizer_update"):
            new_state = state.apply_gradients(
                grads, new_batch_stats=new_stats
            )
            metrics = dict(metrics)
            metrics["loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
        if cfg.anomaly_sentinel:  # static flag: one fixed compiled program
            # Divergence sentinel (resilience/anomaly.py): a non-finite or
            # grad-spiking step selects the OLD params/opt_state via
            # jnp.where — fully on device, no host sync, no extra program.
            with jax.named_scope("train.sentinel"):
                new_state, sen_metrics = guard_update(
                    state, new_state, loss, metrics["grad_norm"], cfg
                )
            metrics.update(sen_metrics)
        return new_state, metrics

    if mesh is None:
        jitted = jax.jit(step, donate_argnums=0)
    else:
        repl = replicated(mesh)
        jitted = jax.jit(
            step,
            in_shardings=(repl, batch_sharding(mesh), repl),
            out_shardings=(repl, repl),
            donate_argnums=0,
        )
    while len(_STEP_CACHE) >= _STEP_CACHE_MAX:
        _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
    _STEP_CACHE[cache_key] = jitted
    return jitted


def make_synthetic_batch(rng: jax.Array, batch: int, height: int, width: int):
    """Random (image1, image2, flow, valid) batch in the train-step's
    contract — shared by the bench's train-step measurement and the
    driver's multichip dryrun so both exercise the same workload."""
    k1, k2, k3 = jax.random.split(rng, 3)
    B, H, W = batch, height, width
    return {
        "image1": jax.random.uniform(k1, (B, H, W, 3), jnp.float32, 0, 255),
        "image2": jax.random.uniform(k2, (B, H, W, 3), jnp.float32, 0, 255),
        "flow": jax.random.normal(k3, (B, H, W, 2), jnp.float32),
        "valid": jnp.ones((B, H, W), jnp.float32),
    }


def make_eval_step(model: RAFT, iters: int, mesh: Optional[Mesh] = None):
    """Returns ``eval_step(variables, image1, image2) -> (flow_lr, flow_up)``
    (test-mode forward)."""

    def step(variables, image1, image2):
        return model.apply(
            variables, image1, image2, iters=iters, test_mode=True, mesh=mesh
        )

    if mesh is None:
        return jax.jit(step)
    repl = replicated(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    img = NamedSharding(mesh, P("data", "spatial", None, None))
    return jax.jit(
        step, in_shardings=(repl, img, img), out_shardings=(repl, repl)
    )
