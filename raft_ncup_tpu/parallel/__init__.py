from raft_ncup_tpu.parallel.mesh import make_mesh  # noqa: F401
from raft_ncup_tpu.parallel.step import (  # noqa: F401
    make_eval_step,
    make_train_step,
)
