from raft_ncup_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    mesh_fingerprint,
    replicated,
)
from raft_ncup_tpu.parallel.multihost import (  # noqa: F401
    allreduce_sum_across_hosts,
    barrier,
    device_put_batch,
    global_batch,
    initialize_distributed,
    is_main_process,
    is_multihost,
)
from raft_ncup_tpu.parallel.step import (  # noqa: F401
    make_eval_step,
    make_train_step,
)
