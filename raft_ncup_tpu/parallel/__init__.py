from raft_ncup_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicated,
)
from raft_ncup_tpu.parallel.multihost import (  # noqa: F401
    barrier,
    global_batch,
    initialize_distributed,
    is_multihost,
)
from raft_ncup_tpu.parallel.step import (  # noqa: F401
    make_eval_step,
    make_train_step,
)
