"""Final-flow upsampler registry (reference: core/upsampler.py).

The NCUP path is the paper's contribution: zero-stuff the low-res flow
onto the high-res grid, estimate per-pixel confidences from guidance
(+ data), and interpolate with the normalized-conv U-Net. The bilinear
upsampler baseline is also provided; PAC/DJIF ablation heads live in
``raft_ncup_tpu.nn.pac``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_ncup_tpu.config import UpsamplerConfig
from raft_ncup_tpu.nn.nconv_unet import NConvUNet
from raft_ncup_tpu.nn.weights_est import SimpleWeightsNet, UNetWeightsNet
from raft_ncup_tpu.ops.geometry import (
    adaptive_area_resize,
    bilinear_resize_align_corners,
)
from raft_ncup_tpu.ops.nconv import zero_stuff_upsample


class NConvUpsampler(nn.Module):
    """Normalized-convolution upsampler (reference: core/upsampler.py:75-210).

    Forward (shipped config: scale=4, use_data_for_guidance=True,
    channels_to_batch=True, est_on_high_res=False, use_residuals=False):

    1. zero-stuff the low-res data x4 onto the high-res grid;
    2. area-resize the guidance to the low-res grid, concat with the data,
       run the weights-estimation net (sigmoid confidences at low res);
    3. zero-stuff the confidences to high res;
    4. fold channels into the batch dim and run the NConv U-Net on
       (data, confidence).
    """

    cfg: UpsamplerConfig
    use_bn: bool = False  # BN in the weights net: sintel-configured models
    dtype: Any = None

    @nn.compact
    def __call__(
        self, x_lowres: jax.Array, guidance: jax.Array, *, train: bool = False
    ) -> jax.Array:
        cfg = self.cfg
        s = cfg.scale
        B, H, W, C = x_lowres.shape

        x_highres = zero_stuff_upsample(x_lowres, s, s)

        if cfg.est_on_high_res:
            data_for_guidance = x_highres
            guid = bilinear_resize_align_corners(guidance, (H * s, W * s))
        else:
            data_for_guidance = x_lowres
            guid = adaptive_area_resize(guidance, (H, W))

        if cfg.weights_est_net == "binary":
            # Binary mask fallback (reference: core/upsampler.py:139-141).
            w = (data_for_guidance > 0).astype(x_lowres.dtype)
        else:
            if cfg.use_data_for_guidance:
                west_in = jnp.concatenate([data_for_guidance, guid], axis=-1)
            else:
                west_in = guid
            if cfg.weights_est_net == "simple":
                w = SimpleWeightsNet(
                    num_ch=cfg.weights_est_num_ch,
                    out_ch=C,
                    filter_sz=cfg.weights_est_filter_sz,
                    dilation=cfg.weights_est_dilation,
                    use_bn=self.use_bn,
                    dtype=self.dtype,
                    name="weights_est_net",
                )(west_in, train=train)
            elif cfg.weights_est_net == "unet":
                w = UNetWeightsNet(
                    num_ch=cfg.weights_est_num_ch,
                    out_ch=C,
                    dtype=self.dtype,
                    name="weights_est_net",
                )(west_in, train=train)
            else:
                raise ValueError(f"unknown weights_est_net: {cfg.weights_est_net!r}")

        w_highres = w if cfg.est_on_high_res else zero_stuff_upsample(w, s, s)

        interp = NConvUNet(
            in_ch=1 if cfg.channels_to_batch else C,
            channels_multiplier=cfg.channels_multiplier,
            num_downsampling=cfg.num_downsampling,
            encoder_filter_sz=cfg.encoder_filter_sz,
            decoder_filter_sz=cfg.decoder_filter_sz,
            out_filter_sz=cfg.out_filter_sz,
            pos_fn=cfg.pos_fn,
            use_bias=cfg.use_bias,
            data_pooling=cfg.data_pooling,
            shared_encoder=cfg.shared_encoder,
            use_double_conv=cfg.use_double_conv,
            name="interpolation_net",
        )

        oh, ow = H * s, W * s
        if cfg.channels_to_batch:
            # (B, H, W, C) -> (B*C, H, W, 1): channel c of sample b lands at
            # batch index b*C + c, matching the reference's NCHW
            # ``view(ib*ic, 1, oh, ow)`` (core/upsampler.py:168).
            xd = x_highres.transpose(0, 3, 1, 2).reshape(B * C, oh, ow, 1)
            wd = w_highres.transpose(0, 3, 1, 2).reshape(B * C, oh, ow, 1)
            out, _ = interp(xd, wd)
            out = out.reshape(B, C, oh, ow).transpose(0, 2, 3, 1)
        else:
            out, _ = interp(x_highres, w_highres)

        if cfg.use_residuals:
            out = jnp.where(x_highres > 0, x_highres, out)
        return out


class BilinearUpsampler(nn.Module):
    """align_corners=True bilinear baseline (reference:
    core/upsampler.py:213-220)."""

    cfg: UpsamplerConfig

    @nn.compact
    def __call__(
        self, x_lowres: jax.Array, guidance: jax.Array, *, train: bool = False
    ) -> jax.Array:
        B, H, W, C = x_lowres.shape
        s = self.cfg.scale
        return bilinear_resize_align_corners(x_lowres, (H * s, W * s))


def build_upsampler(
    cfg: UpsamplerConfig, dataset: str, dtype: Any = None, name: str = "upsampler"
) -> nn.Module:
    """Upsampler factory (reference: core/upsampler.py:10-72). BatchNorm in
    the weights-estimation net is enabled iff the model is configured for
    Sintel (reference: core/upsampler.py:41-42)."""
    if cfg.kind == "nconv":
        return NConvUpsampler(
            cfg, use_bn=(dataset == "sintel"), dtype=dtype, name=name
        )
    if cfg.kind == "bilinear":
        return BilinearUpsampler(cfg, name=name)
    if cfg.kind in ("pac", "djif"):
        try:
            from raft_ncup_tpu.nn.pac import build_pac_upsampler
        except ImportError as e:
            raise NotImplementedError(
                f"upsampler kind {cfg.kind!r} requires raft_ncup_tpu.nn.pac"
            ) from e
        return build_pac_upsampler(cfg, dtype=dtype, name=name)
    raise ValueError(f"unknown upsampler kind: {cfg.kind!r}")
