"""Recurrent update blocks (reference: core/update.py).

Motion encoder fuses correlation features and current flow; a conv GRU
(separable 1x5/5x1 for the Basic variant) refines a hidden state; a flow
head emits the per-iteration flow delta, and (for the RAFT baseline) a mask
head emits the convex-upsampling weights scaled by 0.25 (reference:
core/update.py:138-140).

These run inside ``lax.scan`` over refinement iterations, so everything is
shape-static. The GRU state is the scan carry.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_ncup_tpu.nn.layers import Conv2d


class FlowHead(nn.Module):
    """reference: core/update.py:6-14."""

    hidden_dim: int = 256
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = Conv2d(self.hidden_dim, 3, dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        return Conv2d(2, 3, dtype=self.dtype, name="conv2")(x)


class ConvGRU(nn.Module):
    """Plain 3x3 conv GRU (reference: core/update.py:16-31)."""

    hidden_dim: int = 128
    dtype: Any = None

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> jax.Array:
        hx = jnp.concatenate([h, x], axis=-1)
        z = nn.sigmoid(Conv2d(self.hidden_dim, 3, dtype=self.dtype, name="convz")(hx))
        r = nn.sigmoid(Conv2d(self.hidden_dim, 3, dtype=self.dtype, name="convr")(hx))
        q = nn.tanh(
            Conv2d(self.hidden_dim, 3, dtype=self.dtype, name="convq")(
                jnp.concatenate([r * h, x], axis=-1)
            )
        )
        return (1 - z) * h + z * q


class SepConvGRU(nn.Module):
    """Separable GRU: a horizontal (1x5) pass then a vertical (5x1) pass
    (reference: core/update.py:33-60)."""

    hidden_dim: int = 128
    dtype: Any = None

    @nn.compact
    def __call__(self, h: jax.Array, x: jax.Array) -> jax.Array:
        for suffix, ksize in (("1", (1, 5)), ("2", (5, 1))):
            hx = jnp.concatenate([h, x], axis=-1)
            z = nn.sigmoid(
                Conv2d(self.hidden_dim, ksize, dtype=self.dtype, name=f"convz{suffix}")(hx)
            )
            r = nn.sigmoid(
                Conv2d(self.hidden_dim, ksize, dtype=self.dtype, name=f"convr{suffix}")(hx)
            )
            q = nn.tanh(
                Conv2d(self.hidden_dim, ksize, dtype=self.dtype, name=f"convq{suffix}")(
                    jnp.concatenate([r * h, x], axis=-1)
                )
            )
            h = (1 - z) * h + z * q
        return h


class SmallMotionEncoder(nn.Module):
    """reference: core/update.py:62-77."""

    corr_planes: int
    dtype: Any = None

    @nn.compact
    def __call__(self, flow: jax.Array, corr: jax.Array) -> jax.Array:
        cor = nn.relu(Conv2d(96, 1, dtype=self.dtype, name="convc1")(corr))
        flo = nn.relu(Conv2d(64, 7, dtype=self.dtype, name="convf1")(flow))
        flo = nn.relu(Conv2d(32, 3, dtype=self.dtype, name="convf2")(flo))
        out = nn.relu(
            Conv2d(80, 3, dtype=self.dtype, name="conv")(
                jnp.concatenate([cor, flo], axis=-1)
            )
        )
        return jnp.concatenate([out, flow], axis=-1)


class BasicMotionEncoder(nn.Module):
    """reference: core/update.py:79-97."""

    corr_planes: int
    dtype: Any = None

    @nn.compact
    def __call__(self, flow: jax.Array, corr: jax.Array) -> jax.Array:
        cor = nn.relu(Conv2d(256, 1, dtype=self.dtype, name="convc1")(corr))
        cor = nn.relu(Conv2d(192, 3, dtype=self.dtype, name="convc2")(cor))
        flo = nn.relu(Conv2d(128, 7, dtype=self.dtype, name="convf1")(flow))
        flo = nn.relu(Conv2d(64, 3, dtype=self.dtype, name="convf2")(flo))
        out = nn.relu(
            Conv2d(128 - 2, 3, dtype=self.dtype, name="conv")(
                jnp.concatenate([cor, flo], axis=-1)
            )
        )
        return jnp.concatenate([out, flow], axis=-1)


class SmallUpdateBlock(nn.Module):
    """reference: core/update.py:99-112. No mask head: the small path
    upsamples bilinearly."""

    corr_planes: int
    hidden_dim: int = 96
    dtype: Any = None

    @nn.compact
    def __call__(
        self, net: jax.Array, inp: jax.Array, corr: jax.Array, flow: jax.Array
    ) -> tuple[jax.Array, Optional[jax.Array], jax.Array]:
        motion = SmallMotionEncoder(self.corr_planes, dtype=self.dtype, name="encoder")(
            flow, corr
        )
        x = jnp.concatenate([inp, motion], axis=-1)
        net = ConvGRU(self.hidden_dim, dtype=self.dtype, name="gru")(net, x)
        delta = FlowHead(128, dtype=self.dtype, name="flow_head")(net)
        return net, None, delta


class BasicUpdateBlock(nn.Module):
    """reference: core/update.py:114-141.

    ``use_mask_head=False`` reproduces raft_nc_dbl's deletion of the convex
    mask head (reference: core/raft_nc_dbl.py:68) — the NCUP upsampler
    consumes the GRU hidden state as guidance instead.
    """

    corr_planes: int
    hidden_dim: int = 128
    use_mask_head: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(
        self, net: jax.Array, inp: jax.Array, corr: jax.Array, flow: jax.Array
    ) -> tuple[jax.Array, Optional[jax.Array], jax.Array]:
        motion = BasicMotionEncoder(self.corr_planes, dtype=self.dtype, name="encoder")(
            flow, corr
        )
        x = jnp.concatenate([inp, motion], axis=-1)
        net = SepConvGRU(self.hidden_dim, dtype=self.dtype, name="gru")(net, x)
        delta = FlowHead(256, dtype=self.dtype, name="flow_head")(net)

        mask = None
        if self.use_mask_head:
            m = nn.relu(Conv2d(256, 3, dtype=self.dtype, name="mask_conv1")(net))
            m = Conv2d(64 * 9, 1, dtype=self.dtype, name="mask_conv2")(m)
            # 0.25 scale to balance gradients (reference: core/update.py:140).
            mask = 0.25 * m
        return net, mask, delta
