"""Feature/context encoders (reference: core/extractor.py).

Stride-8 CNNs in NHWC: 7x7/s2 stem + three 2-block residual stages
(64->96->128 for Basic at strides 1,2,2; bottleneck 32->64->96 for Small)
+ 1x1 output conv. Norm selectable per encoder: instance for fnet, batch
(Basic) / none (Small) for cnet (reference: core/raft.py:45-53). Encoder
convs use kaiming_normal(fan_out) init (reference: core/extractor.py:150-157).

The siamese trick (two images concatenated along batch, reference:
core/extractor.py:168-192) is applied by the caller — it halves the number
of XLA conv dispatches and batches better on the MXU.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax

from raft_ncup_tpu.nn.layers import Conv2d, Norm


class ResidualBlock(nn.Module):
    """Two 3x3 convs + identity/downsample shortcut (reference:
    core/extractor.py:6-56)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        ng = self.planes // 8

        def conv(s: int, name: str) -> Conv2d:
            return Conv2d(
                self.planes, 3, stride=s, init_mode="kaiming_out",
                dtype=self.dtype, name=name,
            )

        y = conv(self.stride, "conv1")(x)
        y = Norm(self.norm_fn, num_groups=ng, name="norm1")(y, train=train)
        y = nn.relu(y)
        y = conv(1, "conv2")(y)
        y = Norm(self.norm_fn, num_groups=ng, name="norm2")(y, train=train)
        y = nn.relu(y)

        if self.stride != 1:
            x = Conv2d(
                self.planes, 1, stride=self.stride, init_mode="kaiming_out",
                dtype=self.dtype, name="downsample_conv",
            )(x)
            x = Norm(self.norm_fn, num_groups=ng, name="downsample_norm")(
                x, train=train
            )
        return nn.relu(x + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (reference: core/extractor.py:60-116)."""

    planes: int
    norm_fn: str = "group"
    stride: int = 1
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        p4 = self.planes // 4
        ng = self.planes // 8
        y = Conv2d(p4, 1, init_mode="kaiming_out", dtype=self.dtype, name="conv1")(x)
        y = Norm(self.norm_fn, num_groups=ng, name="norm1")(y, train=train)
        y = nn.relu(y)
        y = Conv2d(
            p4, 3, stride=self.stride, init_mode="kaiming_out", dtype=self.dtype,
            name="conv2",
        )(y)
        y = Norm(self.norm_fn, num_groups=ng, name="norm2")(y, train=train)
        y = nn.relu(y)
        y = Conv2d(
            self.planes, 1, init_mode="kaiming_out", dtype=self.dtype, name="conv3"
        )(y)
        y = Norm(self.norm_fn, num_groups=ng, name="norm3")(y, train=train)
        y = nn.relu(y)

        if self.stride != 1:
            x = Conv2d(
                self.planes, 1, stride=self.stride, init_mode="kaiming_out",
                dtype=self.dtype, name="downsample_conv",
            )(x)
            x = Norm(self.norm_fn, num_groups=ng, name="downsample_norm")(
                x, train=train
            )
        return nn.relu(x + y)


class Encoder(nn.Module):
    """Stride-8 encoder; ``small`` selects the bottleneck variant."""

    output_dim: int = 128
    norm_fn: str = "batch"
    dropout: float = 0.0
    small: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(
        self, x: jax.Array, *, train: bool = False, bn_train: bool | None = None
    ) -> jax.Array:
        # `train` gates dropout; `bn_train` gates BatchNorm statistic
        # updates (False = frozen BN, the reference's freeze_bn: train.py:185).
        bn = train if bn_train is None else bn_train
        stem = 32 if self.small else 64
        stages = (32, 64, 96) if self.small else (64, 96, 128)
        block = BottleneckBlock if self.small else ResidualBlock

        x = Conv2d(
            stem, 7, stride=2, init_mode="kaiming_out", dtype=self.dtype, name="conv1"
        )(x)
        # Stem GroupNorm uses 8 groups (reference: core/extractor.py:124,201).
        x = Norm(self.norm_fn, num_groups=8, name="norm1")(x, train=bn)
        x = nn.relu(x)

        for i, (dim, stride) in enumerate(zip(stages, (1, 2, 2)), start=1):
            x = block(dim, self.norm_fn, stride, dtype=self.dtype, name=f"layer{i}_0")(
                x, train=bn
            )
            x = block(dim, self.norm_fn, 1, dtype=self.dtype, name=f"layer{i}_1")(
                x, train=bn
            )

        x = Conv2d(
            self.output_dim, 1, init_mode="kaiming_out", dtype=self.dtype, name="conv2"
        )(x)
        if self.dropout > 0:
            # Dropout2d semantics: whole channels dropped per sample.
            x = nn.Dropout(
                rate=self.dropout, broadcast_dims=(1, 2), deterministic=not train
            )(x)
        return x


def BasicEncoder(output_dim=128, norm_fn="batch", dropout=0.0, dtype=None, name=None):
    """reference: core/extractor.py:118-192."""
    return Encoder(output_dim, norm_fn, dropout, small=False, dtype=dtype, name=name)


def SmallEncoder(output_dim=128, norm_fn="instance", dropout=0.0, dtype=None, name=None):
    """reference: core/extractor.py:195-267."""
    return Encoder(output_dim, norm_fn, dropout, small=True, dtype=dtype, name=name)
