"""Base layers: convolution and normalization with PyTorch-matching
initialization and numerics.

Initialization parity matters for training-dynamics parity with the
reference, so ``Conv2d`` reproduces torch's defaults exactly:

- kernel: kaiming_uniform(a=sqrt(5))  => U(-b, b), b = sqrt(1 / fan_in)
- bias:   U(-1/sqrt(fan_in), 1/sqrt(fan_in))

and the encoders' explicit ``kaiming_normal_(mode='fan_out')`` (reference:
core/extractor.py:150-157) is available as ``init_mode='kaiming_out'``.

Mixed precision: params live in float32; when ``dtype`` is bfloat16 the
convolution computes in bfloat16 (the TPU analogue of the reference's CUDA
autocast regions), while norms always compute in float32.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# Policy-pinned dtypes (raft_ncup_tpu/precision/; docs/PRECISION.md).
# PARAM_DTYPE: master-weight storage — every PrecisionPolicy preset pins
# param_dtype to f32 (the policy constructor rejects anything else), so
# this module constant IS the policy's param dtype; modules cast params
# to the per-module compute ``dtype`` at use. NORM_DTYPE: normalization
# statistics always compute in f32 (PrecisionPolicy.norm_jnp pins it) —
# the standard mixed-precision exception. graftlint JGL009 forbids raw
# inline dtype literals in nn/ bodies; these named constants are the
# sanctioned routing.
PARAM_DTYPE = jnp.float32
NORM_DTYPE = jnp.float32


def _pair(v) -> tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _uniform_init(bound: float):
    def init(key, shape, dtype=PARAM_DTYPE):
        return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init


class Conv2d(nn.Module):
    """NHWC convolution with torch-compatible padding and init.

    Default padding is kernel//2 per axis — the scheme every conv in the
    reference uses (explicit ``padding=k//2`` at each call site).
    """

    features: int
    kernel_size: Any = 3
    stride: Any = 1
    dilation: Any = 1
    padding: Optional[Any] = None
    use_bias: bool = True
    groups: int = 1
    init_mode: str = "torch"  # 'torch' | 'kaiming_out'
    dtype: Any = None  # compute dtype; None = input dtype

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        dh, dw = _pair(self.dilation)
        cin = x.shape[-1]
        fan_in = (cin // self.groups) * kh * kw

        if self.init_mode == "torch":
            kinit = _uniform_init(math.sqrt(1.0 / fan_in))
        elif self.init_mode == "kaiming_out":
            fan_out = (self.features // self.groups) * kh * kw
            kinit = nn.initializers.normal(stddev=math.sqrt(2.0 / fan_out))
        else:
            raise ValueError(f"unknown init_mode: {self.init_mode!r}")

        kernel = self.param(
            "kernel", kinit, (kh, kw, cin // self.groups, self.features), PARAM_DTYPE
        )

        if self.padding is None:
            ph, pw = kh // 2, kw // 2
        else:
            ph, pw = _pair(self.padding)
        # torch pads k//2 for odd kernels; with dilation the reference
        # computes pad = k//2 + (k-1)(d-1)/2 at call sites — callers pass
        # that explicitly via `padding`.
        pad = ((ph, ph), (pw, pw))

        cdt = self.dtype or x.dtype
        dn = jax.lax.conv_dimension_numbers(
            x.shape, kernel.shape, ("NHWC", "HWIO", "NHWC")
        )
        y = jax.lax.conv_general_dilated(
            x.astype(cdt),
            kernel.astype(cdt),
            window_strides=(sh, sw),
            padding=pad,
            rhs_dilation=(dh, dw),
            dimension_numbers=dn,
            feature_group_count=self.groups,
        )
        if self.use_bias:
            bias = self.param(
                "bias",
                _uniform_init(1.0 / math.sqrt(fan_in)),
                (self.features,),
                PARAM_DTYPE,
            )
            y = y + bias.astype(cdt)
        return y


class ConvTranspose2d(nn.Module):
    """NHWC transposed convolution matching ``nn.ConvTranspose2d`` (used by
    the UNet weights-estimation net, reference: core/interp_weights_est.py:135).
    """

    features: int
    kernel_size: Any = 2
    stride: Any = 2
    use_bias: bool = True
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        cin = x.shape[-1]
        # torch ConvTranspose2d weight is (in, out, kh, kw); its default
        # kaiming_uniform(a=sqrt(5)) reads fan_in from dim 1: out * kh * kw.
        fan_in = self.features * kh * kw
        # Stored (kh, kw, out, in) — torch's (in, out, kh, kw) under the
        # same OIHW->HWIO transpose the importer applies to regular convs.
        # transpose_kernel=True makes lax.conv_transpose the exact gradient
        # of a forward conv, matching nn.ConvTranspose2d bit-for-bit.
        kernel = self.param(
            "kernel",
            _uniform_init(math.sqrt(1.0 / fan_in)),
            (kh, kw, self.features, cin),
            PARAM_DTYPE,
        )
        cdt = self.dtype or x.dtype
        y = jax.lax.conv_transpose(
            x.astype(cdt),
            kernel.astype(cdt),
            strides=(sh, sw),
            padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            transpose_kernel=True,
        )
        if self.use_bias:
            bias = self.param(
                "bias",
                _uniform_init(1.0 / math.sqrt(fan_in)),
                (self.features,),
                PARAM_DTYPE,
            )
            y = y + bias.astype(cdt)
        return y


class Norm(nn.Module):
    """Normalization factory matching the reference's norm_fn choices
    (reference: core/extractor.py:16-38,123-133).

    - 'group': GroupNorm(affine), eps 1e-5.
    - 'batch': BatchNorm, momentum 0.1 (torch) == flax momentum 0.9,
       eps 1e-5. Eval/frozen mode uses running stats.
    - 'instance': per-channel, per-sample normalization without affine
       (torch InstanceNorm2d default affine=False).
    - 'none': identity.

    Norm math always runs in float32 regardless of activation dtype.
    """

    kind: str
    num_groups: Optional[int] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        in_dtype = x.dtype
        x32 = x.astype(NORM_DTYPE)
        if self.kind == "none":
            return x
        if self.kind == "group":
            y = nn.GroupNorm(num_groups=self.num_groups, epsilon=1e-5)(x32)
        elif self.kind == "instance":
            y = nn.GroupNorm(
                num_groups=x.shape[-1], epsilon=1e-5, use_bias=False, use_scale=False
            )(x32)
        elif self.kind == "batch":
            y = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5
            )(x32)
        else:
            raise ValueError(f"unknown norm kind: {self.kind!r}")
        return y.astype(in_dtype)
