"""Normalized-convolution U-Net (reference: core/nconv_modules.py:25-136).

A confidence-aware interpolation network: every layer is a normalized
convolution propagating (data, confidence) pairs; downsampling pools
confidence and gathers data at the confidence argmax; the decoder
nearest-upsamples and concatenates skip features.

Faithfulness note: the reference decoder indexes ``x[i + nds]`` /
``c[nds - i]`` (core/nconv_modules.py:128-131). For the shipped
``num_downsampling=1`` configs this concatenates the full-resolution
encoder output *with itself* and never consumes the downsampled branch —
the deepest encoder output is overwritten before use. We reproduce that
wiring exactly (checkpoint + behavior parity); XLA dead-code-eliminates
the unused branch, so it costs nothing.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_ncup_tpu.nn.layers import PARAM_DTYPE
from raft_ncup_tpu.ops.geometry import upsample_nearest
from raft_ncup_tpu.ops.nconv import downsample_data_conf, nconv2d, positivity


class NConv2dLayer(nn.Module):
    """Normalized conv layer with softplus-reparameterized weights.

    The raw parameter is named ``weight_p`` to mirror the reference's
    EnforcePos reparameterization (core/nconv_modules.py:218-242): the
    effective kernel is ``pos_fn(weight_p)``, and ``weight_p`` is
    initialized to ``pos_fn(N(2, sqrt(2/n)))`` with n = kh*kw*out_ch
    (core/nconv_modules.py:207-209 followed by EnforcePos.apply).
    """

    features: int
    kernel_size: int = 3
    pos_fn: str = "softplus"
    use_bias: bool = False
    groups: int = 1

    @nn.compact
    def __call__(
        self, data: jax.Array, conf: jax.Array
    ) -> tuple[jax.Array, jax.Array]:
        k = self.kernel_size
        cin = data.shape[-1]
        n = k * k * self.features

        def raw_init(key, shape, dtype=PARAM_DTYPE):
            w = 2.0 + math.sqrt(2.0 / n) * jax.random.normal(key, shape, dtype)
            return positivity(w, self.pos_fn)

        raw = self.param(
            "weight_p", raw_init, (k, k, cin // self.groups, self.features), PARAM_DTYPE
        )
        weight = positivity(raw, self.pos_fn)

        bias = None
        if self.use_bias:
            fan_in = (cin // self.groups) * k * k
            bound = 1.0 / math.sqrt(fan_in)

            def bias_init(key, shape, dtype=PARAM_DTYPE):
                return jax.random.uniform(key, shape, dtype, -bound, bound)

            bias = self.param("bias", bias_init, (self.features,), PARAM_DTYPE)

        return nconv2d(
            data, conf, weight, bias, groups=self.groups, propagate_conf=True
        )


class NConvUNet(nn.Module):
    """reference: core/nconv_modules.py:25-136 (constructor defaults and
    the shipped config: train_raft_nc_things.sh:37-46)."""

    in_ch: int = 1
    channels_multiplier: int = 2
    num_downsampling: int = 1
    encoder_filter_sz: int = 5
    decoder_filter_sz: int = 3
    out_filter_sz: int = 1
    pos_fn: str = "softplus"
    groups: int = 1
    use_bias: bool = False
    data_pooling: str = "conf_based"
    shared_encoder: bool = True
    use_double_conv: bool = False

    @nn.compact
    def __call__(
        self, data: jax.Array, conf: jax.Array
    ) -> tuple[jax.Array, jax.Array]:
        mult = self.in_ch * self.channels_multiplier
        nds = self.num_downsampling

        nconv_in = NConv2dLayer(
            mult, self.encoder_filter_sz, self.pos_fn, self.use_bias, self.groups,
            name="nconv_in",
        )
        n_x2 = 2 if self.use_double_conv else 1
        nconv_x2 = [
            NConv2dLayer(
                mult, self.encoder_filter_sz, self.pos_fn, self.use_bias, self.groups,
                name=f"nconv_x2_{i}",
            )
            for i in range(n_x2)
        ]
        if not self.shared_encoder:
            deep_encoders = [
                NConv2dLayer(
                    mult, self.encoder_filter_sz, self.pos_fn, self.use_bias,
                    self.groups, name=f"encoder_{i + 1}",
                )
                for i in range(nds)
            ]
        decoders = [
            NConv2dLayer(
                mult, self.decoder_filter_sz, self.pos_fn, self.use_bias, self.groups,
                name=f"decoder_{i}",
            )
            for i in range(nds)
        ]
        nconv_out = NConv2dLayer(
            self.in_ch, self.out_filter_sz, self.pos_fn, False, self.groups,
            name="nconv_out",
        )

        def enc0(d, c):
            d, c = nconv_in(d, c)
            for layer in nconv_x2:
                d, c = layer(d, c)
            return d, c

        def enc_deep(i, d, c):
            # Shared encoder reuses the first nconv_x2 layer at every scale
            # (reference: core/nconv_modules.py:77-79).
            if self.shared_encoder:
                return nconv_x2[0](d, c)
            return deep_encoders[i](d, c)

        x: list = [None] * (nds * 2 + 1)
        c: list = [None] * (nds * 2 + 1)
        x[0], c[0] = data, conf

        if nds == 0:
            x[0], c[0] = enc0(x[0], c[0])
        else:
            for i in range(nds + 1):
                if i == 0:
                    x[i + 1], c[i + 1] = enc0(x[i], c[i])
                else:
                    d_ds, c_ds = downsample_data_conf(x[i], c[i], self.data_pooling)
                    x[i + 1], c[i + 1] = enc_deep(i - 1, d_ds, c_ds)
            for i in range(nds):
                # Faithful reference indexing (see module docstring).
                target_h, target_w = c[nds - i].shape[1], c[nds - i].shape[2]
                src_h = x[i + nds].shape[1]
                factor = target_h // src_h if src_h else 1
                if factor > 1:
                    x_up = upsample_nearest(x[i + nds], factor)
                    c_up = upsample_nearest(c[i + nds], factor)
                else:
                    x_up, c_up = x[i + nds], c[i + nds]
                x[i + nds + 1], c[i + nds + 1] = decoders[i](
                    jnp.concatenate([x_up, x[nds - i]], axis=-1),
                    jnp.concatenate([c_up, c[nds - i]], axis=-1),
                )

        return nconv_out(x[-1], c[-1])
