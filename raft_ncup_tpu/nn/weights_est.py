"""Confidence/weights estimation networks for the NCUP upsampler
(reference: core/interp_weights_est.py)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from raft_ncup_tpu.nn.layers import Conv2d, ConvTranspose2d, Norm


class SimpleWeightsNet(nn.Module):
    """Conv(+BN)+ReLU stack with a sigmoid 1x1-ish head (reference:
    core/interp_weights_est.py:10-47).

    ``num_ch`` excludes the input channel count (it is inferred from the
    input, unlike the reference which prepends it to the list). BatchNorm
    is enabled for Sintel-configured models only (reference:
    core/upsampler.py:41-46).
    """

    num_ch: tuple[int, ...] = (64, 32)
    out_ch: int = 2
    filter_sz: tuple[int, ...] = (3, 3, 1)
    dilation: tuple[int, ...] = (1, 1, 1)
    use_bn: bool = False
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        assert len(self.filter_sz) == len(self.num_ch) + 1
        for i, ch in enumerate(self.num_ch):
            k, d = self.filter_sz[i], self.dilation[i]
            pad = k // 2 + ((k - 1) * (d - 1)) // 2
            x = Conv2d(
                ch, k, dilation=d, padding=pad, dtype=self.dtype, name=f"conv{i}"
            )(x)
            if self.use_bn:
                x = Norm("batch", name=f"bn{i}")(x, train=train)
            x = nn.relu(x)
        k, d = self.filter_sz[-1], self.dilation[-1]
        pad = k // 2 + ((k - 1) * (d - 1)) // 2
        x = Conv2d(
            self.out_ch, k, dilation=d, padding=pad, dtype=self.dtype, name="out"
        )(x)
        return nn.sigmoid(x)


class _DoubleConv(nn.Module):
    """(conv => BN => ReLU) * 2 (reference: core/interp_weights_est.py:85-100)."""

    out_ch: int
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        for i in range(2):
            x = Conv2d(self.out_ch, 3, dtype=self.dtype, name=f"conv{i}")(x)
            x = Norm("batch", name=f"bn{i}")(x, train=train)
            x = nn.relu(x)
        return x


class UNetWeightsNet(nn.Module):
    """Classic double-conv U-Net with ConvTranspose ups and pad-to-match
    skips (reference: core/interp_weights_est.py:50-155)."""

    num_ch: tuple[int, ...] = (16, 32, 64)
    out_ch: int = 2
    dtype: Any = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False) -> jax.Array:
        n_down = len(self.num_ch) - 1
        feats = [
            _DoubleConv(self.num_ch[0], dtype=self.dtype, name="inconv")(
                x, train=train
            )
        ]
        for i in range(n_down):
            y = nn.max_pool(feats[-1], (2, 2), strides=(2, 2))
            feats.append(
                _DoubleConv(self.num_ch[i + 1], dtype=self.dtype, name=f"down{i}")(
                    y, train=train
                )
            )

        y = feats[-1]
        for i in range(n_down):
            skip = feats[-i - 2]
            y = ConvTranspose2d(
                y.shape[-1], 2, stride=2, dtype=self.dtype, name=f"up{i}_tconv"
            )(y)
            dh = skip.shape[1] - y.shape[1]
            dw = skip.shape[2] - y.shape[2]
            y = jnp.pad(
                y,
                (
                    (0, 0),
                    (dh // 2, dh - dh // 2),
                    (dw // 2, dw - dw // 2),
                    (0, 0),
                ),
            )
            y = _DoubleConv(
                self.num_ch[-i - 2], dtype=self.dtype, name=f"up{i}_conv"
            )(jnp.concatenate([skip, y], axis=-1), train=train)

        y = Conv2d(self.out_ch, 1, dtype=self.dtype, name="outconv")(y)
        return nn.sigmoid(y)
