from raft_ncup_tpu.nn.layers import Conv2d, ConvTranspose2d, Norm  # noqa: F401
from raft_ncup_tpu.nn.extractor import BasicEncoder, SmallEncoder  # noqa: F401
from raft_ncup_tpu.nn.update import (  # noqa: F401
    BasicMotionEncoder,
    BasicUpdateBlock,
    ConvGRU,
    FlowHead,
    SepConvGRU,
    SmallMotionEncoder,
    SmallUpdateBlock,
)
from raft_ncup_tpu.nn.nconv_unet import NConv2dLayer, NConvUNet  # noqa: F401
from raft_ncup_tpu.nn.weights_est import SimpleWeightsNet, UNetWeightsNet  # noqa: F401
from raft_ncup_tpu.nn.upsampler import (  # noqa: F401
    BilinearUpsampler,
    NConvUpsampler,
    build_upsampler,
)
