"""PAC / DJIF / joint-bilateral upsampler heads (ablation baselines).

JAX re-make of the reference's comparison upsamplers (reference:
core/pac_upsampler.py:67-251 and the wrappers at core/upsampler.py:223-242).
The hand-written autograd machinery of the original is unnecessary here —
the PAC primitives in ``raft_ncup_tpu.ops.pac`` are plain differentiable
functions.

All heads share the upsampler interface ``__call__(x_lowres, guidance,
train=False) -> x_highres`` with channel-last tensors; multi-channel
targets fold channels into the batch like the reference's
``convert_to_single_channel`` (reference: core/pac_upsampler.py:16-36).
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from raft_ncup_tpu.config import UpsamplerConfig
from raft_ncup_tpu.nn.layers import PARAM_DTYPE, Conv2d
from raft_ncup_tpu.ops.pac import (
    extract_patches,
    pac_gaussian_kernel,
    pac_kernel2d,
    pacconv2d,
    pacconv_transpose2d,
    pacpool2d,
    smooth_kernel_2d,
    zero_stuff_mask,
)


def parse_kernel_type(kernel_type: str) -> dict:
    """Parse the reference's kernel-type strings (reference:
    core/pac_modules.py:545-563,672-674): 'gaussian' or
    'inv_{alpha}_{lambda}[_asym][_fixed]'."""
    if kernel_type == "gaussian":
        return dict(base="gaussian", alpha=None, lam=None,
                    asym=False, fixed=False)
    if kernel_type.startswith("inv_"):
        parts = kernel_type.split("_")
        return dict(
            base="inv",
            alpha=float(parts[1]),
            lam=float(parts[2]),
            asym="asym" in parts[3:],
            fixed="fixed" in parts[3:],
        )
    raise ValueError(f"kernel_type set to invalid value ({kernel_type})")


class _PacKernelMixin:
    """Shared adapting-kernel plumbing for the PAC module wrappers: the
    kernel-type string, smooth-kernel options, and the learnable
    inv-alpha/lambda and 'full_*' smooth-kernel parameters."""

    def _kernel_params(self, n_channels: int) -> dict:
        kt = parse_kernel_type(self.kernel_type)
        kw: dict = dict(kernel_type=kt["base"], asym=kt["asym"])
        if kt["base"] == "inv":
            shape = (n_channels,) if n_channels > 0 else ()
            if kt["fixed"]:
                kw["inv_alpha"] = jnp.full(shape, kt["alpha"])
                kw["inv_lambda"] = jnp.full(shape, kt["lam"])
            else:
                kw["inv_alpha"] = self.param(
                    "inv_alpha", lambda rng: jnp.full(shape, kt["alpha"])
                )
                kw["inv_lambda"] = self.param(
                    "inv_lambda", lambda rng: jnp.full(shape, kt["lam"])
                )
        if self.smooth_kernel_type == "none":
            pass
        elif self.smooth_kernel_type.startswith("full_"):
            sz = int(self.smooth_kernel_type.split("_")[-1])
            # Learnable smoothing filter, init 1/size^2 (reference:
            # core/pac_modules.py:566-567,641-642).
            kw["smooth_kernel"] = self.param(
                "smooth_kernel",
                lambda rng: jnp.full((sz, sz), 1.0 / (sz * sz)),
            )
        else:
            kw["smooth_kernel"] = smooth_kernel_2d(self.smooth_kernel_type)
        return kw


class PacConv2d(nn.Module, _PacKernelMixin):
    """Pixel-adaptive convolution module (reference:
    core/pac_modules.py:662-710): a standard conv whose spatially-varying
    kernel is the product of a learned filter and a guidance-adapting
    kernel. ``__call__(x, guide, mask=None)``; returns the output, or
    ``(output, mask_out)`` when ``mask`` is given."""

    features: int
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0  # torch Conv2d default (reference: :676)
    dilation: int = 1
    use_bias: bool = True
    kernel_type: str = "gaussian"
    smooth_kernel_type: str = "none"
    normalize_kernel: bool = False
    shared_filters: bool = False

    @nn.compact
    def __call__(self, x, guide, mask=None):
        k, cin = self.kernel_size, x.shape[-1]
        if self.shared_filters and self.features != cin:
            raise ValueError("shared_filters requires features == in-channels")
        # torch 'uniform' filler: U(-b, b), b = 1/sqrt(in*k*k), scaled by
        # in-channels for shared filters (reference: :586-596).
        bound = 1.0 / math.sqrt(cin * k * k)
        if self.shared_filters:
            bound *= cin
            wshape = (k * k,)
        else:
            wshape = (k * k, cin, self.features)
        weight = self.param(
            "weight",
            lambda rng: jax.random.uniform(
                rng, wshape, minval=-bound, maxval=bound
            ),
        )
        bias = (
            self.param(
                "bias",
                lambda rng: jax.random.uniform(
                    rng, (self.features,), minval=-bound, maxval=bound
                ),
            )
            if self.use_bias
            else None
        )
        kernel, mask_out = pac_kernel2d(
            guide, k, stride=self.stride, dilation=self.dilation,
            padding=self.padding, normalize_kernel=self.normalize_kernel,
            mask=mask, **self._kernel_params(0),
        )
        pad = (self.padding, self.padding)
        out = pacconv2d(
            x, kernel, weight, bias, self.dilation, pad, pad,
            stride=self.stride, shared_filters=self.shared_filters,
        )
        return out if mask_out is None else (out, mask_out)


class PacPool2d(nn.Module, _PacKernelMixin):
    """Pixel-adaptive pooling module (reference:
    core/pac_modules.py:765-816): kernel-weighted window sum, optionally
    with per-channel kernels. ``out_channels`` sizes the learnable
    inv-alpha/lambda for channel-wise 'inv_*' kernels."""

    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    kernel_type: str = "gaussian"
    smooth_kernel_type: str = "none"
    channel_wise: bool = False
    normalize_kernel: bool = False
    out_channels: int = -1

    @nn.compact
    def __call__(self, x, guide, mask=None):
        if self.channel_wise and guide.shape[-1] != x.shape[-1]:
            raise ValueError(
                "input and kernel must have the same number of channels "
                "when channel_wise=True"
            )
        n_ch = self.out_channels if self.channel_wise else 0
        kernel, mask_out = pac_kernel2d(
            guide, self.kernel_size, stride=self.stride,
            dilation=self.dilation, padding=self.padding,
            channel_wise=self.channel_wise,
            normalize_kernel=self.normalize_kernel,
            mask=mask, **self._kernel_params(n_ch),
        )
        out = pacpool2d(
            x, kernel, self.kernel_size, self.dilation,
            stride=self.stride, padding=self.padding,
        )
        return out if mask_out is None else (out, mask_out)


def _fold_channels(x: jax.Array) -> tuple[jax.Array, int]:
    """(B, H, W, C) -> (B*C, H, W, 1)."""
    B, H, W, C = x.shape
    if C == 1:
        return x, 1
    return x.transpose(0, 3, 1, 2).reshape(B * C, H, W, 1), C


def _unfold_channels(x: jax.Array, ch: int) -> jax.Array:
    if ch == 1:
        return x
    BC, H, W, one = x.shape
    return x.reshape(BC // ch, ch, H, W).transpose(0, 2, 3, 1)


def _repeat_for_channels(x: jax.Array, ch: int) -> jax.Array:
    """Tile guidance along batch to match folded channels."""
    if ch == 1:
        return x
    B, H, W, C = x.shape
    return jnp.repeat(x, ch, axis=0)


def _resize_half_pixel(x: jax.Array, out_hw: tuple[int, int]) -> jax.Array:
    """align_corners=False bilinear (torch F.interpolate default)."""
    B, H, W, C = x.shape
    return jax.image.resize(
        x, (B, out_hw[0], out_hw[1], C), method="bilinear"
    )


class PacConvTranspose2d(nn.Module, _PacKernelMixin):
    """Guided 2x-or-more upsampling convolution (reference:
    core/pac_modules.py:628-722 module, native forward :462-467).

    ``__call__(x_low, guide_high)``: the Gaussian adapting kernel comes
    from the output-resolution guidance; weight layout (k*k, Cin, Cout).
    """

    in_ch: int
    out_ch: int
    kernel_size: int = 5
    stride: int = 2
    padding: int = 2
    output_padding: int = 1
    normalize_kernel: bool = False
    use_bias: bool = True
    identity_init: bool = False
    kernel_type: str = "gaussian"
    smooth_kernel_type: str = "none"
    filler: str = "uniform"

    def _linear_filler(self) -> jax.Array:
        """Bilinear-interpolation weights on the channel diagonal, the
        'linear' filler (reference: core/pac_modules.py:597-611)."""
        k, s = self.kernel_size, self.stride
        p = (k - (2 * s - 1)) // 2
        w1 = (
            np.concatenate(
                [np.zeros(p), np.arange(1, s), np.arange(s, 0, -1), np.zeros(p)]
            )
            / s
        )
        if self.normalize_kernel:
            w1 = w1 * np.array(
                [((k - j - 1) // s) + (j // s) + 1.0 for j in range(k)]
            )
        w2 = (w1[:, None] * w1[None, :]).reshape(k * k)
        eye = np.zeros((k * k, self.in_ch, self.out_ch), np.float32)
        for c in range(min(self.in_ch, self.out_ch)):
            eye[:, c, c] = w2
        return jnp.asarray(eye, PARAM_DTYPE)

    @nn.compact
    def __call__(self, x: jax.Array, guide: jax.Array) -> jax.Array:
        k = self.kernel_size

        if self.identity_init:
            eye = jnp.zeros((k * k, self.in_ch, self.out_ch))
            for c in range(min(self.in_ch, self.out_ch)):
                eye = eye.at[:, c, c].set(1.0)
            weight = self.param("weight", lambda rng: eye)
        elif self.filler == "linear":
            init = self._linear_filler()
            weight = self.param("weight", lambda rng: init)
        else:
            # Torch ConvTranspose2d default init: U(-b, b), b = 1/sqrt(fan).
            bound = 1.0 / math.sqrt(self.in_ch * k * k)
            weight = self.param(
                "weight",
                lambda rng: jax.random.uniform(
                    rng, (k * k, self.in_ch, self.out_ch),
                    minval=-bound, maxval=bound,
                ),
            )
        if not self.use_bias:
            bias = None
        elif self.filler == "linear":
            # The linear filler zeroes the bias (reference:
            # core/pac_modules.py:610-611).
            bias = self.param(
                "bias", lambda rng: jnp.zeros((self.out_ch,))
            )
        else:
            bias = self.param(
                "bias",
                lambda rng: jax.random.uniform(
                    rng, (self.out_ch,),
                    minval=-1.0 / math.sqrt(self.in_ch * k * k),
                    maxval=1.0 / math.sqrt(self.in_ch * k * k),
                ),
            )

        # Transposed kernels are computed at the OUTPUT resolution with
        # 'same' padding — asymmetric split for even kernel sizes, as the
        # historical gaussian path padded (reference: core/pac_modules.py:365-367).
        span = k - 1
        kernel, _ = pac_kernel2d(
            guide, k,
            pad_lo=(span // 2, span // 2),
            pad_hi=(span - span // 2, span - span // 2),
            **self._kernel_params(0),
        )
        if self.normalize_kernel:
            # Taps landing on stuffed zeros contribute nothing; normalize
            # over the real-sample taps (reference:
            # core/pac_modules.py:352-360,417-424 with transposed mask).
            pattern = zero_stuff_mask(x.shape[1:3], self.stride, x.dtype)
            span = (k - 1)
            pad = span - self.padding
            pat = extract_patches(
                pattern, k,
                pad_lo=(pad, pad),
                pad_hi=(pad + self.output_padding, pad + self.output_padding),
            )[..., 0]
            kernel = kernel * pat
            kernel = kernel / jnp.maximum(
                kernel.sum(axis=3, keepdims=True), 1e-12
            )
        return pacconv_transpose2d(
            x, kernel, weight, bias,
            stride=self.stride, padding=self.padding,
            output_padding=self.output_padding,
        )


class PacJointUpsample(nn.Module):
    """Guided upsampler with target/guidance/final branches and log2(factor)
    PacConvTranspose2d stages (reference: core/pac_upsampler.py:153-251)."""

    factor: int
    channels: int = 1
    guide_channels: int = 3
    n_t_layers: int = 3
    n_g_layers: int = 3
    n_f_layers: int = 2
    n_filters: int = 32
    k_ch: int = 16
    f_sz_1: int = 5
    f_sz_2: int = 5

    @nn.compact
    def __call__(
        self, x_lowres: jax.Array, guidance: jax.Array, *, train: bool = False
    ) -> jax.Array:
        assert math.log2(self.factor) % 1 == 0, "factor must be a power of 2"
        num_ups = int(math.log2(self.factor))
        x, ch0 = _fold_channels(x_lowres)

        # Target branch at low res.
        for li in range(self.n_t_layers):
            x = Conv2d(self.n_filters, self.f_sz_1, name=f"t_conv{li + 1}")(x)
            if li < self.n_t_layers - 1:
                x = jax.nn.relu(x)

        # Guidance branch emits k_ch kernel-feature channels per stage.
        g = guidance
        for li in range(self.n_g_layers):
            out_ch = (
                self.k_ch * num_ups
                if li == self.n_g_layers - 1
                else self.n_filters
            )
            g = Conv2d(out_ch, self.f_sz_1, name=f"g_conv{li + 1}")(g)
            if li < self.n_g_layers - 1:
                g = jax.nn.relu(g)

        # Upsampling stages: guide features resized to each stage's output
        # resolution (reference: core/pac_upsampler.py:239-248).
        H, W = x_lowres.shape[1:3]
        for i in range(num_ups):
            scale = 2 ** (i + 1)
            g_cur = g[..., i * self.k_ch : (i + 1) * self.k_ch]
            if scale != self.factor:
                g_cur = _resize_half_pixel(
                    g_cur, (H * scale, W * scale)
                )
            g_cur = _repeat_for_channels(g_cur, ch0)
            x = PacConvTranspose2d(
                self.n_filters,
                self.n_filters,
                kernel_size=self.f_sz_2,
                stride=2,
                padding=(self.f_sz_2 - 1) // 2,
                output_padding=self.f_sz_2 % 2,
                name=f"up_convt{i + 1}",
            )(x, g_cur)
            x = jax.nn.relu(x)

        # Final prediction branch.
        for li in range(self.n_f_layers):
            out_ch = 1 if li == self.n_f_layers - 1 else self.n_filters
            x = Conv2d(out_ch, self.f_sz_1, name=f"f_conv{li + 1}")(x)
            if li < self.n_f_layers - 1:
                x = jax.nn.relu(x)

        return _unfold_channels(x, ch0)


class DJIF(nn.Module):
    """Deep joint image filtering (reference: core/pac_upsampler.py:105-145):
    bilinear-upsample the target, then CNN branches for target and guidance
    fused by a joint branch."""

    factor: int
    channels: int = 1
    guide_channels: int = 3
    fs: Sequence[int] = (9, 1, 5)
    ns_tg: Sequence[int] = (96, 48, 1)
    ns_f: Sequence[int] = (64, 32)

    @nn.compact
    def __call__(
        self, x_lowres: jax.Array, guidance: jax.Array, *, train: bool = False
    ) -> jax.Array:
        x, ch0 = _fold_channels(x_lowres)
        if x.shape[2] < guidance.shape[2]:
            x = _resize_half_pixel(
                x, (x.shape[1] * self.factor, x.shape[2] * self.factor)
            )

        # The reference distributes the total t/g-branch padding evenly
        # (paddings_tg = (2, 2, 2) for fs=(9, 1, 5)) rather than per-layer
        # k//2; intermediate resolutions and border behavior must match for
        # imported reference DJIF weights to reproduce outputs
        # (reference: core/pac_upsampler.py:109-110,115-127). Generalized
        # to any layer count: equal shares, remainder on the last layer.
        total_pad = sum(f // 2 for f in self.fs)
        n_layers = len(self.fs)
        share = total_pad // n_layers
        pads_tg = (share,) * (n_layers - 1) + (
            total_pad - (n_layers - 1) * share,
        )

        def branch(v, prefix):
            for li, (n, f) in enumerate(zip(self.ns_tg, self.fs)):
                v = Conv2d(
                    n, f, padding=pads_tg[li], name=f"{prefix}_conv{li + 1}"
                )(v)
                if li < len(self.ns_tg) - 1:
                    v = jax.nn.relu(v)
            return v

        t = branch(x, "t")
        g = branch(guidance, "g")
        g = _repeat_for_channels(g, ch0)

        v = jnp.concatenate([t, g], axis=-1)
        chans = tuple(self.ns_f) + (1,)
        for li, (n, f) in enumerate(zip(chans, self.fs)):
            v = Conv2d(n, f, name=f"j_conv{li + 1}")(v)
            if li < len(chans) - 1:
                v = jax.nn.relu(v)
        return _unfold_channels(v, ch0)


class JointBilateral(nn.Module):
    """Classic joint bilateral upsampling as a fixed-weight PAC transpose
    conv over [color * scale_color, position * scale_space] guidance
    (reference: core/pac_upsampler.py:67-93)."""

    factor: int
    channels: int = 2
    kernel_size: int = 5
    scale_space: float = 0.125
    scale_color: float = 1.0

    @nn.compact
    def __call__(
        self, x_lowres: jax.Array, guidance: jax.Array, *, train: bool = False
    ) -> jax.Array:
        x, ch0 = _fold_channels(x_lowres)
        B, H, W, C = guidance.shape
        yy = jnp.arange(H, dtype=guidance.dtype)[None, :, None, None]
        xx = jnp.arange(W, dtype=guidance.dtype)[None, None, :, None]
        guide = jnp.concatenate(
            [
                guidance * self.scale_color,
                jnp.broadcast_to(yy, (B, H, W, 1)) * self.scale_space,
                jnp.broadcast_to(xx, (B, H, W, 1)) * self.scale_space,
            ],
            axis=-1,
        )
        guide = _repeat_for_channels(guide, ch0)
        k, f = self.kernel_size, self.factor
        out = PacConvTranspose2d(
            1,
            1,
            kernel_size=k,
            stride=f,
            padding=1 + (k - f - 1) // 2,
            output_padding=(k - f) % 2,
            normalize_kernel=True,
            use_bias=False,
            identity_init=True,
            name="convt",
        )(x, guide)
        return _unfold_channels(out, ch0)


class _PacHead(nn.Module):
    """Adapter giving PAC/DJIF heads the registry interface."""

    cfg: UpsamplerConfig
    kind: str
    dtype: Any = None

    @nn.compact
    def __call__(
        self, x_lowres: jax.Array, guidance: jax.Array, *, train: bool = False
    ) -> jax.Array:
        C = x_lowres.shape[-1]
        Gc = guidance.shape[-1]
        # Guidance arrives at the input (low) resolution from the GRU
        # hidden state; the heads want it at output resolution (reference
        # wires full-res RGB guidance; here it is upsampled feature
        # guidance).
        H, W = x_lowres.shape[1:3]
        s = self.cfg.scale
        guide_hr = _resize_half_pixel(guidance, (H * s, W * s))
        if self.kind == "pac":
            head = PacJointUpsample(
                factor=s, channels=C, guide_channels=Gc, name="pac"
            )
        else:
            head = DJIF(
                factor=s, channels=C, guide_channels=Gc, name="djif"
            )
        return head(x_lowres, guide_hr, train=train)


def build_pac_upsampler(
    cfg: UpsamplerConfig, dtype: Any = None, name: str = "upsampler"
) -> nn.Module:
    """Factory entry used by the upsampler registry (reference wrapper
    classes: core/upsampler.py:223-242)."""
    return _PacHead(cfg, kind=cfg.kind, dtype=dtype, name=name)
