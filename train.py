#!/usr/bin/env python
"""Training driver (reference-compatible CLI).

The TPU re-make of the reference trainer (reference: train.py:167-261):
same stages, loss, schedule, validation cadence and flag names — but the
step is one jitted SPMD program over a (data, spatial) device mesh, the
input pipeline is a host-sharded threaded loader with device-side batch
prefetch (transfer overlapped with compute; metrics accumulate on device
so the steady-state loop never syncs the host), and checkpoints carry
the full train state (params + optimizer + step) via orbax.

Fault tolerance (raft_ncup_tpu/resilience/; docs/RESILIENCE.md):

- the divergence sentinel rides inside the jitted step (non-finite or
  grad-spiking steps are skip-updates; K consecutive bad steps halt the
  run, roll back to the last good checkpoint and exit EXIT_DIVERGED);
- SIGTERM/SIGINT trigger one atomic, multihost-agreed checkpoint plus
  exact-resume metadata, then a clean exit with EXIT_PREEMPTED;
- dataset reads and checkpoint saves retry with bounded backoff, with
  per-run accounting in log.txt;
- ``--chaos`` injects deterministic faults for the resilience tests.

Example (mirrors train_raft_nc_things.sh):
    python train.py --name raft_nc_things --model raft_nc_dbl \
        --stage things --num_steps 100000 --batch_size 6 \
        --lr 0.000125 --image_size 400 720 --final_upsampling=NConvUpsampler
"""

from __future__ import annotations

import contextlib
import os
import signal
import sys

import jax
import numpy as np


def main(argv=None) -> int:
    from raft_ncup_tpu.cli import parse_train
    from raft_ncup_tpu.data import DevicePrefetcher, FlowLoader, fetch_training_set
    from raft_ncup_tpu.evaluation import VALIDATORS
    from raft_ncup_tpu.parallel.mesh import batch_sharding, make_mesh
    from raft_ncup_tpu.parallel.multihost import (
        initialize_distributed,
        is_main_process,
        is_multihost,
    )
    from raft_ncup_tpu.parallel.step import make_train_step
    from raft_ncup_tpu.resilience import (
        EXIT_DIVERGED,
        EXIT_PREEMPTED,
        ChaosDataset,
        ChaosSpec,
        PreemptionHandler,
        chaos_batches,
        resume_metadata,
    )
    from raft_ncup_tpu.training.checkpoint import (
        CheckpointManager,
        load_pretrained_trunk,
    )
    from raft_ncup_tpu.training.logger import Logger
    from raft_ncup_tpu.training.optim import build_schedule
    from raft_ncup_tpu.training.state import create_train_state

    args, model_cfg, train_cfg, data_cfg = parse_train(argv)
    initialize_distributed()  # no-op off-pod; wires processes on a pod
    from raft_ncup_tpu.utils.knobs import knob_flag

    if knob_flag("RAFT_NCUP_COMPILATION_CACHE"):
        # Persistent XLA cache: kill/resume cycles hit warm executables
        # (resume overhead = restore latency, not a recompile). Opt-in
        # by env and OFF by default: on the CPU CI host, reloading cache
        # entries for the fwd+bwd train program has produced glibc heap
        # corruption in this jax build (both in-process re-enables and
        # child reloads) — use on accelerator hosts, where the cache is
        # the difference between seconds and minutes of resume.
        from raft_ncup_tpu.utils.runtime import enable_compilation_cache

        enable_compilation_cache()
    np.random.seed(train_cfg.seed)  # reference: train.py:345-346
    chaos = ChaosSpec.parse(args.chaos)

    run_dir = os.path.join(train_cfg.checkpoint_dir, train_cfg.name)
    # One writer per pod: only process 0 owns log.txt/TensorBoard (orbax
    # saves stay all-process — it coordinates its own shard writes).
    # Validation itself still runs on EVERY process: the validators
    # host-shard the frames and all-reduce the metric sums, so each
    # process computes its slice and returns identical global numbers.
    logger = Logger(
        run_dir, config=train_cfg, sum_freq=train_cfg.sum_freq,
        active=is_main_process(),
    )
    if chaos.active:
        logger.write_text(f"chaos: {chaos.render()}")

    # Device mesh: data-parallel over all chips unless told otherwise. The
    # per-step global batch must divide evenly over the data axis; when the
    # size is left implicit single-host, use the largest batch divisor that
    # fits. Multi-host, every host's chips must be in the mesh (a host with
    # no addressable mesh devices cannot feed its batch shard), so the mesh
    # always spans all devices and the batch must divide it.
    n_dev = len(jax.devices())
    multihost = is_multihost()
    if train_cfg.data_parallel:
        data_par = train_cfg.data_parallel
        if train_cfg.batch_size % data_par:
            raise SystemExit(
                f"--batch_size {train_cfg.batch_size} not divisible by "
                f"--data_parallel {data_par}"
            )
        if multihost and data_par * train_cfg.spatial_parallel != n_dev:
            raise SystemExit(
                f"multi-host mesh must span all {n_dev} devices, got "
                f"{data_par} x {train_cfg.spatial_parallel}"
            )
    else:
        data_par = max(1, n_dev // train_cfg.spatial_parallel)
        if multihost:
            if train_cfg.batch_size % data_par:
                raise SystemExit(
                    f"--batch_size {train_cfg.batch_size} must be divisible "
                    f"by the {data_par}-way data axis on a multi-host mesh"
                )
        else:
            while train_cfg.batch_size % data_par:
                data_par -= 1
    use_mesh = data_par * train_cfg.spatial_parallel > 1
    mesh = (
        make_mesh(data=data_par, spatial=train_cfg.spatial_parallel)
        if use_mesh
        else None
    )
    logger.write_text(
        f"devices={n_dev} mesh=({data_par} data x "
        f"{train_cfg.spatial_parallel} spatial)"
    )

    model, state = create_train_state(
        jax.random.PRNGKey(train_cfg.seed), model_cfg, train_cfg
    )

    if train_cfg.load_pretrained:
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        merged = load_pretrained_trunk(train_cfg.load_pretrained, variables)
        state = state.replace(
            params=merged["params"],
            batch_stats=merged.get("batch_stats", state.batch_stats),
        )
        logger.write_text(f"warm-started trunk from {train_cfg.load_pretrained}")

    # Exact-resume metadata rides next to every orbax payload and is
    # verified before any restore: a wrong-arch/seed resume fails with a
    # clear message, not an orbax pytree error.
    meta = resume_metadata(model_cfg, train_cfg)
    ckpt = CheckpointManager(run_dir, max_to_keep=5, metadata=meta)
    if train_cfg.restore_ckpt:
        same_dir = (
            os.path.abspath(train_cfg.restore_ckpt) == os.path.abspath(run_dir)
        )
        restore_mgr = (
            ckpt
            if same_dir
            else CheckpointManager(train_cfg.restore_ckpt, metadata=meta)
        )
        try:
            state = restore_mgr.restore(state)
        finally:
            if restore_mgr is not ckpt:
                restore_mgr.close()
        logger.write_text(
            f"restored step {int(state.step)} from {train_cfg.restore_ckpt}"
        )

    dataset = fetch_training_set(
        train_cfg.stage, train_cfg.image_size, data_cfg
    )
    if chaos.ioerror_reads:
        dataset = ChaosDataset(dataset, chaos.ioerror_reads)
    # --batch_size is the GLOBAL batch (reference semantics); each host
    # loads its slice.
    n_proc = jax.process_count()
    if train_cfg.batch_size % n_proc:
        raise SystemExit(
            f"--batch_size {train_cfg.batch_size} not divisible by "
            f"{n_proc} hosts"
        )
    loader = FlowLoader(
        dataset,
        batch_size=train_cfg.batch_size // n_proc,
        seed=train_cfg.seed,
        num_workers=data_cfg.num_workers,
        prefetch=data_cfg.prefetch,
        io_retries=data_cfg.io_retries,
        io_retry_backoff_s=data_cfg.io_retry_backoff_s,
    )
    logger.write_text(
        f"training with {len(dataset)} pairs "
        f"({len(loader)} batches/epoch/host)"
    )

    step_fn = make_train_step(model, train_cfg, mesh=mesh)
    schedule = build_schedule(train_cfg)
    # Batch shardings feed the device prefetcher on every mesh run (not
    # just multihost): single-process device_put straight into the step's
    # input layout means jit dispatch never re-lays-out the batch.
    shardings = batch_sharding(mesh) if mesh is not None else None

    def run_validation(step: int) -> None:
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        if multihost:
            # The validators host-shard the frames (mesh=None path), so
            # each host runs DIFFERENT host-local forwards. Pod-global
            # jax.Arrays must not flow in: computation-follows-data would
            # put those divergent programs on the global device
            # assignment and desynchronize the pod. Pull params to host
            # numpy so every forward is process-local.
            variables = jax.tree.map(np.asarray, variables)
        for val_set in train_cfg.validation:
            results = VALIDATORS[val_set](model, variables, data_cfg)
            logger.write_dict(step, results)

    total = train_cfg.num_steps
    # Resume the data stream where the restored run left off: the loader
    # is deterministic per (seed, epoch, index), so the (epoch, batch)
    # position is derived from the restored step and the intra-epoch
    # batches already consumed are skipped without loading.
    step_i = int(state.step)
    start_step = step_i
    per_epoch = max(len(loader), 1)
    batches = loader.batches(
        start_epoch=step_i // per_epoch, start_batch=step_i % per_epoch
    )
    if chaos.nan_steps:
        batches = chaos_batches(
            batches, chaos.nan_steps, start_step=step_i,
            log=logger.write_text,
        )
    # Async input pipeline: a worker thread moves host batches onto device
    # (into the step's batch sharding) depth>=2 steps ahead, so in steady
    # state next() hands back an already-device-resident batch and the
    # loop's only work between dispatches is the rng fold-in.
    prefetcher = DevicePrefetcher(
        batches,
        depth=data_cfg.device_prefetch,  # <2 trades overlap for HBM headroom
        mesh=mesh,
        shardings=shardings,
    )
    # --strict_guards: the invariants graftlint proves statically,
    # asserted live — implicit host pulls inside the step scope raise
    # GuardViolation immediately; steady-state recompiles fail the run at
    # the end-of-loop check. Validation/checkpointing stay outside the
    # guarded scope (they legitimately pull to host and compile new
    # shapes). See docs/ANALYSIS.md.
    step_guard = None
    guard_scope = contextlib.nullcontext
    if args.strict_guards:
        from raft_ncup_tpu.analysis.guards import StepGuard

        step_guard = StepGuard()
        guard_scope = step_guard.scope
    sentinel_on = train_cfg.anomaly_sentinel and state.sentinel is not None
    profiling = False
    profile_scope = contextlib.ExitStack()
    loop_scope = contextlib.ExitStack()
    if step_guard is not None:
        loop_scope.enter_context(step_guard)
    # SIGTERM/SIGINT set a flag here; the loop polls it at the step
    # boundary (multihost: agreed via a fixed-cadence all-reduce so every
    # process saves the same step).
    preempt = loop_scope.enter_context(PreemptionHandler())
    # Flight recorder (observability/flight.py): every fault exit of
    # this run — sentinel halt (76), preemption drain (75) — banks one
    # bounded atomic dump under the run dir, next to the checkpoints a
    # postmortem reads anyway. Attached per-run to the process hub
    # (right before the loop, past every argument-validation exit);
    # detached in the teardown so re-entrant runs (tests) never dump
    # into a stale directory.
    from raft_ncup_tpu.observability import FlightRecorder, get_telemetry

    tel = get_telemetry()
    prev_flight = tel.flight
    if is_main_process():
        tel.flight = FlightRecorder(os.path.join(run_dir, "flight"))
    train_health = tel.health("train", fresh=True)
    status = 0
    preempted = halted = False
    train_health.ready(f"training from step {step_i}")
    try:
        while step_i < total:
            if preempt.poll(step_i):
                preempted = True
                break
            if args.profile_steps and step_i == start_step + 1:
                # Skip the compile step, then trace a few hot steps.
                from raft_ncup_tpu.utils.profiling import trace

                profile_scope.enter_context(
                    trace(os.path.join(run_dir, "profile"))
                )
                profiling = True
            with guard_scope():
                device_batch = next(prefetcher)
                rng = jax.random.fold_in(
                    jax.random.PRNGKey(train_cfg.seed), step_i
                )
                state, metrics = step_fn(state, device_batch, rng)
                step_i += 1  # host-side counter; int(state.step) would sync
                logger.push(step_i - 1, metrics, lr=schedule(step_i - 1))
            if chaos.sigterm_after == step_i:
                # Chaos harness: a REAL signal through the real handler,
                # pinned to a step boundary so tests replay exactly.
                os.kill(os.getpid(), signal.SIGTERM)
            if profiling and step_i >= start_step + 1 + args.profile_steps:
                jax.block_until_ready(metrics["loss"])
                profile_scope.close()
                profiling = False
                logger.write_text(
                    f"profile trace written to {run_dir}/profile"
                )
            if sentinel_on and step_i % train_cfg.sum_freq == 0:
                # The sentinel's ONLY host pull: window cadence, explicit
                # sanctioned device_get — the steady-state loop stays
                # sync-free (same contract as the Logger's boundary pull).
                sen = jax.device_get(state.sentinel)
                # Telemetry rides the SAME sanctioned pull: host ints
                # into gauges, never a second sync (observability/).
                from raft_ncup_tpu.observability import get_telemetry

                tel = get_telemetry()
                tel.gauge_set("train_sentinel_skipped", int(sen["skipped"]))
                tel.gauge_set(
                    "train_sentinel_consecutive", int(sen["consecutive"])
                )
                tel.gauge_set(
                    "train_sentinel_ema_grad_norm",
                    float(sen["ema_grad_norm"]),
                )
                if int(sen["skipped"]):
                    logger.write_text(
                        f"sentinel @ {step_i}: skipped={int(sen['skipped'])} "
                        f"consecutive={int(sen['consecutive'])} "
                        f"ema_grad_norm={float(sen['ema_grad_norm']):.4f}"
                    )
                if int(sen["consecutive"]) >= train_cfg.sentinel_halt_after:
                    tel.event(
                        "train_sentinel_halt", step=step_i,
                        consecutive=int(sen["consecutive"]),
                    )
                    train_health.halted(
                        f"sentinel: {int(sen['consecutive'])} "
                        f"consecutive bad steps @ {step_i}"
                    )
                    # Fault trigger: bank the timeline (sentinel gauges,
                    # io-retry events, the halt event itself) before the
                    # rollback + exit-76 path discards the process.
                    tel.flight_dump(
                        "sentinel_halt", step=step_i,
                        consecutive=int(sen["consecutive"]),
                        skipped=int(sen["skipped"]),
                    )
                    halted = True
                    break
            if step_i % train_cfg.val_freq == 0 or step_i == total:
                ckpt.save(state)  # synchronous: committed on return
                run_validation(step_i)
        # ---- post-loop: clean completion / preemption / sentinel halt --
        if preempted:
            # The one atomic preemption checkpoint: every process agreed
            # on this step, orbax commits the step directory atomically,
            # resume metadata rides along. Skip when the val_freq
            # boundary of this very step already saved it — orbax raises
            # StepAlreadyExists for a re-save, which would turn a clean
            # preemption into a crash exit.
            if ckpt.latest_step != step_i:
                ckpt.save(state)  # synchronous: committed on return
            train_health.draining(f"preempted @ {step_i}")
            # Fault trigger: the drain decision + the timeline that led
            # to it (preemption_signal event included), banked AFTER the
            # checkpoint commit so the dump can name a saved step.
            tel.flight_dump(
                "preemption_drain", step=step_i,
                checkpoint_step=ckpt.latest_step,
            )
            logger.write_text(
                f"preempted @ {step_i}: checkpoint saved, exiting "
                f"{EXIT_PREEMPTED}"
            )
            status = EXIT_PREEMPTED
        elif halted:
            logger.write_text(
                f"sentinel halt @ {step_i}: "
                f">={train_cfg.sentinel_halt_after} consecutive bad steps"
            )
            # Skip-updates kept the in-memory params last-good, but a
            # persistent bad streak means the run has gone wrong: roll
            # back to the last checkpoint on disk and hand the decision
            # to the operator via the distinct exit code.
            if ckpt.latest_step is not None:
                state = ckpt.restore(state)
                logger.write_text(
                    f"rolled back to last good checkpoint "
                    f"(step {int(state.step)})"
                )
            else:
                logger.write_text("no checkpoint available to roll back to")
            status = EXIT_DIVERGED
        if step_guard is not None and status == 0:
            s = step_guard.stats
            logger.write_text(
                f"strict_guards: warmup_compiles={s.warmup_compiles} "
                f"steady_recompiles={s.recompiles} "
                f"host_transfers={s.host_transfers} "
                f"sanctioned_gets={s.sanctioned_gets}"
            )
            step_guard.check()  # raises on steady-state recompilation
        # Per-run IO-fault accounting: a run that survived on retries or
        # quarantined samples says so in log.txt.
        if not loader.retry_stats.clean:
            logger.write_text("io-retry: " + loader.retry_stats.summary())
        if not ckpt.retry_stats.clean:
            logger.write_text("ckpt-retry: " + ckpt.retry_stats.summary())
    finally:
        # Teardown ONLY. The final save belongs to the clean paths above
        # (natural completion saves at the step_i == total boundary;
        # preemption saves explicitly): re-saving here after a mid-loop
        # crash would persist a possibly-inconsistent step, and a save
        # failure would shadow the loop's real exception. Closers are
        # individually shielded for the same reason — teardown noise must
        # never outrank the error that got us here.
        for closer in (
            loop_scope.close,
            profile_scope.close,
            prefetcher.close,
            ckpt.close,
            logger.close,
        ):
            try:
                closer()
            except Exception as e:
                print(f"teardown ({closer.__qualname__}): {e}",
                      file=sys.stderr)
        # Detach this run's flight recorder (re-entrant runs must not
        # dump into a finished run's directory).
        get_telemetry().flight = prev_flight
    if status == 0:
        print(f"done: {int(state.step)} steps, checkpoints in {run_dir}")
    else:
        kind = "preempted" if preempted else "diverged"
        print(
            f"{kind}: exiting {status} at step {step_i}, "
            f"checkpoints in {run_dir}"
        )
    return status


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
