#!/usr/bin/env python
"""Training driver (reference-compatible CLI).

The TPU re-make of the reference trainer (reference: train.py:167-261):
same stages, loss, schedule, validation cadence and flag names — but the
step is one jitted SPMD program over a (data, spatial) device mesh, the
input pipeline is a host-sharded threaded loader with device-side batch
prefetch (transfer overlapped with compute; metrics accumulate on device
so the steady-state loop never syncs the host), and checkpoints carry
the full train state (params + optimizer + step) via orbax.

Example (mirrors train_raft_nc_things.sh):
    python train.py --name raft_nc_things --model raft_nc_dbl \
        --stage things --num_steps 100000 --batch_size 6 \
        --lr 0.000125 --image_size 400 720 --final_upsampling=NConvUpsampler
"""

from __future__ import annotations

import contextlib
import os
import sys

import jax
import numpy as np


def main(argv=None) -> None:
    from raft_ncup_tpu.cli import parse_train
    from raft_ncup_tpu.data import DevicePrefetcher, FlowLoader, fetch_training_set
    from raft_ncup_tpu.evaluation import VALIDATORS
    from raft_ncup_tpu.parallel.mesh import batch_sharding, make_mesh
    from raft_ncup_tpu.parallel.multihost import (
        initialize_distributed,
        is_main_process,
        is_multihost,
    )
    from raft_ncup_tpu.parallel.step import make_train_step
    from raft_ncup_tpu.training.checkpoint import (
        CheckpointManager,
        load_pretrained_trunk,
    )
    from raft_ncup_tpu.training.logger import Logger
    from raft_ncup_tpu.training.optim import build_schedule
    from raft_ncup_tpu.training.state import create_train_state

    args, model_cfg, train_cfg, data_cfg = parse_train(argv)
    initialize_distributed()  # no-op off-pod; wires processes on a pod
    np.random.seed(train_cfg.seed)  # reference: train.py:345-346

    run_dir = os.path.join(train_cfg.checkpoint_dir, train_cfg.name)
    # One writer per pod: only process 0 owns log.txt/TensorBoard (orbax
    # saves stay all-process — it coordinates its own shard writes).
    # Validation itself still runs on EVERY process: the validators
    # host-shard the frames and all-reduce the metric sums, so each
    # process computes its slice and returns identical global numbers.
    logger = Logger(
        run_dir, config=train_cfg, sum_freq=train_cfg.sum_freq,
        active=is_main_process(),
    )

    # Device mesh: data-parallel over all chips unless told otherwise. The
    # per-step global batch must divide evenly over the data axis; when the
    # size is left implicit single-host, use the largest batch divisor that
    # fits. Multi-host, every host's chips must be in the mesh (a host with
    # no addressable mesh devices cannot feed its batch shard), so the mesh
    # always spans all devices and the batch must divide it.
    n_dev = len(jax.devices())
    multihost = is_multihost()
    if train_cfg.data_parallel:
        data_par = train_cfg.data_parallel
        if train_cfg.batch_size % data_par:
            raise SystemExit(
                f"--batch_size {train_cfg.batch_size} not divisible by "
                f"--data_parallel {data_par}"
            )
        if multihost and data_par * train_cfg.spatial_parallel != n_dev:
            raise SystemExit(
                f"multi-host mesh must span all {n_dev} devices, got "
                f"{data_par} x {train_cfg.spatial_parallel}"
            )
    else:
        data_par = max(1, n_dev // train_cfg.spatial_parallel)
        if multihost:
            if train_cfg.batch_size % data_par:
                raise SystemExit(
                    f"--batch_size {train_cfg.batch_size} must be divisible "
                    f"by the {data_par}-way data axis on a multi-host mesh"
                )
        else:
            while train_cfg.batch_size % data_par:
                data_par -= 1
    use_mesh = data_par * train_cfg.spatial_parallel > 1
    mesh = (
        make_mesh(data=data_par, spatial=train_cfg.spatial_parallel)
        if use_mesh
        else None
    )
    logger.write_text(
        f"devices={n_dev} mesh=({data_par} data x "
        f"{train_cfg.spatial_parallel} spatial)"
    )

    model, state = create_train_state(
        jax.random.PRNGKey(train_cfg.seed), model_cfg, train_cfg
    )

    if train_cfg.load_pretrained:
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        merged = load_pretrained_trunk(train_cfg.load_pretrained, variables)
        state = state.replace(
            params=merged["params"],
            batch_stats=merged.get("batch_stats", state.batch_stats),
        )
        logger.write_text(f"warm-started trunk from {train_cfg.load_pretrained}")

    ckpt = CheckpointManager(run_dir, max_to_keep=5)
    if train_cfg.restore_ckpt:
        restore_mgr = (
            ckpt
            if os.path.abspath(train_cfg.restore_ckpt) == os.path.abspath(run_dir)
            else CheckpointManager(train_cfg.restore_ckpt)
        )
        state = restore_mgr.restore(state)
        logger.write_text(
            f"restored step {int(state.step)} from {train_cfg.restore_ckpt}"
        )

    dataset = fetch_training_set(
        train_cfg.stage, train_cfg.image_size, data_cfg
    )
    # --batch_size is the GLOBAL batch (reference semantics); each host
    # loads its slice.
    n_proc = jax.process_count()
    if train_cfg.batch_size % n_proc:
        raise SystemExit(
            f"--batch_size {train_cfg.batch_size} not divisible by "
            f"{n_proc} hosts"
        )
    loader = FlowLoader(
        dataset,
        batch_size=train_cfg.batch_size // n_proc,
        seed=train_cfg.seed,
        num_workers=data_cfg.num_workers,
        prefetch=data_cfg.prefetch,
    )
    logger.write_text(
        f"training with {len(dataset)} pairs "
        f"({len(loader)} batches/epoch/host)"
    )

    step_fn = make_train_step(model, train_cfg, mesh=mesh)
    schedule = build_schedule(train_cfg)
    # Batch shardings feed the device prefetcher on every mesh run (not
    # just multihost): single-process device_put straight into the step's
    # input layout means jit dispatch never re-lays-out the batch.
    shardings = batch_sharding(mesh) if mesh is not None else None

    def run_validation(step: int) -> None:
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        if multihost:
            # The validators host-shard the frames (mesh=None path), so
            # each host runs DIFFERENT host-local forwards. Pod-global
            # jax.Arrays must not flow in: computation-follows-data would
            # put those divergent programs on the global device
            # assignment and desynchronize the pod. Pull params to host
            # numpy so every forward is process-local.
            variables = jax.tree.map(np.asarray, variables)
        for val_set in train_cfg.validation:
            results = VALIDATORS[val_set](model, variables, data_cfg)
            logger.write_dict(step, results)

    total = train_cfg.num_steps
    # Resume the data stream where the restored run left off: the loader
    # is deterministic per (seed, epoch, index), so the (epoch, batch)
    # position is derived from the restored step and the intra-epoch
    # batches already consumed are skipped without loading.
    step_i = int(state.step)
    start_step = step_i
    per_epoch = max(len(loader), 1)
    batches = loader.batches(
        start_epoch=step_i // per_epoch, start_batch=step_i % per_epoch
    )
    # Async input pipeline: a worker thread moves host batches onto device
    # (into the step's batch sharding) depth>=2 steps ahead, so in steady
    # state next() hands back an already-device-resident batch and the
    # loop's only work between dispatches is the rng fold-in.
    prefetcher = DevicePrefetcher(
        batches,
        depth=data_cfg.device_prefetch,  # <2 trades overlap for HBM headroom
        mesh=mesh,
        shardings=shardings,
    )
    # --strict_guards: the invariants graftlint proves statically,
    # asserted live — implicit host pulls inside the step scope raise
    # GuardViolation immediately; steady-state recompiles fail the run at
    # the end-of-loop check. Validation/checkpointing stay outside the
    # guarded scope (they legitimately pull to host and compile new
    # shapes). See docs/ANALYSIS.md.
    step_guard = None
    guard_scope = contextlib.nullcontext
    if args.strict_guards:
        from raft_ncup_tpu.analysis.guards import StepGuard

        step_guard = StepGuard()
        guard_scope = step_guard.scope
    profiling = False
    profile_scope = contextlib.ExitStack()
    loop_scope = contextlib.ExitStack()
    if step_guard is not None:
        loop_scope.enter_context(step_guard)
    try:
        while step_i < total:
            if args.profile_steps and step_i == start_step + 1:
                # Skip the compile step, then trace a few hot steps.
                from raft_ncup_tpu.utils.profiling import trace

                profile_scope.enter_context(
                    trace(os.path.join(run_dir, "profile"))
                )
                profiling = True
            with guard_scope():
                device_batch = next(prefetcher)
                rng = jax.random.fold_in(
                    jax.random.PRNGKey(train_cfg.seed), step_i
                )
                state, metrics = step_fn(state, device_batch, rng)
                step_i += 1  # host-side counter; int(state.step) would sync
                logger.push(step_i - 1, metrics, lr=schedule(step_i - 1))
            if profiling and step_i >= start_step + 1 + args.profile_steps:
                jax.block_until_ready(metrics["loss"])
                profile_scope.close()
                profiling = False
                logger.write_text(
                    f"profile trace written to {run_dir}/profile"
                )
            if step_i % train_cfg.val_freq == 0 or step_i == total:
                ckpt.save(state)
                ckpt.wait()
                run_validation(step_i)
        if step_guard is not None:
            s = step_guard.stats
            logger.write_text(
                f"strict_guards: warmup_compiles={s.warmup_compiles} "
                f"steady_recompiles={s.recompiles} "
                f"host_transfers={s.host_transfers} "
                f"sanctioned_gets={s.sanctioned_gets}"
            )
            step_guard.check()  # raises on steady-state recompilation
    finally:
        loop_scope.close()
        profile_scope.close()
        prefetcher.close()  # joins the worker; closes the batches generator
        ckpt.save(state)
        ckpt.wait()
        ckpt.close()
        logger.close()
    print(f"done: {int(state.step)} steps, checkpoints in {run_dir}")


if __name__ == "__main__":
    main(sys.argv[1:])
