"""Hardware (Mosaic-compiled) validation of the Pallas corr-lookup kernel.

The CPU suite validates the kernel in interpret mode
(tests/test_corr_pallas.py); these tests compile it for real
(``interpret=False``) on the chip, check equivalence against the
materialized-volume path at the training-crop level shapes
(368x768 crop -> 46x96 at 1/8 res, C=256, r=4 — reference:
train_raft_nc_sintel.sh:14, core/corr.py:23-44), and time it against the
XLA paths. Timings are printed (run with ``-s``) and attached to the
pytest report; equivalence is the hard assertion.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_ncup_tpu.ops.corr import (
    build_corr_pyramid,
    corr_lookup,
    corr_lookup_onthefly,
)
from raft_ncup_tpu.ops.corr_pallas import corr_lookup_pallas
from raft_ncup_tpu.ops.geometry import coords_grid

# Training-crop geometry at 1/8 resolution.
B, C, RADIUS, LEVELS = 1, 256, 4, 4
H8, W8 = 368 // 8, 768 // 8


def _inputs(seed=0):
    g = np.random.default_rng(seed)
    fmap1 = jnp.asarray(g.normal(size=(B, H8, W8, C)), jnp.float32)
    fmap2 = jnp.asarray(g.normal(size=(B, H8, W8, C)), jnp.float32)
    coords = coords_grid(B, H8, W8) + jnp.asarray(
        g.uniform(-6, 6, (B, H8, W8, 2)), jnp.float32
    )
    return fmap1, fmap2, coords


def _sync(out):
    # On the axon tunnel block_until_ready returns before the computation
    # finishes; pulling a scalar to host is the only honest sync point
    # (same rationale as bench.py's measure_throughput).
    return np.asarray(out.reshape(-1)[0])


def _time(fn, *args, reps=10):
    _sync(fn(*args))  # compile + warm
    _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / reps


def test_pallas_compiles_and_matches_volume_on_tpu():
    fmap1, fmap2, coords = _inputs()
    ref = jax.jit(
        lambda a, b, c: corr_lookup(
            build_corr_pyramid(a, b, LEVELS), c, RADIUS
        )
    )(fmap1, fmap2, coords)
    out = jax.jit(
        lambda a, b, c: corr_lookup_pallas(a, b, c, RADIUS, LEVELS, False)
    )(fmap1, fmap2, coords)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_pallas_timing_vs_xla_paths(record_property, capsys):
    fmap1, fmap2, coords = _inputs(1)
    t = {}
    t["volume"] = _time(
        jax.jit(
            lambda a, b, c: corr_lookup(
                build_corr_pyramid(a, b, LEVELS), c, RADIUS
            )
        ),
        fmap1, fmap2, coords,
    )
    t["onthefly"] = _time(
        jax.jit(
            lambda a, b, c: corr_lookup_onthefly(a, b, c, RADIUS, LEVELS)
        ),
        fmap1, fmap2, coords,
    )
    t["pallas"] = _time(
        jax.jit(
            lambda a, b, c: corr_lookup_pallas(a, b, c, RADIUS, LEVELS, False)
        ),
        fmap1, fmap2, coords,
    )
    for k, v in t.items():
        record_property(f"corr_lookup_{k}_ms", round(v * 1e3, 3))
    with capsys.disabled():
        print(
            "\ncorr lookup @ {}x{} r={} L={}: ".format(H8, W8, RADIUS, LEVELS)
            + ", ".join(f"{k}={v*1e3:.2f}ms" for k, v in t.items())
        )
    # Soft perf expectation: the fused kernel must at least beat the
    # gather-based XLA path it replaces; against the MXU volume path it is
    # recorded, not gated (bench.py decides the default impl from data).
    assert t["pallas"] < t["onthefly"] * 1.5, t


def test_pallas_in_model_forward_on_tpu():
    """Flagship model forward with corr_impl='pallas', Mosaic-compiled."""
    from raft_ncup_tpu.config import flagship_config
    from raft_ncup_tpu.models.raft import get_model

    cfg = flagship_config(
        dataset="sintel", corr_impl="pallas", mixed_precision=True
    )
    model = get_model(cfg)
    shape = (1, 96, 128, 3)
    variables = model.init(jax.random.PRNGKey(0), shape)
    img = jnp.linspace(0, 255, num=int(np.prod(shape)), dtype=jnp.float32).reshape(shape)
    lr, up = jax.jit(
        lambda v, a, b: model.apply(v, a, b, iters=4, test_mode=True)
    )(variables, img, img)
    assert up.shape == (1, 96, 128, 2)
    assert bool(jnp.isfinite(up).all())


def test_banded_tier_compiles_and_matches_on_tpu(monkeypatch):
    """The BANDED tier Mosaic-compiled for real (docs/PERF.md "Banded
    dispatch"): force residency off so every level takes the banded
    kernel at the training-crop shape, and pin equivalence against the
    volume path. This is the chip-window acceptance for the 4K tier —
    the same kernel, DMA pattern, and chunk table that carry 1080p
    levels 0-1 and all of 4K's large levels."""
    from raft_ncup_tpu.ops import corr_pallas as cpk

    monkeypatch.setattr(cpk, "fits_vmem", lambda *a, **k: False)
    fmap1, fmap2, coords = _inputs(2)
    ref = jax.jit(
        lambda a, b, c: corr_lookup(
            build_corr_pyramid(a, b, LEVELS), c, RADIUS
        )
    )(fmap1, fmap2, coords)
    cpk.reset_dispatch_counts()
    out = jax.jit(
        lambda a, b, c: corr_lookup_pallas(a, b, c, RADIUS, LEVELS, False)
    )(fmap1, fmap2, coords)
    counts = cpk.dispatch_counts()
    assert counts["banded"] == LEVELS and counts["fallback"] == 0
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_banded_timing_vs_resident_on_tpu(record_property, capsys, monkeypatch):
    """Record (not gate) the banded tier's cost vs the resident kernel
    at a shape both can run — the number item 1's autotuner needs to
    price band_rows against residency."""
    from raft_ncup_tpu.ops import corr_pallas as cpk

    fmap1, fmap2, coords = _inputs(3)
    t_res = _time(
        jax.jit(
            lambda a, b, c: corr_lookup_pallas(a, b, c, RADIUS, LEVELS, False)
        ),
        fmap1, fmap2, coords,
    )
    monkeypatch.setattr(cpk, "fits_vmem", lambda *a, **k: False)
    t_band = _time(
        jax.jit(
            lambda a, b, c: corr_lookup_pallas(a, b, c, RADIUS, LEVELS, False)
        ),
        fmap1, fmap2, coords,
    )
    record_property("corr_lookup_resident_ms", round(t_res * 1e3, 3))
    record_property("corr_lookup_banded_ms", round(t_band * 1e3, 3))
    with capsys.disabled():
        print(
            f"\nbanded corr lookup @ {H8}x{W8}: resident={t_res*1e3:.2f}ms "
            f"banded={t_band*1e3:.2f}ms"
        )
