"""Hardware (Mosaic-compiled) validation of the fused NConv2d kernel.

Equivalence vs the XLA two-conv composition at the NCUP production shape
(channels_to_batch: (B*2, H, W, 1) at the training crop, 5x5 encoder —
reference: core/nconv_modules.py:164-199, core/upsampler.py:167-171) and
a timing comparison. The timing decides whether RAFT_NCUP_NCONV_IMPL
defaults to the kernel on TPU; equivalence is the hard assertion.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from raft_ncup_tpu.ops.nconv import nconv2d, positivity
from raft_ncup_tpu.ops.nconv_pallas import nconv2d_fused

B, H, W = 4, 368, 768  # B*2 flow channels of a batch-2 crop
K, CIN, COUT = 5, 1, 2


def _inputs(seed=0):
    g = np.random.default_rng(seed)
    data = jnp.asarray(g.normal(size=(B, H, W, CIN)), jnp.float32)
    conf = jnp.asarray(g.random((B, H, W, CIN)), jnp.float32)
    weight = positivity(
        jnp.asarray(g.normal(2.0, 0.5, (K, K, CIN, COUT)), jnp.float32)
    )
    bias = jnp.asarray(g.normal(size=(COUT,)), jnp.float32)
    return data, conf, weight, bias


def _sync(out):
    return np.asarray(out[0].reshape(-1)[0])


def _time(fn, *args, reps=20):
    _sync(fn(*args))
    _sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / reps


def test_fused_nconv_compiles_and_matches_on_tpu():
    data, conf, weight, bias = _inputs()
    ref = jax.jit(lambda d, c, w, b: nconv2d(d, c, w, b))(
        data, conf, weight, bias
    )
    out = jax.jit(lambda d, c, w, b: nconv2d_fused(d, c, w, b))(
        data, conf, weight, bias
    )
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(out[1]), np.asarray(ref[1]), rtol=1e-4, atol=1e-4
    )


def test_fused_nconv_timing(record_property, capsys):
    data, conf, weight, bias = _inputs(1)
    t_xla = _time(
        jax.jit(lambda d, c, w, b: nconv2d(d, c, w, b)),
        data, conf, weight, bias,
    )
    t_fused = _time(
        jax.jit(lambda d, c, w, b: nconv2d_fused(d, c, w, b)),
        data, conf, weight, bias,
    )
    record_property("nconv_xla_ms", round(t_xla * 1e3, 3))
    record_property("nconv_fused_ms", round(t_fused * 1e3, 3))
    with capsys.disabled():
        print(
            f"\nnconv @ {B}x{H}x{W} k={K}: xla={t_xla*1e3:.2f}ms "
            f"fused={t_fused*1e3:.2f}ms ({t_xla/t_fused:.2f}x)"
        )
    # Recorded, not hard-gated: the default impl is flipped only on data.
    assert t_fused < t_xla * 2.0, (t_fused, t_xla)
