"""TPU-gated hardware tests.

This directory deliberately has its own conftest: the main ``tests/``
suite forces an 8-device virtual CPU platform, while these tests need the
real chip. The inherited axon TPU backend can HANG inside
``jax.devices()`` (VERDICT.md r02), so liveness is decided by a bounded
subprocess probe before any in-process backend init; everything is
skipped when the probe fails.

Run manually when the chip responds:  python -m pytest tests_tpu/ -v
"""

import pytest

from raft_ncup_tpu.utils.backend_probe import probe_backend

_PROBE_TIMEOUT_S = 90


_THIS_DIR = __file__.rsplit("/", 1)[0]


def _in_process_platform():
    """The platform THIS process will actually use. Under a root-level
    `pytest` run, tests/conftest.py has already forced jax.config to cpu —
    probing the chip would then be misleading: these tests would execute
    on the cpu-forced in-process backend regardless of chip health."""
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return "cpu"
    import jax

    return getattr(jax.config, "jax_platforms", None)


def pytest_collection_modifyitems(config, items):
    # Scope to items in THIS directory: a root-level `pytest` run passes
    # every collected item (including tests/) through subdirectory
    # conftests, and skipping those would silently disable the CPU suite.
    tpu_items = [i for i in items if str(i.path).startswith(_THIS_DIR)]
    if not tpu_items:
        return
    if _in_process_platform() == "cpu":
        reason = (
            "in-process backend forced to cpu (run `pytest tests_tpu/` "
            "standalone to target the chip)"
        )
    else:
        pr = probe_backend(_PROBE_TIMEOUT_S)
        if pr.platform not in (None, "cpu"):
            return
        reason = (
            "no live TPU backend "
            f"(probe={pr.platform or pr.reason}: {pr.detail})"
        )
    marker = pytest.mark.skip(reason=reason)
    for item in tpu_items:
        item.add_marker(marker)
