#!/usr/bin/env python
"""Demo driver: run flow on a folder of frames and write visualizations.

The reference pops cv2 windows (reference: demo.py:44-47); headless TPU
hosts have no display, so visualizations are written to ``--output``
(png side-by-side of frame and colorized flow) instead, with ``--show``
restoring the interactive behavior.

Example:
    python demo.py --model checkpoints/raft_chairs --path demo-frames
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np


def main(argv=None) -> None:
    from raft_ncup_tpu.cli import add_model_args, model_config_from_args
    from raft_ncup_tpu.io import read_image
    from raft_ncup_tpu.models.raft import RAFT
    from raft_ncup_tpu.ops import InputPadder
    from raft_ncup_tpu.viz import flow_to_image

    parser = argparse.ArgumentParser(description="RAFT flow demo (TPU)")
    parser.add_argument("--path", required=True, help="folder of frames")
    parser.add_argument("--output", default="demo_out")
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--show", action="store_true")
    parser.add_argument("--restore_ckpt", default=None,
                        help="alias of --model for our CLI symmetry")
    add_model_args(parser)
    from raft_ncup_tpu.cli import add_platform_arg, apply_platform

    add_platform_arg(parser)
    args = parser.parse_args(argv)
    apply_platform(args)

    # In the reference demo, --model is the checkpoint path (demo.py:52-53)
    # and the architecture is plain raft. Keep that: if --model points at a
    # file/dir treat it as the checkpoint.
    ckpt = args.restore_ckpt
    if os.path.exists(args.model):
        ckpt, args.model = args.model, "raft"

    model_cfg = model_config_from_args(args, dataset="sintel")
    model = RAFT(model_cfg)

    from evaluate import load_variables

    variables = load_variables(model, model_cfg, ckpt)

    files = sorted(
        glob.glob(os.path.join(args.path, "*.png"))
        + glob.glob(os.path.join(args.path, "*.jpg"))
    )
    if len(files) < 2:
        raise SystemExit(f"need >= 2 frames in {args.path}")
    os.makedirs(args.output, exist_ok=True)

    @jax.jit
    def forward(variables, img1, img2):
        return model.apply(
            variables, img1, img2, iters=args.iters, test_mode=True
        )

    for f1, f2 in zip(files[:-1], files[1:]):
        img1 = read_image(f1).astype(np.float32)[None]
        img2 = read_image(f2).astype(np.float32)[None]
        padder = InputPadder(img1.shape)
        p1, p2 = padder.pad(jnp.asarray(img1), jnp.asarray(img2))
        _, flow_up = forward(variables, p1, p2)
        # unpad on device (pure slice), then ONE explicit pull per frame —
        # np.asarray here would be an implicit d2h sync (JGL001's runtime
        # analogue).
        flow = jax.device_get(padder.unpad(flow_up)[0])

        vis = np.concatenate(
            [img1[0].astype(np.uint8), flow_to_image(flow)], axis=0
        )
        out = os.path.join(
            args.output, os.path.splitext(os.path.basename(f1))[0] + "_flow.png"
        )
        import cv2

        cv2.imwrite(out, vis[:, :, ::-1])
        print(f"{f1} -> {out}")
        if args.show:
            cv2.imshow("flow", vis[:, :, ::-1] / 255.0)
            cv2.waitKey()


if __name__ == "__main__":
    main(sys.argv[1:])
