"""Benchmark: flagship-model inference throughput on the available chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: frame-pairs/sec/chip for raft_nc_dbl (NCUP) test-mode inference at
12 GRU iterations, 368x768 (the Sintel fine-tune crop,
reference: train_raft_nc_sintel.sh:14). The reference records no
throughput anywhere (BASELINE.md), so ``vs_baseline`` compares against
this framework's own recorded baselines in ``docs/perf_baseline.json``
(keyed by platform+shape+impl); when no baseline exists for the platform
the run is the first recording and ``vs_baseline`` is 1.0.

Robustness (round-1 postmortem: the axon TPU backend failed to init and
the bench crashed with a traceback, recording nothing): the measurement
runs in a child process; the parent retries the TPU backend with bounded
timeouts, then falls back to ``JAX_PLATFORMS=''`` (auto-pick), then to an
explicit CPU run at a reduced shape. Every path — including total
failure — ends with the parent printing one parseable JSON line and
exiting 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD_ENV = "_RAFT_NCUP_BENCH_CHILD"
_REPO = os.path.dirname(os.path.abspath(__file__))
_BASELINE_FILE = os.path.join(_REPO, "docs", "perf_baseline.json")

# Full bench shape (the Sintel fine-tune crop) and the reduced shape used
# for the CPU fallback (full-res NCUP x12 iters on host CPU takes minutes
# per call; the fallback exists to record *a* number, clearly labeled).
FULL = dict(batch=2, height=368, width=768, iters=12)
SMALL = dict(batch=1, height=96, width=128, iters=4)

TPU_ATTEMPTS = 2
TPU_TIMEOUT_S = 900  # cold NCUP compile on the chip can take minutes
FALLBACK_TIMEOUT_S = 1500


def _baseline_key(platform: str, corr_impl: str, shape: dict) -> str:
    return (
        f"{platform}:{corr_impl}:{shape['batch']}x{shape['height']}"
        f"x{shape['width']}x{shape['iters']}"
    )


def _load_baselines() -> dict:
    try:
        with open(_BASELINE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _child_main() -> None:
    """Measure in-process and print the result JSON (child only)."""
    import jax

    # The axon boot hook bakes JAX_PLATFORMS=axon into jax.config at
    # interpreter start, which overrides the env var — the fallbacks must
    # force the config itself (the tests/conftest.py recipe).
    if "_BENCH_FORCE_PLATFORM" in os.environ:
        jax.config.update(
            "jax_platforms", os.environ["_BENCH_FORCE_PLATFORM"]
        )

    import numpy as np

    from __graft_entry__ import build_forward
    from raft_ncup_tpu.utils.profiling import measure_throughput

    shape = json.loads(os.environ.get("_BENCH_SHAPE") or json.dumps(FULL))
    corr_impl = os.environ.get("BENCH_CORR_IMPL", "volume")
    platform = jax.devices()[0].platform
    if platform == "cpu" and shape == FULL:
        # Full-res NCUP x12 iters is a TPU workload; on a host-CPU backend
        # record the reduced shape rather than time out recording nothing.
        shape = SMALL

    fwd, (variables, img1, img2) = build_forward(
        shape=(shape["batch"], shape["height"], shape["width"], 3),
        iters=shape["iters"],
        mixed_precision=(platform == "tpu"),
        corr_impl=corr_impl,
    )
    forward = jax.jit(fwd)

    # On the axon TPU tunnel ``block_until_ready`` returns before the
    # computation finishes; pulling a scalar to host is the only honest
    # synchronization point.
    rate = measure_throughput(
        lambda: forward(variables, img1, img2),
        warmup=2,
        reps=5,
        sync=lambda out: np.asarray(out[1][0, 0, 0, 0]),
    )
    pairs_per_sec = shape["batch"] * rate

    key = _baseline_key(platform, corr_impl, shape)
    baseline = _load_baselines().get(key)
    vs = pairs_per_sec / baseline if baseline else 1.0
    print(
        json.dumps(
            {
                "metric": (
                    f"raft_nc_dbl frame-pairs/sec/chip @ {shape['iters']} "
                    f"iters {shape['height']}x{shape['width']} "
                    f"({platform}, corr={corr_impl})"
                ),
                "value": round(pairs_per_sec, 4),
                "unit": "pairs/s",
                "vs_baseline": round(vs, 3),
                "baseline_key": key,
            }
        )
    )


def _run_child(env_overrides: dict, shape: dict, timeout_s: float):
    """Run the measurement in a child; returns the parsed JSON dict or None."""
    env = dict(os.environ)
    env.update(env_overrides)
    env[_CHILD_ENV] = "1"
    env["_BENCH_SHAPE"] = json.dumps(shape)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=_REPO,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"bench attempt timed out after {timeout_s}s", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            out = json.loads(line)
            if isinstance(out, dict) and "value" in out:
                return out
        except ValueError:
            continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-8:]
    print(
        f"bench attempt failed rc={proc.returncode}:\n" + "\n".join(tail),
        file=sys.stderr,
    )
    return None


def main() -> None:
    if os.environ.get(_CHILD_ENV) == "1":
        _child_main()
        return

    result = None
    # 1) The inherited platform (axon TPU under the driver), with retries —
    #    round 1 died on a transient backend-init failure.
    for attempt in range(TPU_ATTEMPTS):
        result = _run_child({}, FULL, TPU_TIMEOUT_S)
        if result:
            break
        if attempt < TPU_ATTEMPTS - 1:
            time.sleep(10 * (attempt + 1))
    # 2) Let jax auto-pick a backend (JAX_PLATFORMS='' is the documented
    #    escape hatch printed by the round-1 crash itself).
    if not result:
        result = _run_child(
            {"JAX_PLATFORMS": "", "_BENCH_FORCE_PLATFORM": ""},
            FULL, FALLBACK_TIMEOUT_S,
        )
    # 3) Explicit CPU at a reduced shape: always yields a number.
    if not result:
        result = _run_child(
            {"JAX_PLATFORMS": "cpu", "_BENCH_FORCE_PLATFORM": "cpu"},
            SMALL, FALLBACK_TIMEOUT_S,
        )
    if not result:
        result = {
            "metric": "raft_nc_dbl frame-pairs/sec/chip (no backend available)",
            "value": 0.0,
            "unit": "pairs/s",
            "vs_baseline": 0.0,
        }
    _maybe_record_baseline(result)
    print(json.dumps(result))


def _maybe_record_baseline(result: dict) -> None:
    """First successful recording for a (platform, impl, shape) key becomes
    the fixed baseline later rounds are measured against. The driver
    commits repo changes at round end, so the file persists."""
    key = result.pop("baseline_key", None)
    if not key or not result.get("value"):
        return
    baselines = _load_baselines()
    if key in baselines:
        return
    baselines[key] = result["value"]
    try:
        os.makedirs(os.path.dirname(_BASELINE_FILE), exist_ok=True)
        with open(_BASELINE_FILE, "w") as f:
            json.dump(baselines, f, indent=2, sort_keys=True)
            f.write("\n")
    except OSError as e:
        print(f"could not record baseline: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
