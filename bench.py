"""Benchmark: flagship-model inference throughput on the available chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: frame-pairs/sec/chip for raft_nc_dbl (NCUP) test-mode inference at
12 GRU iterations, 368x768 (the Sintel fine-tune crop,
reference: train_raft_nc_sintel.sh:14). The reference records no
throughput anywhere (BASELINE.md), so ``vs_baseline`` is the ratio to
BASELINE_PAIRS_PER_SEC below — this framework's own first recorded
round-1 number on a single TPU chip, fixed so later rounds show relative
progress. It is NOT a PyTorch-reference comparison.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from __graft_entry__ import build_forward
from raft_ncup_tpu.utils.profiling import measure_throughput

# First recorded value (round 1, single TPU chip, 2026-07-29) is the fixed
# baseline all later rounds are measured against.
BASELINE_PAIRS_PER_SEC = 1.3

BATCH = 2
HEIGHT, WIDTH = 368, 768
ITERS = 12
WARMUP = 2
REPS = 5


def main() -> None:
    platform = jax.devices()[0].platform
    corr_impl = os.environ.get("BENCH_CORR_IMPL", "volume")
    fwd, (variables, img1, img2) = build_forward(
        shape=(BATCH, HEIGHT, WIDTH, 3),
        iters=ITERS,
        mixed_precision=(platform == "tpu"),
        corr_impl=corr_impl,
    )
    forward = jax.jit(fwd)

    # On the axon TPU tunnel ``block_until_ready`` returns before the
    # computation finishes; pulling a scalar to host is the only honest
    # synchronization point.
    rate = measure_throughput(
        lambda: forward(variables, img1, img2),
        warmup=WARMUP,
        reps=REPS,
        sync=lambda out: np.asarray(out[1][0, 0, 0, 0]),
    )
    pairs_per_sec = BATCH * rate
    vs = pairs_per_sec / BASELINE_PAIRS_PER_SEC if BASELINE_PAIRS_PER_SEC else 0.0
    print(
        json.dumps(
            {
                "metric": f"raft_nc_dbl frame-pairs/sec/chip @ {ITERS} iters "
                f"{HEIGHT}x{WIDTH} ({platform}, corr={corr_impl})",
                "value": round(pairs_per_sec, 3),
                "unit": "pairs/s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
